#!/usr/bin/env python
"""CoNLL NER finetuning entry point, TPU-native.

Thin alias of `run_finetune.py --task ner` (identical CLI — parity with
the reference run_ner.py :19-261): the task-shaped half lives in
bert_pytorch_tpu/tasks/ner_task.py, the shared loop in
bert_pytorch_tpu/training/finetune.py.
"""

from __future__ import annotations


def parse_arguments(argv=None):
    from bert_pytorch_tpu.tasks.ner_task import parse_arguments as parse

    return parse(argv)


def main(argv=None):
    from bert_pytorch_tpu.tasks import registry
    from bert_pytorch_tpu.training.finetune import run_task

    return run_task(registry.get("ner"), parse_arguments(argv))


if __name__ == "__main__":
    main()
