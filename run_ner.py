#!/usr/bin/env python
"""CoNLL NER finetuning entry point, TPU-native.

Parity with the reference run_ner.py (:19-261): BertForTokenClassification
with len(labels)+1 classes, FusedAdam (no bias correction) with the
bias/LayerNorm no-decay split, per-epoch 1/(1+0.05*epoch) LR decay, grad-norm
clip 5.0, macro-F1 on val/test. Deviation: evaluation runs one forward pass
returning loss and logits together (the reference ran two,
run_ner.py:187-191 — a noted inefficiency, not a semantic difference).
"""

from __future__ import annotations

import argparse
import json
import os

import numpy as np


def parse_arguments(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--train_file", type=str, required=True)
    p.add_argument("--val_file", default=None, type=str)
    p.add_argument("--test_file", default=None, type=str)
    p.add_argument("--labels", type=str, nargs="+", required=True)
    p.add_argument("--model_config_file", type=str, required=True)
    p.add_argument("--model_checkpoint", type=str, default=None,
                   help="pretraining checkpoint dir (orbax); optional")
    p.add_argument("--vocab_file", default=None, type=str)
    p.add_argument("--uppercase", action="store_true", default=False)
    p.add_argument("--tokenizer", type=str, default=None,
                   choices=["wordpiece", "bpe"])
    p.add_argument("--epochs", type=int, default=10)
    p.add_argument("--lr", type=float, default=5e-6)
    p.add_argument("--clip_grad", type=float, default=5.0)
    p.add_argument("--batch_size", type=int, default=32)
    p.add_argument("--max_seq_len", type=int, default=128)
    p.add_argument("--seed", type=int, default=42)
    p.add_argument("--output_dir", type=str, default="results/ner")
    p.add_argument("--metrics_port", type=int, default=None,
                   help="serve live /metrics + /healthz on this port while "
                        "the run is alive (telemetry/exporter.py; 0 = "
                        "ephemeral). Default: off")
    p.add_argument("--dtype", type=str, default="bfloat16",
                   choices=["bfloat16", "float32"])
    p.add_argument("--watchdog_timeout", type=float, default=0.0,
                   help="hung-step watchdog (resilience/watchdog.py): a "
                        "host phase exceeding this many seconds dumps "
                        "all-thread stacks and acts per "
                        "--watchdog_action; 0 = off (docs/RESILIENCE.md)")
    p.add_argument("--watchdog_action", type=str, default="abort",
                   choices=["abort", "warn"])
    return p.parse_args(argv)


def main(argv=None):
    args = parse_arguments(argv)
    os.makedirs(args.output_dir, exist_ok=True)

    import jax
    import jax.numpy as jnp
    import optax

    from bert_pytorch_tpu.config import BertConfig, pad_vocab_size
    from bert_pytorch_tpu.data import ner
    from bert_pytorch_tpu.data.tokenization import (get_bpe_tokenizer,
                                                    get_wordpiece_tokenizer)
    from bert_pytorch_tpu.models import BertForTokenClassification, losses
    from bert_pytorch_tpu.optim.adam import fused_adam
    from bert_pytorch_tpu.optim.lamb import default_weight_decay_mask
    from bert_pytorch_tpu.parallel import dist
    from bert_pytorch_tpu.telemetry import (collect_provenance,
                                            flops_per_seq, init_run,
                                            lookup_peak_flops)
    from bert_pytorch_tpu.telemetry.stepwatch import DEFAULT_PEAK
    from bert_pytorch_tpu.training import TrainState, make_sharded_state

    np.random.seed(args.seed)
    # the single telemetry wiring path (telemetry/run.py) — same call as
    # run_pretraining/run_squad/bench, one record schema per phase label
    tel = init_run(phase="ner",
                   log_prefix=os.path.join(args.output_dir, "ner_log"),
                   verbose=dist.is_main_process(), jsonl=True,
                   metrics_port=args.metrics_port)
    logger = tel.logger
    compile_watch = tel.compile_watch
    # survival kit (docs/RESILIENCE.md): SIGTERM/SIGINT -> emergency
    # checkpoint of the in-progress finetune state; optional hung-step
    # watchdog
    from bert_pytorch_tpu.resilience import PreemptionGuard
    from bert_pytorch_tpu.resilience.preemption import \
        finetune_emergency_save
    from bert_pytorch_tpu.resilience.watchdog import arm_watchdog

    guard = PreemptionGuard(registry=tel.registry, log=logger.info)
    guard.install()
    watchdog = None
    survival = {}  # latest (state, step) the except-path may checkpoint
    try:
        tel.log_header(**collect_provenance())

        config = BertConfig.from_json_file(args.model_config_file)
        config = config.replace(
            vocab_size=pad_vocab_size(config.vocab_size, 8))
        vocab_file = args.vocab_file or config.vocab_file
        tok_kind = args.tokenizer or config.tokenizer
        if not vocab_file:
            raise SystemExit("vocab_file required (CLI or model config)")
        if tok_kind == "bpe":
            tokenizer = get_bpe_tokenizer(vocab_file,
                                          uppercase=args.uppercase)
        else:
            tokenizer = get_wordpiece_tokenizer(vocab_file,
                                                uppercase=args.uppercase)

        num_labels = len(args.labels) + 1  # + padding label 0 (reference :224)
        compute_dtype = (jnp.bfloat16 if args.dtype == "bfloat16"
                         else jnp.float32)
        model = BertForTokenClassification(config, num_labels=num_labels,
                                           dtype=compute_dtype)

        datasets = {}
        for split, path in (("train", args.train_file),
                            ("val", args.val_file),
                            ("test", args.test_file)):
            if path:
                datasets[split] = ner.NERDataset(
                    path, tokenizer, args.labels,
                    max_seq_len=args.max_seq_len)
        train_arrays = datasets["train"].arrays()
        steps_per_epoch = max(1, len(datasets["train"]) // args.batch_size)

        # per-epoch decay lr/(1+0.05*epoch) (reference LambdaLR,
        # run_ner.py:245)
        def schedule(step):
            epoch = step // steps_per_epoch
            return args.lr / (1.0 + 0.05 * epoch)

        tx = fused_adam(schedule, weight_decay=0.01,
                        weight_decay_mask=default_weight_decay_mask,
                        bias_correction=False)
        if args.clip_grad and args.clip_grad > 0:
            tx = optax.chain(optax.clip_by_global_norm(args.clip_grad), tx)

        sample = jnp.zeros((2, args.max_seq_len), jnp.int32)
        init_fn = lambda r: model.init(r, sample, sample, sample)
        state, _ = make_sharded_state(jax.random.PRNGKey(args.seed),
                                      init_fn, tx)

        if args.model_checkpoint:
            from run_squad import load_pretrained_params

            params = load_pretrained_params(args.model_checkpoint,
                                            state.params, log=logger.info)
            state = TrainState(step=state.step, params=params,
                               opt_state=state.opt_state)
            logger.info(
                f"loaded pretrained weights from {args.model_checkpoint}")

        def loss_fn(params, batch, rng, deterministic):
            logits = model.apply(
                {"params": params}, batch["input_ids"],
                jnp.zeros_like(batch["input_ids"]), batch["attention_mask"],
                deterministic=deterministic,
                rngs=None if deterministic else {"dropout": rng})
            loss = losses.token_classification_loss(
                logits, batch["labels"], ignore_index=ner.IGNORE_LABEL)
            return loss, logits

        @jax.jit
        def train_step(state, batch, rng):
            (loss, _), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                state.params, batch, rng, False)
            updates, opt_state = tx.update(grads, state.opt_state,
                                           state.params)
            params = optax.apply_updates(state.params, updates)
            return TrainState(step=state.step + 1, params=params,
                              opt_state=opt_state), loss

        # eval logits come from the SAME pure forward the serving engine
        # compiles (tasks/predict.py); only the loss is eval-specific
        from bert_pytorch_tpu.tasks import predict

        ner_forward = predict.build_ner_forward(model)

        @jax.jit
        def eval_step(params, batch):
            logits = ner_forward(params, batch)
            loss = losses.token_classification_loss(
                logits, batch["labels"], ignore_index=ner.IGNORE_LABEL)
            return loss, logits

        def run_eval(split):
            arrays = datasets[split].arrays()
            n = len(arrays["input_ids"])
            loss_sum, loss_w = 0.0, 0.0
            logits_, labels_ = [], []
            for lo in range(0, n, args.batch_size):
                idx = np.arange(lo, min(lo + args.batch_size, n))
                pad = args.batch_size - len(idx)
                full = (np.concatenate([idx, np.zeros(pad, np.int64)])
                        if pad else idx)
                batch = {k: np.asarray(v[full]) for k, v in arrays.items()}
                keep = len(idx)
                if pad:
                    # duplicated tail-padding rows must not contribute to loss
                    batch["labels"][keep:] = ner.IGNORE_LABEL
                batch = {k: jnp.asarray(v) for k, v in batch.items()}
                loss, logits = eval_step(state.params, batch)
                loss_sum += float(loss) * keep
                loss_w += keep
                logits_.append(np.asarray(logits)[:keep])
                labels_.append(arrays["labels"][idx])
            all_logits = np.concatenate(logits_)
            all_labels = np.concatenate(labels_)
            f1 = ner.macro_f1(all_logits, all_labels)
            diag = ner.classification_diagnostics(all_logits, all_labels,
                                                  label_names=args.labels)
            return loss_sum / max(loss_w, 1.0), f1, diag

        # real StepWatch perf records (shared flops_per_seq; n_pred=0 — the
        # token-classifier head is noise next to the trunk). One interval
        # per epoch: log_freq = steps_per_epoch.
        peak = lookup_peak_flops(jax.devices()[0].device_kind)
        sw = tel.make_stepwatch(
            flops_per_step=flops_per_seq(config, args.max_seq_len,
                                         config.vocab_size, 0)
            * args.batch_size,
            seqs_per_step=args.batch_size, seq_len=args.max_seq_len,
            peak_flops=(peak or DEFAULT_PEAK) * jax.device_count(),
            log_freq=max(1, steps_per_epoch))
        watchdog = arm_watchdog(
            args.watchdog_timeout, args.watchdog_action, sw,
            registry=tel.registry, log=logger.info,
            out_dir=args.output_dir)

        rng = jax.random.PRNGKey(args.seed)
        results = {}
        host_step = 0  # host-side mirror of state.step: the emergency-
        # save snapshot must not force a device sync in the hot loop
        order_rng = np.random.RandomState(args.seed)
        for epoch in range(args.epochs):
            order = order_rng.permutation(len(train_arrays["input_ids"]))
            for lo in range(0, len(order) - args.batch_size + 1,
                            args.batch_size):
                with sw.phase("data_prep"):
                    idx = order[lo:lo + args.batch_size]
                    batch = {k: jnp.asarray(v[idx])
                             for k, v in train_arrays.items()}
                rng, srng = jax.random.split(rng)
                with sw.phase("dispatch"):
                    state, loss = train_step(state, batch, srng)
                host_step += 1
                survival["state"], survival["step"] = state, host_step
                perf = sw.step_done()
                if perf is not None:
                    tel.log_perf(int(state.step), perf)
            with sw.phase("metric_flush"):
                tel.log_train(int(state.step), epoch=epoch,
                              loss=float(loss),
                              learning_rate=float(
                                  schedule(int(state.step) - 1)))
            if "val" in datasets:
                with sw.pause():  # eval time must not pollute the next
                    vloss, vf1, vdiag = run_eval("val")  # epoch's interval
                logger.log("val", int(state.step), epoch=epoch, loss=vloss,
                           macro_f1=vf1)
                logger.info("val diagnostics: " + json.dumps(vdiag))
                results["val_f1"] = vf1

        perf = sw.flush()  # partial final interval
        if perf is not None:
            tel.log_perf(int(state.step), perf)

        if "test" in datasets:
            tloss, tf1, tdiag = run_eval("test")
            logger.log("test", int(state.step), loss=tloss, macro_f1=tf1)
            logger.info("test diagnostics: " + json.dumps(tdiag))
            results["test_f1"] = tf1
            results["test_diagnostics"] = tdiag

        logger.info(json.dumps(results))
        logger.info(f"compiles: {compile_watch.snapshot()}")
        return results
    except BaseException as exc:
        # preemption-safe finetuning: SIGTERM/SIGINT mid-epoch saves the
        # in-progress state (the reference lost the whole finetune run)
        finetune_emergency_save(guard, exc, survival,
                                os.path.join(args.output_dir, "ckpt"),
                                "ner", registry=tel.registry,
                                log=logger.info)
        raise
    finally:
        for closeable in (watchdog, guard):
            if closeable is not None:
                try:
                    closeable.close()
                except Exception:
                    pass
        tel.close()


if __name__ == "__main__":
    main()
