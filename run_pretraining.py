#!/usr/bin/env python
"""BERT/RoBERTa pretraining entry point, TPU-native.

Capability parity with the reference's run_pretraining.py (CLI surface
:70-167, setup :170-221, train loop :453-581) on the SPMD execution model:
no torch.distributed.launch fan-out, no DDP wrapper, no GradScaler — one
process per TPU-VM host, one jitted train step over a (data, fsdp, model,
seq) mesh, gradients reduced by compiler-inserted collectives over ICI.

Telemetry (bert_pytorch_tpu/telemetry/, docs/OBSERVABILITY.md): an in-graph
health pack (non-finite counts, grad-spike z-score, --nonfinite_action
policy), per-interval StepWatch records (step time, data-wait vs dispatch,
seq/s, tokens/s, MFU), compile counting with loud recompile warnings, HBM
snapshots, and provenance-stamped log headers.

Usage (mirrors the reference):
  python run_pretraining.py --config_file configs/bert_pretraining_phase1_config.json \
      --input_dir data/encoded/seq128 --output_dir results/phase1
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
import time
from pathlib import Path

import numpy as np


def parse_arguments(argv=None):
    parser = argparse.ArgumentParser(description=__doc__)
    # Optional json run config overriding defaults (CLI > config > defaults,
    # reference run_pretraining.py:152-166)
    parser.add_argument("--config_file", default=None, type=str,
                        help="JSON run config overriding defaults")
    parser.add_argument("--input_dir", default=None, type=str,
                        help="dir containing .hdf5 shards")
    parser.add_argument("--output_dir", default=None, type=str,
                        help="dir for checkpoints and logs")
    parser.add_argument("--model_config_file", default=None, type=str,
                        help="BERT model config JSON")
    # dynamic masking (reference :86-91)
    parser.add_argument("--masked_token_fraction", type=float, default=0.2)
    parser.add_argument("--max_predictions_per_seq", type=int, default=80)
    parser.add_argument("--init_checkpoint", type=str, default="",
                        help="seed model weights (not optimizer state) from "
                             "an external checkpoint before step 0: a "
                             "reference torch save (ckpt_*.pt), a Google TF "
                             "release, or a framework orbax dir[@step]. "
                             "Ignored when output_dir already holds a "
                             "resumable checkpoint (auto-resume wins). The "
                             "migration path for continuing a GPU-pretrained "
                             "run on TPU, e.g. phase 2 from a reference "
                             "phase-1 ckpt_7038.pt")
    # training configuration (reference :93-108)
    parser.add_argument("--num_steps_per_checkpoint", type=int, default=200)
    parser.add_argument("--keep_checkpoints", type=int, default=3,
                        help="rolling checkpoint window size (reference kept "
                             "3, run_pretraining.py:513-516); raise to keep "
                             "intermediate checkpoints for finetune curves")
    parser.add_argument("--prefetch_batches", type=int, default=2,
                        help="host batches assembled ahead on an executor "
                             "thread (gather + dynamic masking overlap the "
                             "device step; 0 = assemble synchronously). The "
                             "reference used 4 DataLoader workers for the "
                             "same overlap (run_pretraining.py:384)")
    parser.add_argument("--steps_per_loop", type=int, default=1,
                        help="optimization steps per host dispatch: >1 runs "
                             "a device-side lax.fori_loop over that many "
                             "steps (host only feeds data / logs at loop "
                             "boundaries) — amortizes dispatch latency; "
                             "metrics are logged once per loop from its "
                             "final step (health/anomaly flags are "
                             "max-accumulated across the loop so nothing "
                             "is lost)")
    parser.add_argument("--skip_checkpoint", action="store_true")
    parser.add_argument("--checkpoint_activations", action="store_true")
    parser.add_argument("--log_prefix", type=str, default="logfile")
    parser.add_argument("--seed", type=int, default=42)
    # hyperparameters (reference :110-126)
    parser.add_argument("--learning_rate", default=5e-5, type=float)
    parser.add_argument("--lr_decay", default="poly", type=str,
                        choices=["poly", "linear", "cosine", "constant"])
    parser.add_argument("--warmup_proportion", default=0.01, type=float)
    parser.add_argument("--global_batch_size", default=2 ** 16, type=int)
    parser.add_argument("--local_batch_size", default=8, type=int,
                        help="per-data-shard microbatch size (reference: per-GPU)")
    parser.add_argument("--max_steps", default=1000, type=int)
    parser.add_argument("--steps", default=None, type=int,
                        help="steps to perform this session (default: to max_steps)")
    parser.add_argument("--previous_phase_end_step", default=0, type=int)
    # K-FAC (reference :128-144)
    parser.add_argument("--kfac", action="store_true", default=False)
    parser.add_argument("--kfac_inv_interval", type=int, default=10)
    parser.add_argument("--kfac_factor_interval", type=int, default=1)
    parser.add_argument("--kfac_stat_decay", type=float, default=0.95)
    parser.add_argument("--kfac_damping", type=float, default=0.003)
    parser.add_argument("--kfac_kl_clip", type=float, default=0.001)
    parser.add_argument("--kfac_stats_dtype", type=str, default="f32",
                        choices=["f32", "bf16"],
                        help="dtype of the per-microbatch K-FAC factor "
                             "STATISTICS on the wire (optim/kfac.py "
                             "stats_dtype): bf16 halves the factor-psum "
                             "bytes; the EMA accumulator and resting "
                             "factors stay f32 either way (the reduction "
                             "upcasts before summing). f32 is the exact "
                             "round-15 program, bit for bit")
    parser.add_argument("--kfac_skip_layers", nargs="+", type=str,
                        default=["cls_predictions", "embeddings"])
    # TPU-native knobs (no reference equivalent)
    parser.add_argument("--mesh", type=str, default="",
                        help="mesh axis sizes, e.g. 'data=8,fsdp=1,model=1,seq=1'; "
                             "empty = all devices on data")
    parser.add_argument("--dtype", type=str, default="bfloat16",
                        choices=["bfloat16", "float32"])
    parser.add_argument("--grad_dtype", type=str, default="auto",
                        choices=["auto", "bfloat16", "float32"],
                        help="gradient accumulation dtype; auto follows "
                             "--dtype (bf16 grads against fp32 masters, the "
                             "apex-O2-equivalent default)")
    parser.add_argument("--mask_token_index", type=int, default=None,
                        help="[MASK] id; default: looked up in vocab_file")
    parser.add_argument("--vocab_pad_multiple", type=int, default=128,
                        help="pad vocab for the MXU (reference padded to 8)")
    parser.add_argument("--optimizer", type=str, default="lamb",
                        choices=["lamb", "bert_adam", "fused_adam"])
    parser.add_argument("--profile_steps", type=str, default=None,
                        help="'start,stop' step range to capture a jax.profiler "
                             "trace. Host loop phases carry TraceAnnotations "
                             "(data_wait/data_prep/h2d/dispatch/metric_flush) "
                             "and the model is named_scope-annotated "
                             "(embeddings/attention/mlp/mlm_head), so the "
                             "trace maps time to code, not fused-op soup")
    # telemetry (docs/OBSERVABILITY.md)
    parser.add_argument("--log_freq", type=int, default=10,
                        help="optimization steps per StepWatch interval "
                             "record (tag 'perf': step_time_ms, seq_per_sec, "
                             "tokens_per_sec, MFU, data_wait/dispatch "
                             "breakdown, compile counts, HBM peak). Per-step "
                             "'train' records are unaffected")
    parser.add_argument("--health_pack", type=str, default="on",
                        choices=["on", "off"],
                        help="in-graph health pack (telemetry/health.py): "
                             "non-finite counts for loss and per-group "
                             "grads, grad-norm EMA + z-score spike flag, "
                             "param-norm drift — all returned through the "
                             "non-blocking metrics readback")
    parser.add_argument("--nonfinite_action", type=str, default="log",
                        choices=["log", "skip", "halt"],
                        help="policy when the health pack flags a non-finite "
                             "loss/grad step: 'log' warns loudly and trains "
                             "on; 'skip' drops the update IN-GRAPH (params/"
                             "optimizer state stay bit-identical — the host "
                             "only learns one step later, too late to "
                             "intervene); 'halt' stops the run after "
                             "logging. Requires --health_pack=on")
    parser.add_argument("--stacked_params", type=str, default="auto",
                        choices=["auto", "true", "false"],
                        help="encoder parameter layout: 'true' = one nn.scan "
                             "stack with a leading (L, ...) axis (O(1) "
                             "compile time), 'false' = per-layer modules "
                             "(no scan-wgrad dynamic-update-slice traffic "
                             "in backward — faster at BERT-Large when the "
                             "stack is fully unrolled anyway, O(L) compile "
                             "time). 'auto' keeps the model config's value. "
                             "Checkpoints resume across either choice "
                             "(layout converted losslessly on restore)")
    parser.add_argument("--zero1", type=str, default="auto",
                        choices=["auto", "true", "false"],
                        help="ZeRO-1 optimizer-state sharding over the data "
                             "mesh axis (parallel/zero.py): moments stored "
                             "1/N per chip, gradient reduce-scatter + "
                             "shard-local LAMB update + param all-gather — "
                             "the apex DistributedFusedLAMB analog. 'auto' "
                             "enables it whenever the data axis is >1; "
                             "checkpoints of sharded moments save/restore "
                             "transparently (orbax is sharding-native)")
    parser.add_argument("--zero1_overlap", action="store_true",
                        help="gather-on-use ZeRO-1 (requires --zero1): "
                             "params rest in the 1/N shard layout between "
                             "steps and are re-gathered leaf-by-leaf at the "
                             "point of use, so the all-gathers become "
                             "per-layer ops the latency-hiding scheduler "
                             "overlaps with forward compute instead of one "
                             "blocking constraint after the update. "
                             "Bit-identical values; only the collective "
                             "schedule changes")
    parser.add_argument("--zero1_rs", action="store_true",
                        help="reduce-scatter ZeRO-1 gradients (requires "
                             "--zero1; forces --zero1_overlap): the grad "
                             "tree exits the backward through psum_scatter "
                             "into the exact 1/N shard the update owns, "
                             "instead of a full all-reduce every device "
                             "then slices — half the gradient bytes on "
                             "the wire. Bit-identical values (pinned in "
                             "tests against the all-reduce arm of the "
                             "same program); needs a data-only mesh "
                             "(every non-data axis trivial)")
    parser.add_argument("--fused_optim", type=str, default="off",
                        choices=["off", "auto", "xla", "pallas"],
                        help="fused multi-tensor LAMB update (ops/pallas/"
                             "fused_optim.py, the apex FusedLAMB / amp_C "
                             "analogue): flatten the update math across "
                             "leaves into fixed-size blocks — one kernel "
                             "sweep instead of per-leaf op soup. 'auto' "
                             "picks pallas on TPU, xla elsewhere; the xla "
                             "impl is bit-identical to off, the pallas "
                             "kernel agrees to a few ulps (lamb only; "
                             "other --optimizer values ignore this)")
    parser.add_argument("--fsdp_overlap", action="store_true",
                        help="gather-on-use for fsdp-RESIDENT params "
                             "(parallel/zero.make_fsdp_plan): each param's "
                             "point-of-use all-gather becomes an explicit, "
                             "independent per-leaf node the latency-hiding "
                             "scheduler can interleave with forward compute "
                             "— instead of wherever (and fused however) "
                             "GSPMD implicitly re-materializes the leaf. "
                             "No-op when the mesh's fsdp axis is trivial; "
                             "with --zero1 it forces --zero1_overlap (the "
                             "resting layout must match the update's "
                             "output pin)")
    parser.add_argument("--mesh_config", type=str, default="auto",
                        choices=["auto", "production", "base"],
                        help="named feature config from the rules table "
                             "(parallel/rules.py CONFIG_OVERRIDES): "
                             "'production' turns on the collective-time "
                             "pack the mesh qualifies for — packing, "
                             "ZeRO-1 overlap (data>1), fsdp gather-on-use "
                             "(fsdp>1), ring attention (seq>1) — measured "
                             "by the dp_seq_packing_overlap MULTICHIP "
                             "variant. 'auto' selects production on real "
                             "accelerators when the mesh has a non-trivial "
                             "parallel axis (forced-CPU harness meshes "
                             "keep 'base' so test/bench programs only "
                             "change when asked); 'base' keeps every "
                             "feature at its own flag's default")
    parser.add_argument("--coalesce_reductions", type=str, default="off",
                        choices=["on", "off"],
                        help="bucket the cross-device reduction storm "
                             "(parallel/coalesce.py): LAMB per-tensor "
                             "trust norms, the pre-normalization global "
                             "norm and the logged grad_norm compile to a "
                             "handful of vector all-reduces instead of "
                             "two scalars per parameter leaf; with --kfac "
                             "the factor statistics reduce in "
                             "size-capped buckets too (--kfac_bucket_mb). "
                             "Values bit-identical for the norm paths; "
                             "K-FAC factor parity documented in "
                             "docs/PERF.md round 15")
    parser.add_argument("--kfac_bucket_mb", type=float, default=4.0,
                        help="bucket size cap (MB) for coalesced K-FAC "
                             "factor reductions (--coalesce_reductions); "
                             "the deterministic assignment is recorded in "
                             "the run header")
    parser.add_argument("--kfac_factor_sync_freq", type=int, default=1,
                        help="sync (reduce + EMA) K-FAC factor statistics "
                             "only every N steps — they are EMA-smoothed, "
                             "so off-steps skip the factor collectives "
                             "entirely under --coalesce_reductions. 1 "
                             "(default) compiles the exact legacy "
                             "program; parity at freq=1 is test-pinned")
    parser.add_argument("--h2d_prefetch", type=int, default=1,
                        help="batches kept device-resident ahead of dispatch "
                             "(data/sharded.py DevicePrefetcher): the next "
                             "batch's host->device transfer is issued before "
                             "the current step dispatches, so the copy rides "
                             "the wire under device compute and the h2d "
                             "StepWatch bucket measures only the issue. 0 "
                             "disables (synchronous put, the pre-round-11 "
                             "behavior). Ignored when --steps_per_loop>1 "
                             "(chunks already amortize the put)")
    parser.add_argument("--overlap_flags", type=str, default="on",
                        choices=["on", "off"],
                        help="apply the libtpu async-collective + "
                             "latency-hiding-scheduler flag pack "
                             "(parallel/xla_flags.py) so grad reduce-scatter "
                             "/ param all-gather overlap compute; no-op off "
                             "TPU. 'off' leaves LIBTPU_INIT_ARGS untouched")
    parser.add_argument("--rng_impl", type=str, default="threefry2x32",
                        choices=["rbg", "unsafe_rbg", "threefry2x32"],
                        help="PRNG for dropout keys. threefry (JAX default) "
                             "gives stable bit-streams across versions and "
                             "backends; pass 'rbg' for ~10%% faster steps on "
                             "v5e at the cost of that stability guarantee "
                             "(rbg streams are not version-portable)")
    parser.add_argument("--packing", action="store_true",
                        help="sequence packing (data/packing.py): assemble "
                             "each batch row from multiple short examples "
                             "with block-diagonal segment attention, "
                             "per-segment positions and per-segment NSP — "
                             "the padded FLOPs the perf record's "
                             "pad_fraction measures become real work. "
                             "Default off; resume-compatible (the packer "
                             "buffer checkpoints with the sampler cursor)")
    parser.add_argument("--packing_max_segments", type=int, default=8,
                        help="max examples packed into one row (bounds the "
                             "static per-segment NSP arrays)")
    parser.add_argument("--packing_lookahead", type=int, default=4,
                        help="batches of examples the packer may look ahead "
                             "when filling rows; higher = better packing "
                             "efficiency, more host RAM in flight")
    # flight recorder (docs/OBSERVABILITY.md "Postmortem debugging")
    parser.add_argument("--flight_recorder", type=str, default="on",
                        choices=["on", "off"],
                        help="black-box ring of the last --recorder_window "
                             "batches + RNG keys + metric records "
                             "(telemetry/flight_recorder.py); dumps a "
                             "self-contained repro bundle under "
                             "<output_dir>/repro_bundles when the health "
                             "pack flags a non-finite step or the process "
                             "dies (signal/exception). tools/replay.py "
                             "re-executes the offending step from the "
                             "bundle + the matching checkpoint")
    parser.add_argument("--recorder_window", type=int, default=8,
                        help="optimization steps of loader output the "
                             "flight recorder holds (host RAM bound: "
                             "window * host batch bytes). Replaying a bad "
                             "step needs a checkpoint at most this many "
                             "steps behind it — size against "
                             "--num_steps_per_checkpoint when full "
                             "replayability matters. Auto-raised to "
                             "2x --steps_per_loop (the metric readback "
                             "lags one dispatch)")
    parser.add_argument("--metrics_port", type=int, default=None,
                        help="serve live Prometheus-text /metrics and a "
                             "/healthz JSON (last step, last health-pack "
                             "flags, compile count) on this port while "
                             "the run is alive (telemetry/exporter.py; "
                             "0 = ephemeral port, logged at startup). "
                             "Default: off")
    parser.add_argument("--inject_nonfinite_step", type=int, default=None,
                        help="fault-injection drill: poison layer 0's "
                             "attention output kernel with one NaN at "
                             "exactly this global step (in-graph, "
                             "deterministic — replays from the bundle), "
                             "to fire-drill the alarm -> recorder -> "
                             "replay -> bisect pipeline on a real run")
    # streaming data plane (data/streaming.py, docs/DATA.md): tokenize raw
    # text on the fly instead of reading offline-encoded HDF5 shards
    parser.add_argument("--stream_dir", default=None, type=str,
                        help="STREAM MODE: directory (or glob) of raw .txt "
                             "corpus files (blank-line-delimited documents, "
                             "pipeline/format.py contract) tokenized on the "
                             "fly by a worker pool — no offline encode "
                             "cycle. Mutually exclusive with --input_dir. "
                             "Deterministic multi-host record sharding, "
                             "resumable checkpointed cursors (resume is "
                             "bit-identical, masks included), composes "
                             "with --packing / --prefetch_batches / "
                             "--h2d_prefetch unchanged")
    parser.add_argument("--stream_vocab", default=None, type=str,
                        help="vocab file for the streaming tokenizer "
                             "(default: the model config's vocab_file)")
    parser.add_argument("--stream_tokenizer", default="wordpiece", type=str,
                        choices=["wordpiece", "bpe"],
                        help="tokenizer family for stream mode (native C++ "
                             "encoder used automatically when built)")
    parser.add_argument("--stream_seq_len", default=128, type=int,
                        help="example length in stream mode (records chunk "
                             "into [CLS] + stream_seq_len-2 tokens + [SEP]); "
                             "the offline plane reads this off the shards "
                             "instead")
    parser.add_argument("--stream_workers", default=2, type=int,
                        help="tokenize worker threads; results are consumed "
                             "in submission order so worker count changes "
                             "pacing only, never the batch stream")
    parser.add_argument("--stream_queue_batches", default=4, type=int,
                        help="bounded example-queue depth in batches: full "
                             "queue stalls the tokenize workers (bounded "
                             "RAM), empty queue surfaces as the data_wait "
                             "StepWatch bucket; live depth exported as "
                             "bert_stream_queue_depth")
    parser.add_argument("--tensorboard", type=str, default="on",
                        choices=["on", "off"],
                        help="tensorboard metric sink. 'off' skips the "
                             "torch.utils.tensorboard import (~4s of "
                             "tensorflow/keras pulled in at startup) — "
                             "worth it for short-lived drill/CI sessions "
                             "where startup dominates")
    parser.add_argument("--force_cpu", action="store_true",
                        help="force the CPU backend before jax initializes "
                             "(CI/drill harness; this box's sitecustomize "
                             "registers a remote TPU plugin, so the env "
                             "var alone is not enough — same recipe as "
                             "run_server.py / tests/conftest.py)")
    # resilience / survival kit (bert_pytorch_tpu/resilience/,
    # docs/RESILIENCE.md): preemption-safe checkpointing is always on
    # (SIGTERM -> emergency checkpoint of the last completed step);
    # these flags configure the watchdog and the chaos drills
    parser.add_argument("--watchdog_timeout", type=float, default=0.0,
                        help="hung-step watchdog (resilience/watchdog.py): "
                             "if any host phase (dispatch/readback/h2d/"
                             "checkpoint/data_wait) exceeds this many "
                             "seconds, dump all-thread stacks + a "
                             "flight-recorder bundle and act per "
                             "--watchdog_action. Device-side stalls exit "
                             "72 (device hang), data_wait stalls exit 73 "
                             "(input starvation) — tools/supervise.py "
                             "retries only the latter. 0 = off (default); "
                             "set to several multiples of your worst "
                             "legitimate step/checkpoint time")
    parser.add_argument("--watchdog_action", type=str, default="abort",
                        choices=["abort", "warn"],
                        help="on a watchdog trip: 'abort' hard-exits with "
                             "the distinct code (supervisor-friendly); "
                             "'warn' logs + dumps once per stall and "
                             "keeps waiting (drills, soak runs)")
    parser.add_argument("--chaos", type=str, default=None,
                        choices=["sigkill_at_step", "sigterm_at_step",
                                 "corrupt_newest_ckpt", "stall_dispatch"],
                        help="fault-injection drill (resilience/chaos.py): "
                             "SIGKILL/SIGTERM self before --chaos_step, "
                             "corrupt the newest checkpoint at the first "
                             "save boundary at/after it (then SIGKILL), "
                             "or stall the dispatch phase there. Fires "
                             "only in the first supervised incarnation "
                             "(BERT_SUPERVISOR_RESTARTS==0) so the "
                             "restarted run survives the drill")
    parser.add_argument("--chaos_step", type=int, default=None,
                        help="global step the --chaos fault fires at "
                             "(required with --chaos)")
    parser.add_argument("--chaos_stall_secs", type=float, default=3.0,
                        help="stall length for --chaos stall_dispatch "
                             "(pick > --watchdog_timeout to trip it)")
    parser.add_argument("--slo_config", type=str, default=None,
                        help="SLO spec file (configs/slo.json): evaluate "
                             "the train-phase specs (step-time ceiling, "
                             "checkpoint freshness, non-finite rate) live "
                             "through the burn-rate engine — alerts land "
                             "in the log + /healthz status when "
                             "--metrics_port is on (docs/OBSERVABILITY.md)")
    parser.add_argument("--slo_eval_interval_s", type=float, default=5.0,
                        help="burn-rate engine evaluation period")
    parser.add_argument("--slo_action", type=str, default="log",
                        choices=["log", "halt"],
                        help="on a sustained page-severity train SLO "
                             "breach: 'log' keeps going; 'halt' exits "
                             "with the DISTINCT code EXIT_SLO_BREACH (76) "
                             "— retryable, tools/supervise.py restarts it "
                             "(unlike 71/72 a fresh process often clears "
                             "a stuck input pipeline or straggler)")
    parser.add_argument("--slo_halt_after_s", type=float, default=60.0,
                        help="how long a page alert must stay firing "
                             "before --slo_action=halt pulls the plug")
    parser.add_argument("--stream_inject", default=None, type=str,
                        choices=["slow_producer", "corrupt_record",
                                 "worker_crash"],
                        help="streaming fault drill: slow_producer sleeps "
                             "in the workers (starves the consumer -> "
                             "data_wait), corrupt_record poisons every 7th "
                             "owned record (skipped-and-counted, "
                             "bert_stream_records_dropped_total), "
                             "worker_crash kills a tokenize task once per "
                             "5th record (detected + restarted with its "
                             "cursor intact — the stream stays "
                             "bit-identical)")

    from bert_pytorch_tpu.config import merge_args_with_config

    args = merge_args_with_config(parser, argv)
    validate_stream_args(parser, args, argv)
    if args.chaos and args.chaos_step is None:
        parser.error("--chaos requires --chaos_step (the global step the "
                     "fault fires at)")
    return args


# stream flags that only make sense with --stream_dir; a half-configured
# CLI mix fails at argparse time, not deep inside the loader (satellite:
# CLI validation bugfix)
_STREAM_DEPENDENT_FLAGS = ("stream_vocab", "stream_tokenizer",
                           "stream_seq_len", "stream_workers",
                           "stream_queue_batches", "stream_inject")


def validate_stream_args(parser, args, argv=None) -> None:
    """Argparse-time validation of the stream/offline mode split: the two
    planes' flags must conflict loudly, not fail deep in the loader.

    Explicit-flag detection shares config.explicit_cli_keys with the
    CLI-wins config merge (value-vs-default comparison would miss an
    explicitly-passed default and misreport run-config keys as CLI
    flags). Run-config JSON keys for the OTHER plane are deliberately
    tolerated — a shared config may carry settings for both planes; an
    explicit CLI mode flag overrides the config's plane, and only an
    unresolvable mix (both modes from the same precedence level) errors."""
    from bert_pytorch_tpu.config import explicit_cli_keys

    explicit = None  # computed at most once, only when needed

    def cli(flag: str) -> bool:
        nonlocal explicit
        if explicit is None:
            explicit = explicit_cli_keys(parser, argv)
        return flag in explicit

    if args.stream_dir and args.input_dir:
        # an explicit CLI plane choice beats a config-sourced one (the
        # CLI-wins precedence the config merge already implements)
        if cli("stream_dir") and not cli("input_dir"):
            args.input_dir = None
        elif cli("input_dir") and not cli("stream_dir"):
            args.stream_dir = None
        else:
            parser.error(
                "--stream_dir (streaming plane) and --input_dir (offline "
                "sharded-HDF5 plane) are mutually exclusive — pick one "
                "data plane per run")
    if not args.stream_dir:
        stray = [f for f in _STREAM_DEPENDENT_FLAGS if cli(f)]
        if stray:
            parser.error(
                "--" + " --".join(sorted(stray)) + " require --stream_dir "
                "(they configure the streaming plane; --input_dir reads "
                "offline shards and ignores them)")


def parse_mesh_arg(mesh_arg: str):
    if not mesh_arg:
        return None
    out = {}
    for part in mesh_arg.split(","):
        k, v = part.split("=")
        out[k.strip()] = int(v)
    return out


def find_mask_token_index(args, config) -> int:
    if args.mask_token_index is not None:
        return args.mask_token_index
    # stream_vocab is consulted ONLY in stream mode: an offline run whose
    # shared run-config carries a streaming vocab must keep reading the
    # [MASK] id of the vocab its shards were encoded with
    stream_vocab = (getattr(args, "stream_vocab", None)
                    if getattr(args, "stream_dir", None) else None)
    vocab_file = stream_vocab or getattr(config, "vocab_file", None)
    if vocab_file and os.path.exists(vocab_file):
        from bert_pytorch_tpu.data.tokenization import load_vocab

        vocab = load_vocab(vocab_file)
        if "[MASK]" in vocab:
            return vocab["[MASK]"]
        if "<mask>" in vocab:
            return vocab["<mask>"]
    return 103  # [MASK] in the standard BERT vocab


class NonFiniteHalt(RuntimeError):
    """--nonfinite_action=halt tripped: a non-finite loss/gradient step was
    flagged by the in-graph health pack."""


class SLOBreachHalt(RuntimeError):
    """--slo_action=halt tripped: a page-severity train SLO stayed firing
    past --slo_halt_after_s. Exits EXIT_SLO_BREACH (76) — retryable."""


def make_optimizer(name: str, schedule, norm_reducer=None, fused="off"):
    """The pretraining optimizer zoo, keyed by --optimizer. Module-level so
    tools/replay.py rebuilds the exact same transformation chain from a
    flight-recorder manifest — one construction site, no drift.
    `norm_reducer` (parallel/coalesce.NormReducer, --coalesce_reductions)
    buckets LAMB's trust-norm/global-norm all-reduces; the other
    optimizers have no per-tensor norms to coalesce. `fused` is the
    --fused_optim choice — the multi-tensor update path, LAMB only."""
    from bert_pytorch_tpu.optim import adam
    from bert_pytorch_tpu.optim.lamb import (lamb,
                                             default_weight_decay_mask,
                                             default_trust_batch_axes)

    if name == "lamb":
        return lamb(schedule, weight_decay=0.01,
                    weight_decay_mask=default_weight_decay_mask,
                    trust_batch_axes=default_trust_batch_axes,
                    norm_reducer=norm_reducer,
                    fused=fused != "off",
                    fused_impl="auto" if fused in ("off", "auto") else fused)
    if name == "bert_adam":
        return adam.bert_adam(schedule, weight_decay=0.01,
                              weight_decay_mask=default_weight_decay_mask)
    return adam.fused_adam(schedule)


def main(argv=None):
    args = parse_arguments(argv)
    if not (args.input_dir or args.stream_dir) or not args.output_dir:
        raise SystemExit("--output_dir and one data plane (--input_dir for "
                         "offline shards, --stream_dir for raw-text "
                         "streaming) are required")

    # must land in the env before the first backend touch (libtpu reads
    # LIBTPU_INIT_ARGS once, at initialization)
    overlap_added = []
    if args.overlap_flags == "on":
        from bert_pytorch_tpu.parallel.xla_flags import apply_overlap_flags

        overlap_added = apply_overlap_flags()

    if args.force_cpu:
        os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    if args.force_cpu:
        jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_default_prng_impl", args.rng_impl)
    import jax.numpy as jnp

    from bert_pytorch_tpu.config import BertConfig, pad_vocab_size
    from bert_pytorch_tpu.data.sharded import (
        HostShardSampler, PretrainingDataLoader, ShardIndex)
    from bert_pytorch_tpu.models import BertForPreTraining
    from bert_pytorch_tpu.optim import schedulers
    from bert_pytorch_tpu.parallel import dist, mesh as mesh_lib
    from bert_pytorch_tpu.telemetry import (
        HealthConfig, collect_provenance, flops_per_seq, hbm_snapshot,
        init_run, init_telemetry_state, lookup_peak_flops)
    from bert_pytorch_tpu.telemetry.stepwatch import DEFAULT_PEAK
    from bert_pytorch_tpu.resilience import ChaosMonkey, PreemptionGuard
    from bert_pytorch_tpu.resilience.preemption import (emergency_save,
                                                        is_preemption_exit)
    from bert_pytorch_tpu.resilience.watchdog import arm_watchdog
    from bert_pytorch_tpu.training import (
        CheckpointManager, build_pretrain_step, make_sharded_state)
    from bert_pytorch_tpu.training.pretrain import (StepProgram,
                                                    stack_microbatches,
                                                    chain_steps)

    dist.initialize()
    np.random.seed(args.seed + dist.get_rank())

    mesh = mesh_lib.make_mesh(parse_mesh_arg(args.mesh))
    data_shards = mesh_lib.data_shard_count(mesh)
    n_hosts = dist.get_world_size()

    # accumulation math (reference :208-218): global batch realized as
    # accum_steps microbatches of local_batch per data shard
    micro_global = args.local_batch_size * data_shards
    accum_steps = max(1, math.ceil(args.global_batch_size / micro_global))
    host_step_batch = accum_steps * micro_global // n_hosts

    os.makedirs(args.output_dir, exist_ok=True)
    # ONE telemetry wiring path (telemetry/run.py): logger + compile watch
    # + registry (+ /metrics server and the multi-host perf fold when
    # enabled) come from init_run — the same call run_squad/run_ner/bench
    # make, so every phase emits identically-shaped records
    tel = init_run(
        phase="pretrain",
        log_prefix=os.path.join(args.output_dir, args.log_prefix),
        verbose=dist.is_main_process(),
        tensorboard=(args.tensorboard == "on"), jsonl=True,
        metrics_port=args.metrics_port,
        multihost_dir=(os.path.join(args.output_dir, "metrics_hosts")
                       if n_hosts > 1 else None),
        process_index=dist.get_rank(), process_count=n_hosts)
    logger = tel.logger
    compile_watch = tel.compile_watch
    # every resource created below is released in the finally block, on the
    # success AND exception paths (logger/trace/loader/manager leak fix)
    loader = manager = recorder = None
    crash_flush = None  # bound once the loop-scope pieces exist
    emergency_ckpt = None  # bound once state/manager exist (preemption)
    guard = watchdog = slo_eval = None
    trace_active = False
    try:
        prov = collect_provenance(mesh=mesh)
        tel.log_header(**prov)
        logger.info(f"devices={jax.device_count()} hosts={n_hosts} "
                    f"mesh={dict(mesh.shape)} accumulation_steps={accum_steps} "
                    f"effective_global_batch={accum_steps * micro_global}")
        # -- named mesh config (parallel/rules.py CONFIG_OVERRIDES) ---------
        # 'production' = the round-15 collective-time pack; 'auto' selects
        # it on real accelerators whenever the mesh has a non-trivial
        # parallel axis. Forced-CPU meshes (the test/bench harness) stay
        # on 'base' under auto so harness programs only change when asked
        # — the composition is still measured there by bench.py's
        # dp_seq_packing_overlap variant.
        from bert_pytorch_tpu.parallel import rules as rules_lib

        production = (args.mesh_config == "production"
                      or (args.mesh_config == "auto"
                          and jax.devices()[0].platform != "cpu"
                          and rules_lib.production_qualifies(mesh)))
        mesh_config_name = (rules_lib.PRODUCTION_CONFIG if production
                            else rules_lib.mesh_config(mesh))
        prod_features = {}
        if production:
            prod_features = rules_lib.production_features(mesh)
            if prod_features["packing"] and not args.packing:
                args.packing = True
            if prod_features["zero1"] and args.zero1 == "auto":
                args.zero1 = "true"
            if prod_features["zero1_overlap"] and args.zero1 != "false":
                args.zero1_overlap = True
            if prod_features["fsdp_overlap"]:
                args.fsdp_overlap = True
            logger.info(
                "mesh_config=production: "
                + " ".join(f"{k}={'on' if v else 'off'}"
                           for k, v in sorted(prod_features.items())))

        use_zero1 = (args.zero1 == "true"
                     or (args.zero1 == "auto" and mesh.shape["data"] > 1))
        zero1_overlap = bool(args.zero1_overlap) and use_zero1
        if args.zero1_overlap and not use_zero1:
            logger.info("WARNING: --zero1_overlap ignored (--zero1 is off "
                        "or the data axis is trivial)")
        fsdp_overlap = bool(args.fsdp_overlap) and mesh.shape["fsdp"] > 1
        if args.fsdp_overlap and not fsdp_overlap:
            logger.info("WARNING: --fsdp_overlap ignored (the mesh's fsdp "
                        "axis is trivial)")
        if fsdp_overlap and use_zero1 and not zero1_overlap:
            # the combined plan's post-update pin leaves params in the
            # data-appended shard layout — the resting layout must match,
            # which is exactly what --zero1_overlap constructs
            zero1_overlap = True
            logger.info("--fsdp_overlap with --zero1 forces "
                        "--zero1_overlap (resting layout must match the "
                        "update's output pin)")
        zero1_rs = bool(args.zero1_rs) and use_zero1
        if args.zero1_rs and not use_zero1:
            logger.info("WARNING: --zero1_rs ignored (--zero1 is off or "
                        "the data axis is trivial)")
        if zero1_rs:
            from bert_pytorch_tpu.parallel.zero import rs_supported

            if not rs_supported(mesh):
                # an explicit perf flag on a mesh it cannot serve is a
                # config error, not something to silently fall back from
                raise SystemExit(
                    "--zero1_rs needs a data-only mesh (every non-data "
                    f"axis trivial); got {dict(mesh.shape)} — drop the "
                    "flag or reshape the mesh")
            if not zero1_overlap:
                # the shard_map region consumes replicated params and
                # emits SHARDED grads: the params must rest sharded and
                # gather at point of use, which is the overlap layout
                zero1_overlap = True
                logger.info("--zero1_rs forces --zero1_overlap (the "
                            "scattered grad lands in the shard the "
                            "update owns; params must rest sharded)")
        coalesce = args.coalesce_reductions == "on"
        if zero1_rs and args.kfac and not coalesce:
            # the rs shard_map region emits PARTIAL factor statistics
            # only the bucketed reducer knows how to consume
            coalesce = True
            logger.info("--zero1_rs with --kfac forces "
                        "--coalesce_reductions on (factor statistics "
                        "leave the shard_map region as per-device "
                        "partials; the bucketed psum completes them)")
        if overlap_added:
            logger.info("overlap flag pack applied to LIBTPU_INIT_ARGS: "
                        + " ".join(overlap_added))
        health_cfg = (HealthConfig(action=args.nonfinite_action)
                      if args.health_pack == "on" else None)
        if health_cfg is None and args.nonfinite_action != "log":
            raise SystemExit(
                f"--nonfinite_action={args.nonfinite_action} requires "
                "--health_pack=on")

        # -- model config --------------------------------------------------
        if not args.model_config_file:
            raise SystemExit("--model_config_file (or run config) required")
        config = BertConfig.from_json_file(args.model_config_file)
        config = config.replace(
            vocab_size=pad_vocab_size(config.vocab_size,
                                      args.vocab_pad_multiple),
            dtype=args.dtype,
            checkpoint_activations=args.checkpoint_activations)
        if args.stacked_params != "auto":
            config = config.replace(
                stacked_params=(args.stacked_params == "true"))
        compute_dtype = (jnp.bfloat16 if args.dtype == "bfloat16"
                         else jnp.float32)
        grad_dtype_name = (args.dtype if args.grad_dtype == "auto"
                           else args.grad_dtype)
        grad_dtype = (jnp.bfloat16 if grad_dtype_name == "bfloat16"
                      else None)
        model = BertForPreTraining(config, dtype=compute_dtype)

        # -- optimizer + schedule ------------------------------------------
        schedule = schedulers.make_schedule(
            args.lr_decay, args.learning_rate, args.max_steps,
            warmup=args.warmup_proportion,
            offset=args.previous_phase_end_step)
        tx = make_optimizer(args.optimizer, schedule,
                            fused=args.fused_optim)

        kfac = None
        if args.kfac:
            from bert_pytorch_tpu.optim.kfac import KFAC, KFACConfig

            # K-FAC + activation checkpointing compose: sow/perturb taps
            # under nn.remat re-fire during the recomputed forward, producing
            # factors identical to the un-rematted run (verified bit-exact in
            # tests/test_kfac.py::test_kfac_taps_under_remat); the reference
            # likewise ran both together (run_pretraining.py:257-258,311-345)
            config = config.replace(kfac_taps=True)
            model = BertForPreTraining(config, dtype=compute_dtype)
            # mesh=... -> distributed factor/inverse ownership: each device
            # stores and inverts only its slice of the layer-stacked factors
            # (the reference's HYBRID_OPT work partitioning,
            # run_pretraining.py:325-327); single-device meshes keep the
            # replicated layout (nothing to distribute)
            kfac = KFAC(KFACConfig(
                inv_interval=args.kfac_inv_interval,
                factor_interval=args.kfac_factor_interval,
                stat_decay=args.kfac_stat_decay,
                damping=args.kfac_damping,
                kl_clip=args.kfac_kl_clip,
                skip_layers=tuple(args.kfac_skip_layers),
                learning_rate=schedule,
                # --kfac_stats_dtype bf16: per-microbatch statistics thin
                # on the wire; the EMA/resting factors stay factor_dtype
                stats_dtype=(jnp.bfloat16
                             if args.kfac_stats_dtype == "bf16" else None)),
                mesh=mesh if data_shards > 1 else None,
                # --coalesce_reductions: factor statistics reduce in
                # size-capped buckets (one psum per bucket) instead of
                # one all-reduce per factor; assignment logged below
                factor_bucket_bytes=(int(args.kfac_bucket_mb * 2 ** 20)
                                     if coalesce else None),
                factor_sync_freq=args.kfac_factor_sync_freq)

        # -- dataset --------------------------------------------------------
        mask_id = find_mask_token_index(args, config)
        if args.stream_dir:
            # streaming plane (data/streaming.py, docs/DATA.md): raw text
            # tokenized on the fly; the rest of the loop — prefetch
            # executor, DevicePrefetcher/--h2d_prefetch staging, packing,
            # flight-recorder tap, checkpointed cursor — is byte-for-byte
            # the offline path's, by the shared loader interface
            from bert_pytorch_tpu.data.streaming import (
                StreamingPretrainingLoader, discover_sources,
                resolve_mask_id)
            from bert_pytorch_tpu.data.tokenization import TOKENIZERS

            sources = discover_sources(args.stream_dir)
            if not sources:
                raise SystemExit(f"no .txt corpus under {args.stream_dir}")
            vocab_path = (args.stream_vocab
                          or getattr(config, "vocab_file", None))
            if not vocab_path or not os.path.exists(vocab_path):
                raise SystemExit(
                    "stream mode needs a tokenizer vocab: pass "
                    "--stream_vocab or set vocab_file in the model config")
            tokenizer = TOKENIZERS[args.stream_tokenizer](vocab_path)
            if args.mask_token_index is None:
                # the tokenizer is the authority in stream mode: a BPE
                # .json vocab's <mask> is invisible to the line-based
                # find_mask_token_index lookup
                tokenizer_mask = resolve_mask_id(tokenizer)
                if tokenizer_mask is not None:
                    mask_id = tokenizer_mask
            loader = StreamingPretrainingLoader(
                sources, tokenizer, batch_size=host_step_batch,
                seq_len=args.stream_seq_len,
                mask_token_index=mask_id,
                max_pred_per_seq=args.max_predictions_per_seq,
                masked_lm_prob=args.masked_token_fraction,
                vocab_size=config.vocab_size, seed=args.seed,
                world_size=n_hosts, rank=dist.get_rank(),
                num_workers=args.stream_workers,
                queue_batches=args.stream_queue_batches,
                prefetch_batches=max(0, args.prefetch_batches),
                packing=args.packing,
                packing_max_segments=args.packing_max_segments,
                packing_lookahead=args.packing_lookahead,
                registry=tel.registry, inject=args.stream_inject)
            # /healthz names the plane's live cursor (telemetry/run.py)
            tel.attach_stream(loader)
            logger.info(
                f"dataset: STREAMING {len(sources)} raw-text sources "
                f"(hash {loader.sources_hash}), {args.stream_workers} "
                f"tokenize workers, seq {args.stream_seq_len}, host step "
                f"batch {host_step_batch}; [MASK]={mask_id}"
                + (f"; packing on (<= {args.packing_max_segments} "
                   "segments/row)" if args.packing else "")
                + (f"; FAULT INJECTION: {args.stream_inject}"
                   if args.stream_inject else ""))
        else:
            files = sorted(str(p)
                           for p in Path(args.input_dir).rglob("*.hdf5"))
            if not files:
                raise SystemExit(f"no .hdf5 shards under {args.input_dir}")
            index = ShardIndex(files)
            sampler = HostShardSampler(len(index), world_size=n_hosts,
                                       rank=dist.get_rank(), seed=args.seed)
            loader = PretrainingDataLoader(
                index, sampler, batch_size=host_step_batch,
                mask_token_index=mask_id,
                max_pred_per_seq=args.max_predictions_per_seq,
                masked_lm_prob=args.masked_token_fraction,
                vocab_size=config.vocab_size,
                seed=args.seed + dist.get_rank(),
                prefetch_batches=max(0, args.prefetch_batches),
                packing=args.packing,
                packing_max_segments=args.packing_max_segments,
                packing_lookahead=args.packing_lookahead)
            logger.info(f"dataset: {len(index)} samples in "
                        f"{len(index.files)} shards; host step batch "
                        f"{host_step_batch}; [MASK]={mask_id}"
                        + (f"; packing on (<= {args.packing_max_segments} "
                           "segments/row)" if args.packing else ""))

        # -- state: fresh or auto-resume (reference :236-255) ---------------
        sample = next(iter(loader))
        # peeked one batch for shapes; rewind through the LOADER so any
        # batches the prefetch executor assembled ahead are drained, not
        # replayed stale (pending=() also clears the packer's carry buffer)
        if args.stream_dir:
            loader.load_state_dict(loader.initial_state())
        else:
            loader.load_state_dict(dict(loader.state_dict(), index=0,
                                        pending=()))
        stacked = stack_microbatches(sample, accum_steps)
        seq_len = int(np.asarray(sample["input_ids"]).shape[-1])

        # gathered-MLM-head budget: a packed row pools several examples'
        # masked positions, so the per-ROW cap grows beyond the per-example
        # --max_predictions_per_seq. Each example contributes at most
        # min(max_pred, floor(len * fraction)) + 1 (the masker's >=1 floor),
        # so the row total is bounded by floor(S * fraction) + segments and
        # by segments * max_pred; mlm_dropped warns loudly if reality ever
        # exceeds this.
        max_pred_row = args.max_predictions_per_seq
        if args.packing:
            max_pred_row = min(
                seq_len,
                args.packing_max_segments * args.max_predictions_per_seq,
                int(seq_len * args.masked_token_fraction)
                + args.packing_max_segments)
            logger.info(f"packing: gathered MLM head scores up to "
                        f"{max_pred_row} positions/row "
                        f"(per-example cap {args.max_predictions_per_seq})")

        def init_fn(rng):
            return model.init(rng, jnp.asarray(stacked["input_ids"][0]),
                              jnp.asarray(stacked["token_type_ids"][0]),
                              jnp.asarray(stacked["attention_mask"][0]))

        ckpt_dir = os.path.join(args.output_dir, "pretrain_ckpts")
        manager = CheckpointManager(ckpt_dir,
                                    max_to_keep=args.keep_checkpoints,
                                    registry=tel.registry, log=logger.info)
        # every integrity sidecar carries the provenance stamp (and the
        # program fingerprint once the first dispatch's HLO parse lands)
        manager.manifest_context["provenance"] = prov
        # /healthz gains last_checkpoint_step + seconds_since_checkpoint
        tel.attach_checkpoints(manager)

        # the production config resolves its rule rows through the table's
        # named entry (identical to base today — the name is what carries
        # the feature pack); construction and the sharding_rules gate read
        # the same resolution
        resolved_rules = (rules_lib.resolve(
            mesh, config=rules_lib.PRODUCTION_CONFIG) if production
            else None)
        with mesh_lib.logical_rules():
            state, shardings = make_sharded_state(
                jax.random.PRNGKey(args.seed), init_fn, tx, mesh=mesh,
                rules=resolved_rules,
                zero1=use_zero1, zero1_params=zero1_overlap)

        zero1_plan = None
        if use_zero1:
            from bert_pytorch_tpu.parallel.zero import make_zero1_plan

            zero1_plan = make_zero1_plan(state.params, shardings.params,
                                         mesh, gather_on_use=zero1_overlap,
                                         reduce_scatter=zero1_rs)
            if zero1_plan is None:
                logger.info("zero1: nothing shardable over the data axis; "
                            "running the replicated update")
            else:
                logger.info(f"zero1: LAMB state sharded "
                            f"{mesh.shape['data']}-way over the data axis "
                            + ("(psum_scatter grads -> shard-local update "
                               "-> per-leaf gather-on-use next step "
                               "(--zero1_rs))" if zero1_rs else
                               "(reduce-scatter -> shard-local update -> "
                               + ("per-leaf gather-on-use next step "
                                  "(--zero1_overlap)" if zero1_overlap
                                  else "all-gather)")))
                # the silent-skip bugfix: leaves the derivation left
                # replicated are warned about by make_zero1_plan and
                # counted on the live registry so a layout regression
                # shows on /metrics, not just in a log scrollback
                tel.registry.gauge(
                    "bert_zero1_replicated_leaves",
                    "param leaves the ZeRO-1 spec derivation left on "
                    "their base layout (divisibility fallback)").set(
                        len(zero1_plan.replicated_leaves))

        plan = zero1_plan
        if fsdp_overlap:
            from bert_pytorch_tpu.parallel.zero import make_fsdp_plan

            fplan = make_fsdp_plan(state.params, shardings.params, mesh,
                                   zero1=zero1_plan is not None,
                                   warn_skipped=False)
            if fplan is None:
                logger.info("fsdp_overlap: nothing fsdp-sharded; keeping "
                            "the implicit layout")
            else:
                plan = fplan
                logger.info(
                    f"fsdp_overlap: per-leaf gather-on-use over the "
                    f"{mesh.shape['fsdp']}-way fsdp axis"
                    + (" composed with the zero1 overlap"
                       if zero1_plan is not None else ""))

        norm_reducer = None
        if coalesce and plan is not None:
            from bert_pytorch_tpu.parallel.coalesce import NormReducer

            norm_reducer = NormReducer(plan.grad_shardings, mesh)
            # rebuild the optimizer with the reducer: init semantics are
            # identical (the state above restores/donates unchanged),
            # only the update's norm reductions re-route
            tx = make_optimizer(args.optimizer, schedule,
                                norm_reducer=norm_reducer,
                                fused=args.fused_optim)
            logger.info("coalesce_reductions: trust-norm/global-norm "
                        "all-reduces bucketed (parallel/coalesce.py)")
        elif coalesce and kfac is not None and kfac.bucketed:
            # no sharded param layout to bucket norms over, but the K-FAC
            # factor psums (constructed above with factor_bucket_bytes)
            # ARE bucketed — say exactly that, never "ignored"
            logger.info("coalesce_reductions: K-FAC factor reductions "
                        "bucketed; trust norms stay per-tensor (no "
                        "sharded param layout to bucket)")
        elif coalesce:
            logger.info("WARNING: --coalesce_reductions has nothing to "
                        "bucket (no sharded layout, no bucketed K-FAC "
                        "— single-axis mesh?)")

        if kfac is not None:
            from bert_pytorch_tpu.training import init_kfac_state
            from bert_pytorch_tpu.training.pretrain import \
                build_kfac_pretrain_step

            state, pert_template = init_kfac_state(
                model, kfac, state,
                (stacked["input_ids"][0], stacked["token_type_ids"][0],
                 stacked["attention_mask"][0]))
            # gathered MLM head: score only the <=max_predictions_per_seq
            # masked positions (the loader caps masking there, so the loss
            # is exact)
            step_fn = build_kfac_pretrain_step(
                model, tx, kfac, pert_template, schedule=schedule,
                accum_steps=accum_steps,
                max_predictions=max_pred_row,
                grad_dtype=grad_dtype, zero1=plan, health=health_cfg,
                nan_inject_step=args.inject_nonfinite_step,
                norm_reducer=norm_reducer)
            if kfac.bucket_assignment is not None:
                logger.info("kfac: bucketed factor reductions — "
                            f"{len(kfac.bucket_assignment)} bucket(s): "
                            + json.dumps(kfac.bucket_assignment))
        else:
            step_fn = build_pretrain_step(
                model, tx, schedule=schedule, accum_steps=accum_steps,
                max_predictions=max_pred_row,
                grad_dtype=grad_dtype, zero1=plan, health=health_cfg,
                nan_inject_step=args.inject_nonfinite_step,
                norm_reducer=norm_reducer)
        epoch = 0
        if manager.latest_step() is not None:
            abstract = jax.tree.map(
                lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                               sharding=x.sharding),
                state)
            # tolerant of checkpoints written under the other encoder layout
            # (--stacked_params flipped mid-run): converted bit-exact on
            # restore. A torn/corrupt/digest-mismatched newest checkpoint
            # is quarantined (step_N.corrupt, loud warning naming the
            # failed item) and the walk falls back newest->oldest
            # (resilience/manifest.py) instead of crashing auto-resume
            state, extra, resumed = manager.restore_with_fallback(abstract)
            epoch = extra.get("epoch", 0)
            if "sampler" in extra:
                loader.load_state_dict(extra["sampler"])
            logger.info(f"auto-resumed from step {resumed}")
        elif args.init_checkpoint:
            # seed weights from an external checkpoint (reference ckpt_*.pt /
            # TF release / orbax dir) — optimizer state and step stay fresh;
            # missing/mismatched subtrees keep their fresh init and are
            # reported
            from run_squad import load_pretrained_params

            state = state.replace(params=load_pretrained_params(
                args.init_checkpoint, state.params, log=logger.info))

        if health_cfg is not None:
            # the EMA carry is attached AFTER restore and stripped before
            # every save: checkpoints never contain it, so their structure
            # is identical with the pack on or off (state.py contract)
            state = state.replace(telemetry=init_telemetry_state())

        # StepProgram = jit + explicit first-dispatch lower/compile: same
        # one XLA compile, but the executable's HLO stays reachable for
        # the program fingerprint below (and tools/graphcheck.py gates the
        # same builders' compiled structure in CI)
        jit_step = StepProgram(step_fn)
        steps_per_loop = max(1, args.steps_per_loop)
        jit_chunk = (StepProgram(chain_steps(step_fn, steps_per_loop,
                                             per_step_batch=True))
                     if steps_per_loop > 1 else None)

        # -- double-buffered h2d (round 11) ---------------------------------
        # DevicePrefetcher keeps the next batch's device_put in flight while
        # the current step computes; with --steps_per_loop>1 the whole-chunk
        # put already amortizes across n steps, so prefetch stays off there.
        h2d_depth = max(0, args.h2d_prefetch)
        use_h2d_prefetch = h2d_depth > 0 and steps_per_loop == 1
        if h2d_depth > 0 and not use_h2d_prefetch:
            logger.info("h2d prefetch: off (--steps_per_loop>1 stages whole "
                        "chunks; the per-chunk put already amortizes)")
        elif use_h2d_prefetch:
            logger.info(f"h2d prefetch: depth {h2d_depth} (next batch put to "
                        "device before the current step dispatches)")
        pf_holder = [None]  # the live DevicePrefetcher, per epoch

        def sampler_state():
            """Loader state as of the last batch the STEP LOOP consumed —
            under prefetch the loader itself runs ahead, so checkpoints
            must read the prefetcher's lagged snapshot, not the loader."""
            pf = pf_holder[0]
            return (pf.state_dict() if pf is not None
                    else loader.state_dict())

        target_step = args.previous_phase_end_step + args.max_steps
        session_limit = (int(state.step) + args.steps
                         if args.steps is not None else target_step)
        profile_range = None
        if args.profile_steps:
            lo, hi = args.profile_steps.split(",")
            profile_range = (int(lo), int(hi))

        # -- telemetry: StepWatch / MFU ------------------------------------
        # analytic FLOPs for one optimization step: per-seq fwd+bwd FLOPs
        # (gathered MLM head — only max_predictions positions hit the vocab
        # matmul) times the effective global batch; steps_per_loop is
        # handled by counting n steps per dispatch
        # micro_global spans the mesh-wide data axis, so seqs_per_step (and
        # therefore step_flops) is already GLOBAL across hosts — it pairs
        # with the global peak (peak_per_device * device_count) for MFU
        seqs_per_step = accum_steps * micro_global
        step_flops = flops_per_seq(
            config, seq_len, config.vocab_size,
            max_pred_row) * seqs_per_step
        peak = lookup_peak_flops(jax.devices()[0].device_kind,
                                 dtype=config.dtype)
        if peak is None:
            # unknown hardware (CPU backend): report MFU against the
            # DEFAULT_PEAK reference chip, same convention as bench.py;
            # the 'perf' record carries peak_flops so it is self-describing
            peak = DEFAULT_PEAK
        sw = tel.make_stepwatch(flops_per_step=step_flops,
                                seqs_per_step=seqs_per_step,
                                seq_len=seq_len,
                                peak_flops=peak * jax.device_count(),
                                log_freq=args.log_freq,
                                n_devices=jax.device_count())
        logger.info(
            f"telemetry: {step_flops / 1e9:.2f} GFLOP/step global, "
            f"peak {peak / 1e12:.0f} TFLOP/s/device, health_pack="
            f"{args.health_pack} nonfinite_action={args.nonfinite_action} "
            f"log_freq={args.log_freq}")

        # -- flight recorder: the black box ---------------------------------
        # captures loader output at the yield boundary (batch_tap), binds
        # batches to step ids + dispatch RNG below, and dumps a repro
        # bundle next to the checkpoints on a flagged step or crash. All
        # host-side references — no copies, no added device sync.
        recorder = None
        if args.flight_recorder == "on":
            from bert_pytorch_tpu.telemetry import FlightRecorder
            from bert_pytorch_tpu.telemetry.flight_recorder import \
                per_host_dir

            kfac_info = None
            if args.kfac:
                kfac_info = {
                    "inv_interval": args.kfac_inv_interval,
                    "factor_interval": args.kfac_factor_interval,
                    "stat_decay": args.kfac_stat_decay,
                    "damping": args.kfac_damping,
                    "kl_clip": args.kfac_kl_clip,
                    "skip_layers": list(args.kfac_skip_layers),
                    "factor_bucket_bytes": kfac.factor_bucket_bytes
                    if coalesce else None,
                    "factor_sync_freq": args.kfac_factor_sync_freq,
                    "bucket_assignment": kfac.bucket_assignment,
                    "stats_dtype": args.kfac_stats_dtype,
                }
            # the metric readback lags one dispatch: by the time a flagged
            # step is seen, the NEXT dispatch's record_dispatch has already
            # run its eviction. The flagged chunk survives it only if the
            # ring holds two full dispatches — clamp, or the flagship
            # nonfinite bundle could not replay its own trigger step.
            window = max(args.recorder_window, 2 * steps_per_loop)
            if window > args.recorder_window:
                logger.info(
                    f"flight recorder: window raised {args.recorder_window}"
                    f" -> {window} (2x --steps_per_loop: the one-dispatch "
                    "metric lag must not evict the flagged chunk)")
            recorder = FlightRecorder(
                per_host_dir(os.path.join(args.output_dir, "repro_bundles")),
                window=window,
                run_info={
                    "accum_steps": accum_steps,
                    "steps_per_loop": steps_per_loop,
                    "seed": args.seed,
                    "max_pred_row": max_pred_row,
                    "grad_dtype": grad_dtype_name,
                    "optimizer": args.optimizer,
                    "learning_rate": args.learning_rate,
                    "lr_decay": args.lr_decay,
                    "warmup_proportion": args.warmup_proportion,
                    "max_steps": args.max_steps,
                    "previous_phase_end_step": args.previous_phase_end_step,
                    "rng_impl": args.rng_impl,
                    "health_pack": args.health_pack,
                    "nonfinite_action": args.nonfinite_action,
                    "zero1": zero1_plan is not None,
                    "zero1_overlap": (zero1_plan is not None
                                      and zero1_plan.gather_on_use),
                    "zero1_rs": (zero1_plan is not None
                                 and zero1_plan.reduce_scatter),
                    "fused_optim": args.fused_optim,
                    "fsdp_overlap": (plan is not None
                                     and plan.axis == "fsdp"),
                    "mesh_config": mesh_config_name,
                    # the FLAG, not the reducer: replay re-derives the
                    # reducer under the same `and plan is not None`
                    # condition, and K-FAC-only bucketing (kfac_info's
                    # factor_bucket_bytes) must not be recorded as off
                    "coalesce_reductions": coalesce,
                    "kfac": kfac_info,
                    "mesh": {k: int(v) for k, v in dict(mesh.shape).items()},
                    "seq_len": seq_len,
                    "local_batch_size": args.local_batch_size,
                    "global_batch_size": args.global_batch_size,
                    "packing": args.packing,
                    "packing_max_segments": args.packing_max_segments,
                    "inject_nonfinite_step": args.inject_nonfinite_step,
                    "stream": bool(args.stream_dir),
                },
                model_config=config.to_dict(),
                checkpoint_dir=ckpt_dir,
                provenance=collect_provenance(mesh=mesh),
                checkpoint_step_fn=manager.latest_step)
            # bundle manifests carry the registry snapshot at dump time
            # and the jsonl path the metrics tail mirrors
            tel.attach_recorder(recorder)
            if args.stream_dir:
                # streaming bundles additionally carry the source list +
                # cursor + recent batch->record windows (manifest schema-v2
                # optional key), so replay names the exact records involved
                recorder.stream_info_fn = loader.stream_info
            if not use_h2d_prefetch:
                # under prefetch the loader yields AHEAD of dispatch; the
                # tap moves to the prefetcher (set at construction below)
                # so the ring still sees batches in dispatch order
                loader.batch_tap = recorder.capture_batch
            recorder.install_crash_handlers()
            recorder.arm()
            logger.info(f"flight recorder: on, window={window} steps, "
                        f"bundles under {recorder.out_dir}")

        # -- survival kit (bert_pytorch_tpu/resilience/, docs/RESILIENCE.md)
        # Preemption guard: layered AFTER the recorder's handlers, so one
        # SIGTERM walks guard -> recorder -> SystemExit(143) and the
        # except-path below lands BOTH the crash bundle and the emergency
        # checkpoint of the last completed step.
        guard = PreemptionGuard(registry=tel.registry, log=logger.info)
        guard.install()
        watchdog = arm_watchdog(
            args.watchdog_timeout, args.watchdog_action, sw,
            registry=tel.registry, log=logger.info,
            out_dir=args.output_dir, recorder=recorder)
        chaos = None
        if args.chaos:
            chaos = ChaosMonkey(args.chaos, args.chaos_step,
                                stall_secs=args.chaos_stall_secs,
                                log=logger.info)
            if chaos.mode:
                logger.info(f"CHAOS armed: {chaos.mode} at step "
                            f"{chaos.at_step}")

        # SLO plane (telemetry/slo.py, docs/OBSERVABILITY.md): the SAME
        # burn-rate engine the server runs, here over the train-phase
        # specs — step-time ceiling, checkpoint freshness, non-finite
        # rate — reading the registry this loop already feeds
        slo_engine = None
        if getattr(args, "slo_config", None):
            from bert_pytorch_tpu.telemetry.slo import (SLOEngine,
                                                        SLOEvaluator,
                                                        load_slo_config)

            slo_cfg = load_slo_config(args.slo_config)
            slo_engine = SLOEngine(slo_cfg.specs_for("train"),
                                   slo_cfg.windows, tel.registry,
                                   phase="train", log=logger.info)

            def _checkpoint_age_s():
                _, landed = manager.freshness()
                if landed is None:
                    return None  # nothing saved or restored yet: no sample
                return max(0.0, time.time() - float(landed))

            slo_engine.set_source("checkpoint_age_s", _checkpoint_age_s)
            tel.attach_slo(slo_engine)
            slo_eval = SLOEvaluator(
                slo_engine,
                interval_s=args.slo_eval_interval_s).start()
            logger.info(
                f"slo: {len(slo_cfg.specs_for('train'))} train spec(s) "
                f"from {args.slo_config}, action={args.slo_action}"
                + (f" (halt after {args.slo_halt_after_s:g}s of "
                   "page-severity firing)" if args.slo_action == "halt"
                   else ""))

        # -- train loop (reference :482-549) --------------------------------
        # The host never blocks on the step it just dispatched: metrics for
        # step N are pulled to floats only after step N+1 is in flight, so
        # input prep (dynamic masking, H2D) overlaps device compute.
        train_start = time.time()
        global_step = start_step = int(state.step)
        loss_sum, loss_n = 0.0, 0
        # per-dispatch PRNG: fold_in(base, first_step) rather than a
        # sequential split chain, so dropout keys are a pure function of
        # the global step — a preempted run resumed from ANY checkpoint
        # derives the identical keys an uninterrupted run would, which is
        # what makes the survival drill's bit-identity hold with dropout
        # on (the sequential chain restarted from split #1 on resume)
        rng_base = jax.random.PRNGKey(args.seed + 1000 + dist.get_rank())
        done = False
        pending = None  # (step, epoch, metrics) awaiting logging
        warned_dropped = False
        halt_pending = None  # message; raised after cleanup-safe point
        dispatches = 0  # jit calls made; gates compile-warmup closure
        fp_holder = [None]  # program fingerprint, filled by a worker thread
        fp_logged = [False]
        fp_thread = [None]

        def maybe_log_fingerprint():
            """Main-thread consumer of the fingerprint worker: append the
            header extension once the parse has landed. Idempotent."""
            fp = fp_holder[0]
            if fp is None or fp_logged[0]:
                return
            fp_logged[0] = True
            tel.log_header(
                **prov,
                program_fingerprint=fp["hash"],
                program_collectives=" ".join(
                    f"{k}={v}" for k, v in sorted(
                        fp["collective_counts"].items())))

        def flush_pending():
            nonlocal pending, loss_sum, loss_n, warned_dropped, halt_pending
            if pending is None:
                return
            step_i, epoch_i, m = pending
            pending = None
            with sw.phase("metric_flush"), \
                    jax.profiler.TraceAnnotation("host/metric_flush"):
                vals = {k: float(v) for k, v in m.items()}
            if recorder is not None:
                # metrics tail rides in the bundle: the black box records
                # what tripped, not just the inputs
                recorder.note_metrics(step_i, vals)
            loss = vals.pop("loss")
            bad = (vals.get("loss_nonfinite", 0) > 0
                   or vals.get("grad_nonfinite", 0) > 0)
            if math.isfinite(loss) and not bad:
                loss_sum += loss
                loss_n += 1
            if vals.get("mlm_dropped", 0) > 0 and not warned_dropped:
                warned_dropped = True
                logger.info(
                    f"WARNING: step {step_i}: "
                    f"{int(vals['mlm_dropped'])} masked positions beyond "
                    "--max_predictions_per_seq lost supervision — the data "
                    "pipeline and step config disagree (raise "
                    "--max_predictions_per_seq or lower "
                    "--masked_token_fraction)")
            if bad:
                groups = ", ".join(
                    f"{k.removeprefix('grad_nonfinite_')}="
                    f"{int(v)}" for k, v in sorted(vals.items())
                    if k.startswith("grad_nonfinite_") and v > 0)
                handled = {"log": "training on (--nonfinite_action=log)",
                           "skip": "update was skipped in-graph",
                           "halt": "halting"}[args.nonfinite_action]
                logger.info(
                    f"WARNING: step {step_i}: NON-FINITE "
                    f"loss/gradients (step_loss={loss}, "
                    f"nonfinite grads: {groups or 'none'}) — {handled}")
            elif vals.get("grad_spike", 0) > 0:
                logger.info(
                    f"WARNING: step {step_i}: gradient-norm spike "
                    f"(z={vals.get('grad_norm_z', 0):.1f}, "
                    f"norm={vals.get('grad_norm', 0):.3g} vs EMA "
                    f"{vals.get('grad_norm_ema', 0):.3g})")
            tel.log_train(step_i, epoch=epoch_i,
                          average_loss=loss_sum / max(loss_n, 1),
                          step_loss=loss, **vals)
            bundle = None
            if bad and recorder is not None:
                # dump for EVERY action: even log/skip runs want the
                # offline repro of what the health pack just flagged
                bundle = recorder.dump("nonfinite", trigger_step=step_i)
                logger.info(
                    f"flight recorder: repro bundle for step {step_i} "
                    f"dumped to {bundle} (replay: python tools/replay.py "
                    f"--bundle {bundle} --bisect)")
            if bad and args.nonfinite_action == "halt":
                halt_pending = (
                    f"non-finite loss/gradients at step {step_i} and "
                    "--nonfinite_action=halt; last checkpoint is the "
                    "restart point"
                    + (f"; repro bundle: {bundle}" if bundle else ""))

        def crash_flush_impl(exc):
            """Crash-safe exit (satellite): whatever kills the run —
            SIGTERM/SIGINT (mapped to SystemExit by the recorder's
            handler), an exception, a NonFiniteHalt — the buffered
            metrics (pending readback + StepWatch partial interval) land
            in the sinks and the flight recorder dumps its bundle BEFORE
            the stack unwinds. bench.py has guaranteed this for its JSON
            since round 7; the training loop now matches."""
            try:
                flush_pending()
            except Exception:
                pass
            try:
                rec = sw.flush()
                if rec is not None:
                    tel.log_perf(global_step, rec)
            except Exception:
                pass
            if recorder is not None and recorder.last_dump is None:
                try:
                    path = recorder.dump(type(exc).__name__.lower(),
                                         trigger_step=global_step)
                    logger.info(f"flight recorder: crash bundle dumped "
                                f"to {path}")
                except Exception:
                    pass

        crash_flush = crash_flush_impl
        emergency_done = [False]
        # (step, sampler snapshot, epoch) captured right after each
        # dispatch — the SAME program point the periodic save reads, so
        # an emergency save is label-coherent: a preemption signal can
        # land between the loader yielding step N+1's batch and its
        # dispatch, where the LIVE sampler state already covers a batch
        # the params never consumed (resume from such a pair would skip
        # one batch and silently fork the run)
        sampler_coherent = [None]

        def emergency_ckpt_impl(exc):
            """Preemption-safe checkpointing (resilience/preemption.py):
            when the unwind was caused by a preemption notice, one final
            SYNCHRONOUS save + wait of the last completed step — a
            preempted run loses zero completed steps. One-shot (the
            atexit backstop and double signals cannot double-save), and
            never past a halt-flagged step (the last checkpoint must
            stay the restart point, not the post-blowup params)."""
            if emergency_done[0] or args.skip_checkpoint or halt_pending:
                return
            preempted = (guard is not None
                         and guard.preempted_signal is not None) \
                or is_preemption_exit(exc)
            if not preempted:
                return
            emergency_done[0] = True
            try:
                step = int(state.step)  # the device's truth, not the
                # host counter — a signal between dispatch and the
                # host-side increment must not mislabel the save
                snap = sampler_coherent[0]
                if snap is None:
                    logger.info(
                        "preemption: no step completed this session — "
                        "nothing to emergency-checkpoint")
                    return
                if snap[0] == step:
                    sampler_snap, epoch_snap = snap[1], snap[2]
                else:
                    # signal landed in the dispatch->snapshot gap: no
                    # new yield has happened yet, so the LIVE state is
                    # coherent with the just-advanced params
                    sampler_snap, epoch_snap = sampler_state(), epoch
                emergency_save(manager, step,
                               state.replace(telemetry=None),
                               extra={"sampler": sampler_snap,
                                      "epoch": epoch_snap},
                               log=logger.info)
            except Exception as e:
                logger.info(f"WARNING: emergency checkpoint failed: {e} "
                            "(the last periodic checkpoint is the "
                            "restart point)")

        emergency_ckpt = emergency_ckpt_impl

        def timed_batches():
            """Yields (numpy_batch, device_batch_or_None) pairs. With h2d
            prefetch the pair's device half was put while the PREVIOUS step
            computed (DevicePrefetcher); without it the loop does the
            stack+put itself and the device half is None."""
            if use_h2d_prefetch:
                from bert_pytorch_tpu.data.sharded import DevicePrefetcher

                def waited():
                    it = iter(loader)
                    while True:
                        with sw.phase("data_wait"), \
                                jax.profiler.TraceAnnotation(
                                    "host/data_wait"):
                            try:
                                b = next(it)
                            except StopIteration:
                                return
                        yield b

                def put_fn(b):
                    with sw.phase("data_prep"), \
                            jax.profiler.TraceAnnotation("host/data_prep"):
                        st = stack_microbatches(b, accum_steps)
                    with sw.phase("h2d"), \
                            jax.profiler.TraceAnnotation("host/h2d"):
                        return mesh_lib.host_to_device_batch(mesh, st)

                pf = DevicePrefetcher(
                    waited(), put_fn, depth=h2d_depth,
                    state_fn=loader.state_dict,
                    batch_tap=(recorder.capture_batch
                               if recorder is not None else None))
                pf_holder[0] = pf
                yield from pf
            else:
                it = iter(loader)
                while True:
                    with sw.phase("data_wait"), \
                            jax.profiler.TraceAnnotation("host/data_wait"):
                        try:
                            batch = next(it)
                        except StopIteration:
                            return
                    yield batch, None

        # logical_rules must be active while the step traces (first jit_step
        # call), or every nn.with_logical_constraint inside the model
        # becomes a silent no-op and SPMD layout falls back to pure
        # propagation
        chunk_buf = []  # steps_per_loop>1: host-side batch staging

        with mesh, mesh_lib.logical_rules():
            while not done:
                for batch_np, dev_batch in timed_batches():
                    if global_step >= min(target_step, session_limit):
                        done = True
                        break
                    if halt_pending:
                        raise NonFiniteHalt(halt_pending)
                    if slo_engine is not None and args.slo_action == "halt":
                        since = slo_engine.page_firing_since()
                        if (since is not None and
                                time.time() - since >= args.slo_halt_after_s):
                            firing = sorted({a["slo"] for a in
                                             slo_engine.alerts_view()["firing"]})
                            raise SLOBreachHalt(
                                f"train SLO breach: page alert(s) {firing} "
                                f"firing for "
                                f"{time.time() - since:.0f}s (>= "
                                f"--slo_halt_after_s "
                                f"{args.slo_halt_after_s:g}) at step "
                                f"{global_step} — exiting "
                                "EXIT_SLO_BREACH(76) for the supervisor "
                                "to restart")
                    if chaos is not None:
                        chaos.before_dispatch(global_step + 1)
                    if (profile_range and not trace_active
                            and profile_range[0] <= global_step
                            < profile_range[1]):
                        jax.profiler.start_trace(
                            os.path.join(args.output_dir, "traces"))
                        trace_active = True
                    with sw.phase("data_prep"), \
                            jax.profiler.TraceAnnotation("host/data_prep"):
                        if dev_batch is None:
                            stacked = stack_microbatches(batch_np,
                                                         accum_steps)
                        # real (non-pad) tokens this host feeds the step;
                        # every host feeds the same count in expectation, so
                        # x n_hosts matches the global seqs_per_step basis
                        sw.note_tokens(
                            float(np.asarray(batch_np["attention_mask"])
                                  .sum()) * n_hosts)
                    remaining = min(target_step, session_limit) - global_step
                    if steps_per_loop > 1 and remaining >= steps_per_loop:
                        # stage until a full device-side loop's worth is ready
                        chunk_buf.append(stacked)
                        if len(chunk_buf) < steps_per_loop:
                            continue
                        with sw.phase("data_prep"), \
                                jax.profiler.TraceAnnotation("host/data_prep"):
                            chunk = {k: np.stack([b[k] for b in chunk_buf])
                                     for k in chunk_buf[0]}
                            chunk_buf = []
                        with sw.phase("h2d"), \
                                jax.profiler.TraceAnnotation("host/h2d"):
                            batch = mesh_lib.host_to_device_batch(
                                mesh, chunk, n_leading=2)
                        step_rng = jax.random.fold_in(rng_base,
                                                      global_step + 1)
                        with sw.phase("dispatch"), \
                                jax.profiler.TraceAnnotation("host/dispatch"):
                            if chaos is not None:
                                chaos.stall(global_step + 1)
                            state, metrics = jit_chunk(state, batch, step_rng)
                        stepped = steps_per_loop
                    else:
                        if dev_batch is not None:
                            batch = dev_batch  # put while the last step ran
                        else:
                            with sw.phase("h2d"), \
                                    jax.profiler.TraceAnnotation("host/h2d"):
                                batch = mesh_lib.host_to_device_batch(
                                    mesh, stacked)
                        step_rng = jax.random.fold_in(rng_base,
                                                      global_step + 1)
                        with sw.phase("dispatch"), \
                                jax.profiler.TraceAnnotation("host/dispatch"):
                            if chaos is not None:
                                chaos.stall(global_step + 1)
                            state, metrics = jit_step(state, batch, step_rng)
                        stepped = 1
                    if recorder is not None:
                        # bind the staged loader batches to the steps this
                        # dispatch performs + the dispatch PRNG key
                        recorder.record_dispatch(global_step + 1, stepped,
                                                 np.asarray(step_rng))
                    global_step += stepped
                    sampler_coherent[0] = (global_step, sampler_state(),
                                           epoch)
                    dispatches += 1
                    if dispatches == 1:
                        # program fingerprint (collective counts + donation
                        # hash) of whichever program the first dispatch
                        # AOT-compiled: stamped into every flight-recorder
                        # bundle and re-logged as a header extension so
                        # tools/replay.py can warn when a replay's program
                        # structure diverges from the recorded run's. The
                        # HLO text render + parse runs on a worker thread —
                        # at BERT-Large scale the optimized HLO is tens of
                        # MB and must not stall dispatch 2; the header is
                        # logged from THIS thread once the result lands
                        # (MetricLogger is not thread-safe).
                        import threading

                        def _fingerprint_worker():
                            for prog, n in ((jit_chunk, steps_per_loop),
                                            (jit_step, 1)):
                                f = (prog.fingerprint()
                                     if prog is not None else None)
                                if f is not None:
                                    fp = dict(f, steps_per_loop=n)
                                    if recorder is not None:
                                        recorder.program_fingerprint = fp
                                    # later checkpoints' integrity
                                    # sidecars carry it too
                                    manager.manifest_context[
                                        "program_fingerprint"] = fp
                                    fp_holder[0] = fp
                                    return

                        fp_thread[0] = threading.Thread(
                            target=_fingerprint_worker,
                            name="program-fingerprint", daemon=True)
                        fp_thread[0].start()
                    maybe_log_fingerprint()
                    flush_pending()
                    pending = (global_step, epoch, metrics)
                    perf = sw.step_done(stepped)
                    if perf is not None:
                        # warmup closes at the first interval with >=3
                        # dispatches behind it: jit legitimately compiles
                        # twice (first call sees uncommitted input
                        # shardings, the donated output commits them), so
                        # only a compile past dispatch 3 is a true mid-run
                        # recompile worth a loud warning
                        if dispatches >= 3:
                            compile_watch.mark_steady()
                        perf.update(compile_watch.snapshot())
                        perf.update(hbm_snapshot())
                        tel.log_perf(global_step, perf)
                    if trace_active and global_step >= profile_range[1]:
                        jax.profiler.stop_trace()
                        trace_active = False
                    if (not args.skip_checkpoint
                            and global_step % args.num_steps_per_checkpoint
                            < (steps_per_loop if remaining >= steps_per_loop
                               else 1)):
                        flush_pending()
                        if halt_pending:
                            # never checkpoint past a halt-flagged step: the
                            # LAST saved state must stay the restart point,
                            # not the post-blowup params
                            raise NonFiniteHalt(halt_pending)
                        with sw.phase("checkpoint"):
                            # loader.state_dict lags to the last YIELDED
                            # batch, so a resume replays nothing even with
                            # prefetch running ahead; telemetry EMAs are
                            # ephemeral — stripped so checkpoint structure
                            # never depends on the health pack
                            manager.save(
                                global_step, state.replace(telemetry=None),
                                extra={"sampler": sampler_state(),
                                       "epoch": epoch})
                        if chaos is not None:
                            chaos.after_checkpoint(manager, global_step)
                else:
                    loader.reset_epoch()
                    pf_holder[0] = None  # next epoch builds a fresh one
                    epoch += 1

        flush_pending()
        if fp_thread[0] is not None:
            # short runs can finish before the fingerprint parse does;
            # give it a moment so the header extension still lands (the
            # thread is daemonic — a stuck parse never blocks shutdown)
            fp_thread[0].join(timeout=10.0)
            maybe_log_fingerprint()
        if halt_pending:
            raise NonFiniteHalt(halt_pending)
        if trace_active:
            jax.profiler.stop_trace()
            trace_active = False
        train_time = time.time() - train_start
        steps_done = global_step - start_step
        if not args.skip_checkpoint and steps_done:
            manager.save(global_step, state.replace(telemetry=None),
                         extra={"sampler": sampler_state(),
                                "epoch": epoch})
        manager.wait()
        if steps_done:
            # end-of-run throughput line (reference :574-580) — uses the
            # *effective* global batch actually trained per step
            seq_per_sec = accum_steps * micro_global * steps_done / train_time
            logger.info(f"training_seq_per_sec = {seq_per_sec:.2f} "
                        f"({steps_done} steps in {train_time:.1f}s)")
            logger.info(f"compiles: {compile_watch.snapshot()}")
        if recorder is not None:
            recorder.disarm()  # clean exit: the atexit backstop stands down
        return int(state.step), train_time
    except BaseException as exc:
        # crash-safe flush (satellite): buffered metrics + black box land
        # before the unwind; crash_flush is None only if the failure
        # happened before the loop-scope pieces existed (nothing buffered)
        if crash_flush is not None:
            crash_flush(exc)
        # preemption-safe checkpointing: the emergency save runs AFTER
        # the bundle dump (the black box must land even if the save
        # fails) and only on the preemption-signal unwind path
        if emergency_ckpt is not None:
            emergency_ckpt(exc)
        raise
    finally:
        # error-path resource cleanup (satellite: logger/trace leak fix) —
        # each close guarded so one failing teardown can't mask the others
        # or the original exception
        if trace_active:
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
        # tel.close() releases the /metrics server, compile-watch listener,
        # multi-host aggregator, and every logger sink. Order matters for
        # the signal chain: guard.close() restores the recorder's handler,
        # recorder.close() then restores the original — closing the
        # recorder first would let guard re-install a dead layer
        for closeable in (slo_eval, watchdog, guard, recorder, tel, loader,
                          manager):
            if closeable is not None:
                try:
                    closeable.close()
                except Exception:
                    pass


def _cli(argv=None) -> int:
    """Script entry: a NonFiniteHalt exits with the DISTINCT code
    EXIT_NONFINITE_HALT (71) and a one-line FATAL (carrying the
    repro-bundle path) instead of a raw traceback — the operator AND
    supervisor contract for --nonfinite_action=halt (tools/supervise.py
    refuses to retry 71: restarting replays the same deterministic
    blowup). An SLOBreachHalt (--slo_action=halt) exits EXIT_SLO_BREACH
    (76) — restart-worthy, the supervisor retries it. Everything else
    propagates (tracebacks for real bugs, 128+sig for signals).
    Exit-code contract: docs/RESILIENCE.md."""
    from bert_pytorch_tpu.resilience import (EXIT_NONFINITE_HALT,
                                             EXIT_SLO_BREACH)

    try:
        main(argv)
    except NonFiniteHalt as e:
        print(f"FATAL: {e}", file=sys.stderr)
        return EXIT_NONFINITE_HALT
    except SLOBreachHalt as e:
        print(f"FATAL: {e}", file=sys.stderr)
        return EXIT_SLO_BREACH
    return 0


if __name__ == "__main__":
    sys.exit(_cli())
