#!/usr/bin/env python
"""Benchmark: BERT-Large MLM pretraining throughput on one chip, at both
phase-1 (seq 128) and phase-2 (seq 512) recipes.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "seq/s/chip", "vs_baseline": N,
   "seq512_value": N, "seq512_mfu": N, ...}

The reference publishes no measured numbers (README Performance section is
empty; BASELINE.md), so vs_baseline is reported against the north-star
contract in BASELINE.json: >=50% MFU. vs_baseline = achieved_MFU / 0.50 —
1.0 means the 50% target is met exactly; >1.0 beats it. The headline value
is the phase-1 (seq128) number; the phase-2 (seq512,
max_predictions_per_seq=80, reference phase2 config:3-10) result rides along
in the same line as seq512_*.

Methodology matches the reference's training_seq_per_sec (global_batch x
steps / train_time, run_pretraining.py:578-580) measured over the full jitted
train step (fwd + bwd + LAMB update), steady-state after warmup. Each
candidate runs in a fresh subprocess so an OOM attempt cannot poison the next
one's device heap; sync is via a scalar fetch because block_until_ready does
not flush the remote-relay pipeline.

Harness contract (round-5): the sweep ALWAYS lands a parsed JSON line.
Candidates are ordered best-known-first, a wall-clock budget
(BENCH_BUDGET_S, default 2100 s) gates every child launch, and SIGTERM /
SIGALRM handlers flush the final JSON from whatever has been measured so
far — a truncated sweep still reports its best. (Round 4 lost its headline
to an external timeout that arrived mid-grid, BENCH_r04.json rc=124.)
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np

# FLOPs model + peak table live in telemetry/stepwatch.py — ONE source of
# truth shared with run_pretraining's live MFU, so the bench headline and
# the training-time number can never drift apart.
from bert_pytorch_tpu.telemetry.stepwatch import (  # noqa: E402,F401
    DEFAULT_PEAK, PEAK_FLOPS, flops_per_seq, lookup_peak_flops)

# Phase recipes (reference config/bert_pretraining_phase{1,2}_config.json).
PHASES = {
    128: {"max_pred": 20, "lr": 6e-3, "total_steps": 7038, "warmup": 0.2843},
    512: {"max_pred": 80, "lr": 4e-3, "total_steps": 1563, "warmup": 0.128},
}
MASK_FRACTION = 0.15  # reference masked_token_fraction, shared by children


def _bench_base_config(seq_len: int, on_tpu: bool):
    """Child-process setup shared by the grid candidates and the packing
    pair: BERT-Large config (CPU-smoke shrink applied), padded vocab, the
    phase recipe, and the BENCH_RNG PRNG selection. Keeping this in ONE
    place is what makes the packing-pair numbers comparable with the grid
    numbers in the same JSON."""
    import jax

    from bert_pytorch_tpu.config import BertConfig, pad_vocab_size

    phase = PHASES[seq_len] if seq_len in PHASES else PHASES[128]
    max_pred = phase["max_pred"]
    here = os.path.dirname(os.path.abspath(__file__))
    cfg = BertConfig.from_json_file(
        os.path.join(here, "configs/bert_large_uncased_config.json"))
    if not on_tpu:  # CPU smoke fallback: shrink so the line still prints
        cfg = cfg.replace(num_hidden_layers=2, hidden_size=256,
                          intermediate_size=1024, num_attention_heads=4)
        max_pred = min(max_pred, 20)
    cfg = cfg.replace(vocab_size=pad_vocab_size(cfg.vocab_size, 128))
    # threefry2x32 = run_pretraining's default: the headline must measure
    # the configuration a user actually gets. rbg was a measured ~10%
    # step-time win on v5e pre-r5 (threefry bit generation dominated
    # nn.Dropout); with counter-hash dropout everywhere the PRNG only
    # draws one 32-bit seed per dropout site per step, so the gap is gone
    # and production keeps threefry's cross-version bit-stream stability.
    # BENCH_RNG=rbg reproduces the old opt-in measurement.
    jax.config.update("jax_default_prng_impl",
                      os.environ.get("BENCH_RNG", "threefry2x32"))
    return cfg, phase, max_pred


def _bench_lamb(phase: dict):
    """The phase-recipe schedule + LAMB pair every bench child measures."""
    from bert_pytorch_tpu.optim import schedulers
    from bert_pytorch_tpu.optim.lamb import (lamb, default_weight_decay_mask,
                                             default_trust_batch_axes)

    sched = schedulers.poly_warmup_schedule(
        phase["lr"], total_steps=phase["total_steps"],
        warmup=phase["warmup"])
    tx = lamb(sched, weight_decay=0.01,
              weight_decay_mask=default_weight_decay_mask,
              trust_batch_axes=default_trust_batch_axes)
    return sched, tx


def run_candidate(batch: int, seq_len: int, steps: int, on_tpu: bool,
                  attn: str, remat: str, unroll: int,
                  accum: int = 1, stacked: bool = True) -> dict:
    """Measure one config; called in the child process. `remat` is a
    checkpoint-policy name ("dots", "mlp_only", "nothing") or "none" for an
    un-rematted stack. `stacked` is the encoder parameter layout
    (config.stacked_params): False kills the scan-backward wgrad
    dynamic-update-slice writes (per-layer param leaves, always fully
    unrolled)."""
    # overlap flag pack (parallel/xla_flags.py) before the backend comes up:
    # single-chip it is inert (no collectives to schedule), but the headline
    # must measure the same runtime configuration run_pretraining ships.
    # BENCH_OVERLAP=0 opts out for A/B.
    if os.environ.get("BENCH_OVERLAP", "1") == "1":
        from bert_pytorch_tpu.parallel.xla_flags import apply_overlap_flags

        apply_overlap_flags()
    import jax
    import jax.numpy as jnp

    from bert_pytorch_tpu.models import BertForPreTraining
    from bert_pytorch_tpu.telemetry.run import init_run
    from bert_pytorch_tpu.training import build_pretrain_step, make_sharded_state
    from bert_pytorch_tpu.training.pretrain import stack_microbatches

    # compile accounting rides into the result record: a candidate whose
    # measured window recompiled is NOT a steady-state number. Wired
    # through the same init_run path as the entry points (verbose=False:
    # the child's stdout belongs to its JSON result protocol)
    tel = init_run(phase="bench", verbose=False)
    compile_watch = tel.compile_watch

    cfg, phase, max_pred = _bench_base_config(seq_len, on_tpu)

    # BENCH_* env knobs for perf experiments without editing the file:
    # BENCH_FUSED=0 (XLA LayerNorm instead of Pallas), BENCH_RNG,
    # BENCH_DROPOUT=0, BENCH_OPT=sgd. The attention impl / batch / unroll /
    # remat policy are per-candidate child CLI flags (--attn etc.).
    fused = os.environ.get("BENCH_FUSED", "1") == "1"
    cfg = cfg.replace(attention_impl=attn, fused_ops=fused,
                      checkpoint_activations=(remat != "none"),
                      remat_policy=(remat if remat != "none" else "dots"),
                      scan_unroll=unroll, stacked_params=stacked)
    if os.environ.get("BENCH_DROPOUT", "1") == "0":
        cfg = cfg.replace(hidden_dropout_prob=0.0,
                          attention_probs_dropout_prob=0.0)
    if os.environ.get("BENCH_FUSED_DROPOUT", "1") == "0":
        cfg = cfg.replace(fused_dropout_ln=False)  # nn.Dropout + LN ablation
    # finer ablations for the perf budget map: attention-kernel dropout and
    # hidden (residual) dropout cost measured independently
    if os.environ.get("BENCH_ATTN_DROPOUT", "1") == "0":
        cfg = cfg.replace(attention_probs_dropout_prob=0.0)
    if os.environ.get("BENCH_HIDDEN_DROPOUT", "1") == "0":
        cfg = cfg.replace(hidden_dropout_prob=0.0)
    model = BertForPreTraining(cfg, dtype=jnp.bfloat16)

    rng = np.random.RandomState(0)
    n_rows = batch * accum
    ids = rng.randint(5, cfg.vocab_size, (n_rows, seq_len)).astype(np.int32)
    # exactly max_pred masked positions per row, like a full phase sample
    labels = np.full((n_rows, seq_len), -1, np.int64)
    for b in range(n_rows):
        pos = rng.choice(seq_len, max_pred, replace=False)
        labels[b, pos] = ids[b, pos]
    batch_np = {
        "input_ids": ids,
        "token_type_ids": np.zeros_like(ids),
        "attention_mask": np.ones_like(ids),
        "masked_lm_labels": labels.astype(np.int32),
        "next_sentence_labels": rng.randint(0, 2, (n_rows,)).astype(np.int32),
    }
    micro_batch = {k: jnp.asarray(v) for k, v in
                   stack_microbatches(batch_np, accum).items()}

    sched, tx = _bench_lamb(phase)
    if os.environ.get("BENCH_OPT") == "sgd":  # optimizer-cost diagnosis only
        import optax

        tx = optax.sgd(sched)
    grad_dtype = (None if os.environ.get("BENCH_GRAD_DTYPE") == "f32"
                  else jnp.bfloat16)
    step_fn = build_pretrain_step(model, tx, schedule=sched,
                                  accum_steps=accum,
                                  max_predictions=max_pred,
                                  grad_dtype=grad_dtype)

    def init_fn(r):
        return model.init(r, micro_batch["input_ids"][0],
                          micro_batch["token_type_ids"][0],
                          micro_batch["attention_mask"][0])

    state, _ = make_sharded_state(jax.random.PRNGKey(0), init_fn, tx)

    # Device-side K-step loop: the host dispatches ONE program for the whole
    # measured window (training/pretrain.chain_steps — the same inner loop
    # run_pretraining exposes as --steps_per_loop). Through this
    # environment's remote TPU relay a single dispatch costs ~24 ms and does
    # not pipeline, which would put a harness-artifact floor under every
    # step; on a directly-attached TPU VM the same loop is simply the
    # idiomatic "host only feeds data and logs" structure.
    from bert_pytorch_tpu.training.pretrain import chain_steps

    multi_fn = jax.jit(chain_steps(step_fn, steps), donate_argnums=(0,))
    single = jax.jit(step_fn, donate_argnums=(0,))
    state, metrics = single(state, micro_batch, jax.random.PRNGKey(0))
    float(metrics["loss"])  # scalar fetch = true device sync
    state, metrics = multi_fn(state, micro_batch, jax.random.PRNGKey(1))
    float(metrics["loss"])  # compile + warmup of the chained program
    compile_watch.mark_steady()  # compiles past here taint the measurement
    profile_dir = os.environ.get("BENCH_PROFILE_DIR")
    if profile_dir:  # trace exactly the steady-state measured window
        jax.profiler.start_trace(profile_dir)
    t0 = time.time()
    state, metrics = multi_fn(state, micro_batch, jax.random.PRNGKey(2))
    loss = float(metrics["loss"])
    dt = time.time() - t0
    if profile_dir:
        jax.profiler.stop_trace()

    dev = jax.devices()[0]
    # effective flash kernel-grid layout, only when a flash kernel actually
    # runs ("auto" resolves to pallas beyond seq 256) — derived through the
    # same gate the kernel dispatch uses, so the record cannot lie about
    # which path was measured
    flash_layout = None
    if attn == "pallas" or (attn == "auto" and seq_len > 256):
        from bert_pytorch_tpu.ops.pallas.flash_attention import _use_native

        flash_layout = ("native" if _use_native(
            seq_len, cfg.num_attention_heads, cfg.head_dim) else "bh")
    seqs_per_sec = batch * accum * steps / dt
    fps = flops_per_seq(cfg, seq_len, cfg.vocab_size, max_pred)
    # single-chip bench always computes in bf16 (model built with
    # jnp.bfloat16 above) — quote MFU against the bf16 peak explicitly
    peak = lookup_peak_flops(dev.device_kind, dtype="bf16") or DEFAULT_PEAK
    mfu = seqs_per_sec * fps / peak
    cw = compile_watch.snapshot()
    info = {"device": dev.device_kind, "batch": batch, "seq": seq_len,
            "attn": attn, "remat": remat, "unroll": unroll,
            "accum": accum, "stacked": stacked, "steps": steps,
            "mfu": round(mfu, 4),
            "loss": round(loss, 3), "dt_s": round(dt, 3),
            "compiles": cw["compiles"],
            "compile_secs": cw["compile_secs"],
            "recompiles_in_window": cw["recompiles_after_warmup"]}
    if flash_layout is not None:
        info["flash_layout"] = flash_layout
    tel.close()
    return {
        "seqs_per_sec": round(seqs_per_sec, 2),
        "mfu": round(mfu, 4),
        "_info": info,
    }


def run_packing_candidate(seq_len: int, steps: int, on_tpu: bool,
                          packed: bool, batch: int) -> dict:
    """Measure one member of the packed-vs-padded pair (child process).

    Both members train on the SAME deterministically generated example set
    (varied lengths, seed 0) — the same global token budget — so their
    real_tokens_per_sec ratio is the packing speedup and nothing else:
    `packed` first-fits the examples into `batch` rows of seq_len with
    block-diagonal segment attention; `padded` feeds them one per row,
    dense-padded to seq_len, exactly like the pre-round-9 pipeline."""
    if os.environ.get("BENCH_OVERLAP", "1") == "1":
        from bert_pytorch_tpu.parallel.xla_flags import apply_overlap_flags

        apply_overlap_flags()
    import jax
    import jax.numpy as jnp

    from bert_pytorch_tpu.data import packing as packing_lib
    from bert_pytorch_tpu.models import BertForPreTraining
    from bert_pytorch_tpu.training import (build_pretrain_step,
                                           make_sharded_state)
    from bert_pytorch_tpu.training.pretrain import (chain_steps,
                                                    stack_microbatches)

    max_segments = 8
    cfg, phase, max_pred = _bench_base_config(seq_len, on_tpu)
    cfg = cfg.replace(attention_impl="auto", next_sentence=True,
                      fused_ops=os.environ.get("BENCH_FUSED", "1") == "1")
    model = BertForPreTraining(cfg, dtype=jnp.bfloat16 if on_tpu
                               else jnp.float32)

    # deterministic varied-length corpus: mean length ~0.62*S, the regime
    # where packing fits 1-3 examples per row
    rng = np.random.RandomState(0)
    n_candidates = batch * 3
    lengths = rng.randint(seq_len // 4, seq_len + 1, n_candidates)
    ids = rng.randint(5, cfg.vocab_size, (n_candidates, seq_len)) \
        .astype(np.int32)
    attention_mask = (np.arange(seq_len)[None, :]
                      < lengths[:, None]).astype(np.int32)
    ids *= attention_mask
    labels = np.full((n_candidates, seq_len), -1, np.int64)
    for i in range(n_candidates):
        n_mask = max(1, min(max_pred, int(lengths[i] * MASK_FRACTION)))
        pos = rng.choice(lengths[i], n_mask, replace=False)
        labels[i, pos] = ids[i, pos]
    examples = {
        "input_ids": ids,
        "token_type_ids": np.zeros_like(ids),
        "attention_mask": attention_mask,
        "masked_lm_labels": labels,
        "next_sentence_labels": rng.randint(0, 2, (n_candidates,))
        .astype(np.int32),
    }
    bins = packing_lib.first_fit(lengths, batch, seq_len, max_segments)
    placed = sorted(i for members in bins for i in members)
    kept = {k: v[placed] for k, v in examples.items()}
    n_examples = len(placed)
    real_tokens = int(kept["attention_mask"].sum())

    if packed:
        remap = {old: new for new, old in enumerate(placed)}
        bins = [[remap[i] for i in members] for members in bins]
        batch_np = packing_lib.pack_examples(kept, bins, seq_len,
                                             max_segments)
        # same per-row gathered-head budget formula as run_pretraining.py
        max_pred_row = min(seq_len, max_segments * max_pred,
                           int(seq_len * MASK_FRACTION) + max_segments)
        rows = batch
    else:
        batch_np = dict(kept)
        batch_np["masked_lm_labels"] = \
            batch_np["masked_lm_labels"].astype(np.int32)
        max_pred_row = max_pred
        rows = n_examples

    micro = {k: jnp.asarray(v) for k, v in
             stack_microbatches(batch_np, 1).items()}
    sched, tx = _bench_lamb(phase)
    step_fn = build_pretrain_step(model, tx, schedule=sched, accum_steps=1,
                                  max_predictions=max_pred_row,
                                  grad_dtype=jnp.bfloat16 if on_tpu
                                  else None)

    def init_fn(r):
        return model.init(r, micro["input_ids"][0],
                          micro["token_type_ids"][0],
                          micro["attention_mask"][0])

    state, _ = make_sharded_state(jax.random.PRNGKey(0), init_fn, tx)
    multi_fn = jax.jit(chain_steps(step_fn, steps), donate_argnums=(0,))
    state, metrics = multi_fn(state, micro, jax.random.PRNGKey(1))
    float(metrics["loss"])  # compile + warmup; scalar fetch = sync
    t0 = time.time()
    state, metrics = multi_fn(state, micro, jax.random.PRNGKey(2))
    loss = float(metrics["loss"])
    dt = time.time() - t0

    return {
        "mode": "packed" if packed else "padded",
        "seq": seq_len,
        "rows_per_step": rows,
        "examples_per_step": n_examples,
        "real_tokens_per_step": real_tokens,
        "packing_efficiency": round(real_tokens / (rows * seq_len), 4),
        "real_tokens_per_sec": round(real_tokens * steps / dt, 1),
        "seqs_per_sec": round(rows * steps / dt, 2),
        "loss": round(loss, 3),
        "dt_s": round(dt, 3),
    }


def _measure_packing_pair(seq_len: int, steps: int, on_tpu: bool,
                          batch: int) -> None:
    """Run the packed and padded children (same token budget) and record
    the pair + speedup for the final JSON. Budget-gated like the grids."""
    here = os.path.abspath(__file__)
    pair = {}
    for mode in ("packed", "padded"):
        remaining = DEADLINE[0] - time.time()
        if remaining < EST_COST[0]:
            print(f"# budget: skipping packing pair ({mode})",
                  file=sys.stderr)
            SKIPPED[0] = True
            return
        cmd = [sys.executable, here, "--packing-child", "--mode", mode,
               "--seq", str(seq_len), "--steps", str(steps),
               "--batch", str(batch)]
        if not on_tpu:
            cmd.append("--cpu")
        res = _run_child(cmd, min(900.0, remaining - 15.0))
        if res is None:
            print(f"# packing pair {mode} timed out; skipping pair",
                  file=sys.stderr)
            SKIPPED[0] = True
            return
        stdout, stderr, rc = res
        for line in stdout.splitlines():
            if line.startswith("BENCH_RESULT "):
                pair[mode] = json.loads(line[len("BENCH_RESULT "):])
        if mode not in pair:
            print(stderr[-2000:], file=sys.stderr)
            print(f"# packing pair {mode} failed rc={rc}; skipping pair",
                  file=sys.stderr)
            SKIPPED[0] = True
            return
        print(f"# packing pair measured {pair[mode]}", file=sys.stderr)
    PACKING_PAIR.update(pair)
    PACKING_PAIR["speedup_real_tokens_per_sec"] = round(
        pair["packed"]["real_tokens_per_sec"]
        / max(pair["padded"]["real_tokens_per_sec"], 1e-9), 4)


# Candidate grids: (batch, attn, remat_policy, unroll, accum, stacked),
# ordered BEST-KNOWN-FIRST so a budget-truncated sweep still lands the
# headline. "none" = un-rematted stack; "mlp_only" recomputes only the
# (B, S, 4E) wide-MLP activations (models/bert.py remat policies), trading
# cheap MLP recompute for batch headroom. attention "xla_checkpoint" frees
# the (B, H, S, S) probs; "auto" resolves to the Pallas flash kernel.
# stacked=False is the unstacked per-layer parameter layout
# (config.stacked_params): wgrads write into per-layer leaves instead of
# dynamic_update_slice into the (L, ...) stack — the 9.4% DUS bucket in the
# seq512 trace (docs/PERF.md) — and at seq512 it pairs with the flash
# kernel's native (B, S, H, D) layout (no transpose pass, the 4.9% bucket).
# accum > 1 measures the reference RECIPE configuration (phase global
# batches are 65536/32768 — far above one chip's micro batch,
# config/bert_pretraining_phase{1,2}_config.json:3), so the
# once-per-optimization-step LAMB cost amortizes over the microbatches
# exactly as it does in real training.
CANDIDATES_128 = [
    # unstacked first: the r5 winner config minus its scan-wgrad DUS writes
    # (same batch/accum; the stack was already fully unrolled, so the only
    # delta is the parameter layout).
    (64, "xla", "none", 24, 32, False),
    # r5 winner family: fused residual-dropout-LN kernel (measured 65.1-65.3%
    # MFU at accum 32; r4's 53.0% was the same config with nn.Dropout).
    # Batch expansion via remat is measured dead: b80/b96 mlp_only OOM at
    # 17.3/20.4G vs 15.75G HBM. accum 64 is dropped: its ~0.2-pt edge over
    # accum 32 (r4) is not worth the budget after its 6-step window
    # reproducibly degraded to 160 s through the remote relay (r5 sweep,
    # 0.19 MFU — relay pathology on very long single programs).
    (64, "xla", "none", 24, 32, True),
    (64, "xla", "none", 24, 16, False),
    (16, "xla", "dots", 1, 1, True),    # fit-anywhere floor (small HBM)
]
CANDIDATES_512 = [
    # unstacked + native-layout flash: attacks the two structural buckets
    # left in the r5 seq512 trace (9.4% DUS + 4.9% layout copies)
    (16, "auto", "none", 24, 32, False),
    (16, "auto", "none", 24, 32, True),  # r5: 50.7% with fused dropout-LN
    # no accum-64 here: its ~63 s single device program trips this
    # environment's remote-relay watchdog ("TPU worker process crashed or
    # restarted", twice, r4 run) and accum 32 already amortizes LAMB fully.
    # b24/b32 mlp_only OOM (19.0/24.8G); b20 un-rematted measured 49.9% —
    # b16 stays the knee.
    (16, "auto", "none", 24, 16, False),
    (16, "auto", "none", 24, 8, True),
    (4, "xla_checkpoint", "dots", 1, 1, True),  # fit-anywhere floor
]
OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Ran out of memory",
               "Exceeded hbm", "out of memory")

# --- always-land-the-JSON machinery (round-5, VERDICT item 1) ---
BEST: dict = {}          # seq_len -> best measured result, updated live
PACKING_PAIR: dict = {}  # packed-vs-padded pair + speedup (round 9)
ON_TPU = [False]
_EMITTED = [False]
_CHILD = [None]          # live child Popen, killed on signal
DEADLINE = [None]        # wall-clock emit deadline
# per-candidate cost estimate, shared across grids: cold-compile guess
# (~60-120 s via the remote relay + 3 measurement windows), then the most
# recent child's observed wall time x1.2 — grows after slow/hung children
EST_COST = [240.0]


SKIPPED = [False]        # any candidate skipped/timed out -> truncated_sweep


def emit_final(partial: bool = False, signal_safe: bool = False) -> None:
    """Print the one JSON line from BEST. Idempotent. With signal_safe,
    bypasses buffered stdio (a SIGTERM landing mid-print would otherwise
    hit CPython's BufferedWriter reentrancy guard and kill the process
    before the JSON gets out)."""
    if _EMITTED[0]:
        return
    _EMITTED[0] = True
    if 128 not in BEST:
        msg = "# no seq128 result measured before the deadline\n"
        os.write(2, msg.encode()) if signal_safe else sys.stderr.write(msg)
        return
    out = {
        "metric": ("bert_large_mlm_seq128_train_throughput" if ON_TPU[0]
                   else "bench_smoke_cpu"),
        "value": BEST[128]["seqs_per_sec"],
        "unit": "seq/s/chip",
        "vs_baseline": round(BEST[128]["mfu"] / 0.50, 4),
        "compiles": BEST[128]["_info"].get("compiles"),
        "recompiles_in_window": BEST[128]["_info"].get(
            "recompiles_in_window"),
    }
    if 512 in BEST:
        out["seq512_value"] = BEST[512]["seqs_per_sec"]
        out["seq512_mfu"] = BEST[512]["mfu"]
        out["seq512_vs_baseline"] = round(BEST[512]["mfu"] / 0.50, 4)
        out["seq512_compiles"] = BEST[512]["_info"].get("compiles")
    if PACKING_PAIR:
        # packed-vs-padded over the identical example set (same global
        # token budget): the real_tokens_per_sec ratio IS the packing win
        out["packing"] = PACKING_PAIR
    if partial or SKIPPED[0]:
        out["truncated_sweep"] = True
    if not signal_safe:
        # self-describing artifact (ISSUE 3 provenance satellite). Skipped
        # on the signal path: collect() shells out to git, which is not
        # async-signal-safe. device=False — the parent process must never
        # initialize the TPU backend (children own the device).
        try:
            from bert_pytorch_tpu.telemetry.provenance import collect

            # the PARENT env's pack state is reported; the measurement
            # children apply the overlap pack themselves iff BENCH_OVERLAP=1
            # (run_candidate), so record that intent alongside
            out["provenance"] = collect(device=False, extra={
                "bench_overlap": os.environ.get("BENCH_OVERLAP", "1")})
        except Exception:
            pass
    line = json.dumps(out) + "\n"
    if signal_safe:
        os.write(1, line.encode())
    else:
        sys.stdout.write(line)
        sys.stdout.flush()


def _signal_flush(signum, frame):
    """External timeout (SIGTERM) or our own alarm: flush JSON and exit 0
    so the driver parses a real result instead of recording rc=124. Only
    async-signal-tolerant calls here: os.write, no buffered prints."""
    os.write(2, f"# signal {signum}: flushing partial result\n".encode())
    child = _CHILD[0]
    if child is not None and child.poll() is None:
        child.kill()
    emit_final(partial=True, signal_safe=True)
    # exit 0 only if there is a headline to parse
    os._exit(0 if 128 in BEST else 1)


def _run_child(cmd, timeout_s: float, env=None):
    """Popen wrapper that records the live child so the signal handler can
    kill it; returns (stdout, stderr, rc) or None on timeout."""
    child = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                             stderr=subprocess.PIPE, text=True, env=env)
    _CHILD[0] = child
    try:
        out, err = child.communicate(timeout=timeout_s)
        return out, err, child.returncode
    except subprocess.TimeoutExpired:
        child.kill()
        child.communicate()
        return None
    finally:
        _CHILD[0] = None


def _measure_grid(seq_len: int, candidates, steps: int, on_tpu: bool):
    """Run candidates best-first in fresh subprocesses, respecting the
    wall-clock deadline: a child is only launched if the remaining budget
    plausibly covers it, and its timeout is clipped to the budget. Updates
    BEST[seq_len] after every measurement so a signal flush mid-grid still
    reports the best so far.

    A non-OOM child failure is retried once (the remote-compile relay on
    this box throws transient connection errors) and then skipped with a
    warning."""
    here = os.path.abspath(__file__)
    n_measured = 0
    for batch, attn, remat, unroll, accum, stacked in candidates:
        remaining = DEADLINE[0] - time.time()
        if remaining < EST_COST[0]:
            print(f"# budget: {remaining:.0f}s left < {EST_COST[0]:.0f}s "
                  f"estimate; skipping rest of seq{seq_len} grid",
                  file=sys.stderr)
            SKIPPED[0] = True
            break
        # measurement window ~48 optimizer-equivalent steps regardless of
        # accumulation depth so every candidate gets a comparable timing run
        c_steps = max(6, steps // accum) if accum > 1 else steps
        cmd = [sys.executable, here, "--child", "--batch", str(batch),
               "--steps", str(c_steps), "--seq", str(seq_len),
               "--attn", attn, "--unroll", str(unroll),
               "--accum", str(accum), "--remat", remat,
               "--stacked", "1" if stacked else "0"]
        if not on_tpu:
            cmd.append("--cpu")
        # attempt 1: as configured. attempt 2: same config again (the
        # remote-compile relay throws transient connection errors — a
        # flake must NOT cost the native-layout measurement). attempt 3,
        # flash candidates only: FLASH_LAYOUT=bh, so a deterministic
        # native-kernel compile failure still lands the rest of the
        # candidate (layout/batch/accum) on the transposing grid.
        attempts = (1, 2, 3) if attn in ("auto", "pallas") else (1, 2)
        for attempt in attempts:
            t_start = time.time()
            child_budget = min(900.0, DEADLINE[0] - time.time() - 15.0)
            if child_budget < 60.0:
                SKIPPED[0] = True
                break
            env = None
            if attempt == 3:
                env = dict(os.environ, FLASH_LAYOUT="bh")
                print(f"# retrying b={batch} {attn} seq={seq_len} with "
                      "FLASH_LAYOUT=bh", file=sys.stderr)
            res = _run_child(cmd, child_budget, env=env)
            if res is None:
                elapsed = time.time() - t_start
                print(f"# candidate b={batch} {attn} remat={remat} "
                      f"seq={seq_len} timed out after {elapsed:.0f}s; "
                      "skipping", file=sys.stderr)
                # a hung child proves candidates can cost this much: raise
                # the estimate so the gate stops launching doomed ones
                EST_COST[0] = max(EST_COST[0], elapsed * 1.2)
                SKIPPED[0] = True
                break
            stdout, stderr, rc = res
            result = None
            for line in stdout.splitlines():
                if line.startswith("BENCH_RESULT "):
                    result = json.loads(line[len("BENCH_RESULT "):])
            if result is not None:
                print(f"# measured {result['_info']}", file=sys.stderr)
                n_measured += 1
                took = time.time() - t_start
                EST_COST[0] = max(180.0, took * 1.2)
                if (seq_len not in BEST
                        or result["seqs_per_sec"]
                        > BEST[seq_len]["seqs_per_sec"]):
                    BEST[seq_len] = result
                break
            if any(m in stderr for m in OOM_MARKERS):
                print(f"# candidate b={batch} {attn} remat={remat} "
                      f"seq={seq_len} OOM", file=sys.stderr)
                break
            # neither result nor OOM: transient relay flake or a real bug —
            # retry once, then skip
            print(stderr[-2000:], file=sys.stderr)
            print(f"# candidate b={batch} {attn} seq={seq_len} failed "
                  f"with a non-OOM error (rc={rc}), "
                  f"attempt {attempt}", file=sys.stderr)
            if attempt == attempts[-1]:  # no measurement: mark the sweep
                SKIPPED[0] = True
    if not n_measured and candidates:
        print(f"# seq{seq_len}: nothing measured in this block",
              file=sys.stderr)


# --- measured multichip scaling bench (round 7) -------------------------
# Sweeps {pure-DP, DP+ZeRO-1, fsdp} over an n-device mesh plus a 1-device
# baseline, and reports per-variant step time, seq/s/chip, and scaling
# efficiency (seq/s/chip / single-chip seq/s). Upgrades MULTICHIP_r*.json
# from a dryrun-only artifact to a perf trajectory. On a box without n real
# chips the sweep runs on the forced n-device CPU mesh — the relative
# DP-vs-ZeRO-1 cost is still real (a replicated LAMB update is executed
# once per device; the sharded one 1/n per device), absolute seq/s is not
# TPU-comparable and the JSON records the platform.
#
# The model is deliberately optimizer-heavy (big vocab embedding, thin
# trunk, accum=1, gathered MLM head): the quantity under test is the
# once-per-step update + collective path, not the matmul throughput the
# single-chip headline already measures.

MULTICHIP_MODEL = dict(vocab_size=32768, hidden_size=128,
                       num_hidden_layers=2, num_attention_heads=4,
                       intermediate_size=512, max_position_embeddings=64)
MULTICHIP_SEQ = 32
MULTICHIP_BATCH_PER_SHARD = 2
MULTICHIP_MAX_PRED = 4


def _mc_packed_batch(cfg, batch_global: int, seq: int, max_pred: int,
                     max_segments: int = 4):
    """Synthetic PACKED batch through the production packer: two
    half-row-length examples per row (deterministic bins — the quantity
    under test is the packed step's collective/compute profile, not the
    packer), exactly `max_pred` masked positions per example."""
    from bert_pytorch_tpu.data.packing import pack_examples

    rng = np.random.RandomState(0)
    n = batch_global * 2
    ln = seq // 2
    ids = rng.randint(5, cfg.vocab_size, (n, seq)).astype(np.int32)
    mask = np.zeros((n, seq), np.int32)
    mask[:, :ln] = 1
    labels = np.full((n, seq), -1, np.int32)
    for b in range(n):
        pos = rng.choice(ln, max_pred, replace=False)
        labels[b, pos] = ids[b, pos]
    ex = {
        "input_ids": ids,
        "token_type_ids": np.zeros_like(ids),
        "attention_mask": mask,
        "masked_lm_labels": labels,
        "next_sentence_labels": rng.randint(0, 2, (n,)).astype(np.int32),
    }
    bins = [[2 * i, 2 * i + 1] for i in range(batch_global)]
    return pack_examples(ex, bins, seq, max_segments)


def _mc_time_variant(label, mesh, cfg, steps: int, reps: int,
                     zero1: bool = False, overlap: bool = False,
                     packed: bool = False, fsdp_overlap: bool = False,
                     rs: bool = False, trace_dir=None):
    """Measure one mesh/variant in-process; returns the per-variant record.

    `overlap` = gather-on-use ZeRO-1 (params rest 1/N-sharded, re-gathered
    per leaf at the point of use). `fsdp_overlap` = gather-on-use for the
    fsdp axis (parallel/zero.make_fsdp_plan — explicit per-leaf gathers
    instead of GSPMD's implicit re-materialization). `packed` runs a
    2-segments/row packed batch through the segment-aware attention; the
    dp_seq_packing_overlap variant composes packed + ring + zero1-overlap
    — the `production` mesh_config, measured rather than assumed.
    `trace_dir` additionally captures one traced window per variant and
    lands its collective/compute/host breakdown — incl. the round-15
    per-KIND collective split (telemetry/trace.py collective_kind_ms) —
    in the record, the attribution behind the scaling-efficiency
    numbers. `rs` (round 16, implies zero1+overlap and a data-only mesh)
    routes gradients through the reduce-scatter region with coalesced
    trust-ratio norms: the per-kind split is the gate target — all-reduce
    ms down, reduce-scatter ms up."""
    import jax
    import jax.numpy as jnp

    from bert_pytorch_tpu.optim import schedulers
    from bert_pytorch_tpu.optim.lamb import (lamb, default_weight_decay_mask,
                                             default_trust_batch_axes)
    from bert_pytorch_tpu.models import BertForPreTraining
    from bert_pytorch_tpu.parallel import mesh as mesh_lib
    from bert_pytorch_tpu.parallel.zero import make_zero1_plan
    from bert_pytorch_tpu.telemetry.run import init_run
    from bert_pytorch_tpu.training import build_pretrain_step, make_sharded_state
    from bert_pytorch_tpu.training.pretrain import (chain_steps,
                                                    stack_microbatches)

    import __graft_entry__ as graft

    # same init_run wiring path as the entry points (phase label 'bench')
    tel = init_run(phase="bench", verbose=False)
    compile_watch = tel.compile_watch

    n_shards = mesh_lib.data_shard_count(mesh)
    n_dev = mesh.devices.size
    batch_global = MULTICHIP_BATCH_PER_SHARD * n_shards
    max_pred_row = MULTICHIP_MAX_PRED * (2 if packed else 1)
    if packed:
        batch_np = _mc_packed_batch(cfg, batch_global, MULTICHIP_SEQ,
                                    MULTICHIP_MAX_PRED)
    else:
        # the dryrun's synthetic-batch builder (same premasked-width
        # contract as the gathered MLM head: exactly max_pred masked
        # positions per row)
        batch_np = graft._make_batch(cfg, 1, batch_global, MULTICHIP_SEQ,
                                     MULTICHIP_MAX_PRED)
    stacked = stack_microbatches(batch_np, 1)

    model = BertForPreTraining(cfg, dtype=jnp.float32
                               if jax.devices()[0].platform == "cpu"
                               else jnp.bfloat16)
    sched = schedulers.poly_warmup_schedule(1e-3, total_steps=1000,
                                            warmup=0.1)
    tx = lamb(sched, weight_decay=0.01,
              weight_decay_mask=default_weight_decay_mask,
              trust_batch_axes=default_trust_batch_axes)

    def init_fn(r):
        return model.init(r, jnp.asarray(stacked["input_ids"][0]),
                          jnp.asarray(stacked["token_type_ids"][0]),
                          jnp.asarray(stacked["attention_mask"][0]))

    with mesh_lib.logical_rules():
        state, shardings = make_sharded_state(
            jax.random.PRNGKey(0), init_fn, tx, mesh=mesh, zero1=zero1,
            zero1_params=overlap)
    plan = (make_zero1_plan(state.params, shardings.params, mesh,
                            gather_on_use=overlap, reduce_scatter=rs,
                            warn_skipped=False)
            if zero1 else None)
    if fsdp_overlap:
        from bert_pytorch_tpu.parallel.zero import make_fsdp_plan

        fplan = make_fsdp_plan(state.params, shardings.params, mesh,
                               zero1=plan is not None, warn_skipped=False)
        if fplan is not None:
            plan = fplan
    norm_reducer = None
    if rs and plan is not None:
        # coalesced trust-ratio norms are what keep the rs program's
        # all-reduce count at O(buckets) instead of O(leaves) — without
        # them the per-leaf norm reductions hand back most of the
        # all-reduces the scatter path just removed
        from bert_pytorch_tpu.parallel.coalesce import NormReducer

        norm_reducer = NormReducer(plan.grad_shardings, mesh)
        tx = lamb(sched, weight_decay=0.01,
                  weight_decay_mask=default_weight_decay_mask,
                  trust_batch_axes=default_trust_batch_axes,
                  norm_reducer=norm_reducer)
    step_fn = build_pretrain_step(model, tx, schedule=sched, accum_steps=1,
                                  max_predictions=max_pred_row,
                                  zero1=plan, norm_reducer=norm_reducer)
    from bert_pytorch_tpu.training.pretrain import StepProgram

    # StepProgram = same one compile jit would do, but the executable's
    # HLO stays reachable — the collective inventory below is the static
    # counterpart of the traced time_breakdown
    chained = StepProgram(chain_steps(step_fn, steps))
    batch = mesh_lib.host_to_device_batch(mesh, stacked)
    breakdown = None
    inventory = None
    with mesh, mesh_lib.logical_rules():
        state, metrics = chained(state, batch, jax.random.PRNGKey(1))
        float(metrics["loss"])  # compile + warmup; scalar fetch = sync
        hlo_text = chained.as_text()
        if hlo_text is not None:
            from bert_pytorch_tpu.analysis.hlo import collective_inventory

            inventory = collective_inventory(hlo_text)
            # per-STEP counts read better next to step_time_ms than
            # whole-chunk totals (the chunk is `steps` identical bodies)
            inventory["steps_per_program"] = steps
        dts = []
        for rep in range(reps):
            t0 = time.time()
            state, metrics = chained(state, batch,
                                     jax.random.PRNGKey(2 + rep))
            loss = float(metrics["loss"])
            dts.append(time.time() - t0)
        if trace_dir is not None:
            # one EXTRA traced window after the timed reps (tracing costs;
            # the wall-clock numbers above stay untainted), summarized into
            # the collective/compute/host buckets per variant
            from bert_pytorch_tpu.telemetry.trace import summarize_trace

            tdir = os.path.join(trace_dir, label)
            jax.profiler.start_trace(tdir)
            try:
                state, m = chained(state, batch, jax.random.PRNGKey(99))
                float(m["loss"])
            finally:
                jax.profiler.stop_trace()
            try:
                breakdown = summarize_trace(tdir, steps=steps,
                                            n_devices=n_dev)
                breakdown.pop("trace_file", None)  # tempdir path: noise
            except Exception as e:  # a missing trace must not kill the sweep
                breakdown = {"error": f"{type(e).__name__}: {e}"}
    dt = min(dts)
    seqs_per_sec = batch_global * steps / dt
    cw = compile_watch.snapshot()
    tel.close()
    rec = {
        "label": label,
        "mesh": {k: int(v) for k, v in mesh.shape.items()},
        "n_devices": int(n_dev),
        "zero1": bool(zero1 and plan is not None),
        "zero1_overlap": bool(zero1 and plan is not None and overlap),
        "zero1_rs": bool(rs and plan is not None
                         and getattr(plan, "reduce_scatter", False)),
        "fsdp_overlap": bool(fsdp_overlap and plan is not None
                             and plan.axis == "fsdp"),
        "packed": bool(packed),
        "batch_global": int(batch_global),
        "step_time_ms": round(dt / steps * 1e3, 3),
        "seqs_per_sec": round(seqs_per_sec, 2),
        "seqs_per_sec_per_chip": round(seqs_per_sec / n_dev, 2),
        "loss": round(loss, 3),
        "compiles": cw["compiles"],
        "compile_secs": cw["compile_secs"],
    }
    if breakdown is not None:
        rec["time_breakdown"] = breakdown
    if inventory is not None:
        # the static collective inventory next to the measured breakdown:
        # WHAT the program moves, beside WHERE the time went
        rec["collectives"] = inventory
    # the multichip model computes in f32 on CPU meshes, bf16 on TPU (see
    # the BertForPreTraining construction above) — the peak must match
    peak = lookup_peak_flops(
        jax.devices()[0].device_kind,
        dtype="f32" if jax.devices()[0].platform == "cpu" else "bf16")
    if peak is not None:  # CPU mesh: absolute MFU would be fiction — omit
        fps = flops_per_seq(cfg, MULTICHIP_SEQ, cfg.vocab_size,
                            max_pred_row)
        rec["mfu"] = round(seqs_per_sec * fps / (peak * n_dev), 4)
    if zero1 and plan is not None:
        # record that the moments genuinely live sharded (the thing ZeRO-1
        # claims), so the JSON cannot report a silently-replicated run
        mu_leaves = jax.tree.leaves(state.opt_state.mu)
        rec["moment_shards"] = max(
            len(l.sharding.device_set) if not l.sharding.is_fully_replicated
            else 1 for l in mu_leaves)
    if overlap and plan is not None:
        # ...and that the PARAMS genuinely rest sharded between steps (the
        # thing gather-on-use claims)
        p_leaves = jax.tree.leaves(state.params)
        rec["param_shards_at_rest"] = max(
            len(l.sharding.device_set) if not l.sharding.is_fully_replicated
            else 1 for l in p_leaves)
    return rec


def multichip_measure(n_devices: int, out_path=None, budget_s=None,
                      steps: int = 10, reps: int = 3) -> dict:
    """Run the multichip sweep in a process that already exposes >=
    n_devices devices. Writes `out_path` incrementally after every variant
    (a killed run still leaves the variants measured so far on disk) and
    prints one final `MULTICHIP_BENCH {json}` line."""
    import jax

    from bert_pytorch_tpu.config import BertConfig
    from bert_pytorch_tpu.parallel import mesh as mesh_lib

    if jax.device_count() < n_devices:
        raise RuntimeError(
            f"{jax.device_count()} devices visible, need {n_devices}")
    deadline = time.time() + budget_s if budget_s else None
    est = [150.0]

    cfg = BertConfig(next_sentence=True, dtype="float32", fused_ops=False,
                     attention_impl="xla", hidden_dropout_prob=0.0,
                     attention_probs_dropout_prob=0.0, **MULTICHIP_MODEL)
    # the seq-sharded variants need an impl the ring dispatch serves
    # (ops/attention.py routes impl in {ring, pallas} to ring_sharded when
    # the ambient mesh has seq>1; impl='xla' is the documented opt-out)
    cfg_ring = cfg.replace(attention_impl="ring")
    devs = jax.devices()[:n_devices]
    half = max(1, n_devices // 2)
    # (label, mesh, variant kwargs) — ordered so the round-11 quantities
    # under test (overlap ZeRO-1, seq-axis composition) land before the
    # budget can truncate the tail
    plan = [
        ("single", mesh_lib.make_mesh({"data": 1}, devices=devs[:1]),
         dict()),
        ("dp", mesh_lib.make_mesh({"data": n_devices}, devices=devs),
         dict()),
        ("dp_zero1", mesh_lib.make_mesh({"data": n_devices}, devices=devs),
         dict(zero1=True)),
        ("dp_zero1_overlap",
         mesh_lib.make_mesh({"data": n_devices}, devices=devs),
         dict(zero1=True, overlap=True)),
        # round 16: grads leave the step through psum_scatter instead of
        # all-reduce-then-slice (half the gradient bytes on the wire),
        # with coalesced trust-ratio norms. Data-only meshes by
        # construction (parallel/zero.rs_supported); production_rs is the
        # production composition minus the seq axis — packing + ZeRO-1
        # overlap + rs — so the packed loss path is measured on the
        # scatter region too
        ("dp_zero1_rs",
         mesh_lib.make_mesh({"data": n_devices}, devices=devs),
         dict(zero1=True, overlap=True, rs=True)),
        ("production_rs",
         mesh_lib.make_mesh({"data": n_devices}, devices=devs),
         dict(packed=True, zero1=True, overlap=True, rs=True)),
        ("fsdp", mesh_lib.make_mesh({"fsdp": n_devices}, devices=devs),
         dict()),
        # gather-on-use for the fsdp axis (--fsdp_overlap): the implicit
        # GSPMD re-materialization above vs explicit per-leaf gathers the
        # scheduler can overlap — the round-15 tentpole, measured
        ("fsdp_overlap",
         mesh_lib.make_mesh({"fsdp": n_devices}, devices=devs),
         dict(fsdp_overlap=True)),
    ]
    if n_devices >= 2:  # the seq axis needs 2 devices; 'single' covers n=1
        plan[4:4] = [
            ("dp_seq", mesh_lib.make_mesh({"data": half, "seq": 2},
                                          devices=devs[:half * 2]),
             dict(cfg=cfg_ring)),
            ("dp_seq_packing", mesh_lib.make_mesh({"data": half, "seq": 2},
                                                  devices=devs[:half * 2]),
             dict(cfg=cfg_ring, packed=True)),
            # the `production` mesh_config composition (packing + ring
            # attention + ZeRO-1 overlap on one mesh) — gated so the
            # default is measured, not assumed
            ("dp_seq_packing_overlap",
             mesh_lib.make_mesh({"data": half, "seq": 2},
                                devices=devs[:half * 2]),
             dict(cfg=cfg_ring, packed=True, zero1=True, overlap=True)),
        ]
    from bert_pytorch_tpu.telemetry.provenance import collect

    out = {
        "n_devices": n_devices,
        "platform": jax.devices()[0].platform,
        "device_kind": jax.devices()[0].device_kind,
        "measured": True,
        "model": dict(MULTICHIP_MODEL, seq=MULTICHIP_SEQ,
                      batch_per_shard=MULTICHIP_BATCH_PER_SHARD,
                      max_predictions=MULTICHIP_MAX_PRED, accum=1),
        "steps_per_window": steps,
        "provenance": collect(),  # backend already up in this child
        "variants": {},
    }

    def flush():
        if out_path:
            tmp = out_path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(out, f, indent=1, sort_keys=True)
            os.replace(tmp, out_path)

    # write the empty skeleton BEFORE the first (minutes-long) compile: a
    # signal landing in that window must flush THIS run's (empty) record,
    # not a stale previous MULTICHIP json left at the same path
    flush()

    import shutil
    import tempfile

    trace_root = tempfile.mkdtemp(prefix="multichip_traces_")
    for label, mesh, opts in plan:
        if deadline is not None and time.time() + est[0] > deadline:
            print(f"# multichip: budget exhausted before {label}; truncating",
                  file=sys.stderr)
            out["truncated"] = True
            break
        t0 = time.time()
        rec = _mc_time_variant(label, mesh, opts.pop("cfg", cfg), steps,
                               reps, trace_dir=trace_root, **opts)
        est[0] = max(60.0, (time.time() - t0) * 1.2)
        single = out["variants"].get("single")
        if single and label != "single":
            rec["scaling_efficiency"] = round(
                rec["seqs_per_sec_per_chip"] / single["seqs_per_sec"], 4)
        out["variants"][label] = rec
        print(f"# multichip measured {label}: "
              f"{rec['step_time_ms']} ms/step, "
              f"{rec['seqs_per_sec_per_chip']} seq/s/chip",
              file=sys.stderr)
        flush()

    dp = out["variants"].get("dp")
    dpz = out["variants"].get("dp_zero1")
    dpo = out["variants"].get("dp_zero1_overlap")
    if dp and dpz:
        out["zero1_step_time_ratio_vs_dp"] = round(
            dpz["step_time_ms"] / dp["step_time_ms"], 4)
    if dpz and dpo:
        # the round-11 headline: gather-on-use vs the blocking all-gather
        out["zero1_overlap_step_time_ratio_vs_zero1"] = round(
            dpo["step_time_ms"] / dpz["step_time_ms"], 4)
    dprs = out["variants"].get("dp_zero1_rs")
    if dpo and dprs:
        # the round-16 headline: reduce-scatter grads + coalesced norms
        # vs the all-reduce-then-slice overlap step
        out["zero1_rs_step_time_ratio_vs_overlap"] = round(
            dprs["step_time_ms"] / dpo["step_time_ms"], 4)
    fs = out["variants"].get("fsdp")
    fso = out["variants"].get("fsdp_overlap")
    if fs and fso:
        # the round-15 headline: explicit gather-on-use vs GSPMD's
        # implicit fsdp re-materialization
        out["fsdp_overlap_step_time_ratio_vs_fsdp"] = round(
            fso["step_time_ms"] / fs["step_time_ms"], 4)
    flush()
    # the breakdowns are extracted into the json; the raw traces are
    # ~100 MB/sweep and would otherwise accumulate in /tmp across CI runs
    shutil.rmtree(trace_root, ignore_errors=True)
    print("MULTICHIP_BENCH " + json.dumps(out, sort_keys=True), flush=True)
    return out


_MC_CHILD = [None]
_MC_OUT = [None]


def _mc_signal_flush(signum, frame):
    """SIGTERM/SIGALRM during the multichip sweep: kill the child and emit
    whatever the incremental file already holds — same always-land-the-JSON
    contract the single-chip sweep gives the headline."""
    os.write(2, f"# signal {signum}: flushing partial multichip result\n"
             .encode())
    child = _MC_CHILD[0]
    if child is not None and child.poll() is None:
        child.kill()
    path = _MC_OUT[0]
    try:
        with open(path) as f:
            data = f.read()
        payload = json.loads(data)
        payload["truncated"] = True
        os.write(1, ("MULTICHIP_BENCH " + json.dumps(payload, sort_keys=True)
                     + "\n").encode())
        os._exit(0)
    except Exception:
        os._exit(1)


def multichip_main():
    """`bench.py --multichip [--devices N]`: bootstrap an N-device mesh (the
    real chips when the box has them, a forced-CPU virtual mesh otherwise)
    in a child process and run multichip_measure there."""
    def arg(name, default=None):
        return (sys.argv[sys.argv.index(name) + 1]
                if name in sys.argv else default)

    n = int(arg("--devices", "8"))
    here = os.path.dirname(os.path.abspath(__file__))
    out_path = os.environ.get(
        "MULTICHIP_OUT", os.path.join(here, "MULTICHIP_r09.json"))
    budget = float(os.environ.get("MULTICHIP_BUDGET_S", "2400"))
    _MC_OUT[0] = out_path

    import __graft_entry__ as graft

    env = dict(os.environ, MULTICHIP_OUT=out_path,
               MULTICHIP_BUDGET_S=str(budget - 60))
    if graft._real_device_count() < n:
        import re as _re

        flags = _re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                        env.get("XLA_FLAGS", "")).strip()
        env["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count={n}").strip()
        env["JAX_PLATFORMS"] = "cpu"
        env["BENCH_MC_FORCE_CPU"] = "1"

    signal.signal(signal.SIGTERM, _mc_signal_flush)
    signal.signal(signal.SIGINT, _mc_signal_flush)
    signal.signal(signal.SIGALRM, _mc_signal_flush)
    signal.alarm(int(budget) + 60)

    cmd = [sys.executable, os.path.abspath(__file__), "--multichip-child",
           "--devices", str(n)]
    child = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                             stderr=subprocess.PIPE, text=True, env=env,
                             cwd=here)
    _MC_CHILD[0] = child
    try:
        stdout, stderr = child.communicate(timeout=budget)
    except subprocess.TimeoutExpired:
        child.kill()
        child.communicate()
        return _mc_signal_flush(signal.SIGALRM, None)
    finally:
        _MC_CHILD[0] = None
    sys.stderr.write(graft.filter_known_noise(stderr))
    sys.stdout.write(stdout)
    sys.stdout.flush()
    if child.returncode != 0:
        raise SystemExit(f"multichip child failed rc={child.returncode}")


def main():
    if "--multichip-child" in sys.argv:
        if os.environ.get("BENCH_MC_FORCE_CPU") == "1":
            import jax

            jax.config.update("jax_platforms", "cpu")
        if os.environ.get("BENCH_OVERLAP", "1") == "1":  # same A/B knob as
            from bert_pytorch_tpu.parallel.xla_flags import \
                apply_overlap_flags  # the single-chip candidates honor

            apply_overlap_flags()
        n = int(sys.argv[sys.argv.index("--devices") + 1]
                if "--devices" in sys.argv else 8)
        budget = os.environ.get("MULTICHIP_BUDGET_S")
        multichip_measure(n, out_path=os.environ.get("MULTICHIP_OUT"),
                          budget_s=float(budget) if budget else None)
        return
    if "--multichip" in sys.argv:
        return multichip_main()
    if "--packing-child" in sys.argv:
        def arg(name, default=None):
            return (sys.argv[sys.argv.index(name) + 1]
                    if name in sys.argv else default)

        result = run_packing_candidate(
            seq_len=int(arg("--seq", "128")),
            steps=int(arg("--steps", "8")),
            on_tpu="--cpu" not in sys.argv,
            packed=arg("--mode", "packed") == "packed",
            batch=int(arg("--batch", "16")),
        )
        print("BENCH_RESULT " + json.dumps(result), flush=True)
        return
    if "--child" in sys.argv:
        def arg(name, default=None):
            return (sys.argv[sys.argv.index(name) + 1]
                    if name in sys.argv else default)

        result = run_candidate(
            batch=int(arg("--batch")),
            seq_len=int(arg("--seq", "128")),
            steps=int(arg("--steps")),
            on_tpu="--cpu" not in sys.argv,
            attn=arg("--attn", "auto"),
            remat=arg("--remat", "none"),
            unroll=int(arg("--unroll", "1")),
            accum=int(arg("--accum", "1")),
            stacked=arg("--stacked", "1") == "1",
        )
        print("BENCH_RESULT " + json.dumps(result), flush=True)
        return

    budget = float(os.environ.get("BENCH_BUDGET_S", "2100"))
    DEADLINE[0] = time.time() + budget
    signal.signal(signal.SIGTERM, _signal_flush)
    signal.signal(signal.SIGINT, _signal_flush)
    signal.signal(signal.SIGALRM, _signal_flush)
    signal.alarm(int(budget) + 60)  # backstop if skip logic miscounts

    # Platform probe in a throwaway subprocess — initializing the TPU in
    # this (parent) process would hold it while children try to attach.
    probe = subprocess.run(
        [sys.executable, "-c",
         "import jax; print(jax.devices()[0].platform)"],
        capture_output=True, text=True, timeout=300)
    ON_TPU[0] = probe.stdout.strip().endswith("tpu")
    on_tpu = ON_TPU[0]

    steps = 48 if on_tpu else 3
    if on_tpu:
        # known winners FIRST, across both grids: even a slow/flaky sweep
        # lands both headline numbers before any budget goes to exploration
        work = [(128, CANDIDATES_128[:1]), (512, CANDIDATES_512[:1]),
                (128, CANDIDATES_128[1:]), (512, CANDIDATES_512[1:])]
    else:
        work = [(128, [(8, "xla", "none", 1, 1, False)])]

    for seq_len, candidates in work:
        _measure_grid(seq_len, candidates, steps, on_tpu)
    # packed-vs-padded pair (round 9): measured after both headline grids
    # so a truncated sweep still lands them first. Phase-2 recipe on TPU
    # (seq 512 is where the flash kernel + block skipping carry the win);
    # the CPU smoke runs a tiny pair so the JSON field always exists.
    if on_tpu:
        _measure_packing_pair(512, steps=24, on_tpu=True, batch=16)
    else:
        _measure_packing_pair(128, steps=2, on_tpu=False, batch=4)
    for seq_len in sorted(BEST):
        print(f"# best seq{seq_len}: {BEST[seq_len]['_info']}",
              file=sys.stderr)

    if 128 not in BEST:
        raise SystemExit("no seq128 benchmark configuration measured")
    emit_final()


if __name__ == "__main__":
    main()
