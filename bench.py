#!/usr/bin/env python
"""Benchmark: BERT-Large MLM seq128 pretraining throughput on one chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "seq/s/chip", "vs_baseline": N}

The reference publishes no measured numbers (README Performance section is
empty; BASELINE.md), so vs_baseline is reported against the north-star
contract in BASELINE.json: >=50% MFU. vs_baseline = achieved_MFU / 0.50 —
1.0 means the 50% target is met exactly; >1.0 beats it.

Methodology matches the reference's training_seq_per_sec (global_batch x
steps / train_time, run_pretraining.py:578-580) measured over the full jitted
train step (fwd + bwd + LAMB update), steady-state after warmup.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

# Peak bf16 FLOP/s per chip by device kind (public figures).
PEAK_FLOPS = {
    "TPU v4": 275e12,
    "TPU v5": 459e12,
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v6e": 918e12,
    "TPU v6": 918e12,
}
DEFAULT_PEAK = 275e12


def flops_per_seq(cfg, seq_len: int, vocab: int) -> float:
    """Analytic fwd+bwd FLOPs for one sequence (6*P_matmul*S for the dense
    matmuls + 12*L*E*S^2 for attention score/value products)."""
    E, F, L = cfg.hidden_size, cfg.intermediate_size, cfg.num_hidden_layers
    per_layer = 4 * E * E + 2 * E * F          # qkv+proj, mlp in+out (matmul params)
    dense = L * per_layer + vocab * E + E * E  # + tied decoder + mlm transform
    return 6.0 * dense * seq_len + 12.0 * L * E * seq_len * seq_len


def main():
    import jax
    import jax.numpy as jnp

    from bert_pytorch_tpu.config import BertConfig, pad_vocab_size
    from bert_pytorch_tpu.models import BertForPreTraining
    from bert_pytorch_tpu.optim import schedulers
    from bert_pytorch_tpu.optim.lamb import lamb, default_weight_decay_mask
    from bert_pytorch_tpu.training import build_pretrain_step, make_sharded_state
    from bert_pytorch_tpu.training.pretrain import stack_microbatches

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    seq_len = 128
    steps = 20 if on_tpu else 3

    base_cfg = BertConfig.from_json_file("configs/bert_large_uncased_config.json")
    if not on_tpu:  # CPU smoke fallback: shrink so the line still prints
        base_cfg = base_cfg.replace(num_hidden_layers=2, hidden_size=256,
                                    intermediate_size=1024,
                                    num_attention_heads=4)
    base_cfg = base_cfg.replace(
        vocab_size=pad_vocab_size(base_cfg.vocab_size, 128),
        attention_impl="auto")

    sched = schedulers.poly_warmup_schedule(6e-3, total_steps=7038,
                                            warmup=0.2843)
    tx = lamb(sched, weight_decay=0.01,
              weight_decay_mask=default_weight_decay_mask)

    def try_bench(batch: int, remat: bool):
        cfg = base_cfg.replace(checkpoint_activations=remat)
        model = BertForPreTraining(cfg, dtype=jnp.bfloat16)
        rng = np.random.RandomState(0)
        ids = rng.randint(5, cfg.vocab_size, (batch, seq_len)).astype(np.int32)
        labels = np.where(rng.random((batch, seq_len)) < 0.15, ids, -1)
        batch_np = {
            "input_ids": ids,
            "token_type_ids": np.zeros_like(ids),
            "attention_mask": np.ones_like(ids),
            "masked_lm_labels": labels.astype(np.int32),
            "next_sentence_labels": rng.randint(0, 2, (batch,)).astype(np.int32),
        }
        stacked = {k: jnp.asarray(v) for k, v in
                   stack_microbatches(batch_np, 1).items()}
        step_fn = build_pretrain_step(model, tx, schedule=sched,
                                      accum_steps=1)

        def init_fn(r):
            return model.init(r, stacked["input_ids"][0],
                              stacked["token_type_ids"][0],
                              stacked["attention_mask"][0])

        state, _ = make_sharded_state(jax.random.PRNGKey(0), init_fn, tx)
        jit_step = jax.jit(step_fn, donate_argnums=(0,))
        for i in range(3):  # compile + warmup
            state, metrics = jit_step(state, stacked, jax.random.PRNGKey(i))
        jax.block_until_ready(state.params)
        t0 = time.time()
        for i in range(steps):
            state, metrics = jit_step(state, stacked,
                                      jax.random.PRNGKey(100 + i))
        jax.block_until_ready(state.params)
        return cfg, batch * steps / (time.time() - t0), metrics

    # HBM varies by chip generation (v4: 32G, v5e/v6e: 16G, v5p: 95G);
    # walk down until a config fits
    candidates = ([(128, False), (64, False), (32, False), (64, True),
                   (32, True), (16, True)] if on_tpu else [(8, False)])
    cfg = seqs_per_sec = metrics = None
    batch = remat = None
    for batch, remat in candidates:
        try:
            cfg, seqs_per_sec, metrics = try_bench(batch, remat)
            break
        except Exception as e:  # OOM -> next candidate
            msg = str(e)
            if "RESOURCE_EXHAUSTED" not in msg and "memory" not in msg.lower():
                raise
            print(f"# batch={batch} remat={remat} OOM; retrying smaller",
                  file=sys.stderr)
    if seqs_per_sec is None:
        raise SystemExit("no benchmark configuration fit in device memory")

    fps = flops_per_seq(cfg, seq_len, cfg.vocab_size)
    # longest matching key wins ('TPU v5e' must not hit 'TPU v5')
    kind = dev.device_kind.lower()
    peak = ([v for k, v in sorted(PEAK_FLOPS.items(),
                                  key=lambda kv: -len(kv[0]))
             if k.lower() in kind] or [DEFAULT_PEAK])[0]
    mfu = seqs_per_sec * fps / peak
    result = {
        "metric": "bert_large_mlm_seq128_train_throughput"
                  if on_tpu else "bench_smoke_cpu",
        "value": round(seqs_per_sec, 2),
        "unit": "seq/s/chip",
        "vs_baseline": round(mfu / 0.50, 4),
    }
    print(json.dumps(result))
    print(f"# device={dev.device_kind} batch={batch} remat={remat} "
          f"steps={steps} mfu={mfu:.3f} loss={float(metrics['loss']):.3f}",
          file=sys.stderr)


if __name__ == "__main__":
    main()
