#!/usr/bin/env python
"""Benchmark: BERT-Large MLM seq128 pretraining throughput on one chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "seq/s/chip", "vs_baseline": N}

The reference publishes no measured numbers (README Performance section is
empty; BASELINE.md), so vs_baseline is reported against the north-star
contract in BASELINE.json: >=50% MFU. vs_baseline = achieved_MFU / 0.50 —
1.0 means the 50% target is met exactly; >1.0 beats it.

Methodology matches the reference's training_seq_per_sec (global_batch x
steps / train_time, run_pretraining.py:578-580) measured over the full jitted
train step (fwd + bwd + LAMB update), steady-state after warmup. Each
batch/remat candidate runs in a fresh subprocess so an OOM attempt cannot
poison the next one's device heap; sync is via a scalar fetch because
block_until_ready does not flush the remote-relay pipeline.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

# Peak bf16 FLOP/s per chip by device kind (public figures).
PEAK_FLOPS = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,   # v5e reports device_kind "TPU v5 lite"
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,   # v6e / Trillium
    "TPU v6e": 918e12,
}
DEFAULT_PEAK = 275e12
SEQ_LEN = 128
MAX_PRED = 20  # phase-1 max_predictions_per_seq (reference phase1 config:4)


def flops_per_seq(cfg, seq_len: int, vocab: int, n_pred: int) -> float:
    """Analytic fwd+bwd FLOPs for one sequence: 6*params*positions for the
    dense matmuls + 12*L*E*S^2 for attention score/value products. The MLM
    transform + tied decoder run only on the n_pred gathered masked positions
    (models/bert.py BertForPreTraining), so their FLOPs scale with n_pred,
    not S — MFU counts FLOPs actually computed."""
    E, F, L = cfg.hidden_size, cfg.intermediate_size, cfg.num_hidden_layers
    per_layer = 4 * E * E + 2 * E * F          # qkv+proj, mlp in+out
    trunk = L * per_layer * seq_len
    head = (vocab * E + E * E) * n_pred        # tied decoder + mlm transform
    return 6.0 * (trunk + head) + 12.0 * L * E * seq_len * seq_len


def run_candidate(batch: int, remat: bool, steps: int, on_tpu: bool) -> dict:
    """Measure one (batch, remat) config; called in the child process."""
    import jax
    import jax.numpy as jnp

    from bert_pytorch_tpu.config import BertConfig, pad_vocab_size
    from bert_pytorch_tpu.models import BertForPreTraining
    from bert_pytorch_tpu.optim import schedulers
    from bert_pytorch_tpu.optim.lamb import lamb, default_weight_decay_mask
    from bert_pytorch_tpu.training import build_pretrain_step, make_sharded_state
    from bert_pytorch_tpu.training.pretrain import stack_microbatches

    here = os.path.dirname(os.path.abspath(__file__))
    cfg = BertConfig.from_json_file(
        os.path.join(here, "configs/bert_large_uncased_config.json"))
    if not on_tpu:  # CPU smoke fallback: shrink so the line still prints
        cfg = cfg.replace(num_hidden_layers=2, hidden_size=256,
                          intermediate_size=1024, num_attention_heads=4)
    # BENCH_* env knobs let perf experiments A/B kernels / dropout / PRNG
    # without editing the file
    attn = os.environ.get("BENCH_ATTN", "auto")
    fused = os.environ.get("BENCH_FUSED", "1") == "1"
    # rbg matches run_pretraining's default (threefry dropout bits cost ~10%
    # of step time on v5e)
    jax.config.update("jax_default_prng_impl",
                      os.environ.get("BENCH_RNG", "rbg"))
    cfg = cfg.replace(vocab_size=pad_vocab_size(cfg.vocab_size, 128),
                      attention_impl=attn, fused_ops=fused,
                      checkpoint_activations=remat,
                      remat_policy=os.environ.get("BENCH_REMAT_POLICY",
                                                  "dots"))
    if os.environ.get("BENCH_DROPOUT", "1") == "0":
        cfg = cfg.replace(hidden_dropout_prob=0.0,
                          attention_probs_dropout_prob=0.0)
    model = BertForPreTraining(cfg, dtype=jnp.bfloat16)

    rng = np.random.RandomState(0)
    ids = rng.randint(5, cfg.vocab_size, (batch, SEQ_LEN)).astype(np.int32)
    # exactly MAX_PRED masked positions per row, like a full phase-1 sample
    labels = np.full((batch, SEQ_LEN), -1, np.int64)
    for b in range(batch):
        pos = rng.choice(SEQ_LEN, MAX_PRED, replace=False)
        labels[b, pos] = ids[b, pos]
    batch_np = {
        "input_ids": ids,
        "token_type_ids": np.zeros_like(ids),
        "attention_mask": np.ones_like(ids),
        "masked_lm_labels": labels.astype(np.int32),
        "next_sentence_labels": rng.randint(0, 2, (batch,)).astype(np.int32),
    }
    stacked = {k: jnp.asarray(v) for k, v in
               stack_microbatches(batch_np, 1).items()}

    sched = schedulers.poly_warmup_schedule(6e-3, total_steps=7038,
                                            warmup=0.2843)
    if os.environ.get("BENCH_OPT") == "sgd":  # optimizer-cost diagnosis only
        import optax

        tx = optax.sgd(sched)
    else:
        tx = lamb(sched, weight_decay=0.01,
                  weight_decay_mask=default_weight_decay_mask)
    step_fn = build_pretrain_step(model, tx, schedule=sched, accum_steps=1,
                                  max_predictions=MAX_PRED)

    def init_fn(r):
        return model.init(r, stacked["input_ids"][0],
                          stacked["token_type_ids"][0],
                          stacked["attention_mask"][0])

    state, _ = make_sharded_state(jax.random.PRNGKey(0), init_fn, tx)
    jit_step = jax.jit(step_fn, donate_argnums=(0,))
    for i in range(3):  # compile + warmup
        state, metrics = jit_step(state, stacked, jax.random.PRNGKey(i))
    float(metrics["loss"])  # scalar fetch = true device sync
    t0 = time.time()
    for i in range(steps):
        state, metrics = jit_step(state, stacked, jax.random.PRNGKey(100 + i))
    loss = float(metrics["loss"])
    dt = time.time() - t0

    dev = jax.devices()[0]
    seqs_per_sec = batch * steps / dt
    fps = flops_per_seq(cfg, SEQ_LEN, cfg.vocab_size, MAX_PRED)
    kind = dev.device_kind.lower()
    # longest matching key wins ('TPU v5 lite' must not hit a 'TPU v5' prefix)
    peak = ([v for k, v in sorted(PEAK_FLOPS.items(),
                                  key=lambda kv: -len(kv[0]))
             if k.lower() in kind] or [DEFAULT_PEAK])[0]
    mfu = seqs_per_sec * fps / peak
    return {
        "metric": ("bert_large_mlm_seq128_train_throughput" if on_tpu
                   else "bench_smoke_cpu"),
        "value": round(seqs_per_sec, 2),
        "unit": "seq/s/chip",
        "vs_baseline": round(mfu / 0.50, 4),
        "_info": {"device": dev.device_kind, "batch": batch, "remat": remat,
                  "steps": steps, "mfu": round(mfu, 4),
                  "loss": round(loss, 3), "dt_s": round(dt, 3)},
    }


def main():
    if "--child" in sys.argv:
        batch = int(sys.argv[sys.argv.index("--batch") + 1])
        remat = "--remat" in sys.argv
        steps = int(sys.argv[sys.argv.index("--steps") + 1])
        on_tpu = "--cpu" not in sys.argv
        result = run_candidate(batch, remat, steps, on_tpu)
        print("BENCH_RESULT " + json.dumps(result), flush=True)
        return

    # Platform probe in a throwaway subprocess — initializing the TPU in
    # this (parent) process would hold it while children try to attach.
    probe = subprocess.run(
        [sys.executable, "-c",
         "import jax; print(jax.devices()[0].platform)"],
        capture_output=True, text=True, timeout=300)
    on_tpu = probe.stdout.strip().endswith("tpu")

    steps = 20 if on_tpu else 3
    # (batch, remat): no-remat candidates first (fastest when they fit), then
    # dots-saveable remat for bigger batches, then full remat as the floor
    candidates = ([(96, False), (64, False), (56, False), (48, False),
                   (40, False), (32, False),
                   (128, True), (96, True), (64, True), (16, True)]
                  if on_tpu else [(8, False)])
    here = os.path.abspath(__file__)
    oom_markers = ("RESOURCE_EXHAUSTED", "Ran out of memory",
                   "Exceeded hbm", "out of memory")
    # Measure EVERY candidate that fits (each in a fresh subprocess so an OOM
    # cannot poison the next one's device heap) and report the fastest —
    # first-fit is not fastest (round-1 lesson: batch 32 won the fit race
    # while 64/128 were never measured).
    measured = []
    for batch, remat in candidates:
        cmd = [sys.executable, here, "--child", "--batch", str(batch),
               "--steps", str(steps)]
        if remat:
            cmd.append("--remat")
        if not on_tpu:
            cmd.append("--cpu")
        try:
            proc = subprocess.run(cmd, capture_output=True, text=True,
                                  timeout=1200)
        except subprocess.TimeoutExpired:
            print(f"# candidate batch={batch} remat={remat} timed out; "
                  "skipping", file=sys.stderr)
            continue
        result = None
        for line in proc.stdout.splitlines():
            if line.startswith("BENCH_RESULT "):
                result = json.loads(line[len("BENCH_RESULT "):])
        if result is not None:
            print(f"# measured {result['_info']}", file=sys.stderr)
            measured.append(result)
            continue
        if not any(m in proc.stderr for m in oom_markers):
            # not a memory failure — a real bug; surface it, don't walk on
            print(proc.stderr[-4000:], file=sys.stderr)
            raise SystemExit(
                f"bench candidate batch={batch} remat={remat} failed with a "
                f"non-OOM error (rc={proc.returncode}); see stderr above")
        print(f"# candidate batch={batch} remat={remat} OOM",
              file=sys.stderr)
    if not measured:
        raise SystemExit("no benchmark configuration fit in device memory")
    best = max(measured, key=lambda r: r["value"])
    info = best.pop("_info", {})
    print(f"# best of {len(measured)} measured candidates: {info}",
          file=sys.stderr)
    print(json.dumps(best))


if __name__ == "__main__":
    main()
