#!/usr/bin/env python
"""Capture a jax.profiler trace of the seq512 bench candidate and print the
per-op time breakdown (top-k ops by self time) using the tensorboard profile
plugin's xplane converter — no TensorBoard UI needed.

Usage: python scripts/profile512.py [--batch 16] [--seq 512] [--steps 10]
                                    [--attn auto] [--out /tmp/bpt_profile]
"""

from __future__ import annotations

import glob
import json
import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)


def arg(name, default=None):
    return (sys.argv[sys.argv.index(name) + 1]
            if name in sys.argv else default)


def main():
    batch = int(arg("--batch", "16"))
    seq = int(arg("--seq", "512"))
    steps = int(arg("--steps", "10"))
    attn = arg("--attn", "auto")
    accum = int(arg("--accum", "1"))
    out = arg("--out", "/tmp/bpt_profile")

    import bench

    # bench traces exactly its steady-state measured window when
    # BENCH_PROFILE_DIR is set (compile/warmup excluded)
    os.environ["BENCH_PROFILE_DIR"] = out
    result = bench.run_candidate(batch=batch, seq_len=seq, steps=steps,
                                 on_tpu=True, attn=attn, remat="none",
                                 unroll=24, accum=accum)
    print("MEASURED", json.dumps(result["_info"]))

    xplanes = glob.glob(os.path.join(out, "**", "*.xplane.pb"),
                        recursive=True)
    if not xplanes:
        print("no xplane.pb captured", file=sys.stderr)
        return
    xplane = max(xplanes, key=os.path.getmtime)
    print(f"# xplane: {xplane}")

    from tensorboard_plugin_profile.convert import raw_to_tool_data as rtd

    data, _ = rtd.xspace_to_tool_data([xplane], "framework_op_stats", {})
    if isinstance(data, bytes):
        data = data.decode("utf-8", "replace")
    with open(os.path.join(out, "op_stats.json"), "w") as f:
        f.write(data)
    # the tool returns gviz JSON; pull out rows = op records
    parsed = json.loads(data)
    for table in (parsed if isinstance(parsed, list) else [parsed]):
        cols = [c.get("label", c.get("id", "?"))
                for c in table.get("cols", [])]
        print("#", " | ".join(cols))
        rows = table.get("rows", [])

        def cell(r, i):
            v = r["c"][i]
            return v.get("v") if isinstance(v, dict) else v

        try:
            t_idx = next(i for i, c in enumerate(cols)
                         if "total_self_time" in c.lower()
                         or c.lower() == "self time")
        except StopIteration:
            t_idx = None
        if t_idx is not None:
            rows = sorted(rows, key=lambda r: -(cell(r, t_idx) or 0))
        for r in rows[:40]:
            print(" | ".join(str(cell(r, i)) for i in range(len(cols))))
        break


if __name__ == "__main__":
    main()
