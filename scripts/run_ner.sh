#!/bin/bash
# CoNLL-2003 NER finetuning with the reference recipe (scripts/run_ner.sh:
# 10-16,50-62): LR 5e-6, 5 epochs, batch 32, seq 128.
set -euo pipefail
CKPT=${1:-results/phase2/pretrain_ckpts}
DATA=${2:-data/conll2003}
OUT=${3:-results/ner}
MODEL_CONFIG=${4:-configs/bert_large_uncased_config.json}
shift $(( $# > 4 ? 4 : $# ))
exec python run_ner.py \
    --train_file "$DATA/train.txt" \
    --val_file "$DATA/valid.txt" \
    --test_file "$DATA/test.txt" \
    --labels O B-PER I-PER B-ORG I-ORG B-LOC I-LOC B-MISC I-MISC \
    --model_config_file "$MODEL_CONFIG" \
    --model_checkpoint "$CKPT" \
    --epochs 5 --lr 5e-6 --batch_size 32 --max_seq_len 128 \
    --output_dir "$OUT" "$@"
