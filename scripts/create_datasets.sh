#!/bin/bash
# Offline dataset build: download -> format -> shard -> vocab -> encode.
# Parity with the reference scripts/create_datasets.sh (:80-142), driving the
# bert_pytorch_tpu.pipeline modules instead of the utils/ scripts. Phase-1
# (seq128) and phase-2 (seq512) encodings are produced for BERT mode
# (next_seq_prob 0.5) and seq512 only for RoBERTa mode (next_seq_prob 0).
#
# Usage: scripts/create_datasets.sh --data_dir DATA [--download] [--format]
#        [--shard] [--vocab] [--encode] [--mode bert|roberta]
set -euo pipefail

DATA_DIR=data
MODE=bert
DO_DOWNLOAD=0; DO_FORMAT=0; DO_SHARD=0; DO_VOCAB=0; DO_ENCODE=0
VOCAB_SIZE=30522
PROCESSES=${PROCESSES:-8}

while [[ $# -gt 0 ]]; do
  case "$1" in
    --data_dir) DATA_DIR="$2"; shift 2 ;;
    --mode) MODE="$2"; shift 2 ;;
    --download) DO_DOWNLOAD=1; shift ;;
    --format) DO_FORMAT=1; shift ;;
    --shard) DO_SHARD=1; shift ;;
    --vocab) DO_VOCAB=1; shift ;;
    --encode) DO_ENCODE=1; shift ;;
    --vocab_size) VOCAB_SIZE="$2"; shift 2 ;;
    *) echo "unknown arg $1"; exit 1 ;;
  esac
done

PY="python -m"

if [[ $DO_DOWNLOAD == 1 ]]; then
  $PY bert_pytorch_tpu.pipeline.download --dataset wikicorpus \
      --output_dir "$DATA_DIR/download"
  $PY bert_pytorch_tpu.pipeline.download --dataset squad \
      --output_dir "$DATA_DIR/download"
  $PY bert_pytorch_tpu.pipeline.download --dataset google_pretrained_weights \
      --output_dir "$DATA_DIR/download"
  # wikiextractor (xml -> <doc> blocks); external tool, as in the reference
  wikiextractor "$DATA_DIR/download/wikicorpus/enwiki-latest-pages-articles.xml" \
      -o "$DATA_DIR/extracted" -b 25M --no-templates
fi

if [[ $DO_FORMAT == 1 ]]; then
  $PY bert_pytorch_tpu.pipeline.format --kind wiki \
      --input_dir "$DATA_DIR/extracted" \
      --output_dir "$DATA_DIR/formatted" --shards 256 \
      --processes "$PROCESSES" --name wiki
fi

if [[ $DO_SHARD == 1 ]]; then
  cat "$DATA_DIR"/formatted/*.txt > "$DATA_DIR/formatted/all.txt"
  $PY bert_pytorch_tpu.pipeline.shard -i "$DATA_DIR/formatted/all.txt" \
      -o "$DATA_DIR/sharded" -b 100M
fi

if [[ $DO_VOCAB == 1 ]]; then
  if [[ $MODE == roberta ]]; then TOK=bpe; OUT="$DATA_DIR/vocab/bpe.json";
  else TOK=wordpiece; OUT="$DATA_DIR/vocab/vocab.txt"; fi
  $PY bert_pytorch_tpu.pipeline.vocab -i "$DATA_DIR/sharded" -o "$OUT" \
      -s "$VOCAB_SIZE" --tokenizer "$TOK"
fi

if [[ $DO_ENCODE == 1 ]]; then
  if [[ $MODE == roberta ]]; then
    # RoBERTa: dynamic masking, no NSP, seq512 only (reference :133-141)
    $PY bert_pytorch_tpu.pipeline.encode --input_dir "$DATA_DIR/sharded" \
        --output_dir "$DATA_DIR/encoded" --vocab_file "$DATA_DIR/vocab/bpe.json" \
        --tokenizer bpe --max_seq_len 512 --next_seq_prob 0 \
        --processes "$PROCESSES"
  else
    # BERT: NSP pairs at seq128 (phase 1) and seq512 (phase 2)
    for LEN in 128 512; do
      $PY bert_pytorch_tpu.pipeline.encode --input_dir "$DATA_DIR/sharded" \
          --output_dir "$DATA_DIR/encoded" \
          --vocab_file "$DATA_DIR/vocab/vocab.txt" \
          --tokenizer wordpiece --max_seq_len "$LEN" --next_seq_prob 0.5 \
          --processes "$PROCESSES"
    done
  fi
fi
