#!/bin/bash
# seq128 budget-map ablations at the r4 winner config (b64 accum32), one
# child at a time on the single chip. Results append to results/ablate128.jsonl
# via the BENCH_RESULT lines in the log.
cd "$(dirname "$0")/.."
OUT=results/ablate128.jsonl
mkdir -p results
run() {
  local label="$1"; shift
  echo "# running $label" >&2
  local line
  line=$(env "$@" python bench.py --child --batch 64 --steps 6 --seq 128 \
         --attn "${ATTN:-xla}" --unroll 24 --accum 32 --remat none 2>/dev/null \
         | grep '^BENCH_RESULT ' | tail -1)
  if [ -n "$line" ]; then
    echo "{\"label\": \"$label\", ${line#BENCH_RESULT \{}" >> "$OUT"
    echo "# $label done: $line" >&2
  else
    echo "{\"label\": \"$label\", \"status\": \"fail\"}" >> "$OUT"
    echo "# $label FAILED" >&2
  fi
}

run no_dropout BENCH_DROPOUT=0
run no_attn_dropout BENCH_ATTN_DROPOUT=0
run no_hidden_dropout BENCH_HIDDEN_DROPOUT=0
ATTN=auto run flash_attn
run sgd BENCH_OPT=sgd
run grad_f32 BENCH_GRAD_DTYPE=f32
echo "# all done" >&2
