#!/usr/bin/env bash
# SLO plane CI gate (docs/OBSERVABILITY.md): prove each alert FIRES
# under its injected fault within one fast-window evaluation, and stays
# SILENT on a clean run — an alert that can't demonstrably fire is
# decoration, and one that fires clean is a pager nobody trusts.
#
# Leg A — corrupt_answers drill: serve every registered task with the
#   canary prober on and `--slo_inject corrupt_answers` scoped to squad,
#   arming after a clean head.
#   (a1) during the clean head: /healthz status == ok, zero alerts
#        firing (clean-run silence);
#   (a2) after the fault arms: the prober's decode-verify catches the
#        drift — probe_squad page alert in /v1/alerts, /healthz flips
#        to failing, and ONLY squad goes unhealthy (the fault is
#        localized, the other four tasks stay ok) — all before any
#        assertion on real traffic;
#   (a3) an uninjected task still answers 200 through the real frontend.
#
# Leg B — error_burst drill: same stack, `--slo_inject error_burst`.
#   (b1) clean head: status ok, no alerts;
#   (b2) after arming, a traffic burst must trip the availability PAGE
#        alert (burn > threshold in BOTH windows) within one fast-window
#        evaluation — deadline-bounded, a miss names the missing alert;
#   (b3) `tools/loadtest.py --require_healthy` against the failing
#        server must refuse to send traffic (exit 3).
#
#   scripts/check_slo.sh
#
# Tiny burn-rate windows (seconds, not the production 5m/1h) keep the
# whole gate fast; the window MATH is identical — configs/slo.json is
# the production-shaped spec, this writes its own miniature one.
set -euo pipefail
cd "$(dirname "$0")/.."

WORK="$(mktemp -d)"
SERVER_PID=""
cleanup() {
    [ -n "$SERVER_PID" ] && kill "$SERVER_PID" 2>/dev/null || true
    rm -rf "$WORK"
}
trap cleanup EXIT

echo "check_slo: building fixture (one checkpoint per task) ..." >&2
python scripts/make_serving_fixture.py --out "$WORK/fixture" >&2
mapfile -t SERVE_ARGS < "$WORK/fixture/serve_args.txt"

# miniature windows: page = 3s/12s @ 2x, ticket = 6s/24s @ 1.5x —
# "one fast-window evaluation" below means ~3s of sustained burn
cat > "$WORK/slo.json" <<'EOF'
{
  "windows": {
    "page": {"short_s": 3, "long_s": 12, "burn_rate": 2.0},
    "ticket": {"short_s": 6, "long_s": 24, "burn_rate": 1.5}
  },
  "serve": [
    {"name": "availability", "kind": "availability", "budget": 0.05,
     "min_events": 3},
    {"name": "latency_p99", "kind": "latency", "bound_ms": 10000,
     "budget": 0.05, "min_events": 3}
  ]
}
EOF

start_server() {  # $1 = port file, rest = extra args
    local port_file="$1"; shift
    python run_server.py --force_cpu \
        "${SERVE_ARGS[@]}" \
        --buckets 32,64 --batch_rows 4 \
        --serve_dtype float32 --packing on \
        --port 0 --host 127.0.0.1 --port_file "$port_file" \
        --slo_config "$WORK/slo.json" --slo_eval_interval_s 0.25 \
        --prober on --probe_interval_s 0.5 \
        "$@" &
    SERVER_PID=$!
    for _ in $(seq 1 600); do
        [ -s "$port_file" ] && break
        kill -0 "$SERVER_PID" 2>/dev/null || {
            echo "check_slo: server died during warmup" >&2
            exit 1
        }
        sleep 0.2
    done
    [ -s "$port_file" ] || { echo "check_slo: server never became ready" >&2; exit 1; }
}

stop_server() {
    kill "$SERVER_PID" 2>/dev/null || true
    wait "$SERVER_PID" 2>/dev/null || true
    SERVER_PID=""
}

# -- leg A: corrupt_answers, caught by the prober and localized ---------------
echo "check_slo: leg A — corrupt_answers drill (prober decode-verify)" >&2
start_server "$WORK/portA" \
    --slo_inject corrupt_answers --slo_inject_task squad \
    --slo_inject_after_s 8
PORT="$(cat "$WORK/portA")"

python - "$PORT" <<'EOF'
import json, sys, time, urllib.request, urllib.error

base = f"http://127.0.0.1:{sys.argv[1]}"

def get(path):
    with urllib.request.urlopen(base + path, timeout=10) as r:
        return json.loads(r.read())

def post(path, body):
    req = urllib.request.Request(
        base + path, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=30) as r:
        return r.status, json.loads(r.read())

# (a1) clean head: the fault arms at warmup+8s — the prober has pinned
# baselines by now and NOTHING may be firing
hz = get("/healthz")
assert hz.get("status") == "ok", \
    f"clean head must report status=ok, got {hz.get('status')!r}"
alerts = get("/v1/alerts")
assert not alerts["firing"], \
    f"clean head fired spuriously: {alerts['firing']}"
print("check_slo: (a1) clean head silent, status=ok", file=sys.stderr)

# (a2) the drift must be caught by the PROBER — before this script
# asserts anything about real traffic
deadline = time.time() + 60
while time.time() < deadline:
    hz = get("/healthz")
    bad = (hz.get("prober") or {}).get("unhealthy_tasks", [])
    if bad:
        break
    time.sleep(0.3)
else:
    raise SystemExit("check_slo: MISSED ALERT — corrupt_answers never "
                     "flipped any probe unhealthy (prober decode-verify "
                     "did not catch the drift)")
assert bad == ["squad"], \
    f"fault injected on squad only, but unhealthy: {bad}"
assert hz["status"] == "failing", hz["status"]
alerts = get("/v1/alerts")
probe = [a for a in alerts["firing"] if a["slo"] == "probe_squad"]
assert probe and probe[0]["severity"] == "page", \
    ("check_slo: MISSED ALERT — probe_squad page alert absent from "
     f"/v1/alerts: {alerts['firing']}")
assert alerts["status"] == "failing", alerts["status"]
print(f"check_slo: (a2) probe_squad page alert firing, status=failing, "
      f"localized to {bad}", file=sys.stderr)

# (a3) the four uninjected tasks still serve real traffic
code, out = post("/v1/ner", {"tokens": ["the", "cat", "sat"]})
assert code == 200 and out.get("labels"), (code, out)
print("check_slo: (a3) uninjected task still answers 200", file=sys.stderr)
EOF
stop_server
echo "check_slo: leg A OK" >&2

# -- leg B: error_burst must trip the availability page alert -----------------
echo "check_slo: leg B — error_burst drill (burn-rate page)" >&2
start_server "$WORK/portB" \
    --slo_inject error_burst --slo_inject_after_s 5
PORT="$(cat "$WORK/portB")"

python - "$PORT" <<'EOF'
import json, sys, time, urllib.request, urllib.error

base = f"http://127.0.0.1:{sys.argv[1]}"

def get(path):
    with urllib.request.urlopen(base + path, timeout=10) as r:
        return json.loads(r.read())

def post_any(path, body):
    req = urllib.request.Request(
        base + path, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status
    except urllib.error.HTTPError as e:
        return e.code
    except OSError:
        return None

# (b1) clean head
hz = get("/healthz")
assert hz.get("status") == "ok", hz.get("status")
assert not get("/v1/alerts")["firing"], "clean head fired spuriously"
print("check_slo: (b1) clean head silent, status=ok", file=sys.stderr)

# wait out the arming delay, then burn: every forward now raises, so
# each request lands outcome=error — the page pair (3s/12s windows,
# 0.25s evaluation) must trip within one fast-window evaluation; the
# 45s deadline is warmup slack, not the window budget
time.sleep(5.5)
t0 = time.time()
fired_at = None
while time.time() - t0 < 45:
    post_any("/v1/ner", {"tokens": ["the", "cat", "sat"]})
    alerts = get("/v1/alerts")
    if any(a["slo"] == "availability" and a["severity"] == "page"
           for a in alerts["firing"]):
        fired_at = time.time() - t0
        break
else:
    raise SystemExit("check_slo: MISSED ALERT — error_burst never "
                     "tripped the availability page alert "
                     f"(firing: {get('/v1/alerts')['firing']})")
hz = get("/healthz")
assert hz["status"] == "failing", hz["status"]
print(f"check_slo: (b2) availability page alert fired {fired_at:.1f}s "
      "into the burst, /healthz failing", file=sys.stderr)
EOF

# (b3) a bench leg against a failing server must refuse to run
RC=0
python tools/loadtest.py --url "http://127.0.0.1:$PORT" \
    --require_healthy --rates 5 --duration 1 --tasks ner \
    --out "$WORK/should_not_exist.json" >/dev/null 2>&1 || RC=$?
if [ "$RC" -ne 3 ]; then
    echo "check_slo: FAIL — loadtest --require_healthy exited $RC against" \
         "a failing server (want 3)" >&2
    exit 1
fi
echo "check_slo: (b3) loadtest --require_healthy refused the failing target (exit 3)" >&2
stop_server

echo "check_slo: OK — clean runs silent; corrupt_answers caught by the prober (localized to squad, page alert + failing status); error_burst tripped the availability page within one fast window; --require_healthy gates on it"
