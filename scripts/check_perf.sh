#!/usr/bin/env bash
# Perf regression gate: compare the newest two MULTICHIP artifacts.
#
#   scripts/check_perf.sh [tolerance]
#
# Runs `tools/perfboard.py --check` (jax-free) over the two
# highest-numbered MULTICHIP_r*.json at the repo root and exits nonzero
# naming every throughput/efficiency metric that moved the wrong way
# beyond the tolerance. Fewer than two measured artifacts -> exit 0
# (nothing to compare is not a regression).
#
# Default tolerance is 0.5: the forced-CPU 8-device mesh these artifacts
# come from measures 20-45% whole-sweep wall-clock noise between sessions
# at IDENTICAL programs (docs/PERF.md round 11), so a tight gate here
# would alarm on the harness, not the code. On real TPU hardware pass an
# explicit tolerance (0.1 is the perfboard default) — chip clocks don't
# wander 45%.
set -euo pipefail
cd "$(dirname "$0")/.."

TOLERANCE="${1:-0.5}"

# newest two by round number (version sort handles r09 -> r10 correctly)
mapfile -t ARTIFACTS < <(ls MULTICHIP_r*.json 2>/dev/null | sort -V | tail -2)
if [ "${#ARTIFACTS[@]}" -lt 2 ]; then
    echo "check_perf: fewer than two MULTICHIP_r*.json artifacts — nothing to compare"
    exit 0
fi

echo "check_perf: ${ARTIFACTS[0]} -> ${ARTIFACTS[1]} (tolerance ${TOLERANCE})"
exec python tools/perfboard.py --check "${ARTIFACTS[0]}" "${ARTIFACTS[1]}" \
    --tolerance "${TOLERANCE}"
