#!/usr/bin/env bash
# Perf regression gate: compare the newest two MULTICHIP artifacts, and —
# when two or more exist — the newest two SERVE artifacts.
#
#   scripts/check_perf.sh [tolerance]
#
# Runs `tools/perfboard.py --check` (jax-free) over the two
# highest-numbered MULTICHIP_r*.json (and SERVE_r*.json) at the repo root
# and exits nonzero naming every metric that moved the wrong way beyond
# the tolerance (throughput/efficiency/occupancy higher-better; serving
# p50/p95/p99 latency lower-better; since round 17 each serving mode's
# saturation block gates too — saturation req/s, req/s-per-chip, and the
# vs-single-replica scale-out ratio higher-better, p99-at-saturation
# lower-better, per (replica count, dtype) mode label so a 2-replica
# regression can't hide behind a 1-replica win; since round 15 the
# traced per-variant
# COLLECTIVE-TIME FRACTION gates lower-better alongside step time — the
# share of device time in collectives is the scaling ceiling the
# collective-time work attacks, and as a ratio it is robust to the CPU
# harness's wall-clock noise). Fewer than two measured artifacts of
# a kind -> that kind is skipped (nothing to compare is not a regression).
#
# Default tolerance is 0.6: the forced-CPU harness these artifacts come
# from measures 20-45% whole-sweep wall-clock noise between sessions at
# IDENTICAL programs (docs/PERF.md round 11), and the scaling-efficiency
# metrics COMPOUND two independent drifts (the n-dev step time and the
# single-chip baseline it is normalized by — r07->r08 measured them
# moving opposite ways, -38% single vs +30% dp_seq, a 53% compound at
# identical programs; docs/PERF.md round 15). A tight gate here would
# alarm on the harness, not the code — the noise-robust quantities
# (collective_fraction ratios, graphcheck's exact collective counts)
# carry the regression signal the wall clocks cannot. On real TPU
# hardware pass an explicit tolerance (0.1 is the perfboard default) —
# chip clocks don't wander 45%.
set -euo pipefail
cd "$(dirname "$0")/.."

TOLERANCE="${1:-0.6}"
RC=0

check_pair() {
    local glob="$1"
    local -a artifacts
    # newest two by round number (version sort handles r09 -> r10)
    mapfile -t artifacts < <(ls $glob 2>/dev/null | sort -V | tail -2)
    if [ "${#artifacts[@]}" -lt 2 ]; then
        echo "check_perf: fewer than two $glob artifacts — nothing to compare"
        return 0
    fi
    echo "check_perf: ${artifacts[0]} -> ${artifacts[1]} (tolerance ${TOLERANCE})"
    python tools/perfboard.py --check "${artifacts[0]}" "${artifacts[1]}" \
        --tolerance "${TOLERANCE}" || RC=1
}

check_pair 'MULTICHIP_r*.json'
check_pair 'SERVE_r*.json'
check_pair 'DISTILL_r*.json'
# Distillation accuracy floor (round 19): the newest DISTILL artifact
# must show every student leg within --distill_max_delta of its
# teacher's accuracy — an absolute quality gate on ONE artifact, so it
# runs even before a second round exists to trend against. Direction-
# aware: students beating the teacher always pass.
NEWEST_DISTILL="$(ls DISTILL_r*.json 2>/dev/null | sort -V | tail -1 || true)"
if [ -n "${NEWEST_DISTILL}" ]; then
    echo "check_perf: distill accuracy floor on ${NEWEST_DISTILL}"
    python tools/perfboard.py --check_distill "${NEWEST_DISTILL}" || RC=1
else
    echo "check_perf: no DISTILL_r*.json — accuracy floor skipped"
fi
# BENCH artifacts joined the gate in round 16 (the input_bench streaming
# block: stream.tokens_per_sec higher-better, stream.data_wait_fraction
# lower-better); metrics absent from one side are notes, not failures,
# so the heterogeneous BENCH history gates only its overlapping keys.
check_pair 'BENCH_r*.json'
exit "$RC"
