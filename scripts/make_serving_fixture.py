#!/usr/bin/env python
"""Build a tiny self-contained serving fixture: vocab + model config +
params-only checkpoints for EVERY registered task.

scripts/serve_bench.sh and scripts/check_serve.sh need checkpoints the
server can restore WITHOUT a training run — this writes them in seconds
by iterating tasks/registry.py (a newly registered task automatically
joins the fixture, and therefore the check_serve CI gate): a
randomly-initialized tiny BERT per task head (structure-faithful: same
heads, padded vocab, either encoder layout) saved under the serving
checkpoint contract ({"params": tree}, which `restore_serving_params`
loads through `restore_either_layout`). Random weights serve garbage
answers but real latency — exactly what a load test measures.

    python scripts/make_serving_fixture.py --out /tmp/fixture
    # -> /tmp/fixture/{vocab.txt, model_config.json, <task>_ckpt/...,
    #    serve_args.txt}

`serve_args.txt` holds the ready-made run_server.py argument list for
the whole battery (one token per line; check_serve.sh consumes it).
The NER head is sized for the canonical 5-label CoNLL set
(`--labels B-PER I-PER B-LOC I-LOC O` on run_server.py).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

NER_LABELS = ["B-PER", "I-PER", "B-LOC", "I-LOC", "O"]
CLASS_NAMES = ["negative", "positive"]
NUM_CHOICES = 2

_VOCAB = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"] + (
    "the cat sat on mat a dog did run in park who what where when how "
    "why fast slow red blue green bert serves packed rows thing to of "
    "and is was . , ?").split()


def _force_cpu() -> None:
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")


def build(out_dir: str, hidden: int = 32, layers: int = 2, heads: int = 4,
          max_pos: int = 128, stacked_params: bool = True,
          max_segments: int = 8) -> dict:
    import jax
    import jax.numpy as jnp

    from bert_pytorch_tpu.config import BertConfig, pad_vocab_size
    from bert_pytorch_tpu.tasks import registry
    from bert_pytorch_tpu.training.checkpoint import CheckpointManager
    from bert_pytorch_tpu.training.state import unbox

    os.makedirs(out_dir, exist_ok=True)
    vocab_path = os.path.join(out_dir, "vocab.txt")
    with open(vocab_path, "w", encoding="utf-8") as f:
        f.write("\n".join(_VOCAB) + "\n")

    model_cfg = {
        "vocab_size": len(_VOCAB), "hidden_size": hidden,
        "num_hidden_layers": layers, "num_attention_heads": heads,
        "intermediate_size": hidden * 2, "max_position_embeddings": max_pos,
        "hidden_dropout_prob": 0.0, "attention_probs_dropout_prob": 0.0,
        "tokenizer": "wordpiece", "vocab_file": vocab_path,
        "fused_ops": False, "attention_impl": "xla",
        "stacked_params": stacked_params,
    }
    cfg_path = os.path.join(out_dir, "model_config.json")
    with open(cfg_path, "w", encoding="utf-8") as f:
        json.dump(model_cfg, f, indent=1, sort_keys=True)
        f.write("\n")

    # mirror run_server.py's model construction exactly (padded vocab,
    # same serve_opts the server will derive from its CLI defaults)
    config = BertConfig.from_json_file(cfg_path)
    config = config.replace(vocab_size=pad_vocab_size(config.vocab_size, 8))
    serve_opts = {"labels": NER_LABELS, "class_names": CLASS_NAMES,
                  "num_choices": NUM_CHOICES, "embed_labels": 2,
                  "max_segments": max_segments}
    sample = jnp.zeros((1, min(64, max_pos)), jnp.int32)
    out = {"vocab": vocab_path, "model_config": cfg_path}
    serve_args = ["--model_config_file", cfg_path,
                  "--vocab_file", vocab_path,
                  "--labels", *NER_LABELS,
                  "--class_names", *CLASS_NAMES,
                  "--num_choices", str(NUM_CHOICES)]
    for task in registry.all_tasks():
        spec = registry.get(task)
        model = spec.build_serving_model(config, jnp.float32, serve_opts)
        params = unbox(model.init(jax.random.PRNGKey(0),
                                  sample, sample, sample)["params"])
        ckpt_dir = os.path.join(out_dir, f"{task}_ckpt")
        mgr = CheckpointManager(ckpt_dir)
        mgr.save(0, {"params": params})
        mgr.close()
        out[f"{task}_ckpt"] = ckpt_dir
        serve_args += ["--task_checkpoint", f"{task}={ckpt_dir}"]
    args_path = os.path.join(out_dir, "serve_args.txt")
    with open(args_path, "w", encoding="utf-8") as f:
        f.write("\n".join(serve_args) + "\n")
    out["serve_args"] = args_path
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", required=True)
    ap.add_argument("--hidden", type=int, default=32)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--max_pos", type=int, default=128)
    ap.add_argument("--max_segments", type=int, default=8)
    ap.add_argument("--unstacked", action="store_true",
                    help="write the fixture in the unstacked encoder "
                         "layout (exercises the cross-layout restore)")
    args = ap.parse_args(argv)
    paths = build(args.out, hidden=args.hidden, layers=args.layers,
                  heads=args.heads, max_pos=args.max_pos,
                  stacked_params=not args.unstacked,
                  max_segments=args.max_segments)
    for k, v in sorted(paths.items()):
        print(f"fixture: {k}: {v}")
    print(f"fixture: ner labels: {' '.join(NER_LABELS)}")
    return 0


if __name__ == "__main__":
    _force_cpu()
    sys.exit(main())
