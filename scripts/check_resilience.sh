#!/usr/bin/env bash
# Resilience CI gate (docs/RESILIENCE.md): run the survival-kit drills
# end-to-end and fail unless the kit actually survives.
#
#   scripts/check_resilience.sh            # both drills, both planes
#   CHECK_RESILIENCE_PLANE=offline scripts/check_resilience.sh
#   CHECK_RESILIENCE_DRILL=sigkill scripts/check_resilience.sh
#
# Drill 1 (sigkill, the headline): a pretraining run is SIGKILLed
# mid-interval, tools/supervise.py restarts it, and the resumed run's
# final params + per-step metric stream must be BIT-identical to an
# uninterrupted run — offline and streaming data planes, --packing on.
# Drill 2 (corrupt): the run dies right after its newest checkpoint is
# byte-flipped; the supervised restart must quarantine `<step>.corrupt`,
# warn naming the failed item, fall back to the next-newest, and still
# converge bit-identically.
#
# tools/resilience_drill.py is the single source of truth; the tier-1
# pytest (tests/test_resilience.py) drives the same functions. This
# script is the standalone gate alongside check_graph.sh/check_serve.sh.
set -euo pipefail
cd "$(dirname "$0")/.."

WORK="$(mktemp -d)"
trap 'rm -rf "$WORK"' EXIT

python tools/resilience_drill.py \
    --drill "${CHECK_RESILIENCE_DRILL:-all}" \
    --plane "${CHECK_RESILIENCE_PLANE:-both}" \
    --workdir "$WORK"

echo "check_resilience: OK — the survival kit survived its own drills"
