#!/usr/bin/env python
"""SQuAD-F1-vs-pretraining-steps curve.

Finetunes + evaluates run_squad.py from each intermediate pretraining
checkpoint (the 'does the quality axis scale with pretraining' evidence the
round-3 verdict asked for). Each point is an independent finetune from
`ckpt_dir@step`, evaluated on the held-out dev set.

Usage:
  python scripts/squad_curve.py --ckpt_dir /root/run_r4/out/pretrain_ckpts \
      --steps 1000 2000 5000 10000 20000 \
      --squad_dir /tmp/squad_r4 --model_config /root/run_r4/model_config.json \
      --vocab /root/run_r4/vocab.txt --out docs/squad/curve_r4.jsonl
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--ckpt_dir", required=True)
    p.add_argument("--steps", type=int, nargs="+", required=True)
    p.add_argument("--squad_dir", required=True)
    p.add_argument("--model_config", required=True)
    p.add_argument("--vocab", required=True)
    p.add_argument("--out", required=True)
    p.add_argument("--lr", type=float, default=5e-5)
    p.add_argument("--epochs", type=float, default=2)
    p.add_argument("--batch", type=int, default=32)
    p.add_argument("--max_seq_length", type=int, default=256)
    p.add_argument("--work_dir", default="/tmp/squad_curve")
    p.add_argument("--v2", action="store_true",
                   help="pass --version_2_with_negative through to "
                        "run_squad.py (dataset must carry is_impossible)")
    args = p.parse_args()

    os.makedirs(os.path.dirname(os.path.abspath(args.out)), exist_ok=True)
    done = set()
    if os.path.exists(args.out):
        with open(args.out) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                    # only successful measurements count — a crashed finetune
                    # must be retried on the next invocation
                    if rec.get("rc") == 0 and "f1" in rec:
                        done.add(rec["pretrain_step"])
                except (ValueError, KeyError):
                    pass

    for step in args.steps:
        if step in done:
            print(f"# step {step}: already measured", file=sys.stderr)
            continue
        outdir = os.path.join(args.work_dir, f"step{step}")
        os.makedirs(outdir, exist_ok=True)
        cmd = [
            sys.executable, os.path.join(REPO, "run_squad.py"),
            "--do_train", "--do_predict", "--do_eval",
            "--init_checkpoint", f"{args.ckpt_dir}@{step}",
            "--train_file", os.path.join(args.squad_dir, "train.json"),
            "--predict_file", os.path.join(args.squad_dir, "dev.json"),
            "--vocab_file", args.vocab,
            "--model_config_file", args.model_config,
            "--learning_rate", str(args.lr),
            "--num_train_epochs", str(args.epochs),
            "--train_batch_size", str(args.batch),
            "--predict_batch_size", str(args.batch),
            "--max_seq_length", str(args.max_seq_length),
            "--output_dir", outdir,
        ]
        if args.v2:
            cmd.append("--version_2_with_negative")
        print(f"# finetuning from step {step} ...", file=sys.stderr,
              flush=True)
        try:
            proc = subprocess.run(cmd, capture_output=True, text=True,
                                  timeout=7200)
        except subprocess.TimeoutExpired:
            rec = {"pretrain_step": step, "rc": -1, "error": "timeout"}
            print(json.dumps(rec), flush=True)
            with open(args.out, "a") as f:
                f.write(json.dumps(rec) + "\n")
            continue
        rec = {"pretrain_step": step, "rc": proc.returncode}
        # run_squad prints the eval dict {"exact_match": ..., "f1": ...}
        for line in proc.stdout.splitlines():
            line = line.strip()
            if line.startswith("{") and "f1" in line:
                try:
                    rec.update(json.loads(line.replace("'", '"')))
                except ValueError:
                    pass
        if proc.returncode != 0:
            rec["stderr_tail"] = proc.stderr[-1500:]
        print(json.dumps(rec), flush=True)
        with open(args.out, "a") as f:
            f.write(json.dumps(rec) + "\n")


if __name__ == "__main__":
    main()
