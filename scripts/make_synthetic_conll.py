#!/usr/bin/env python
"""Generate a CoNLL-format token-classification dataset from local text.

No network egress here, so real CoNLL-2003 is unreachable. Labels are
derived from surface form — numbers tag B-NUM, a closed determiner set tags
B-DET, everything else O — which a token classifier can learn nearly
perfectly from embeddings alone. That makes the dataset a functional
validation of the whole NER path (CoNLL parse, subword label propagation,
[SPC]/-100 ignore positions, masked loss, macro-F1 eval), not a benchmark
of linguistic knowledge.

Usage: python scripts/make_synthetic_conll.py CORPUS_DIR OUT_DIR \
           [--train N] [--eval N]
writes OUT_DIR/{train,valid,test}.txt ("word X X label" lines, blank line
between sentences — reference src/ner_dataset.py:73-84 format).
"""

from __future__ import annotations

import argparse
import os
import re

_DETS = {"the", "a", "an", "this", "that", "these", "those"}
_TOKEN = re.compile(r"\w+|[^\w\s]")


def label_of(tok: str) -> str:
    if any(c.isdigit() for c in tok):
        return "B-NUM"
    if tok.lower() in _DETS:
        return "B-DET"
    return "O"


def sentences(corpus_dir: str):
    for fn in sorted(os.listdir(corpus_dir)):
        if not fn.endswith(".txt"):
            continue
        with open(os.path.join(corpus_dir, fn), encoding="utf-8") as f:
            for line in f:
                toks = _TOKEN.findall(line.strip())
                if 6 <= len(toks) <= 60:
                    yield toks


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("corpus_dir")
    p.add_argument("out_dir")
    p.add_argument("--train", type=int, default=3000)
    p.add_argument("--eval", type=int, default=400)
    args = p.parse_args()

    os.makedirs(args.out_dir, exist_ok=True)
    want = {"train": args.train, "valid": args.eval, "test": args.eval}
    gen = sentences(args.corpus_dir)
    for split, n in want.items():
        path = os.path.join(args.out_dir, f"{split}.txt")
        wrote = 0
        with open(path, "w", encoding="utf-8") as f:
            for toks in gen:
                for t in toks:
                    f.write(f"{t} X X {label_of(t)}\n")
                f.write("\n")
                wrote += 1
                if wrote >= n:
                    break
        print(f"{path}: {wrote} sentences")


if __name__ == "__main__":
    main()
