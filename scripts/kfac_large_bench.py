#!/usr/bin/env python
"""BERT-Large + K-FAC on one chip: step time and HBM fit vs plain LAMB.

The reference's K-FAC recipe runs BERT-Large with local_batch 90 on 40GB
A100s (config/bert_kfac_pretraining_phase1_config.json). This measures the
production configuration on one TPU chip: 24-layer stacked factor/inverse
trees resident next to LAMB state, factor stats every step, Cholesky
inversion every --inv_interval steps (amortized into the measured window).

One arm per invocation (OOM isolation — run under a fresh process per arm):
  python scripts/kfac_large_bench.py --arm kfac --batch 24 --accum 8
  python scripts/kfac_large_bench.py --arm lamb --batch 24 --accum 8
Appends one JSON line per run to results/kfac_large.jsonl.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--arm", choices=["kfac", "lamb"], required=True)
    p.add_argument("--batch", type=int, default=24)
    p.add_argument("--accum", type=int, default=8)
    p.add_argument("--steps", type=int, default=10,
                   help="optimizer steps in the measured window (>= "
                        "inv_interval so one inversion is included)")
    p.add_argument("--inv_interval", type=int, default=10)
    p.add_argument("--remat", default="none")
    p.add_argument("--out", default=os.path.join(REPO, "results",
                                                 "kfac_large.jsonl"))
    args = p.parse_args()

    import jax
    import jax.numpy as jnp

    from bert_pytorch_tpu.config import BertConfig, pad_vocab_size
    from bert_pytorch_tpu.models import BertForPreTraining
    from bert_pytorch_tpu.optim import schedulers
    from bert_pytorch_tpu.optim.lamb import (lamb, default_weight_decay_mask,
                                             default_trust_batch_axes)
    from bert_pytorch_tpu.training import (build_pretrain_step,
                                           make_sharded_state)
    from bert_pytorch_tpu.training.pretrain import (chain_steps,
                                                    stack_microbatches)

    jax.config.update("jax_default_prng_impl", "rbg")
    seq, max_pred = 128, 20
    cfg = BertConfig.from_json_file(
        os.path.join(REPO, "configs/bert_large_uncased_config.json"))
    cfg = cfg.replace(vocab_size=pad_vocab_size(cfg.vocab_size, 128),
                      attention_impl="xla", fused_ops=True,
                      checkpoint_activations=(args.remat != "none"),
                      remat_policy=(args.remat if args.remat != "none"
                                    else "dots"),
                      scan_unroll=24,
                      kfac_taps=(args.arm == "kfac"))
    model = BertForPreTraining(cfg, dtype=jnp.bfloat16)

    rng = np.random.RandomState(0)
    n = args.batch * args.accum
    ids = rng.randint(5, cfg.vocab_size, (n, seq)).astype(np.int32)
    labels = np.full((n, seq), -1, np.int64)
    for b in range(n):
        pos = rng.choice(seq, max_pred, replace=False)
        labels[b, pos] = ids[b, pos]
    batch = {
        "input_ids": ids, "token_type_ids": np.zeros_like(ids),
        "attention_mask": np.ones_like(ids),
        "masked_lm_labels": labels.astype(np.int32),
        "next_sentence_labels": rng.randint(0, 2, (n,)).astype(np.int32),
    }
    stacked = {k: jnp.asarray(v) for k, v in
               stack_microbatches(batch, args.accum).items()}

    sched = schedulers.poly_warmup_schedule(6e-3, total_steps=7038,
                                            warmup=0.2843)
    tx = lamb(sched, weight_decay=0.01,
              weight_decay_mask=default_weight_decay_mask,
              trust_batch_axes=default_trust_batch_axes)

    def init_fn(r):
        return model.init(r, stacked["input_ids"][0],
                          stacked["token_type_ids"][0],
                          stacked["attention_mask"][0])

    state, _ = make_sharded_state(jax.random.PRNGKey(0), init_fn, tx)

    if args.arm == "kfac":
        from bert_pytorch_tpu.optim.kfac import KFAC, KFACConfig
        from bert_pytorch_tpu.training import init_kfac_state
        from bert_pytorch_tpu.training.pretrain import (
            build_kfac_pretrain_step)

        # production knobs: reference kfac phase-1 recipe
        # (bert_kfac_pretraining_phase1_config.json:10-12 + CLI defaults)
        kf = KFAC(KFACConfig(inv_interval=args.inv_interval,
                             factor_interval=1, stat_decay=0.95,
                             damping=0.003, kl_clip=0.001,
                             learning_rate=sched))
        state, pert_template = init_kfac_state(
            model, kf, state,
            (stacked["input_ids"][0], stacked["token_type_ids"][0],
             stacked["attention_mask"][0]))
        step_fn = build_kfac_pretrain_step(
            model, tx, kf, pert_template, schedule=sched,
            accum_steps=args.accum, max_predictions=max_pred,
            grad_dtype=jnp.bfloat16)
    else:
        step_fn = build_pretrain_step(model, tx, schedule=sched,
                                      accum_steps=args.accum,
                                      max_predictions=max_pred,
                                      grad_dtype=jnp.bfloat16)

    multi = jax.jit(chain_steps(step_fn, args.steps), donate_argnums=(0,))
    state, metrics = multi(state, stacked, jax.random.PRNGKey(1))
    float(metrics["loss"])  # compile + warmup (includes first inversion)
    t0 = time.time()
    state, metrics = multi(state, stacked, jax.random.PRNGKey(2))
    loss = float(metrics["loss"])
    dt = time.time() - t0

    dev = jax.devices()[0]
    mem = {}
    try:
        stats = dev.memory_stats() or {}
        mem = {k: int(v) for k, v in stats.items()
               if k in ("bytes_in_use", "peak_bytes_in_use",
                        "bytes_limit")}
    except Exception:
        pass
    rec = {
        "arm": args.arm, "batch": args.batch, "accum": args.accum,
        "steps": args.steps, "inv_interval": args.inv_interval,
        "remat": args.remat, "seq": seq,
        "step_time_s": round(dt / args.steps, 4),
        "seqs_per_sec": round(args.batch * args.accum * args.steps / dt, 2),
        "loss": round(loss, 3), "device": dev.device_kind, "hbm": mem,
    }
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "a") as f:
        f.write(json.dumps(rec) + "\n")
    print("KFAC_LARGE " + json.dumps(rec))


if __name__ == "__main__":
    main()
