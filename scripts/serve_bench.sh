#!/usr/bin/env bash
# Serving bench: fleet scale-out saturation curves per (replica count, dtype).
#
#   scripts/serve_bench.sh [SERVE_rNN.json]
#
# Builds a structure-faithful fixture checkpoint, then for each leg starts
# run_server.py and drives an open-loop geometric rate ramp
# (tools/loadtest.py --rate_sweep) with mixed squad/ner traffic, recording
# the saturation point: the best achieved req/s whose p99 stays under the
# shared latency bound. Legs:
#
#   r1_f32   1 replica,  f32 weights   (the scale-out baseline)
#   r2_f32   2 replicas, f32 weights   (work-stealing dispatcher; the
#                                       vs_single_replica ratio perfboard
#                                       gates comes from this leg)
#   r1_int8  1 replica,  int8 weights  (quantized decode under the same
#                                       sweep; served only if the restore-
#                                       time accuracy gate passes)
#
# The assembled artifact lands in perfboard (results/runs.jsonl + RUNS.md
# serving + saturation tables) and scripts/check_perf.sh gates the newest
# two SERVE rounds.
#
# The traffic is heavy-tailed on purpose (--squad_long_every): dominant
# short requests in the small buckets plus one ~440-word squad context
# (bucket 512, a single sliding window, ~50x the short wave's cost) every
# SERVE_LONG_EVERY-th request, placed mid-leg at the same fraction in
# every rate leg. That mix is what the p99-bound saturation metric is
# sensitive to: a single engine head-of-line blocks short traffic behind
# each long wave, while the fleet's idle replica steals the queued short
# waves and the tail stays flat — the mechanism the r2/r1 ratio measures.
# All-short traffic on this 1-core harness CANNOT show a fleet win (total
# CPU work is conserved across replica counts); rare-long traffic shows
# exactly the win real fleets buy with scale-out.
#
# Env knobs: SERVE_SWEEP (START:FACTOR:MAX geometric ramp), SERVE_P99_BOUND
# (ms — 'at equal p99 bound' is what makes saturation req/s comparable
# across legs), SERVE_DURATION (s/rate), SERVE_BUCKETS, SERVE_ROWS,
# SERVE_LONG_EVERY (long-context injection period),
# SERVE_HIDDEN/SERVE_LAYERS/SERVE_MAX_POS (fixture width/depth/window —
# sized so a wave's forward is long enough that queueing, not Python
# overhead, dominates the tail). CPU-only by design: the numbers are a
# harness-relative A/B (replica counts on identical hardware), not TPU
# headline latency.
set -euo pipefail
cd "$(dirname "$0")/.."

OUT="${1:-SERVE_r02.json}"
SWEEP="${SERVE_SWEEP:-10:1.35:250}"
BOUND="${SERVE_P99_BOUND:-250}"
DURATION="${SERVE_DURATION:-8}"
BUCKETS="${SERVE_BUCKETS:-32,64,512}"
ROWS="${SERVE_ROWS:-4}"
HIDDEN="${SERVE_HIDDEN:-128}"
LAYERS="${SERVE_LAYERS:-4}"
MAX_POS="${SERVE_MAX_POS:-512}"
TASKS="${SERVE_TASKS:-squad,ner}"
LONG_EVERY="${SERVE_LONG_EVERY:-256}"
# per-leg slowest-request traces (Chrome trace format) land beside the
# artifact; tools/trace_summary.py --requests renders tail attribution
TRACE_DIR="${SERVE_TRACE_DIR:-results/serve_traces}"
LABELS="B-PER I-PER B-LOC I-LOC O"

WORK="$(mktemp -d)"
SERVER_PID=""
cleanup() {
    [ -n "$SERVER_PID" ] && kill "$SERVER_PID" 2>/dev/null || true
    rm -rf "$WORK"
}
trap cleanup EXIT

echo "serve_bench: building fixture (hidden=$HIDDEN layers=$LAYERS max_pos=$MAX_POS) ..." >&2
python scripts/make_serving_fixture.py --out "$WORK/fixture" \
    --hidden "$HIDDEN" --layers "$LAYERS" --max_pos "$MAX_POS" >&2

run_leg() {
    local label="$1" replicas="$2" dtype="$3" meta_dtype="$4"
    local port_file="$WORK/port_$label"
    python run_server.py --force_cpu \
        --model_config_file "$WORK/fixture/model_config.json" \
        --vocab_file "$WORK/fixture/vocab.txt" \
        --squad_checkpoint "$WORK/fixture/squad_ckpt" \
        --ner_checkpoint "$WORK/fixture/ner_ckpt" \
        --labels $LABELS \
        --buckets "$BUCKETS" --batch_rows "$ROWS" \
        --serve_dtype "$dtype" --serve_replicas "$replicas" --packing on \
        --port 0 --host 127.0.0.1 --port_file "$port_file" &
    SERVER_PID=$!
    for _ in $(seq 1 900); do
        [ -s "$port_file" ] && break
        kill -0 "$SERVER_PID" 2>/dev/null || {
            echo "serve_bench: server ($label) died during warmup" >&2
            exit 1
        }
        sleep 0.2
    done
    [ -s "$port_file" ] || { echo "serve_bench: server ($label) never became ready" >&2; exit 1; }
    local port; port="$(cat "$port_file")"
    echo "serve_bench: [$label] server warm on :$port" >&2
    python tools/loadtest.py --url "http://127.0.0.1:$port" \
        --label "$label" --rate_sweep "$SWEEP" --p99_bound "$BOUND" \
        --duration "$DURATION" --tasks "$TASKS" \
        --squad_long_every "$LONG_EVERY" \
        --meta "replicas=$replicas" --meta "dtype=$meta_dtype" \
        --meta "n_chips=$replicas" \
        --save_traces "$TRACE_DIR" \
        --out "$WORK/$label.json"
    kill "$SERVER_PID" 2>/dev/null || true
    wait "$SERVER_PID" 2>/dev/null || true
    SERVER_PID=""
}

run_leg r1_f32 1 float32 f32
run_leg r2_f32 2 float32 f32
run_leg r1_int8 1 int8 int8

python tools/loadtest.py --assemble "$OUT" \
    "$WORK/r1_f32.json" "$WORK/r2_f32.json" "$WORK/r1_int8.json"
python tools/loadtest.py --validate "$OUT"
python tools/perfboard.py
echo "serve_bench: wrote $OUT (slowest-request traces in $TRACE_DIR) and reindexed the perf board"
