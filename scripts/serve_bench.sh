#!/usr/bin/env bash
# Serving bench: packed vs padded continuous batching at swept request rates.
#
#   scripts/serve_bench.sh [SERVE_rNN.json]
#
# Builds a tiny structure-faithful fixture checkpoint, starts run_server.py
# twice (--packing on, then off — the SAME compiled programs, only the row
# layout differs), drives open-loop traffic with tools/loadtest.py at each
# rate in SERVE_RATES, and assembles the cross-mode artifact perfboard
# indexes (results/runs.jsonl + RUNS.md serving table) and
# scripts/check_perf.sh gates against the previous round.
#
# Env knobs: SERVE_RATES (default "200,1000" req/s — one sub-saturation
# sweep for latency, one past saturation where occupancy/shedding
# behavior shows), SERVE_DURATION (default 3 s/rate), SERVE_BUCKETS
# (default "32,64,128"), SERVE_ROWS (default 4). CPU-only by design: the
# numbers are a harness-relative A/B (packed vs padded on identical
# hardware), not TPU headline latency.
set -euo pipefail
cd "$(dirname "$0")/.."

OUT="${1:-SERVE_r01.json}"
RATES="${SERVE_RATES:-200,1000}"
DURATION="${SERVE_DURATION:-3}"
BUCKETS="${SERVE_BUCKETS:-32,64,128}"
ROWS="${SERVE_ROWS:-4}"
LABELS="B-PER I-PER B-LOC I-LOC O"

WORK="$(mktemp -d)"
SERVER_PID=""
cleanup() {
    [ -n "$SERVER_PID" ] && kill "$SERVER_PID" 2>/dev/null || true
    rm -rf "$WORK"
}
trap cleanup EXIT

echo "serve_bench: building fixture ..." >&2
python scripts/make_serving_fixture.py --out "$WORK/fixture" >&2

run_mode() {
    local label="$1" packing="$2"
    local port_file="$WORK/port_$label"
    python run_server.py --force_cpu \
        --model_config_file "$WORK/fixture/model_config.json" \
        --vocab_file "$WORK/fixture/vocab.txt" \
        --squad_checkpoint "$WORK/fixture/squad_ckpt" \
        --ner_checkpoint "$WORK/fixture/ner_ckpt" \
        --labels $LABELS \
        --buckets "$BUCKETS" --batch_rows "$ROWS" \
        --serve_dtype float32 --packing "$packing" \
        --port 0 --host 127.0.0.1 --port_file "$port_file" &
    SERVER_PID=$!
    for _ in $(seq 1 600); do
        [ -s "$port_file" ] && break
        kill -0 "$SERVER_PID" 2>/dev/null || {
            echo "serve_bench: server ($label) died during warmup" >&2
            exit 1
        }
        sleep 0.2
    done
    [ -s "$port_file" ] || { echo "serve_bench: server ($label) never became ready" >&2; exit 1; }
    local port; port="$(cat "$port_file")"
    echo "serve_bench: [$label] server warm on :$port" >&2
    python tools/loadtest.py --url "http://127.0.0.1:$port" \
        --label "$label" --rates "$RATES" --duration "$DURATION" \
        --out "$WORK/$label.json"
    kill "$SERVER_PID" 2>/dev/null || true
    wait "$SERVER_PID" 2>/dev/null || true
    SERVER_PID=""
}

run_mode packed on
run_mode padded off

python tools/loadtest.py --assemble "$OUT" "$WORK/packed.json" "$WORK/padded.json"
python tools/loadtest.py --validate "$OUT"
python tools/perfboard.py
echo "serve_bench: wrote $OUT and reindexed the perf board"
