#!/usr/bin/env python
"""Long-context attention benchmark on the real chip.

The reference caps sequences at 512 (config/bert_pretraining_phase2_config
.json); long context is a first-class axis here, carried by two mechanisms:
the Pallas blockwise flash kernel on one chip (memory O(S) instead of the
O(S^2) score matrix) and ring attention over the `seq` mesh axis across
chips (ops/ring_attention.py, exercised on the virtual mesh by
__graft_entry__.dryrun_multichip stage 'ring_seq').

This script measures the single-chip half on hardware: fwd+bwd attention
throughput, flash vs XLA, across S in {512..8192} at BERT-Large head
geometry, and writes results/longcontext/longcontext.jsonl.

Usage: python scripts/longcontext_bench.py [--out results/longcontext]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def attention_flops(b: int, s: int, h: int, d: int) -> float:
    """Fwd+bwd matmul FLOPs: fwd QK^T + PV = 2 * 2*b*h*s*s*d; bwd ~2x fwd
    (dQ, dK, dV, and the recomputed/stored-prob products) = 4 dots."""
    fwd = 2 * 2 * b * h * s * s * d
    bwd = 2 * fwd
    return float(fwd + bwd)


def run_case(impl: str, b: int, s: int, h: int, d: int, steps: int = 20):
    import jax
    import jax.numpy as jnp

    from bert_pytorch_tpu.ops.attention import (_pallas_interpret,
                                                dot_product_attention,
                                                make_attention_bias)

    if impl == "pallas":
        # dot_product_attention silently falls back to XLA when the flash
        # kernel's preconditions fail — refuse to record a mislabeled row
        if s % 128 != 0:
            raise RuntimeError(f"flash kernel needs seq % 128 == 0, got {s}")
        if jax.default_backend() != "tpu" and not _pallas_interpret():
            raise RuntimeError(
                "flash kernel needs the TPU backend (or BPT_PALLAS_INTERPRET "
                "for a CPU machinery test) — this row would silently time "
                "the XLA path")

    rng = np.random.RandomState(0)
    shape = (b, s, h, d)
    q = jnp.asarray(rng.randn(*shape), jnp.bfloat16)
    k = jnp.asarray(rng.randn(*shape), jnp.bfloat16)
    v = jnp.asarray(rng.randn(*shape), jnp.bfloat16)
    bias = make_attention_bias(jnp.ones((b, s), jnp.int32), jnp.bfloat16)

    def loss(q, k, v):
        out = dot_product_attention(
            q, k, v, bias=bias, dropout_rng=None, dropout_rate=0.0,
            deterministic=True, impl=impl)
        return jnp.sum(out.astype(jnp.float32) ** 2)

    grad_fn = jax.jit(jax.value_and_grad(loss, argnums=(0, 1, 2)))
    # compile + warm
    val, grads = grad_fn(q, k, v)
    jax.block_until_ready(grads)
    t0 = time.perf_counter()
    for _ in range(steps):
        val, grads = grad_fn(q, k, v)
    jax.block_until_ready(grads)
    dt = (time.perf_counter() - t0) / steps
    tflops = attention_flops(b, s, h, d) / dt / 1e12
    return {"impl": impl, "batch": b, "seq": s, "heads": h, "head_dim": d,
            "ms_per_step": round(dt * 1e3, 3),
            "tflops_per_sec": round(tflops, 2),
            "value": float(val)}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="results/longcontext")
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--seqs", type=int, nargs="+",
                    default=[512, 1024, 2048, 4096, 8192])
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (machinery smoke test; this "
                         "box's sitecustomize ignores JAX_PLATFORMS, so the "
                         "override must go through jax.config)")
    args = ap.parse_args()

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
        os.environ["BPT_PALLAS_INTERPRET"] = "1"

    dev = jax.devices()[0]
    os.makedirs(args.out, exist_ok=True)
    path = os.path.join(args.out, "longcontext.jsonl")
    records = []
    H, D = 16, 64  # BERT-Large head geometry
    # keep tokens-per-case roughly constant so every case does comparable
    # non-attention work; batch floors at 1
    for s in args.seqs:
        b = max(1, 8192 // s)
        for impl in ("pallas", "xla"):
            try:
                rec = run_case(impl, b, s, H, D, steps=args.steps)
            except Exception as e:  # OOM or lowering failure: record, go on
                rec = {"impl": impl, "batch": b, "seq": s,
                       "error": str(e)[:200]}
            rec["device"] = str(dev.device_kind)
            records.append(rec)
            print(json.dumps(rec))
    with open(path, "w") as f:
        for rec in records:
            f.write(json.dumps(rec) + "\n")
    ok = [r for r in records if "error" not in r]
    by = {}
    for r in ok:
        by.setdefault(r["seq"], {})[r["impl"]] = r
    print("\nseq  flash-TFLOP/s  xla-TFLOP/s  speedup")
    for s in sorted({r["seq"] for r in records}):
        d = by.get(s, {})
        flash = (f"{d['pallas']['tflops_per_sec']:12.1f}" if "pallas" in d
                 else f"{'FAILED':>12}")
        xla = (f"{d['xla']['tflops_per_sec']:11.1f}" if "xla" in d
               else f"{'FAILED':>11}")
        sp = (f"{d['pallas']['tflops_per_sec'] / max(d['xla']['tflops_per_sec'], 1e-9):6.2f}x"
              if "pallas" in d and "xla" in d else "")
        print(f"{s:5d}  {flash}  {xla}  {sp}")


if __name__ == "__main__":
    main()
