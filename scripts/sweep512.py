#!/usr/bin/env python
"""seq512 tuning sweep: runs bench.py --child over a grid of flash block
sizes x batch x remat policy (the policy rides the --remat child flag),
each in a fresh subprocess with per-candidate env (FLASH_BLK_Q/K,
BENCH_DROPOUT, FLASH_BWD).

Appends every measurement to results/sweep512.jsonl so an interrupted sweep
keeps its partial results. Run: python scripts/sweep512.py [--steps 20]
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
BENCH = os.path.join(REPO, "bench.py")
OUT = os.path.join(REPO, "results", "sweep512.jsonl")

# (label, batch, attn, remat, env-overrides)
GRID = [
    ("blk512_b16", 16, "auto", False, {}),
    ("blk256_b16", 16, "auto", False, {"FLASH_BLK_Q": "256", "FLASH_BLK_K": "256"}),
    ("blk256q_512k_b16", 16, "auto", False, {"FLASH_BLK_Q": "256", "FLASH_BLK_K": "512"}),
    ("blk512q_256k_b16", 16, "auto", False, {"FLASH_BLK_Q": "512", "FLASH_BLK_K": "256"}),
    ("blk512_b20", 20, "auto", False, {}),
    ("blk512_b24", 24, "auto", False, {}),
    ("blk512_b24_mlponly", 24, "auto", "mlp_only", {}),
    ("blk512_b32_mlponly", 32, "auto", "mlp_only", {}),
    ("blk512_b32_dots", 32, "auto", "dots", {}),
    ("blk512_b48_mlponly", 48, "auto", "mlp_only", {}),
    # diagnostics: dropout-mask cost and fused-vs-split backward
    ("blk512_b16_nodrop", 16, "auto", False, {"BENCH_DROPOUT": "0"}),
    ("blk512_b16_splitbwd", 16, "auto", False, {"FLASH_BWD": "split"}),
    # ablation budget map: each knob isolates one subsystem's cost
    ("abl_b16_sgd", 16, "auto", False, {"BENCH_OPT": "sgd"}),
    ("abl_b16_xla_ln", 16, "auto", False, {"BENCH_FUSED": "0"}),
    ("abl_b16_no_attn_drop", 16, "auto", False, {"BENCH_ATTN_DROPOUT": "0"}),
    ("abl_b16_no_hidden_drop", 16, "auto", False,
     {"BENCH_HIDDEN_DROPOUT": "0"}),
]

OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Ran out of memory", "Exceeded hbm",
               "out of memory")


def main():
    steps = "20"
    if "--steps" in sys.argv:
        steps = sys.argv[sys.argv.index("--steps") + 1]
    only = None
    if "--only" in sys.argv:
        only = sys.argv[sys.argv.index("--only") + 1].split(",")
    os.makedirs(os.path.dirname(OUT), exist_ok=True)
    done = set()
    if os.path.exists(OUT) and "--fresh" not in sys.argv:
        with open(OUT) as f:
            for line in f:
                try:
                    done.add(json.loads(line)["label"])
                except (ValueError, KeyError):
                    pass

    for label, batch, attn, remat, env_over in GRID:
        if label in done:
            print(f"# {label}: already measured, skipping", file=sys.stderr)
            continue
        if only and label not in only:
            continue
        cmd = [sys.executable, BENCH, "--child", "--batch", str(batch),
               "--steps", steps, "--seq", "512", "--attn", attn,
               "--unroll", "24"]
        cmd += ["--remat", remat if isinstance(remat, str) else "none"]
        env = dict(os.environ, **env_over)
        print(f"# running {label} ...", file=sys.stderr, flush=True)
        try:
            proc = subprocess.run(cmd, capture_output=True, text=True,
                                  timeout=1500, env=env)
        except subprocess.TimeoutExpired:
            rec = {"label": label, "status": "timeout"}
        else:
            rec = {"label": label, "status": "fail",
                   "env": env_over, "batch": batch, "remat": remat}
            for line in proc.stdout.splitlines():
                if line.startswith("BENCH_RESULT "):
                    rec.update(json.loads(line[len("BENCH_RESULT "):]))
                    rec["status"] = "ok"
            if rec["status"] == "fail":
                if any(m in proc.stderr for m in OOM_MARKERS):
                    rec["status"] = "oom"
                else:
                    rec["stderr_tail"] = proc.stderr[-1500:]
        print(json.dumps(rec), flush=True)
        with open(OUT, "a") as f:
            f.write(json.dumps(rec) + "\n")


if __name__ == "__main__":
    main()
