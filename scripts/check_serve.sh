#!/usr/bin/env bash
# Serving CI gate: start the server on an ephemeral port with tiny
# checkpoints for EVERY task in the registry, fire a mixed burst across
# all of them through tools/loadtest.py --task_mix, and fail unless
# (a) the server's served-task set EXACTLY matches registry.all_tasks()
#     (a registered-but-unserved or served-but-unregistered task is a
#     coverage hole, not a warning),
# (b) at least one request came back 2xx, and
# (c) the produced SERVE artifact is schema-valid.
#
# Then two fleet legs (round 17):
# (d) 2-replica mixed burst — /healthz must show BOTH replicas in the
#     fleet table, the burst must answer through the work-stealing
#     dispatcher, and SIGTERM must drain every replica to exit 0;
# (e) int8 smoke — quantized squad+classify serving answers a burst, the
#     offline quantcheck gate passes on clean scales AND trips (exit
#     nonzero) on an injected broken scale: a negative control that the
#     accuracy gate actually gates;
# (f) request tracing (round 18) — the mixed burst must export >=1
#     schema-valid request trace via --save_traces covering the full
#     admit -> queue_wait -> dispatch -> compute -> respond lifecycle,
#     and tools/trace_summary.py --requests must summarize it (exit 0).
#
#   scripts/check_serve.sh
#
# Fast by design (short bursts, tiny fixture) — the measured sweep lives
# in scripts/serve_bench.sh; this only proves the stack serves.
set -euo pipefail
cd "$(dirname "$0")/.."

WORK="$(mktemp -d)"
SERVER_PID=""
cleanup() {
    [ -n "$SERVER_PID" ] && kill "$SERVER_PID" 2>/dev/null || true
    rm -rf "$WORK"
}
trap cleanup EXIT

REGISTRY_TASKS="$(python - <<'EOF'
from bert_pytorch_tpu.tasks.registry import all_tasks
print(",".join(all_tasks()))
EOF
)"
echo "check_serve: registry tasks: $REGISTRY_TASKS" >&2

echo "check_serve: building fixture (one checkpoint per task) ..." >&2
python scripts/make_serving_fixture.py --out "$WORK/fixture" >&2

# serve_args.txt is the fixture's ready-made argument list: config,
# vocab, per-task options, and one --task_checkpoint per registered task
mapfile -t SERVE_ARGS < "$WORK/fixture/serve_args.txt"
python run_server.py --force_cpu \
    "${SERVE_ARGS[@]}" \
    --buckets 32,64 --batch_rows 4 \
    --serve_dtype float32 --packing on \
    --port 0 --host 127.0.0.1 --port_file "$WORK/port" &
SERVER_PID=$!

for _ in $(seq 1 600); do
    [ -s "$WORK/port" ] && break
    kill -0 "$SERVER_PID" 2>/dev/null || {
        echo "check_serve: server died during warmup" >&2
        exit 1
    }
    sleep 0.2
done
[ -s "$WORK/port" ] || { echo "check_serve: server never became ready" >&2; exit 1; }
PORT="$(cat "$WORK/port")"

# coverage gate: served set == registered set, from the live /healthz;
# the machine-readable top-level status (round 20, SLO plane) must read
# "ok" on a clean warm server — operators and tools/loadtest.py
# --require_healthy key off this exact field
SERVED_TASKS="$(python - "$PORT" <<'EOF'
import json, sys, urllib.request
with urllib.request.urlopen(f"http://127.0.0.1:{sys.argv[1]}/healthz",
                            timeout=10) as r:
    doc = json.loads(r.read())
assert doc.get("status") == "ok", \
    f"clean warm server must report status=ok, got {doc.get('status')!r}"
print(",".join(sorted(doc["tasks"])))
EOF
)"
if [ "$SERVED_TASKS" != "$REGISTRY_TASKS" ]; then
    echo "check_serve: FAIL — served tasks [$SERVED_TASKS] != registered" \
         "tasks [$REGISTRY_TASKS] (register the task AND serve it)" >&2
    exit 1
fi
echo "check_serve: server warm on :$PORT serving [$SERVED_TASKS] — firing mixed burst" >&2

# loadtest exits 1 on zero 2xx responses — that IS the gate's second half;
# --task_mix all = every registered task, equal weight
python tools/loadtest.py --url "http://127.0.0.1:$PORT" \
    --label smoke --rates "${CHECK_SERVE_RATE:-15}" \
    --duration "${CHECK_SERVE_DURATION:-2}" --task_mix all \
    --save_traces "$WORK/traces" \
    --out "$WORK/smoke.json"

python tools/loadtest.py --assemble "$WORK/SERVE_smoke.json" "$WORK/smoke.json"
python tools/loadtest.py --validate "$WORK/SERVE_smoke.json"

# leg (f): the burst must have left >=1 schema-valid request trace whose
# span set covers the whole lifecycle — proving the tracing path is live
# end to end (admission, packer, dispatcher, engine, respond), not just
# unit-tested
TRACE_FILE="$WORK/traces/traces_smoke.json"
if [ ! -s "$TRACE_FILE" ]; then
    echo "check_serve: FAIL — mixed burst exported no request traces" \
         "(expected $TRACE_FILE from --save_traces)" >&2
    exit 1
fi
python - "$TRACE_FILE" <<'EOF'
import json, sys
with open(sys.argv[1], encoding="utf-8") as f:
    events = json.load(f)["traceEvents"]
by = {}
for ev in events:
    assert ev["ph"] == "X" and ev["name"].startswith("req/"), ev
    assert isinstance(ev["ts"], (int, float)), ev
    assert isinstance(ev["dur"], (int, float)) and ev["dur"] >= 0, ev
    by.setdefault(ev["args"]["trace_id"], set()).add(ev["name"])
want = {"req/admit", "req/queue_wait", "req/dispatch", "req/compute",
        "req/respond"}
full = [tid for tid, names in by.items() if want <= names]
assert full, (f"no exported trace covers the full lifecycle "
              f"{sorted(want)}; saw {len(by)} trace(s)")
print(f"check_serve: {len(full)}/{len(by)} exported trace(s) cover the "
      "full admit->respond lifecycle", file=sys.stderr)
EOF
python tools/trace_summary.py --requests --trace "$TRACE_FILE" >&2

# graceful drain (docs/RESILIENCE.md): SIGTERM must stop admission,
# finish in-flight requests, flush metrics, and exit 0 — a nonzero exit
# here is a crash, not a drain
echo "check_serve: burst OK — drilling graceful drain (SIGTERM)" >&2
kill -TERM "$SERVER_PID"
DRAIN_RC=0
wait "$SERVER_PID" || DRAIN_RC=$?
SERVER_PID=""
if [ "$DRAIN_RC" -ne 0 ]; then
    echo "check_serve: FAIL — SIGTERM drain exited $DRAIN_RC (want 0)" >&2
    exit 1
fi
echo "check_serve: single-replica leg OK — drilling the 2-replica fleet" >&2

# -- leg (d): 2-replica fleet, mixed burst through the work-stealing
# dispatcher, then a full-fleet SIGTERM drain ---------------------------------
python run_server.py --force_cpu \
    "${SERVE_ARGS[@]}" \
    --buckets 32,64 --batch_rows 4 \
    --serve_dtype float32 --serve_replicas 2 --packing on \
    --port 0 --host 127.0.0.1 --port_file "$WORK/port2" &
SERVER_PID=$!
for _ in $(seq 1 600); do
    [ -s "$WORK/port2" ] && break
    kill -0 "$SERVER_PID" 2>/dev/null || {
        echo "check_serve: 2-replica server died during warmup" >&2
        exit 1
    }
    sleep 0.2
done
[ -s "$WORK/port2" ] || { echo "check_serve: 2-replica server never became ready" >&2; exit 1; }
PORT2="$(cat "$WORK/port2")"

# the /healthz fleet table must show BOTH replicas with their compiled
# bucket sets — a 1-entry table means scale-out silently collapsed
python - "$PORT2" <<'EOF'
import json, sys, urllib.request
with urllib.request.urlopen(f"http://127.0.0.1:{sys.argv[1]}/healthz",
                            timeout=10) as r:
    doc = json.loads(r.read())
reps = doc.get("replicas") or []
assert doc.get("serve_replicas") == 2, doc.get("serve_replicas")
assert len(reps) == 2, f"want 2 replicas in /healthz, got {len(reps)}"
for rep in reps:
    assert rep.get("compiled_buckets"), f"replica missing buckets: {rep}"
print(f"check_serve: /healthz fleet table OK: "
      f"{[rep['name'] for rep in reps]}", file=sys.stderr)
EOF

python tools/loadtest.py --url "http://127.0.0.1:$PORT2" \
    --label smoke2r --rates "${CHECK_SERVE_RATE:-15}" \
    --duration "${CHECK_SERVE_DURATION:-2}" --task_mix all \
    --out "$WORK/smoke2r.json"

echo "check_serve: 2-replica burst OK — drilling full-fleet drain (SIGTERM)" >&2
kill -TERM "$SERVER_PID"
DRAIN_RC=0
wait "$SERVER_PID" || DRAIN_RC=$?
SERVER_PID=""
if [ "$DRAIN_RC" -ne 0 ]; then
    echo "check_serve: FAIL — 2-replica SIGTERM drain exited $DRAIN_RC (want 0)" >&2
    exit 1
fi

# -- leg (e): int8 smoke + quantcheck accuracy gate (positive AND
# negative control) -----------------------------------------------------------
echo "check_serve: drilling int8 quantized serving" >&2
python run_server.py --force_cpu \
    "${SERVE_ARGS[@]}" \
    --buckets 32,64 --batch_rows 4 \
    --serve_dtype int8 --packing on \
    --port 0 --host 127.0.0.1 --port_file "$WORK/port8" &
SERVER_PID=$!
for _ in $(seq 1 600); do
    [ -s "$WORK/port8" ] && break
    kill -0 "$SERVER_PID" 2>/dev/null || {
        echo "check_serve: int8 server died during warmup (accuracy gate trip?)" >&2
        exit 1
    }
    sleep 0.2
done
[ -s "$WORK/port8" ] || { echo "check_serve: int8 server never became ready" >&2; exit 1; }
PORT8="$(cat "$WORK/port8")"
python tools/loadtest.py --url "http://127.0.0.1:$PORT8" \
    --label smoke8 --rates "${CHECK_SERVE_RATE:-15}" \
    --duration "${CHECK_SERVE_DURATION:-2}" --task_mix all \
    --out "$WORK/smoke8.json"
kill "$SERVER_PID" 2>/dev/null || true
wait "$SERVER_PID" 2>/dev/null || true
SERVER_PID=""

# offline gate: clean scales pass ...
python tools/quantcheck.py --force_cpu \
    --model_config_file "$WORK/fixture/model_config.json" \
    --task_checkpoint "squad=$WORK/fixture/squad_ckpt" \
    --task_checkpoint "classify=$WORK/fixture/classify_ckpt" \
    --out "$WORK/quantcheck.json"
# ... and a corrupted scale MUST trip it (exit nonzero) — if the gate
# waves a broken quantization through, the gate itself is the bug
if python tools/quantcheck.py --force_cpu \
    --model_config_file "$WORK/fixture/model_config.json" \
    --task_checkpoint "squad=$WORK/fixture/squad_ckpt" \
    --inject broken_scale >"$WORK/quantcheck_broken.log" 2>&1; then
    echo "check_serve: FAIL — quantcheck passed an injected broken scale" >&2
    cat "$WORK/quantcheck_broken.log" >&2
    exit 1
fi
echo "check_serve: quantcheck gate OK (clean passes, broken scale trips)" >&2

echo "check_serve: OK — all $(echo "$REGISTRY_TASKS" | tr ',' '\n' | wc -l) registered tasks served, burst answered, artifact validates, request traces exported + summarized, SIGTERM drained to exit 0; 2-replica fleet burst + drain OK; int8 smoke + quantcheck gate OK"
