#!/usr/bin/env bash
# Serving CI gate: start the server on an ephemeral port with a tiny
# checkpoint, fire a mixed squad/ner burst through tools/loadtest.py, and
# fail unless (a) at least one request came back 2xx and (b) the produced
# SERVE artifact is schema-valid.
#
#   scripts/check_serve.sh
#
# Fast by design (one server run, one short sweep) — the measured sweep
# lives in scripts/serve_bench.sh; this only proves the stack serves.
set -euo pipefail
cd "$(dirname "$0")/.."

WORK="$(mktemp -d)"
SERVER_PID=""
cleanup() {
    [ -n "$SERVER_PID" ] && kill "$SERVER_PID" 2>/dev/null || true
    rm -rf "$WORK"
}
trap cleanup EXIT

echo "check_serve: building fixture ..." >&2
python scripts/make_serving_fixture.py --out "$WORK/fixture" >&2

python run_server.py --force_cpu \
    --model_config_file "$WORK/fixture/model_config.json" \
    --vocab_file "$WORK/fixture/vocab.txt" \
    --squad_checkpoint "$WORK/fixture/squad_ckpt" \
    --ner_checkpoint "$WORK/fixture/ner_ckpt" \
    --labels B-PER I-PER B-LOC I-LOC O \
    --buckets 32,64 --batch_rows 4 \
    --serve_dtype float32 --packing on \
    --port 0 --host 127.0.0.1 --port_file "$WORK/port" &
SERVER_PID=$!

for _ in $(seq 1 600); do
    [ -s "$WORK/port" ] && break
    kill -0 "$SERVER_PID" 2>/dev/null || {
        echo "check_serve: server died during warmup" >&2
        exit 1
    }
    sleep 0.2
done
[ -s "$WORK/port" ] || { echo "check_serve: server never became ready" >&2; exit 1; }
PORT="$(cat "$WORK/port")"
echo "check_serve: server warm on :$PORT — firing mixed burst" >&2

# loadtest exits 1 on zero 2xx responses — that IS the gate's first half
python tools/loadtest.py --url "http://127.0.0.1:$PORT" \
    --label smoke --rates "${CHECK_SERVE_RATE:-15}" \
    --duration "${CHECK_SERVE_DURATION:-2}" --tasks squad,ner \
    --out "$WORK/smoke.json"

python tools/loadtest.py --assemble "$WORK/SERVE_smoke.json" "$WORK/smoke.json"
python tools/loadtest.py --validate "$WORK/SERVE_smoke.json"

# graceful drain (docs/RESILIENCE.md): SIGTERM must stop admission,
# finish in-flight requests, flush metrics, and exit 0 — a nonzero exit
# here is a crash, not a drain
echo "check_serve: burst OK — drilling graceful drain (SIGTERM)" >&2
kill -TERM "$SERVER_PID"
DRAIN_RC=0
wait "$SERVER_PID" || DRAIN_RC=$?
SERVER_PID=""
if [ "$DRAIN_RC" -ne 0 ]; then
    echo "check_serve: FAIL — SIGTERM drain exited $DRAIN_RC (want 0)" >&2
    exit 1
fi
echo "check_serve: OK — burst answered, artifact validates, SIGTERM drained to exit 0"
