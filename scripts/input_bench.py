#!/usr/bin/env python
"""Host-side input-pipeline throughput benchmark.

Measures what one host CPU can feed: the full PretrainingDataLoader path
(shard-row gather + vectorized dynamic 80/10/10 masking + segment/attention
mask derivation) in seqs/sec, at the phase-1 (seq128) and phase-2 (seq512)
recipes, and compares against per-chip consumption (bench.py headline) times
a pod-slice host's chip count. The reference leaned on 4 forked DataLoader
workers for the same margin (run_pretraining.py:384); here masking is
batch-vectorized numpy, so one thread is the baseline and the
`prefetch_batches` executor path is the headroom knob.

Writes results/input_bench.json and prints one JSON line.

`--stream` additionally measures the STREAMING plane (data/streaming.py,
tokenize-on-the-fly) against the offline HDF5 plane over the SAME corpus
and token budget: the raw text is generated once, encoded offline through
the production pipeline (pipeline/encode.py), and both loaders drain the
identical text. Emits a BENCH-schema artifact (`--bench_out`, e.g.
BENCH_r06.json) with a `stream` block — `stream.tokens_per_sec` (the
unpaced tokenize rate), `stream.data_wait_fraction` (fraction of wall time
a consumer PACED AT THE OFFLINE PLANE'S RATE would starve — 0 means the
streaming plane keeps up with what the HDF5 plane can feed), and the
`vs_hdf5` ratio — indexed by tools/perfboard.py into RUNS.md and gated by
scripts/check_perf.sh.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def write_shard(path: str, n: int, seq: int, seed: int = 0) -> None:
    import h5py

    rng = np.random.RandomState(seed)
    ids = rng.randint(5, 30000, (n, seq)).astype(np.int32)
    ids[:, 0] = 1
    sep1, sep2 = seq // 2, seq - 4
    ids[:, sep1] = 2
    ids[:, sep2] = 2
    ids[:, sep2 + 1:] = 0
    specials = np.tile([0, sep1, sep2], (n, 1)).astype(np.int32)
    labels = rng.randint(0, 2, (n,)).astype(np.int8)
    with h5py.File(path, "w") as f:
        f.create_dataset("input_ids", data=ids, compression="gzip")
        f.create_dataset("special_token_positions", data=specials,
                         compression="gzip")
        f.create_dataset("next_sentence_labels", data=labels,
                         compression="gzip")


def measure(seq: int, batch: int, max_pred: int, n_rows: int = 16384,
            n_shards: int = 4, prefetch_batches: int = 0) -> dict:
    from bert_pytorch_tpu.data.sharded import (HostShardSampler,
                                               PretrainingDataLoader,
                                               ShardIndex)

    with tempfile.TemporaryDirectory() as td:
        files = []
        for s in range(n_shards):
            p = os.path.join(td, f"shard{s}.hdf5")
            write_shard(p, n_rows // n_shards, seq, seed=s)
            files.append(p)
        index = ShardIndex(files)
        sampler = HostShardSampler(len(index))
        loader = PretrainingDataLoader(
            index, sampler, batch_size=batch, mask_token_index=3,
            max_pred_per_seq=max_pred, masked_lm_prob=0.15,
            vocab_size=30522, seed=0,
            prefetch_batches=prefetch_batches)
        # time the WHOLE epoch including the first batch: starting the clock
        # after a warmup next() would let the prefetch queue pre-assemble
        # batches for free and overstate the prefetch rows. Shard IO is part
        # of the measured path (it is part of the production path too).
        t0 = time.time()
        n_seqs = 0
        for b in loader:
            n_seqs += b["input_ids"].shape[0]
        dt = time.time() - t0
        loader.close()
    return {"seq": seq, "batch": batch, "max_pred": max_pred,
            "prefetch_batches": prefetch_batches,
            "host_seqs_per_sec": round(n_seqs / dt, 1),
            "n_seqs": n_seqs, "dt_s": round(dt, 3)}


# -- streaming-vs-HDF5 pair (round 16) ----------------------------------------

# word list for the synthetic raw-text corpus; the matching WordPiece vocab
# is specials + these words, so tokenization is loss-free and the offline
# encoder can re-encode the identical text
_WORDS = ["alpha", "bravo", "charlie", "delta", "echo", "foxtrot", "golf",
          "hotel", "india", "juliet", "kilo", "lima", "mike", "november",
          "oscar", "papa", "quebec", "romeo", "sierra", "tango", "uniform",
          "victor", "whiskey", "xray", "yankee", "zulu"]


def write_text_corpus(dirpath: str, n_docs: int, seed: int = 0) -> list:
    """Blank-line-delimited synthetic documents (pipeline/format.py
    contract) plus a matching vocab.txt; returns the corpus file list."""
    rng = np.random.RandomState(seed)
    os.makedirs(dirpath, exist_ok=True)
    files = []
    for f in range(2):
        lines = []
        for _ in range(n_docs // 2):
            for _ in range(rng.randint(3, 8)):
                lines.append(" ".join(
                    rng.choice(_WORDS, rng.randint(6, 20))))
            lines.append("")
        path = os.path.join(dirpath, f"corpus_{f}.txt")
        with open(path, "w", encoding="utf-8") as fh:
            fh.write("\n".join(lines))
        files.append(path)
    vocab = os.path.join(dirpath, "vocab.txt")
    with open(vocab, "w", encoding="utf-8") as fh:
        fh.write("\n".join(["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"]
                           + _WORDS) + "\n")
    return files


def measure_stream_pair(seq: int, batch: int, max_pred: int,
                        n_docs: int = 800, workers: int = 2) -> dict:
    """The satellite pair: offline-encode a synthetic corpus once, then
    drain the SAME text through both planes. Returns the BENCH `stream`
    block."""
    from bert_pytorch_tpu.data.sharded import (HostShardSampler,
                                               PretrainingDataLoader,
                                               ShardIndex)
    from bert_pytorch_tpu.data.streaming import (StreamingPretrainingLoader,
                                                 discover_sources)
    from bert_pytorch_tpu.data.tokenization import get_wordpiece_tokenizer
    from bert_pytorch_tpu.pipeline.encode import encode_file

    with tempfile.TemporaryDirectory() as td:
        corpus_dir = os.path.join(td, "corpus")
        files = write_text_corpus(corpus_dir, n_docs)
        vocab = os.path.join(corpus_dir, "vocab.txt")
        tokenizer = get_wordpiece_tokenizer(vocab)
        vocab_size = tokenizer.get_vocab_size()

        # offline plane: the production encoder over the identical text
        hdf5_dir = os.path.join(td, "encoded")
        os.makedirs(hdf5_dir)
        shards = []
        for i, path in enumerate(files):
            out = os.path.join(hdf5_dir, f"train_{i}.hdf5")
            encode_file(path, out, tokenizer, max_seq_len=seq,
                        next_seq_prob=0.0, short_seq_prob=0.0, seed=i)
            shards.append(out)

        index = ShardIndex(shards)
        sampler = HostShardSampler(len(index))
        hdf5_loader = PretrainingDataLoader(
            index, sampler, batch_size=batch, mask_token_index=4,
            max_pred_per_seq=max_pred, masked_lm_prob=0.15,
            vocab_size=vocab_size, seed=0, prefetch_batches=2)
        t0 = time.time()
        hdf5_tokens = 0
        for b in hdf5_loader:
            hdf5_tokens += int(b["attention_mask"].sum())
        hdf5_dt = max(time.time() - t0, 1e-9)
        hdf5_loader.close()
        if hdf5_tokens == 0:
            raise SystemExit(
                f"input_bench: corpus too small — {len(index)} encoded "
                f"examples yield zero full batches of {batch}; raise "
                "--stream_docs or lower --stream_batch")
        hdf5_rate = hdf5_tokens / hdf5_dt

        def make_stream():
            return StreamingPretrainingLoader(
                discover_sources(corpus_dir), tokenizer,
                batch_size=batch, seq_len=seq, mask_token_index=4,
                max_pred_per_seq=max_pred, masked_lm_prob=0.15,
                vocab_size=vocab_size, seed=0, num_workers=workers,
                prefetch_batches=2)

        # unpaced drain: the plane's raw tokenize throughput
        lo = make_stream()
        t0 = time.time()
        stream_tokens = 0
        for b in lo:
            stream_tokens += int(b["attention_mask"].sum())
        stream_dt = max(time.time() - t0, 1e-9)
        lo.close()
        stream_rate = stream_tokens / stream_dt

        # paced drain: consume at the OFFLINE plane's measured rate and
        # report the fraction of wall time the consumer starved — 0 means
        # streaming keeps up with what sharded-HDF5 could feed
        lo = make_stream()
        it = iter(lo)
        wait = 0.0
        t0 = time.time()
        while True:
            w0 = time.perf_counter()
            try:
                b = next(it)
            except StopIteration:
                break
            wait += time.perf_counter() - w0
            time.sleep(int(b["attention_mask"].sum()) / hdf5_rate)
        paced_dt = max(time.time() - t0, 1e-9)
        lo.close()

    return {
        "seq": seq, "batch": batch, "max_pred": max_pred,
        "workers": workers, "n_docs": n_docs,
        "corpus_tokens": stream_tokens,
        "tokens_per_sec": round(stream_rate, 1),
        "hdf5_tokens_per_sec": round(hdf5_rate, 1),
        "vs_hdf5": round(stream_rate / hdf5_rate, 4),
        "data_wait_fraction": round(wait / paced_dt, 4),
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--chip_seq128", type=float, default=434.0,
                    help="measured per-chip consumption at seq128 (bench.py)")
    ap.add_argument("--chip_seq512", type=float, default=97.1)
    ap.add_argument("--chips_per_host", type=int, default=8,
                    help="v5e pod slices serve up to 8 chips per host")
    ap.add_argument("--out", default=None,
                    help="results json (default results/input_bench.json; "
                         "--stream mode defaults to "
                         "results/input_bench_stream.json so the two "
                         "sweeps' different schemas never clobber each "
                         "other)")
    ap.add_argument("--stream", action="store_true",
                    help="measure the streaming-vs-HDF5 pair instead of "
                         "the offline sweep (same corpus, same token "
                         "budget)")
    ap.add_argument("--stream_docs", type=int, default=800)
    ap.add_argument("--stream_seq", type=int, default=128)
    ap.add_argument("--stream_batch", type=int, default=256)
    ap.add_argument("--stream_workers", type=int, default=2)
    ap.add_argument("--bench_out", default=None,
                    help="also write a BENCH-schema artifact (e.g. "
                         "BENCH_r06.json) for tools/perfboard.py indexing "
                         "and the scripts/check_perf.sh gate")
    args = ap.parse_args()
    out_path = args.out or os.path.join(
        REPO, "results",
        "input_bench_stream.json" if args.stream else "input_bench.json")

    if args.stream:
        block = measure_stream_pair(args.stream_seq, args.stream_batch,
                                    max_pred=20, n_docs=args.stream_docs,
                                    workers=args.stream_workers)
        artifact = {"kind": "input_bench_stream", "rc": 0, "ok": True,
                    "stream": block}
        print(json.dumps(artifact))
        os.makedirs(os.path.dirname(os.path.abspath(out_path)),
                    exist_ok=True)
        with open(out_path, "w", encoding="utf-8") as f:
            json.dump(artifact, f, indent=1)
        if args.bench_out:
            with open(args.bench_out, "w", encoding="utf-8") as f:
                json.dump(artifact, f, indent=1, sort_keys=True)
                f.write("\n")
        return

    rows = []
    for seq, batch, max_pred in ((128, 2048, 20), (512, 512, 80)):
        for pf in (0, 2):
            rows.append(measure(seq, batch, max_pred,
                                n_rows=16384 if seq == 128 else 4096,
                                prefetch_batches=pf))
            print(f"# {rows[-1]}", file=sys.stderr)

    need128 = args.chip_seq128 * args.chips_per_host
    need512 = args.chip_seq512 * args.chips_per_host
    best128 = max(r["host_seqs_per_sec"] for r in rows if r["seq"] == 128)
    best512 = max(r["host_seqs_per_sec"] for r in rows if r["seq"] == 512)
    out = {
        "rows": rows,
        "consumption_seq128_per_host": need128,
        "consumption_seq512_per_host": need512,
        "margin_seq128": round(best128 / need128, 2),
        "margin_seq512": round(best512 / need512, 2),
    }
    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    with open(out_path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({k: v for k, v in out.items() if k != "rows"}))


if __name__ == "__main__":
    main()
