#!/usr/bin/env python
"""Host-side input-pipeline throughput benchmark.

Measures what one host CPU can feed: the full PretrainingDataLoader path
(shard-row gather + vectorized dynamic 80/10/10 masking + segment/attention
mask derivation) in seqs/sec, at the phase-1 (seq128) and phase-2 (seq512)
recipes, and compares against per-chip consumption (bench.py headline) times
a pod-slice host's chip count. The reference leaned on 4 forked DataLoader
workers for the same margin (run_pretraining.py:384); here masking is
batch-vectorized numpy, so one thread is the baseline and the
`prefetch_batches` executor path is the headroom knob.

Writes results/input_bench.json and prints one JSON line.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def write_shard(path: str, n: int, seq: int, seed: int = 0) -> None:
    import h5py

    rng = np.random.RandomState(seed)
    ids = rng.randint(5, 30000, (n, seq)).astype(np.int32)
    ids[:, 0] = 1
    sep1, sep2 = seq // 2, seq - 4
    ids[:, sep1] = 2
    ids[:, sep2] = 2
    ids[:, sep2 + 1:] = 0
    specials = np.tile([0, sep1, sep2], (n, 1)).astype(np.int32)
    labels = rng.randint(0, 2, (n,)).astype(np.int8)
    with h5py.File(path, "w") as f:
        f.create_dataset("input_ids", data=ids, compression="gzip")
        f.create_dataset("special_token_positions", data=specials,
                         compression="gzip")
        f.create_dataset("next_sentence_labels", data=labels,
                         compression="gzip")


def measure(seq: int, batch: int, max_pred: int, n_rows: int = 16384,
            n_shards: int = 4, prefetch_batches: int = 0) -> dict:
    from bert_pytorch_tpu.data.sharded import (HostShardSampler,
                                               PretrainingDataLoader,
                                               ShardIndex)

    with tempfile.TemporaryDirectory() as td:
        files = []
        for s in range(n_shards):
            p = os.path.join(td, f"shard{s}.hdf5")
            write_shard(p, n_rows // n_shards, seq, seed=s)
            files.append(p)
        index = ShardIndex(files)
        sampler = HostShardSampler(len(index))
        loader = PretrainingDataLoader(
            index, sampler, batch_size=batch, mask_token_index=3,
            max_pred_per_seq=max_pred, masked_lm_prob=0.15,
            vocab_size=30522, seed=0,
            prefetch_batches=prefetch_batches)
        # time the WHOLE epoch including the first batch: starting the clock
        # after a warmup next() would let the prefetch queue pre-assemble
        # batches for free and overstate the prefetch rows. Shard IO is part
        # of the measured path (it is part of the production path too).
        t0 = time.time()
        n_seqs = 0
        for b in loader:
            n_seqs += b["input_ids"].shape[0]
        dt = time.time() - t0
        loader.close()
    return {"seq": seq, "batch": batch, "max_pred": max_pred,
            "prefetch_batches": prefetch_batches,
            "host_seqs_per_sec": round(n_seqs / dt, 1),
            "n_seqs": n_seqs, "dt_s": round(dt, 3)}


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--chip_seq128", type=float, default=434.0,
                    help="measured per-chip consumption at seq128 (bench.py)")
    ap.add_argument("--chip_seq512", type=float, default=97.1)
    ap.add_argument("--chips_per_host", type=int, default=8,
                    help="v5e pod slices serve up to 8 chips per host")
    ap.add_argument("--out", default=os.path.join(REPO, "results",
                                                  "input_bench.json"))
    args = ap.parse_args()

    rows = []
    for seq, batch, max_pred in ((128, 2048, 20), (512, 512, 80)):
        for pf in (0, 2):
            rows.append(measure(seq, batch, max_pred,
                                n_rows=16384 if seq == 128 else 4096,
                                prefetch_batches=pf))
            print(f"# {rows[-1]}", file=sys.stderr)

    need128 = args.chip_seq128 * args.chips_per_host
    need512 = args.chip_seq512 * args.chips_per_host
    best128 = max(r["host_seqs_per_sec"] for r in rows if r["seq"] == 128)
    best512 = max(r["host_seqs_per_sec"] for r in rows if r["seq"] == 512)
    out = {
        "rows": rows,
        "consumption_seq128_per_host": need128,
        "consumption_seq512_per_host": need512,
        "margin_seq128": round(best128 / need128, 2),
        "margin_seq512": round(best512 / need512, 2),
    }
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({k: v for k, v in out.items() if k != "rows"}))


if __name__ == "__main__":
    main()
