#!/usr/bin/env python
"""One-off fixture generator: produce tiny HDF5 shards + golden outputs with
THE REFERENCE'S OWN CODE (/root/reference), committed under tests/fixtures.

Run offline where the reference checkout exists:
    python scripts/make_reference_fixtures.py [--ref /root/reference]

Outputs (committed; the test suite never needs the reference checkout):
  tests/fixtures/ref_dynamic.hdf5   — written by the reference's
      utils/encode_data.write_samples_to_hdf5 (its real writer: key names,
      i4 dtype, gzip) from TrainingSample objects
  tests/fixtures/ref_legacy.hdf5    — premasked NVIDIA schema per the
      reference reader src/dataset.py:183-192 (the reference ships no writer
      for this format; schema transcribed from its reader)
  tests/fixtures/ref_expected.npz   — the reference
      ShardedPretrainingDataset's actual __getitem__ outputs over both files
      (masked_input_ids / segment_ids / input_mask / masked_lm_labels /
      next_sentence_labels, src/dataset.py:141-199), np.random seeded for
      the dynamic path

tests/test_data.py::test_reference_golden_files then asserts this
framework's loader reproduces the reference's tensors from the same bytes —
the "drop-in data compatibility" claim, proven instead of asserted.
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SEQ = 32
N = 8
VOCAB = 64
MASK_ID = 3


class _IdentityTokenizer:
    """token_to_id stub: samples carry integer-string tokens; [CLS]/[SEP]
    map to the standard test ids 1/2."""

    def token_to_id(self, tok):
        return {"[CLS]": 1, "[SEP]": 2}.get(tok, None) \
            if not tok.isdigit() else int(tok)


def build_samples(encode_data):
    """TrainingSample objects (the reference writer's input type): it adds
    [CLS]/[SEP] and computes special_token_positions itself."""
    rng = np.random.RandomState(42)
    samples = []
    for i in range(N):
        body = SEQ - 4  # leave a [CLS], two [SEP] and 1 padding slot
        first = body // 2
        toks = [str(t) for t in rng.randint(5, VOCAB, body)]
        s = encode_data.TrainingSample(
            seq_tokens=toks[:first],
            next_seq_tokens=toks[first:],
            is_random_next=bool(rng.randint(0, 2)),
        )
        samples.append(s)
    return samples


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--ref", default="/root/reference")
    args = ap.parse_args()

    sys.path.insert(0, os.path.join(args.ref, "utils"))
    sys.path.insert(0, args.ref)
    import encode_data  # the reference's own writer (utils/encode_data.py)
    from src.dataset import ShardedPretrainingDataset  # reference reader

    outdir = os.path.join(REPO, "tests", "fixtures")
    os.makedirs(outdir, exist_ok=True)
    dyn_path = os.path.join(outdir, "ref_dynamic.hdf5")
    leg_path = os.path.join(outdir, "ref_legacy.hdf5")

    # --- dynamic-format shard via the reference's writer --------------------
    samples = build_samples(encode_data)
    # the writer pops from the list; keep a copy for provenance checks
    encode_data.write_samples_to_hdf5(dyn_path, list(samples),
                                      _IdentityTokenizer(), SEQ)

    # --- legacy premasked shard per the reference reader's schema -----------
    import h5py

    rng = np.random.RandomState(7)
    ids = rng.randint(5, VOCAB, (N, SEQ)).astype(np.int32)
    ids[:, 0] = 1
    ids[:, SEQ - 2] = 2
    ids[:, SEQ - 1] = 0
    segs = np.zeros_like(ids)
    segs[:, SEQ // 2:SEQ - 1] = 1
    mask = (ids != 0).astype(np.int32)
    n_pred = 4
    pos = np.zeros((N, n_pred + 1), np.int32)   # trailing 0 = padding slot
    mids = np.zeros((N, n_pred + 1), np.int32)
    for r in range(N):
        p = rng.choice(np.arange(2, SEQ - 2), n_pred, replace=False)
        p.sort()
        pos[r, :n_pred] = p
        mids[r, :n_pred] = ids[r, p]
        ids[r, p] = MASK_ID  # premasked: file carries masked ids
    labels = rng.randint(0, 2, (N,)).astype(np.int8)
    with h5py.File(leg_path, "w") as f:
        f.create_dataset("input_ids", data=ids, dtype="i4")
        f.create_dataset("segment_ids", data=segs, dtype="i4")
        f.create_dataset("input_mask", data=mask, dtype="i4")
        f.create_dataset("masked_lm_positions", data=pos, dtype="i4")
        f.create_dataset("masked_lm_ids", data=mids, dtype="i4")
        f.create_dataset("next_sentence_labels", data=labels, dtype="i1")

    # --- golden outputs from the reference reader ---------------------------
    expected = {}
    for tag, path in (("dynamic", dyn_path), ("legacy", leg_path)):
        ds = ShardedPretrainingDataset(
            files=[path], mask_token_index=MASK_ID, max_pred_per_seq=5,
            masked_lm_prob=0.15, vocab_size=VOCAB)
        np.random.seed(1234)  # _mask_input draws from global np.random
        fields = [[], [], [], [], []]
        for i in range(len(ds)):
            row = ds[i]
            for j, arr in enumerate(row):
                fields[j].append(np.asarray(arr))
        names = ("masked_input_ids", "segment_ids", "input_mask",
                 "masked_lm_labels", "next_sentence_labels")
        for name, vals in zip(names, fields):
            expected[f"{tag}_{name}"] = np.stack(vals)

    np.savez_compressed(os.path.join(outdir, "ref_expected.npz"), **expected)
    print("wrote", dyn_path, leg_path, "and ref_expected.npz")
    for k, v in expected.items():
        print(f"  {k}: {v.shape} {v.dtype}")


if __name__ == "__main__":
    main()
