#!/usr/bin/env bash
# Distillation CI gate (CPU, minutes): the whole student factory proves
# itself end to end on a tiny marker task —
#
# (a) a teacher finetunes on the marker classify task (run_finetune.py),
# (b) run_distill.py trains a narrower/shallower student from it
#     (packed, tap losses + width-bridging projections) and the logged
#     KD-mix train loss DECREASES (first vs last telemetry record),
# (c) the student checkpoint serves through run_server.py with ITS OWN
#     model_config.json; /healthz reports per-task model_params > 0 and
#     the student's param count is strictly below the teacher's
#     (compression, not relabeling), and a loadtest burst answers 2xx
#     with --model_tag stamped into the mode artifact,
# (d) teacher + student legs assemble into a DISTILL artifact
#     (loadtest --assemble --kind distill) carrying accuracy deltas and
#     vs_teacher_per_chip, schema-valid,
# (e) perfboard --check_distill PASSES on the clean student and TRIPS
#     (exit nonzero) on `run_distill.py --inject broken_student` — the
#     negative control that the accuracy floor actually gates.
#
#   scripts/check_distill.sh
#
# Fast by design (tiny model, short bursts) — the measured sweep lives
# in scripts/distill_bench.sh.
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu

WORK="$(mktemp -d)"
SERVER_PID=""
cleanup() {
    [ -n "$SERVER_PID" ] && kill "$SERVER_PID" 2>/dev/null || true
    rm -rf "$WORK"
}
trap cleanup EXIT

echo "check_distill: building marker-task fixture ..." >&2
python - "$WORK" <<'EOF'
import json, sys
import numpy as np
work = sys.argv[1]
VOCAB = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"] + (
    "the cat sat on mat a dog did run in park fast slow red blue "
    "green and is was to of thing . , ?").split()
open(f"{work}/vocab.txt", "w").write("\n".join(VOCAB) + "\n")
cfg = {"vocab_size": len(VOCAB), "hidden_size": 32,
       "num_hidden_layers": 2, "num_attention_heads": 4,
       "intermediate_size": 64, "max_position_embeddings": 64,
       "hidden_dropout_prob": 0.0, "attention_probs_dropout_prob": 0.0,
       "fused_ops": False, "attention_impl": "xla", "lowercase": True,
       "tokenizer": "wordpiece", "vocab_file": f"{work}/vocab.txt"}
json.dump(cfg, open(f"{work}/model_config.json", "w"))
rng = np.random.RandomState(0)
words = [w for w in VOCAB if not w.startswith("[")]
sent = lambda n: " ".join(rng.choice(words, n))
for split, n in (("train", 32), ("test", 12)):
    with open(f"{work}/cls_{split}.tsv", "w") as f:
        for i in range(n):
            lab = i % 2
            marker = "cat cat cat" if lab else "dog dog dog"
            f.write(f"{'positive' if lab else 'negative'}\t"
                    f"{marker} {sent(2 + i % 8)}\n")
EOF

COMMON_ARGS=(--task classify
    --train_file "$WORK/cls_train.tsv" --test_file "$WORK/cls_test.tsv"
    --model_config_file "$WORK/model_config.json"
    --epochs 14 --lr 1e-3 --batch_size 8 --max_seq_len 32
    --dtype float32)

echo "check_distill: (a) training the teacher ..." >&2
python run_finetune.py "${COMMON_ARGS[@]}" \
    --output_dir "$WORK/teacher" >"$WORK/teacher.log" 2>&1 \
    || { tail -5 "$WORK/teacher.log" >&2; exit 1; }

echo "check_distill: (b) distilling student_1l_16 (packed, taps) ..." >&2
python run_distill.py "${COMMON_ARGS[@]}" \
    --student student_1l_16 --teacher_checkpoint "$WORK/teacher/ckpt" \
    --alpha_hidden 1.0 --alpha_attn 0.5 \
    --packing --packing_max_segments 4 \
    --output_dir "$WORK/student" >"$WORK/student.log" 2>&1 \
    || { tail -5 "$WORK/student.log" >&2; exit 1; }

python - "$WORK/student/distill_summary.json" <<'EOF'
import json, sys
s = json.load(open(sys.argv[1]))
assert s["loss_first"] is not None and s["loss_last"] is not None, s
assert s["loss_last"] < s["loss_first"], \
    f"KD mix loss did not decrease: {s['loss_first']} -> {s['loss_last']}"
assert s["projections"], "width-differing student must carry projections"
print(f"check_distill: KD loss {s['loss_first']:.3f} -> "
      f"{s['loss_last']:.3f}, student acc {s.get('test_accuracy')}, "
      f"teacher acc {s.get('teacher_test_accuracy')}")
EOF

serve_and_burst() {
    # serve_and_burst <ckpt> <config> <tag> <out_mode_json>
    local ckpt="$1" config="$2" tag="$3" out="$4"
    rm -f "$WORK/port"
    python run_server.py --force_cpu \
        --model_config_file "$config" --vocab_file "$WORK/vocab.txt" \
        --task_checkpoint "classify=$ckpt" \
        --class_names negative positive \
        --buckets 32,64 --batch_rows 4 --serve_dtype float32 \
        --packing on --port 0 --host 127.0.0.1 \
        --port_file "$WORK/port" >"$WORK/serve_$tag.log" 2>&1 &
    SERVER_PID=$!
    for _ in $(seq 1 600); do
        [ -s "$WORK/port" ] && break
        kill -0 "$SERVER_PID" 2>/dev/null || {
            echo "check_distill: $tag server died during warmup" >&2
            tail -5 "$WORK/serve_$tag.log" >&2
            exit 1
        }
        sleep 0.2
    done
    local port; port="$(cat "$WORK/port")"
    # satellite: /healthz must carry the served model's parameter count
    python - "$port" "$tag" "$WORK/params_$tag" <<'EOF'
import json, sys, urllib.request
port, tag, out = sys.argv[1:]
with urllib.request.urlopen(f"http://127.0.0.1:{port}/healthz",
                            timeout=10) as r:
    h = json.loads(r.read())
n = h["tasks"]["classify"]["model_params"]
assert isinstance(n, int) and n > 0, h["tasks"]["classify"]
open(out, "w").write(str(n))
print(f"check_distill: {tag} /healthz model_params={n}")
EOF
    python tools/loadtest.py --url "http://127.0.0.1:$port" \
        --label "$tag" --model_tag "$tag" \
        --meta dtype=f32 --meta n_chips=1 \
        --rates 15 --duration 2 --tasks classify --out "$out"
    kill "$SERVER_PID" 2>/dev/null || true
    wait "$SERVER_PID" 2>/dev/null || true
    SERVER_PID=""
}

echo "check_distill: (c) serving teacher + student, short bursts ..." >&2
serve_and_burst "$WORK/teacher/ckpt" "$WORK/model_config.json" \
    teacher "$WORK/mode_teacher.json"
serve_and_burst "$WORK/student/ckpt" "$WORK/student/model_config.json" \
    student_1l_16 "$WORK/mode_student.json"

python - "$WORK/params_teacher" "$WORK/params_student_1l_16" <<'EOF'
import sys
t, s = (int(open(p).read()) for p in sys.argv[1:])
assert s < t, f"student ({s} params) not smaller than teacher ({t})"
print(f"check_distill: compression real — {t} -> {s} params")
EOF
python - "$WORK/mode_student.json" <<'EOF'
import json, sys
doc = json.load(open(sys.argv[1]))
assert doc["meta"]["model_tag"] == "student_1l_16", doc["meta"]
EOF

echo "check_distill: (d) assembling the DISTILL artifact ..." >&2
read -r T_ACC S_ACC <<<"$(python -c "
import json
s = json.load(open('$WORK/student/distill_summary.json'))
print(s['teacher_test_accuracy'], s['test_accuracy'])")"
python tools/loadtest.py --assemble "$WORK/DISTILL_smoke.json" \
    "$WORK/mode_teacher.json" "$WORK/mode_student.json" \
    --kind distill --accuracy "teacher=$T_ACC" \
    --accuracy "student_1l_16=$S_ACC"
python tools/loadtest.py --validate "$WORK/DISTILL_smoke.json"

echo "check_distill: (e) accuracy floor gates ..." >&2
python tools/perfboard.py --check_distill "$WORK/DISTILL_smoke.json" \
    --distill_max_delta 0.25

echo "check_distill: negative control (--inject broken_student) ..." >&2
python run_distill.py "${COMMON_ARGS[@]}" \
    --student student_1l_16 --teacher_checkpoint "$WORK/teacher/ckpt" \
    --packing --packing_max_segments 4 --inject broken_student \
    --output_dir "$WORK/broken" >"$WORK/broken.log" 2>&1 \
    || { tail -5 "$WORK/broken.log" >&2; exit 1; }
BROKEN_ACC="$(python -c "
import json
print(json.load(open('$WORK/broken/distill_summary.json'))['test_accuracy'])")"
python tools/loadtest.py --assemble "$WORK/DISTILL_broken.json" \
    "$WORK/mode_teacher.json" "$WORK/mode_student.json" \
    --kind distill --accuracy "teacher=$T_ACC" \
    --accuracy "student_1l_16=$BROKEN_ACC"
if python tools/perfboard.py --check_distill "$WORK/DISTILL_broken.json" \
    --distill_max_delta 0.25 --quiet; then
    echo "check_distill: FAIL — accuracy gate did NOT trip on the" \
         "broken_student injection (delta vs teacher: $T_ACC ->" \
         "$BROKEN_ACC)" >&2
    exit 1
fi
echo "check_distill: gate tripped on broken_student as required" >&2

echo "check_distill: PASS" >&2
