#!/usr/bin/env bash
# Post-two-phase evaluation battery — run AFTER scripts/run_two_phase.sh
# completes, while the chip is free:
#   1. synthetic SQuAD (same corpus, held-out questions) finetuned from the
#      phase-1-end and phase-2-end checkpoints at seq 256 (methodology of
#      docs/squad/curve_r4.jsonl, directly comparable), plus the phase-2
#      point at seq 384 (the long-window gain the seq-512 phase buys)
#   2. NER from the final checkpoint (results/ner methodology)
#   3. synthetic SQuAD v2 (a third of questions unanswerable) from the
#      phase-2 checkpoint — measures the null-threshold/abstention path
#   4. long-context attention bench (scripts/longcontext_bench.py)
# Idempotent: squad_curve skips measured points; data stages skip when
# present.
set -euo pipefail
WORK=$(realpath -m "${1:-/tmp/r4b}")
REPO=$(cd "$(dirname "$0")/.." && pwd)
cd "$REPO"
P1=${P1_STEPS:-16000}
P2_END=$((P1 + ${P2_STEPS:-3520}))
CK="$WORK/pretrain/pretrain_ckpts"

if [ ! -f "$WORK/squad/train.json" ]; then
  rm -rf "$WORK/squad.tmp"
  python scripts/make_synthetic_squad.py "$WORK/corpus" "$WORK/squad.tmp" \
      --train 12000 --dev 900 --seed 0
  mv "$WORK/squad.tmp" "$WORK/squad"
fi

mkdir -p docs/two_phase
python scripts/squad_curve.py --ckpt_dir "$CK" --steps "$P1" "$P2_END" \
    --squad_dir "$WORK/squad" --model_config "$WORK/model_config.json" \
    --vocab "$WORK/vocab.txt" --out docs/two_phase/squad_seq256.jsonl \
    --lr 5e-5 --epochs 6 --batch 32 --max_seq_length 256 \
    --work_dir "$WORK/squad_ft256"
python scripts/squad_curve.py --ckpt_dir "$CK" --steps "$P2_END" \
    --squad_dir "$WORK/squad" --model_config "$WORK/model_config.json" \
    --vocab "$WORK/vocab.txt" --out docs/two_phase/squad_seq384.jsonl \
    --lr 5e-5 --epochs 6 --batch 24 --max_seq_length 384 \
    --work_dir "$WORK/squad_ft384"

if [ ! -f "$WORK/conll/train.txt" ]; then
  rm -rf "$WORK/conll.tmp"
  python scripts/make_synthetic_conll.py "$WORK/corpus" "$WORK/conll.tmp" \
      --train 8000 --eval 1000
  mv "$WORK/conll.tmp" "$WORK/conll"
fi
if [ ! -f docs/two_phase/ner_final.jsonl ]; then
  python run_ner.py \
      --train_file "$WORK/conll/train.txt" \
      --val_file "$WORK/conll/valid.txt" \
      --test_file "$WORK/conll/test.txt" \
      --labels O B-NUM B-DET \
      --model_config_file "$WORK/model_config.json" \
      --vocab_file "$WORK/vocab.txt" \
      --model_checkpoint "$CK@$P2_END" \
      --epochs 5 --lr 5e-6 --batch_size 32 --max_seq_len 128 \
      --output_dir "$WORK/ner_final"
  cp "$WORK/ner_final/ner_log.jsonl" docs/two_phase/ner_final.jsonl
fi

# SQuAD v2: same corpus with a third of the questions made unanswerable;
# measures the null-threshold path's quality (HasAns/NoAns splits) from the
# phase-2 checkpoint — the v1 curves above never exercise abstention
if [ ! -f "$WORK/squad_v2/train.json" ]; then
  rm -rf "$WORK/squad_v2.tmp"
  python scripts/make_synthetic_squad.py "$WORK/corpus" "$WORK/squad_v2.tmp" \
      --train 12000 --dev 900 --seed 1 --negative_frac 0.33
  mv "$WORK/squad_v2.tmp" "$WORK/squad_v2"
fi
python scripts/squad_curve.py --ckpt_dir "$CK" --steps "$P2_END" \
    --squad_dir "$WORK/squad_v2" --model_config "$WORK/model_config.json" \
    --vocab "$WORK/vocab.txt" --out docs/two_phase/squad_v2.jsonl --v2 \
    --lr 5e-5 --epochs 6 --batch 32 --max_seq_length 256 \
    --work_dir "$WORK/squad_ft_v2"

# re-run unless at least one case actually measured (a jsonl of error
# records must not satisfy the gate)
if ! grep -q tflops_per_sec results/longcontext/longcontext.jsonl 2>/dev/null
then
  python scripts/longcontext_bench.py --out results/longcontext
fi
echo "r4b_after: all stages done"
