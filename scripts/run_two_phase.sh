#!/usr/bin/env bash
# Two-phase BERT pretraining on one TPU chip, scaled from the reference
# recipe (config/bert_pretraining_phase{1,2}_config.json: 7038 seq128 steps
# -> 1563 seq512 steps at half the global batch, resumed from the phase-1
# checkpoint with the schedule offset at previous_phase_end_step).
#
# Scaled here to a BERT-Base on the locally-harvestable corpus:
#   phase 1: 16,000 steps  seq128  global batch 256  lr 5e-4  warmup 0.03
#   phase 2:  3,520 steps  seq512  global batch 128  lr 4e-4  warmup 0.128
# (3520/16000 matches the reference's 1563/7038 step ratio; the batch
# halving matches 32768/65536.)
#
# Usage: scripts/run_two_phase.sh [WORK_DIR]   (default /tmp/r4b)
# Idempotent: each stage is skipped when its output already exists, so the
# script resumes after an interruption; run_pretraining auto-resumes from
# the newest checkpoint in WORK_DIR/pretrain.
set -euo pipefail
WORK=$(realpath -m "${1:-/tmp/r4b}")
REPO=$(cd "$(dirname "$0")/.." && pwd)
cd "$REPO"

P1_STEPS=${P1_STEPS:-16000}
P2_STEPS=${P2_STEPS:-3520}

mkdir -p "$WORK"

# Each data stage writes to a .tmp path and renames on success, so a stage
# interrupted mid-write is re-run (not silently skipped with truncated
# output) the next time the script resumes.
if [ ! -d "$WORK/corpus" ]; then
  rm -rf "$WORK/corpus.tmp"
  python scripts/make_local_corpus.py "$WORK/corpus.tmp" --max-mb 96
  mv "$WORK/corpus.tmp" "$WORK/corpus"
fi

if [ ! -f "$WORK/vocab.txt" ]; then
  python -m bert_pytorch_tpu.pipeline.vocab \
      -i "$WORK/corpus" -o "$WORK/vocab.txt.tmp" -s 8192
  mv "$WORK/vocab.txt.tmp" "$WORK/vocab.txt"
fi

if [ ! -f "$WORK/model_config.json" ]; then
  python - "$WORK" <<'EOF'
import json, os, sys
cfg = json.load(open("docs/loss_curve_16k/model_config.json"))
cfg["vocab_file"] = sys.argv[1] + "/vocab.txt"
tmp = sys.argv[1] + "/model_config.json.tmp"
json.dump(cfg, open(tmp, "w"), indent=2)
os.replace(tmp, sys.argv[1] + "/model_config.json")
EOF
fi

for SEQ in 128 512; do
  if [ ! -d "$WORK/shards$SEQ" ]; then
    rm -rf "$WORK/shards$SEQ.tmp"
    python -m bert_pytorch_tpu.pipeline.encode \
        --input_dir "$WORK/corpus" --output_dir "$WORK/shards$SEQ.tmp" \
        --vocab_file "$WORK/vocab.txt" --max_seq_len "$SEQ" \
        --next_seq_prob 0.5 --processes 10 --seed 0
    mv "$WORK/shards$SEQ.tmp" "$WORK/shards$SEQ"
  fi
done

SH128=$(find "$WORK/shards128" -mindepth 1 -maxdepth 1 -type d | head -1)
SH512=$(find "$WORK/shards512" -mindepth 1 -maxdepth 1 -type d | head -1)

# ---- phase 1: seq128 ----
python run_pretraining.py \
    --input_dir "$SH128" --output_dir "$WORK/pretrain" \
    --model_config_file "$WORK/model_config.json" \
    --global_batch_size 256 --local_batch_size 64 --max_steps "$P1_STEPS" \
    --learning_rate 5e-4 --warmup_proportion 0.03 \
    --max_predictions_per_seq 20 --masked_token_fraction 0.15 \
    --num_steps_per_checkpoint 1000 --keep_checkpoints 25 \
    --log_prefix "$WORK/pretrain/phase1" --rng_impl rbg --seed 42

# ---- phase 2: seq512, resumed from the phase-1 checkpoint ----
python run_pretraining.py \
    --input_dir "$SH512" --output_dir "$WORK/pretrain" \
    --model_config_file "$WORK/model_config.json" \
    --global_batch_size 128 --local_batch_size 16 --max_steps "$P2_STEPS" \
    --previous_phase_end_step "$P1_STEPS" \
    --learning_rate 4e-4 --warmup_proportion 0.128 \
    --max_predictions_per_seq 80 --masked_token_fraction 0.15 \
    --num_steps_per_checkpoint 880 --keep_checkpoints 25 \
    --log_prefix "$WORK/pretrain/phase2" --rng_impl rbg --seed 43
