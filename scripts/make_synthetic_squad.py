#!/usr/bin/env python
"""Generate a SQuAD-v1.1-format extractive QA dataset from a local corpus.

This environment has no network egress, so the real SQuAD v1.1 JSON (and
Google's pretrained weights) cannot be downloaded. This builds a dataset in
the exact SQuAD schema from local text: each question quotes a context
phrase that occurs exactly once in the paragraph, and the answer is the span
that immediately follows it. That makes answers extractive and learnable
from surface cues, which is what lets a briefly-pretrained model finetuned
with run_squad.py demonstrate the full machinery — featurization, sliding
window, training, n-best span extraction, in-process eval — with a
measurable, far-above-chance F1. The numbers are NOT comparable to real
SQuAD; they validate the pipeline, not the model zoo's knowledge.

Usage:
  python scripts/make_synthetic_squad.py CORPUS_DIR OUT_DIR \
      [--train N] [--dev N] [--seed S]
writes OUT_DIR/train.json and OUT_DIR/dev.json.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import random
import re


def _qa_id(prefix: str, *parts) -> str:
    """Deterministic qa id: Python hash() is salted per process
    (PYTHONHASHSEED), which broke --seed reproducibility across runs, and
    truncated-text keys could collide across paragraphs. md5 over the FULL
    key material fixes both."""
    digest = hashlib.md5("\x1f".join(str(p) for p in parts).encode()).hexdigest()
    return f"{prefix}{digest[:16]}"

_WS = re.compile(r"\s+")


def paragraphs_from(corpus_dir: str):
    """Blank-line-separated docs -> cleaned paragraphs of 40-150 words."""
    for fn in sorted(os.listdir(corpus_dir)):
        if not fn.endswith(".txt"):
            continue
        with open(os.path.join(corpus_dir, fn), encoding="utf-8") as f:
            doc: list = []
            for line in f:
                line = line.strip()
                if line:
                    doc.append(line)
                    continue
                if doc:
                    text = _WS.sub(" ", " ".join(doc)).strip()
                    words = text.split()
                    if 40 <= len(words) <= 150:
                        yield text
                    doc = []


def make_qas(text: str, rng: random.Random, max_q: int = 3,
             v2: bool = False):
    """Questions quoting a unique 4-word phrase; answer = following 3 words.
    With v2=True every qa carries is_impossible (SQuAD v2.0 schema)."""
    words = text.split()
    qas = []
    tries = 0
    while len(qas) < max_q and tries < 20:
        tries += 1
        i = rng.randrange(0, len(words) - 8)
        phrase = " ".join(words[i:i + 4])
        if text.count(phrase) != 1:
            continue
        answer = " ".join(words[i + 4:i + 7])
        start = text.index(phrase) + len(phrase) + 1
        if text[start:start + len(answer)] != answer:
            continue
        qa = {
            "id": _qa_id("syn", text, i),
            "question": f"Which words come after the phrase \"{phrase}\"?",
            "answers": [{"text": answer, "answer_start": start}],
        }
        if v2:
            qa["is_impossible"] = False
        qas.append(qa)
    return qas


def make_negative_qa(text: str, other_text: str, rng: random.Random):
    """An unanswerable question: quotes a phrase from ANOTHER paragraph that
    does not occur in this one — same surface form as the answerable
    questions, so the model must actually check the context (the SQuAD v2.0
    task shape: plausible question, no supported answer)."""
    other_words = other_text.split()
    for _ in range(20):
        i = rng.randrange(0, max(len(other_words) - 4, 1))
        phrase = " ".join(other_words[i:i + 4])
        if len(phrase.split()) == 4 and phrase not in text:
            return {
                "id": _qa_id("synneg", text, phrase),
                "question":
                    f"Which words come after the phrase \"{phrase}\"?",
                "answers": [],
                "is_impossible": True,
            }
    return None


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("corpus_dir")
    p.add_argument("out_dir")
    p.add_argument("--train", type=int, default=1500)
    p.add_argument("--dev", type=int, default=300)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--negative_frac", type=float, default=0.0,
                   help="fraction of questions made unanswerable (SQuAD "
                        "v2.0 schema: is_impossible, empty answers)")
    args = p.parse_args()

    v2 = args.negative_frac > 0
    rng = random.Random(args.seed)
    os.makedirs(args.out_dir, exist_ok=True)
    paras = []
    prev_text = None
    for text in paragraphs_from(args.corpus_dir):
        qas = make_qas(text, rng, v2=v2)
        if qas:
            if v2 and prev_text is not None:
                # replace ~negative_frac of the answerable questions with
                # unanswerable ones quoting the previous paragraph; two
                # draws can pick the same source phrase, so dedup by id
                kept, seen_ids = [], set()
                for qa in qas:
                    if rng.random() < args.negative_frac:
                        neg = make_negative_qa(text, prev_text, rng)
                        if neg is not None and neg["id"] not in seen_ids:
                            seen_ids.add(neg["id"])
                            kept.append(neg)
                            continue
                    seen_ids.add(qa["id"])
                    kept.append(qa)
                qas = kept
            paras.append({"context": text, "qas": qas})
            prev_text = text
        if len(paras) >= args.train + args.dev:
            break
    if len(paras) < args.train + args.dev:
        print(f"warning: only {len(paras)} paragraphs available")
    rng.shuffle(paras)
    dev, train = paras[:args.dev], paras[args.dev:args.dev + args.train]
    version = ("2.0-synthetic-local" if v2 else "1.1-synthetic-local")
    for name, split in (("train", train), ("dev", dev)):
        data = {"version": version,
                "data": [{"title": "local-docs", "paragraphs": split}]}
        path = os.path.join(args.out_dir, f"{name}.json")
        with open(path, "w", encoding="utf-8") as f:
            json.dump(data, f)
        n_q = sum(len(p_["qas"]) for p_ in split)
        n_neg = sum(1 for p_ in split for qa in p_["qas"]
                    if qa.get("is_impossible"))
        print(f"{path}: {len(split)} paragraphs, {n_q} questions"
              + (f" ({n_neg} unanswerable)" if v2 else ""))


if __name__ == "__main__":
    main()
