#!/usr/bin/env python
"""Generate a SQuAD-v1.1-format extractive QA dataset from a local corpus.

This environment has no network egress, so the real SQuAD v1.1 JSON (and
Google's pretrained weights) cannot be downloaded. This builds a dataset in
the exact SQuAD schema from local text: each question quotes a context
phrase that occurs exactly once in the paragraph, and the answer is the span
that immediately follows it. That makes answers extractive and learnable
from surface cues, which is what lets a briefly-pretrained model finetuned
with run_squad.py demonstrate the full machinery — featurization, sliding
window, training, n-best span extraction, in-process eval — with a
measurable, far-above-chance F1. The numbers are NOT comparable to real
SQuAD; they validate the pipeline, not the model zoo's knowledge.

Usage:
  python scripts/make_synthetic_squad.py CORPUS_DIR OUT_DIR \
      [--train N] [--dev N] [--seed S]
writes OUT_DIR/train.json and OUT_DIR/dev.json.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import re

_WS = re.compile(r"\s+")


def paragraphs_from(corpus_dir: str):
    """Blank-line-separated docs -> cleaned paragraphs of 40-150 words."""
    for fn in sorted(os.listdir(corpus_dir)):
        if not fn.endswith(".txt"):
            continue
        with open(os.path.join(corpus_dir, fn), encoding="utf-8") as f:
            doc: list = []
            for line in f:
                line = line.strip()
                if line:
                    doc.append(line)
                    continue
                if doc:
                    text = _WS.sub(" ", " ".join(doc)).strip()
                    words = text.split()
                    if 40 <= len(words) <= 150:
                        yield text
                    doc = []


def make_qas(text: str, rng: random.Random, max_q: int = 3):
    """Questions quoting a unique 4-word phrase; answer = following 3 words."""
    words = text.split()
    qas = []
    tries = 0
    while len(qas) < max_q and tries < 20:
        tries += 1
        i = rng.randrange(0, len(words) - 8)
        phrase = " ".join(words[i:i + 4])
        if text.count(phrase) != 1:
            continue
        answer = " ".join(words[i + 4:i + 7])
        start = text.index(phrase) + len(phrase) + 1
        if text[start:start + len(answer)] != answer:
            continue
        qas.append({
            "id": f"syn{abs(hash((text[:40], i))) % 10**10}",
            "question": f"Which words come after the phrase \"{phrase}\"?",
            "answers": [{"text": answer, "answer_start": start}],
        })
    return qas


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("corpus_dir")
    p.add_argument("out_dir")
    p.add_argument("--train", type=int, default=1500)
    p.add_argument("--dev", type=int, default=300)
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args()

    rng = random.Random(args.seed)
    os.makedirs(args.out_dir, exist_ok=True)
    paras = []
    for text in paragraphs_from(args.corpus_dir):
        qas = make_qas(text, rng)
        if qas:
            paras.append({"context": text, "qas": qas})
        if len(paras) >= args.train + args.dev:
            break
    if len(paras) < args.train + args.dev:
        print(f"warning: only {len(paras)} paragraphs available")
    rng.shuffle(paras)
    dev, train = paras[:args.dev], paras[args.dev:args.dev + args.train]
    for name, split in (("train", train), ("dev", dev)):
        data = {"version": "1.1-synthetic-local",
                "data": [{"title": "local-docs", "paragraphs": split}]}
        path = os.path.join(args.out_dir, f"{name}.json")
        with open(path, "w", encoding="utf-8") as f:
            json.dump(data, f)
        n_q = sum(len(p_["qas"]) for p_ in split)
        print(f"{path}: {len(split)} paragraphs, {n_q} questions")


if __name__ == "__main__":
    main()
