#!/usr/bin/env bash
# Distillation bench: the student factory measured as req/s-per-chip at a
# fixed p99 bound.
#
#   scripts/distill_bench.sh [DISTILL_rNN.json]
#
# Pipeline (CPU, self-contained):
#   1. train a teacher on the marker classify task (run_finetune.py) —
#      sized (DISTILL_HIDDEN x DISTILL_LAYERS) so a request's forward
#      dominates Python overhead and the teacher/student FLOP gap shows
#      up in the saturation knee;
#   2. distill two students through run_distill.py (packed, soft-target
#      KD + layer-matched tap losses with width-bridging projections):
#      DISTILL_STUDENT_A (default student_4l_128, ~8x fewer encoder
#      FLOPs) and DISTILL_STUDENT_B (default student_2l_64, ~64x);
#   3. serve teacher (f32) and each student (f32 AND int8) through the
#      same open-loop geometric rate ramp (tools/loadtest.py
#      --rate_sweep) under ONE shared p99 bound, each leg tagged with
#      --model_tag and costed via --cost_per_device_hour;
#   4. assemble the legs + measured task accuracies into a DISTILL
#      artifact (loadtest --assemble --kind distill): per-leg saturation
#      req/s-per-chip, cost_per_1k_tokens, accuracy, accuracy_delta vs
#      the teacher, and saturation.vs_teacher_per_chip — the headline;
#   5. validate, gate the accuracy floor (perfboard --check_distill),
#      and reindex the perf board (RUNS.md distillation table).
#
# The numbers are a harness-relative A/B (teacher vs its students on
# identical hardware under an identical SLO), not TPU headline latency —
# the same contract as serve_bench.sh.
#
# Env knobs: DISTILL_SWEEP (START:FACTOR:MAX), DISTILL_P99_BOUND (ms),
# DISTILL_DURATION (s/rate), DISTILL_HIDDEN/DISTILL_LAYERS (teacher
# size), DISTILL_STUDENT_A/B (student presets), DISTILL_MAX_DELTA
# (accuracy floor), DISTILL_EPOCHS.
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"

OUT="${1:-DISTILL_r01.json}"
SWEEP="${DISTILL_SWEEP:-5:1.6:400}"
BOUND="${DISTILL_P99_BOUND:-150}"
DURATION="${DISTILL_DURATION:-6}"
HIDDEN="${DISTILL_HIDDEN:-256}"
LAYERS="${DISTILL_LAYERS:-8}"
STUDENT_A="${DISTILL_STUDENT_A:-student_4l_128}"
STUDENT_B="${DISTILL_STUDENT_B:-student_2l_64}"
MAX_DELTA="${DISTILL_MAX_DELTA:-0.05}"
EPOCHS="${DISTILL_EPOCHS:-12}"
COST="${DISTILL_COST_PER_DEVICE_HOUR:-1.0}"

WORK="$(mktemp -d)"
SERVER_PID=""
cleanup() {
    [ -n "$SERVER_PID" ] && kill "$SERVER_PID" 2>/dev/null || true
    rm -rf "$WORK"
}
trap cleanup EXIT

echo "distill_bench: building marker-task fixture ..." >&2
python - "$WORK" "$HIDDEN" "$LAYERS" <<'EOF'
import json, sys
import numpy as np
work, hidden, layers = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
VOCAB = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"] + (
    "the cat sat on mat a dog did run in park fast slow red blue "
    "green and is was to of thing bert serves packed rows . , ?").split()
open(f"{work}/vocab.txt", "w").write("\n".join(VOCAB) + "\n")
cfg = {"vocab_size": len(VOCAB), "hidden_size": hidden,
       "num_hidden_layers": layers,
       "num_attention_heads": max(1, hidden // 32),
       "intermediate_size": hidden * 4, "max_position_embeddings": 128,
       "hidden_dropout_prob": 0.0, "attention_probs_dropout_prob": 0.0,
       "fused_ops": False, "attention_impl": "xla", "lowercase": True,
       "tokenizer": "wordpiece", "vocab_file": f"{work}/vocab.txt"}
json.dump(cfg, open(f"{work}/model_config.json", "w"))
rng = np.random.RandomState(0)
words = [w for w in VOCAB if not w.startswith("[")]
sent = lambda n: " ".join(rng.choice(words, n))
for split, n in (("train", 96), ("test", 48)):
    with open(f"{work}/cls_{split}.tsv", "w") as f:
        for i in range(n):
            lab = i % 2
            marker = "cat cat cat" if lab else "dog dog dog"
            f.write(f"{'positive' if lab else 'negative'}\t"
                    f"{marker} {sent(4 + i % 12)}\n")
EOF

COMMON_ARGS=(--task classify
    --train_file "$WORK/cls_train.tsv" --test_file "$WORK/cls_test.tsv"
    --model_config_file "$WORK/model_config.json"
    --epochs "$EPOCHS" --lr 3e-4 --batch_size 8 --max_seq_len 64
    --dtype float32)

echo "distill_bench: training the teacher (${LAYERS}L/${HIDDEN}H) ..." >&2
python run_finetune.py "${COMMON_ARGS[@]}" \
    --output_dir "$WORK/teacher" >"$WORK/teacher.log" 2>&1 \
    || { tail -5 "$WORK/teacher.log" >&2; exit 1; }

distill_student() {
    local preset="$1"
    echo "distill_bench: distilling $preset ..." >&2
    python run_distill.py "${COMMON_ARGS[@]}" \
        --student "$preset" --teacher_checkpoint "$WORK/teacher/ckpt" \
        --alpha_hidden 1.0 --packing --packing_max_segments 4 \
        --output_dir "$WORK/$preset" >"$WORK/$preset.log" 2>&1 \
        || { tail -5 "$WORK/$preset.log" >&2; exit 1; }
}
distill_student "$STUDENT_A"
distill_student "$STUDENT_B"

run_leg() {
    # run_leg <label> <model_tag> <ckpt> <config> <dtype> <meta_dtype>
    local label="$1" tag="$2" ckpt="$3" config="$4" dtype="$5" mdtype="$6"
    local port_file="$WORK/port_$label"
    rm -f "$port_file"
    python run_server.py --force_cpu \
        --model_config_file "$config" --vocab_file "$WORK/vocab.txt" \
        --task_checkpoint "classify=$ckpt" \
        --class_names negative positive \
        --buckets 32,64 --batch_rows 4 \
        --serve_dtype "$dtype" --packing on \
        --cost_per_device_hour "$COST" \
        --port 0 --host 127.0.0.1 --port_file "$port_file" \
        >"$WORK/serve_$label.log" 2>&1 &
    SERVER_PID=$!
    for _ in $(seq 1 900); do
        [ -s "$port_file" ] && break
        kill -0 "$SERVER_PID" 2>/dev/null || {
            echo "distill_bench: server ($label) died during warmup" >&2
            tail -5 "$WORK/serve_$label.log" >&2
            exit 1
        }
        sleep 0.2
    done
    local port; port="$(cat "$port_file")"
    echo "distill_bench: [$label] server warm on :$port — rate ramp" >&2
    python tools/loadtest.py --url "http://127.0.0.1:$port" \
        --label "$label" --model_tag "$tag" \
        --rate_sweep "$SWEEP" --p99_bound "$BOUND" \
        --duration "$DURATION" --tasks classify \
        --meta "dtype=$mdtype" --meta n_chips=1 --meta replicas=1 \
        --out "$WORK/$label.json"
    kill "$SERVER_PID" 2>/dev/null || true
    wait "$SERVER_PID" 2>/dev/null || true
    SERVER_PID=""
}

run_leg teacher_f32 teacher "$WORK/teacher/ckpt" \
    "$WORK/model_config.json" float32 f32
for preset in "$STUDENT_A" "$STUDENT_B"; do
    run_leg "${preset}_f32" "$preset" "$WORK/$preset/ckpt" \
        "$WORK/$preset/model_config.json" float32 f32
    run_leg "${preset}_int8" "$preset" "$WORK/$preset/ckpt" \
        "$WORK/$preset/model_config.json" int8 int8
done

echo "distill_bench: assembling $OUT ..." >&2
read -r T_ACC A_ACC B_ACC <<<"$(python - "$WORK" "$STUDENT_A" "$STUDENT_B" <<'EOF'
import json, sys
work, a, b = sys.argv[1:]
sa = json.load(open(f"{work}/{a}/distill_summary.json"))
sb = json.load(open(f"{work}/{b}/distill_summary.json"))
print(sa["teacher_test_accuracy"], sa["test_accuracy"],
      sb["test_accuracy"])
EOF
)"
echo "distill_bench: accuracies teacher=$T_ACC $STUDENT_A=$A_ACC $STUDENT_B=$B_ACC" >&2
python tools/loadtest.py --assemble "$OUT" \
    "$WORK/teacher_f32.json" \
    "$WORK/${STUDENT_A}_f32.json" "$WORK/${STUDENT_A}_int8.json" \
    "$WORK/${STUDENT_B}_f32.json" "$WORK/${STUDENT_B}_int8.json" \
    --kind distill \
    --accuracy "teacher=$T_ACC" \
    --accuracy "$STUDENT_A=$A_ACC" --accuracy "$STUDENT_B=$B_ACC"
python tools/loadtest.py --validate "$OUT"
python tools/perfboard.py --check_distill "$OUT" \
    --distill_max_delta "$MAX_DELTA"
python tools/perfboard.py
echo "distill_bench: wrote $OUT and reindexed the perf board"
