#!/bin/bash
# SQuAD v1.1 finetuning with the reference recipe (scripts/run_squad.sh:12-45):
# LR 3e-5, 2 epochs, seq 384, doc_stride 128. The pretrained checkpoint is an
# orbax directory from run_pretraining.py (the reference consumed ckpt_8601.pt).
set -euo pipefail
CKPT=${1:-results/phase2/pretrain_ckpts}
DATA=${2:-data/download/squad}
OUT=${3:-results/squad}
MODEL_CONFIG=${4:-configs/bert_large_uncased_config.json}
shift $(( $# > 4 ? 4 : $# ))
exec python run_squad.py \
    --do_train --do_predict --do_eval \
    --train_file "$DATA/train-v1.1.json" \
    --predict_file "$DATA/dev-v1.1.json" \
    --init_checkpoint "$CKPT" \
    --model_config_file "$MODEL_CONFIG" \
    --output_dir "$OUT" \
    --learning_rate 3e-5 --num_train_epochs 2 \
    --max_seq_length 384 --doc_stride 128 \
    --train_batch_size 32 "$@"
