#!/bin/bash
# Launch pretraining. Under SPMD the reference's entire launcher layer
# (torch.distributed.launch per node, Cobalt SSH fan-out, SLURM mpirun —
# SURVEY §5.8) collapses to one python process per TPU-VM host; the TPU
# runtime provides the rendezvous. For multi-host DCN clusters pass the
# coordinator explicitly (bert_pytorch_tpu.parallel.dist.initialize).
#
#   scripts/run_pretraining.sh configs/bert_pretraining_phase1_config.json \
#       data/encoded/sequences_lowercase_max_seq_len_128_next_seq_task_true \
#       results/phase1
set -euo pipefail
CONFIG=${1:?run config json}
INPUT=${2:?input dir with .hdf5 shards}
OUTPUT=${3:?output dir}
shift 3
exec python run_pretraining.py --config_file "$CONFIG" \
    --input_dir "$INPUT" --output_dir "$OUTPUT" "$@"
