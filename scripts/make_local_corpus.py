#!/usr/bin/env python
"""Harvest an English text corpus from docstrings of installed packages.

This environment has no network egress, so the Wikipedia/BooksCorpus
downloaders (bert_pytorch_tpu/pipeline/download.py) cannot run. Docstrings of
the installed scientific-python stack are multiple MB of real English prose —
enough to drive the full offline pipeline (format -> shard -> vocab ->
encode) and produce a descending MLM loss curve on real text.

Output format matches pipeline/format.py's contract: one sentence per line,
blank line between documents.

Usage: python scripts/make_local_corpus.py OUT_DIR [--max-mb N]
"""

from __future__ import annotations

import ast
import os
import re
import sys

_SENT_SPLIT = re.compile(r"(?<=[.!?])\s+(?=[A-Z])")
_WS = re.compile(r"\s+")


def iter_docstrings(root: str):
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in filenames:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            try:
                with open(path, encoding="utf-8", errors="ignore") as f:
                    source = f.read()
                tree = ast.parse(source)
            except (SyntaxError, ValueError, OSError):
                continue
            for node in ast.walk(tree):
                if isinstance(node, (ast.Module, ast.ClassDef,
                                     ast.FunctionDef, ast.AsyncFunctionDef)):
                    doc = ast.get_docstring(node, clean=True)
                    if doc and len(doc) > 120:
                        yield doc
            comment_doc = file_comment_doc(source)
            if comment_doc:
                yield comment_doc


def file_comment_doc(source: str):
    """All `#` comment blocks of a file, joined into ONE document (blank line
    between blocks, so each block is a paragraph) — source comments are the
    other large body of real English prose on a no-egress box (~36 MB in this
    image vs ~25 MB of docstrings). Per-file aggregation keeps the document
    topically coherent (comments of one module discuss one subject), which is
    what the NSP pairing in pipeline/encode.py needs. Real tokenizer COMMENT
    tokens only — a '#'-looking line inside a string literal or docstring is
    not a comment and must not be duplicated into this document."""
    import io
    import tokenize

    blocks: list[str] = []
    block: list[str] = []
    prev_row = -2
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(source).readline))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return None
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        text = tok.string.lstrip("#").strip()
        row = tok.start[0]
        if row > prev_row + 1 and block:  # gap ends the block
            if len(" ".join(block)) > 60:
                blocks.append("\n".join(block))
            block = []
        prev_row = row
        if text and not text.startswith(("!", "-*-", "type:")):
            block.append(text)
    if block and len(" ".join(block)) > 60:
        blocks.append("\n".join(block))
    doc = "\n\n".join(blocks)
    return doc if len(doc) > 120 else None


def doc_to_lines(doc: str):
    """Docstring -> sentences, dropping code-ish lines (indented blocks,
    doctest prompts, parameter tables)."""
    kept = []
    for para in doc.split("\n\n"):
        lines = [ln for ln in para.splitlines()
                 if not ln.startswith((" ", "\t", ">>>", "..."))]
        text = _WS.sub(" ", " ".join(lines)).strip()
        if len(text) < 40 or text.count("|") > 2:
            continue
        kept.extend(s.strip() for s in _SENT_SPLIT.split(text)
                    if len(s.strip()) > 15)
    return kept


def main() -> None:
    out_dir = sys.argv[1]
    max_mb = 64
    if "--max-mb" in sys.argv:
        max_mb = int(sys.argv[sys.argv.index("--max-mb") + 1])
    os.makedirs(out_dir, exist_ok=True)

    import sysconfig

    # site-packages plus the stdlib itself — both are real English prose at
    # docstring granularity; stdlib alone adds several MB
    paths = sysconfig.get_paths()
    roots = [paths["purelib"]]
    stdlib = paths.get("stdlib")
    if stdlib and os.path.isdir(stdlib):
        roots.append(stdlib)
    # the google-cloud-sdk CLI tree (if present) is ~10 MB of additional
    # real-English command help/docstrings — a different register from the
    # scientific stack, which helps corpus diversity
    gcloud = "/usr/lib/google-cloud-sdk/lib"
    if os.path.isdir(gcloud):
        roots.append(gcloud)
    written = 0
    shard = 0
    f = None
    per_shard = 4 * 1024 * 1024
    shard_bytes = 0
    seen = set()
    try:
        for root in roots:
            for doc in iter_docstrings(root):
                lines = doc_to_lines(doc)
                if len(lines) < 3:
                    continue
                key = hash(lines[0])
                if key in seen:  # dedupe identical inherited docstrings
                    continue
                seen.add(key)
                if f is None or shard_bytes > per_shard:
                    if f:
                        f.close()
                    f = open(os.path.join(out_dir, f"docs_{shard:03d}.txt"),
                             "w", encoding="utf-8")
                    shard += 1
                    shard_bytes = 0
                blob = "\n".join(lines) + "\n\n"
                f.write(blob)
                n = len(blob.encode("utf-8"))
                shard_bytes += n
                written += n
                if written > max_mb * 1024 * 1024:
                    print(f"wrote {written/1e6:.1f} MB in {shard} shards")
                    return
    finally:
        if f:
            f.close()
    print(f"wrote {written/1e6:.1f} MB in {shard} shards")


if __name__ == "__main__":
    main()
