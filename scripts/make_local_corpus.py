#!/usr/bin/env python
"""Harvest an English text corpus from prose embedded in installed software.

This environment has no network egress, so the Wikipedia/BooksCorpus
downloaders (bert_pytorch_tpu/pipeline/download.py) cannot run. The box does
hold tens of MB of real English in other forms, each mined by a dedicated
extractor below:

- Python docstrings + `#` comment blocks (site-packages, stdlib, gcloud SDK)
- Markdown/reStructuredText documents (site-packages, node_modules)
- dist-info METADATA long-descriptions (each package's README)
- C/C++ comment blocks (/usr/include and bundled headers), license
  boilerplate filtered

Pretraining quality is bound by corpus *diversity*, not step count, once a
run re-visits the same text dozens of epochs — the extra registers
(tutorial-style READMEs, systems-programming comments) exist precisely to
widen that distribution.

Output format matches pipeline/format.py's contract: one sentence per line,
blank line between documents.

Usage: python scripts/make_local_corpus.py OUT_DIR [--max-mb N]
"""

from __future__ import annotations

import ast
import os
import re
import sys

_SENT_SPLIT = re.compile(r"(?<=[.!?])\s+(?=[A-Z])")
_WS = re.compile(r"\s+")


def iter_docstrings(root: str):
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in filenames:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            try:
                with open(path, encoding="utf-8", errors="ignore") as f:
                    source = f.read()
                tree = ast.parse(source)
            except (SyntaxError, ValueError, OSError):
                continue
            for node in ast.walk(tree):
                if isinstance(node, (ast.Module, ast.ClassDef,
                                     ast.FunctionDef, ast.AsyncFunctionDef)):
                    doc = ast.get_docstring(node, clean=True)
                    if doc and len(doc) > 120:
                        yield doc
            comment_doc = file_comment_doc(source)
            if comment_doc:
                yield comment_doc


def file_comment_doc(source: str):
    """All `#` comment blocks of a file, joined into ONE document (blank line
    between blocks, so each block is a paragraph) — source comments are the
    other large body of real English prose on a no-egress box (~36 MB in this
    image vs ~25 MB of docstrings). Per-file aggregation keeps the document
    topically coherent (comments of one module discuss one subject), which is
    what the NSP pairing in pipeline/encode.py needs. Real tokenizer COMMENT
    tokens only — a '#'-looking line inside a string literal or docstring is
    not a comment and must not be duplicated into this document."""
    import io
    import tokenize

    blocks: list[str] = []
    block: list[str] = []
    prev_row = -2
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(source).readline))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return None
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        text = tok.string.lstrip("#").strip()
        row = tok.start[0]
        if row > prev_row + 1 and block:  # gap ends the block
            if len(" ".join(block)) > 60:
                blocks.append("\n".join(block))
            block = []
        prev_row = row
        if text and not text.startswith(("!", "-*-", "type:")):
            block.append(text)
    if block and len(" ".join(block)) > 60:
        blocks.append("\n".join(block))
    doc = "\n\n".join(blocks)
    return doc if len(doc) > 120 else None


_FENCE = re.compile(r"```.*?```|~~~.*?~~~", re.S)
_MD_IMG = re.compile(r"!\[[^\]]*\]\([^)]*\)")
_MD_LINK = re.compile(r"\[([^\]]*)\]\([^)]*\)")
_MD_MARKUP = re.compile(r"[`*_]{1,3}|^#{1,6}\s+|^[-=~^]{3,}\s*$|^\.\. \S+.*$",
                        re.M)


def _clean_markdown(text: str):
    """Strip code fences, images, link targets, and inline markup; None when
    too little prose remains."""
    text = _FENCE.sub("", text)
    # an unbalanced fence (file truncated mid-block by the read cap, or
    # malformed markdown) would let raw code through as 'prose' — drop
    # everything from the unmatched opener on
    for fence in ("```", "~~~"):
        pos = text.find(fence)
        if pos != -1:
            text = text[:pos]
    text = _MD_IMG.sub("", text)
    text = _MD_LINK.sub(r"\1", text)
    text = _MD_MARKUP.sub("", text)
    return text if len(text) > 300 else None


def iter_markdown_docs(root: str):
    """Markdown/rst files as one document each, code fences and link targets
    stripped. READMEs and docs trees are tutorial-register English — a
    different distribution from docstrings."""
    # prune vendored dep trees under site-packages etc., but not when the
    # root being harvested IS a node_modules tree (then nested deps are the
    # content)
    prune = {"__pycache__", ".git"}
    if "node_modules" not in os.path.abspath(root).split(os.sep):
        prune.add("node_modules")
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d not in prune]
        for fn in filenames:
            if not fn.lower().endswith((".md", ".markdown", ".rst")):
                continue
            if "license" in fn.lower() or "changelog" in fn.lower():
                continue
            try:
                with open(os.path.join(dirpath, fn), encoding="utf-8",
                          errors="ignore") as f:
                    text = f.read(2 * 1024 * 1024)
            except OSError:
                continue
            text = _clean_markdown(text)
            if text:
                yield text


def iter_metadata_docs(purelib: str):
    """PEP 566 long-descriptions: the body of each dist-info METADATA file is
    the package's README (markdown/rst)."""
    import glob

    for meta in glob.glob(os.path.join(purelib, "*.dist-info", "METADATA")):
        try:
            with open(meta, encoding="utf-8", errors="ignore") as f:
                raw = f.read(1024 * 1024)
        except OSError:
            continue
        head, sep, body = raw.partition("\n\n")
        if not sep:
            continue
        body = _clean_markdown(body)
        if body:
            yield body


_LICENSE_MARKERS = ("copyright", "warranty", "spdx", "redistribution",
                    "permission is hereby granted", "gnu general public",
                    "apache license", "all rights reserved")
_C_BLOCK = re.compile(r"/\*.*?\*/|//[^\n]*(?:\n[ \t]*//[^\n]*)*", re.S)
_C_GUTTER = re.compile(r"^[ \t]*(?:/\*+|\*+/|\*+|//+)[ \t]?", re.M)


def iter_c_comment_docs(root: str):
    """C/C++ comment blocks of a header/source file, joined into one document
    per file (same per-file topical-coherence rationale as file_comment_doc).
    Any block containing a license marker anywhere is dropped whole: GPL/MPL
    boilerplate often sits mid-block after a description line, and losing the
    occasional legitimate block that says 'warranty' is cheaper than letting
    thousands of near-identical license paragraphs into the corpus."""
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != ".git"]
        for fn in filenames:
            if not fn.endswith((".h", ".hpp", ".hh", ".c", ".cc", ".cpp")):
                continue
            try:
                with open(os.path.join(dirpath, fn), encoding="utf-8",
                          errors="ignore") as f:
                    source = f.read(4 * 1024 * 1024)
            except OSError:
                continue
            blocks = []
            for m in _C_BLOCK.finditer(source):
                text = _C_GUTTER.sub("", m.group(0)).strip()
                if len(text) < 80:
                    continue
                if any(k in text.lower() for k in _LICENSE_MARKERS):
                    continue
                blocks.append(text)
            doc = "\n\n".join(blocks)
            if len(doc) > 200:
                yield doc


def doc_to_lines(doc: str):
    """Docstring -> sentences, dropping code-ish lines (indented blocks,
    doctest prompts, parameter tables)."""
    kept = []
    for para in doc.split("\n\n"):
        lines = [ln for ln in para.splitlines()
                 if not ln.startswith((" ", "\t", ">>>", "..."))]
        text = _WS.sub(" ", " ".join(lines)).strip()
        if len(text) < 40 or text.count("|") > 2:
            continue
        kept.extend(s.strip() for s in _SENT_SPLIT.split(text)
                    if len(s.strip()) > 15)
    return kept


def main() -> None:
    out_dir = sys.argv[1]
    max_mb = 64
    if "--max-mb" in sys.argv:
        max_mb = int(sys.argv[sys.argv.index("--max-mb") + 1])
    os.makedirs(out_dir, exist_ok=True)

    import sysconfig

    # site-packages plus the stdlib itself — both are real English prose at
    # docstring granularity; stdlib alone adds several MB
    paths = sysconfig.get_paths()
    py_roots = [paths["purelib"]]
    stdlib = paths.get("stdlib")
    if stdlib and os.path.isdir(stdlib):
        py_roots.append(stdlib)
    # the google-cloud-sdk CLI tree (if present) is ~10 MB of additional
    # real-English command help/docstrings — a different register from the
    # scientific stack, which helps corpus diversity
    gcloud = "/usr/lib/google-cloud-sdk/lib"
    if os.path.isdir(gcloud):
        py_roots.append(gcloud)
    md_roots = [r for r in (paths["purelib"], "/usr/lib/node_modules",
                            "/usr/local/lib/node_modules", "/opt/skills")
                if os.path.isdir(r)]
    # /usr/include plus every header tree bundled in site-packages (torch
    # alone ships ~40 MB of commented C++ headers)
    c_roots = [r for r in ("/usr/include", paths["purelib"],
                           paths.get("include", ""))
               if r and os.path.isdir(r)]

    def sources():
        # smaller/diverse registers first so the --max-mb cap can never
        # crowd them out; python docstrings (the largest source) fill the
        # remainder
        for root in md_roots:
            for doc in iter_markdown_docs(root):
                yield "markdown", doc
        for doc in iter_metadata_docs(paths["purelib"]):
            yield "metadata", doc
        for root in c_roots:
            for doc in iter_c_comment_docs(root):
                yield "c_comments", doc
        for root in py_roots:
            for doc in iter_docstrings(root):
                yield "py_docstrings", doc
    written = 0
    shard = 0
    f = None
    per_shard = 4 * 1024 * 1024
    shard_bytes = 0
    seen = set()
    from collections import Counter

    per_source: Counter = Counter()

    def report():
        by_src = ", ".join(f"{k}={v/1e6:.1f}MB"
                           for k, v in per_source.most_common())
        print(f"wrote {written/1e6:.1f} MB in {shard} shards ({by_src})")

    try:
        for src, doc in sources():
            lines = doc_to_lines(doc)
            if len(lines) < 3:
                continue
            # dedupe identical inherited docstrings / vendored files; three
            # lines so distinct READMEs sharing one boilerplate opener don't
            # collide
            key = hash("\n".join(lines[:3]))
            if key in seen:
                continue
            seen.add(key)
            if f is None or shard_bytes > per_shard:
                if f:
                    f.close()
                f = open(os.path.join(out_dir, f"docs_{shard:03d}.txt"),
                         "w", encoding="utf-8")
                shard += 1
                shard_bytes = 0
            blob = "\n".join(lines) + "\n\n"
            f.write(blob)
            n = len(blob.encode("utf-8"))
            shard_bytes += n
            written += n
            per_source[src] += n
            if written > max_mb * 1024 * 1024:
                report()
                return
    finally:
        if f:
            f.close()
    report()


if __name__ == "__main__":
    main()
