#!/usr/bin/env bash
# One-command multichip scaling profile: run the bench.py --multichip
# variant matrix (single / dp / dp_zero1 / dp_zero1_overlap / dp_seq /
# dp_seq_packing / fsdp) with per-variant jax.profiler traces, summarize
# each trace into collective/compute/host buckets, and land everything in
# one MULTICHIP json — so the scaling investigation is reproducible in CI
# and on TPU with the same command.
#
# On a box with >= N real chips the bench runs on them; otherwise it forces
# an N-device CPU mesh (XLA_FLAGS --xla_force_host_platform_device_count,
# handled by bench.py itself). The per-variant time_breakdown lands inside
# the output json; this wrapper additionally runs tools/trace_summary.py on
# a standalone --profile_steps trace of run_pretraining when --train-trace
# is requested, exercising the full operator workflow end to end.
#
# Usage:
#   scripts/profile_multichip.sh [--devices N] [--out PATH] [--budget SECS]
#   scripts/profile_multichip.sh --summarize TRACE_DIR [--steps K] [--devices N]
#
#   --devices N     mesh size (default 8)
#   --out PATH      output json (default MULTICHIP_r07.json in the repo root)
#   --budget SECS   wall-clock budget for the sweep (default 1500)
#   --summarize D   skip the bench; just bucket an existing profiler trace
#                   dir (e.g. <output_dir>/traces from --profile_steps)
set -euo pipefail
REPO=$(cd "$(dirname "$0")/.." && pwd)
cd "$REPO"

DEVICES=8
DEVICES_SET=""
OUT=""
BUDGET=1500
SUMMARIZE=""
STEPS=""

while [[ $# -gt 0 ]]; do
  case "$1" in
    --devices) DEVICES="$2"; DEVICES_SET=1; shift 2 ;;
    --out) OUT="$2"; shift 2 ;;
    --budget) BUDGET="$2"; shift 2 ;;
    --summarize) SUMMARIZE="$2"; shift 2 ;;
    --steps) STEPS="$2"; shift 2 ;;
    *) echo "unknown arg $1" >&2; exit 1 ;;
  esac
done

if [[ -n "$SUMMARIZE" ]]; then
  # only forward --devices when the caller set it: the trace may be from a
  # run with any mesh size, and a silently-injected default of 8 would make
  # every per-device normalization wrong
  ARGS=(--trace "$SUMMARIZE")
  [[ -n "$DEVICES_SET" ]] && ARGS+=(--devices "$DEVICES")
  [[ -n "$STEPS" ]] && ARGS+=(--steps "$STEPS")
  exec python tools/trace_summary.py "${ARGS[@]}"
fi

ENV=(MULTICHIP_BUDGET_S="$BUDGET")
[[ -n "$OUT" ]] && ENV+=(MULTICHIP_OUT="$OUT")

# bench.py --multichip: bootstraps the mesh (forcing an N-device CPU mesh
# when the box lacks real chips), measures every variant with an extra
# traced window each, and embeds the trace_summary buckets per variant as
# variants.<label>.time_breakdown
env "${ENV[@]}" python bench.py --multichip --devices "$DEVICES"

OUT_PATH=${OUT:-$REPO/MULTICHIP_r07.json}
echo
echo "# per-variant collective/compute attribution (${OUT_PATH}):"
python - "$OUT_PATH" <<'EOF'
import json, sys

with open(sys.argv[1]) as f:
    data = json.load(f)
for label, rec in data.get("variants", {}).items():
    tb = rec.get("time_breakdown") or {}
    if "collective_ms_per_step_device" in tb:
        print(f"  {label:<18} step {rec['step_time_ms']:>9.1f} ms"
              f"  collective {tb['collective_ms_per_step_device']:>8.2f}"
              f"  compute {tb['compute_ms_per_step_device']:>8.2f}"
              f"  ms/step/dev  (fraction {tb['collective_fraction']:.1%})")
    else:
        print(f"  {label:<18} step {rec['step_time_ms']:>9.1f} ms"
              f"  (no breakdown: {tb.get('error', 'trace missing')})")
EOF
