#!/usr/bin/env python
"""Per-device K-FAC state footprint: distributed ownership vs replicated.

BERT-Large + K-FAC does not fit one 16G chip with replicated factors
(measured: batch 8, accum 8, un-rematted needs 28.6G — results/
kfac_large.jsonl notes); the reference hit the same wall on GPUs and
distributed inverse ownership (HYBRID_OPT, grad_worker_fraction,
run_pretraining.py:325-327). This audit builds the production-shape
KFACState for BERT-Large on an 8-device virtual mesh in both layouts and
prints the PER-DEVICE bytes for factors and inverses — the number that
decides HBM fit on a pod slice.

Run: python scripts/kfac_shard_audit.py    (CPU; ~1 min)
Writes results/kfac_shard_audit.json.
"""

from __future__ import annotations

import json
import os
import sys

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")
import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def state_bytes(tree) -> dict:
    """(total_bytes, per_device_bytes) over every array leaf — from the
    analyzer's shared per-leaf sharding table (analysis/hlo.sharding_leaves,
    the same walk behind graphcheck's replication pass and
    parallel/zero.assert_moments_sharded), not a private shard loop."""
    from bert_pytorch_tpu.analysis.hlo import sharding_leaves

    leaves = sharding_leaves(tree)
    total = sum(row["bytes"] for row in leaves)
    per_dev = sum(row["per_device_bytes"] for row in leaves)
    return {"total_mb": round(total / 2**20, 1),
            "per_device_mb": round(per_dev / 2**20, 1)}


def unexpected_replication(tree, mesh) -> list:
    """Findings for every leaf that SHOULD be distributed but is fully
    replicated. The expectation comes from the SAME placement derivation
    KFAC.init applies — optim/kfac.state_shardings, which routes through
    the logical-axis-rules table (parallel/rules.stacked_spec): leaves
    whose leading stacked-layer axis the table distributes are expected
    sharded, everything the table deliberately leaves replicated
    (pooler/NSP 2D sites, non-divisible stacks) carries no expectation.
    The audit's former private rank>=3 + min-bytes heuristic is retired
    into that one derivation, so the audit, the live state, and the
    graphcheck sharding_rules gate can never disagree. This is the
    unexpected-replication pass from bert_pytorch_tpu/analysis — the
    audit's former eyeball check, now the same rule CI runs over the
    compiled train step (tools/graphcheck.py)."""
    from bert_pytorch_tpu.analysis.hlo import sharding_leaves
    from bert_pytorch_tpu.analysis.passes import replication_findings
    from bert_pytorch_tpu.optim.kfac import state_shardings

    leaves = sharding_leaves(tree, expected=state_shardings(tree, mesh))
    return [f.to_dict() for f in
            replication_findings(leaves, rule="kfac_shard_audit")]


def main() -> None:
    from bert_pytorch_tpu.config import BertConfig, pad_vocab_size
    from bert_pytorch_tpu.models import BertForPreTraining
    from bert_pytorch_tpu.optim.kfac import KFAC, KFACConfig
    from bert_pytorch_tpu.parallel import mesh as mesh_lib

    cfg = BertConfig.from_json_file(
        os.path.join(REPO, "configs/bert_large_uncased_config.json"))
    cfg = cfg.replace(vocab_size=pad_vocab_size(cfg.vocab_size, 128),
                      kfac_taps=True, fused_ops=False, attention_impl="xla",
                      hidden_dropout_prob=0.0,
                      attention_probs_dropout_prob=0.0)
    model = BertForPreTraining(cfg, dtype=jnp.bfloat16)

    ids = np.ones((2, 8), np.int32)
    variables = jax.eval_shape(
        lambda r: model.init(r, jnp.asarray(ids), jnp.asarray(ids),
                             jnp.asarray(ids)), jax.random.PRNGKey(0))
    pert = jax.tree.map(lambda sd: jnp.zeros(sd.shape, sd.dtype),
                        variables["perturbations"])
    params = jax.tree.map(lambda sd: jnp.zeros(sd.shape, sd.dtype),
                          variables["params"])
    acts_shape = jax.eval_shape(
        lambda p, pe: model.apply(
            {"params": p, "perturbations": pe}, jnp.asarray(ids),
            jnp.asarray(ids), jnp.asarray(ids),
            mutable=["kfac_in"])[1]["kfac_in"],
        params, pert)
    acts0 = jax.tree.map(lambda sd: jnp.zeros(sd.shape, sd.dtype),
                         acts_shape, is_leaf=lambda x: hasattr(x, "shape"))

    mesh = mesh_lib.make_mesh({"data": 4, "fsdp": 2})
    out = {"mesh": dict(mesh.shape), "model": "bert_large (24 layers)"}
    for label, kf in (
            ("replicated", KFAC(KFACConfig())),
            ("sharded", KFAC(KFACConfig(), mesh=mesh))):
        state = kf.init(acts0, pert)
        out[label] = {
            "factors": state_bytes(state.factors),
            "inverses": state_bytes(state.inverses),
        }
        if label == "sharded":
            # distributed ownership must actually distribute: any MB-scale
            # factor/inverse leaf left fully replicated is a fail-open gate
            findings = (unexpected_replication(state.factors, mesh)
                        + unexpected_replication(state.inverses, mesh))
            out[label]["unexpected_replication"] = findings
            for f in findings:
                print(f"WARNING: {f['rule']}: {f['leaf']}: {f['message']}",
                      file=sys.stderr)
        del state
    rep = out["replicated"]
    sh = out["sharded"]
    out["per_device_reduction"] = round(
        (rep["factors"]["per_device_mb"] + rep["inverses"]["per_device_mb"])
        / max(sh["factors"]["per_device_mb"]
              + sh["inverses"]["per_device_mb"], 1e-9), 2)
    os.makedirs(os.path.join(REPO, "results"), exist_ok=True)
    with open(os.path.join(REPO, "results/kfac_shard_audit.json"), "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out, indent=1))


if __name__ == "__main__":
    main()
