#!/usr/bin/env python
"""K-FAC vs LAMB A/B on identical data/config (one chip).

Runs run_pretraining.py twice for --steps optimization steps — once with
LAMB, once with K-FAC (the reference's headline second-order recipe,
config/bert_kfac_pretraining_phase1_config.json:10-12) — from the same seed
on the same shards, then emits a side-by-side per-step loss table.

Usage:
  python scripts/kfac_ab.py --input_dir <shards> --model_config <json> \
      --steps 300 --out results/kfac_ab
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_arm(name: str, extra_flags, args) -> str:
    outdir = os.path.join(args.out, name)
    os.makedirs(outdir, exist_ok=True)
    # run_pretraining joins output_dir onto log_prefix itself — pass the bare
    # arm name or a relative --out would double the path
    prefix = name
    cmd = [
        sys.executable, os.path.join(REPO, "run_pretraining.py"),
        "--input_dir", args.input_dir,
        "--output_dir", outdir,
        "--model_config_file", args.model_config,
        "--global_batch_size", str(args.global_batch),
        "--local_batch_size", str(args.local_batch),
        "--max_steps", str(args.steps),
        "--learning_rate", str(args.lr),
        "--warmup_proportion", "0.1",
        "--max_predictions_per_seq", "20",
        "--masked_token_fraction", "0.15",
        "--skip_checkpoint",
        "--log_prefix", prefix,
        "--rng_impl", "rbg",
        "--seed", str(args.seed),
    ] + extra_flags
    print(f"# arm {name}: {' '.join(cmd)}", file=sys.stderr, flush=True)
    proc = subprocess.run(cmd, capture_output=True, text=True, timeout=7200)
    if proc.returncode != 0:
        raise SystemExit(f"arm {name} failed:\n{proc.stderr[-3000:]}")
    return os.path.join(outdir, prefix + ".jsonl")


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--input_dir", required=True)
    p.add_argument("--model_config", required=True)
    p.add_argument("--steps", type=int, default=300)
    p.add_argument("--lr", type=float, default=5e-4)
    p.add_argument("--kfac_lr", type=float, default=None,
                   help="K-FAC arm LR; default = --lr")
    p.add_argument("--global_batch", type=int, default=256)
    p.add_argument("--local_batch", type=int, default=64)
    p.add_argument("--seed", type=int, default=42)
    p.add_argument("--out", default="results/kfac_ab")
    args = p.parse_args()

    lamb_log = run_arm("lamb", [], args)
    kfac_flags = ["--kfac"]
    if args.kfac_lr is not None:
        args_lr, args.lr = args.lr, args.kfac_lr
        kfac_log = run_arm("kfac", kfac_flags, args)
        args.lr = args_lr
    else:
        kfac_log = run_arm("kfac", kfac_flags, args)

    def series(path):
        out = {}
        with open(path) as f:
            for line in f:
                r = json.loads(line)
                if r.get("tag") == "train":
                    out[r["step"]] = (r.get("step_loss"),
                                      r.get("mlm_accuracy"))
        return out

    la, kf = series(lamb_log), series(kfac_log)
    table = []
    for step in sorted(set(la) & set(kf)):
        table.append({"step": step,
                      "lamb_loss": la[step][0], "kfac_loss": kf[step][0],
                      "lamb_mlm_acc": la[step][1], "kfac_mlm_acc": kf[step][1]})
    summary = os.path.join(args.out, "ab_summary.jsonl")
    with open(summary, "w") as f:
        for row in table:
            f.write(json.dumps(row) + "\n")
    print(json.dumps({"rows": len(table), "summary": summary,
                      "final": table[-1] if table else None}))


if __name__ == "__main__":
    main()
