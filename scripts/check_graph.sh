#!/usr/bin/env bash
# Graph lint CI gate: static analysis of the compiled train steps plus the
# minimal-ruleset Python lint.
#
#   scripts/check_graph.sh [graphcheck args...]
#
# 1. lint: `ruff check` when ruff is installed, else the stdlib fallback
#    `tools/repolint.py` (same rule classes — see ruff.toml).
# 2. graph gate: tools/graphcheck.py lowers + compiles the production
#    pretrain/ZeRO-1/K-FAC/serve step builders on a forced 8-device CPU
#    mesh (incl. the mixed dp x mp combo, the fsdp gather-on-use combo
#    fsdp_overlap_dp2_fsdp4, kfac_zero1_dp8_bucketed — whose
#    checked-in all-reduce ceiling is deliberately <= HALF of
#    kfac_zero1_dp8's, the round-15 coalesced-reduction acceptance — and
#    the round-16 reduce-scatter combos zero1_rs_dp8 / kfac_zero1_rs_dp8,
#    whose budgets pin reduce-scatter > 0 AND an all-reduce ceiling <=
#    half the zero1_dp8 one, the rs-path acceptance) and
#    diffs their collective inventory / donation table / sharding layout
#    / dtype census / memory estimate against results/graph_budgets.json.
#    Every combo's budget declares a sharding_rules block, so the gate
#    also verifies each compiled input leaf's in-sharding against the
#    spec the logical-axis-rules table (bert_pytorch_tpu/parallel/
#    rules.py, docs/SHARDING.md) derives for it. Exit nonzero names the
#    exact rule, op, and leaf.
#
# After an INTENTIONAL program change: re-baseline with
#   python tools/graphcheck.py --write-budgets
# and commit results/graph_budgets.json + results/graph_report.json with a
# note on why the program moved. docs/OBSERVABILITY.md "Static graph
# analysis" is the operator guide.
set -euo pipefail
cd "$(dirname "$0")/.."

if command -v ruff >/dev/null 2>&1; then
    echo "check_graph: lint via ruff"
    ruff check .
else
    echo "check_graph: ruff not installed — stdlib fallback (tools/repolint.py)"
    python tools/repolint.py
fi

exec python tools/graphcheck.py "$@"
