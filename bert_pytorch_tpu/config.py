"""Model + run configuration system.

Capability parity with the reference's three-level config precedence
(CLI > JSON run config > argparse defaults; reference run_pretraining.py:70-167
and :152-166 for the SUPPRESS-parser trick) and its `BertConfig`
(reference src/modeling.py:188-283), re-expressed as a frozen dataclass so it
can ride through `jax.jit` closures and pytree metadata without hashing issues.

Run configs reference model configs via ``model_config_file``
(reference run_pretraining.py:82,224); model configs also carry tokenizer /
data-pipeline keys (``vocab_file``, ``lowercase``, ``tokenizer``) consumed by
the dataset layer (reference run_pretraining.py:359-364).
"""

from __future__ import annotations

import argparse
import copy
import dataclasses
import json
import re
from typing import Any, Dict, Optional


@dataclasses.dataclass(frozen=True)
class BertConfig:
    """Architecture config for the BERT encoder family.

    Field set matches the reference `BertConfig` (src/modeling.py:191-214) plus
    the tokenizer/data keys its JSON model configs carry
    (config/bert_large_uncased_config.json). Frozen + hashable so a config can
    be a static argument to jitted builders.
    """

    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    hidden_act: str = "gelu"
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    initializer_range: float = 0.02
    output_all_encoded_layers: bool = False
    # NSP on/off; when False the token-type embedding and pooler are skipped
    # (reference src/modeling.py:345-348, :855-858 behavior).
    next_sentence: bool = False
    # Tokenizer / data-pipeline keys carried by model config JSONs.
    model_name: Optional[str] = None
    tokenizer: str = "wordpiece"
    vocab_file: Optional[str] = None
    lowercase: bool = True
    # TPU-native additions (absent in reference; defaults preserve parity).
    dtype: str = "bfloat16"          # compute dtype; params stay fp32
    fused_ops: bool = True            # use Pallas kernels where available
    checkpoint_activations: bool = False
    # Attention implementation (resolved in ops/attention.py):
    #   "xla"            plain einsum path; fastest through seq 256 on v5e
    #   "xla_checkpoint" xla path with probs rematerialized in backward
    #                    (flash-like memory at XLA speed)
    #   "pallas"         blockwise flash kernel; wins when the (S, S) score
    #                    matrix is too large to materialize (long context)
    #   "auto"           xla through seq 256, pallas beyond (measured v5e
    #                    crossover)
    attention_impl: str = "auto"
    # Remat policy when checkpoint_activations=True: "nothing" recomputes the
    # whole layer in backward (max memory savings, most recompute — the
    # reference's torch.utils.checkpoint behavior); "dots" saves matmul
    # outputs and recomputes only elementwise/LayerNorm/dropout chains
    # (jax.checkpoint_policies.dots_saveable) — nearly no-remat speed at a
    # fraction of the activation memory, usually the best throughput/batch
    # trade on TPU.
    remat_policy: str = "nothing"
    # lax.scan unroll factor for the layer stack. 1 = compiled while loop
    # (O(1) compile time in depth — the multi-chip default). Higher values
    # unroll the loop body; num_hidden_layers removes the loop entirely,
    # which on v5e removes the dynamic-update-slice traffic of stacking
    # saved activations / sliced params in the loop carry — a measured ~15%
    # step-time win at BERT-Large seq128 b48 (and it frees enough HBM for
    # batch 56-64 un-rematted), at the cost of O(L) compile time.
    # Ignored when stacked_params=False (that path is inherently a full
    # unroll over per-layer modules).
    scan_unroll: int = 1
    # Parameter layout of the encoder stack. True (default): one nn.scan
    # module whose params carry a leading (L, ...) stacked-layer axis — O(1)
    # compile time in depth, but even at full scan_unroll the backward pass
    # accumulates each layer's weight gradient via dynamic_update_slice into
    # the (L, ...) grad buffer (a measured 9.4% of seq512 step time,
    # docs/PERF.md). False: the encoder is built as L separate BertLayer
    # modules (params under encoder/layer_0 .. layer_{L-1}, no leading L
    # axis), so wgrads write straight into per-layer leaves — no DUS
    # traffic, at the cost of O(L) compile time (always fully unrolled).
    # Checkpoints convert losslessly between the two layouts
    # (models/pretrained.py stack_layer_tree/unstack_layer_tree). With
    # dropout off, training trajectories are identical up to reduction
    # order; with dropout on they are statistically equivalent but not
    # bit-equal — the scan folds the dropout rng by layer index while the
    # per-layer modules fold it by module path, so the two layouts draw
    # different per-layer masks.
    stacked_params: bool = True
    # K-FAC activation/output-grad taps on encoder linear layers (sow +
    # perturb). Off by default: taps add intermediates collections that the
    # K-FAC train step consumes (optim/kfac.py).
    kfac_taps: bool = False
    # Postmortem-debug taps at every jax.named_scope boundary (embeddings,
    # per-layer attention & mlp, pooler, mlm/nsp heads): sow into the
    # 'debug_taps' collection so tools/replay.py --bisect can report the
    # first tensor to go non-finite in a replayed step. Off by default —
    # the sows are Python-gated, so the compiled train step is unchanged.
    debug_taps: bool = False
    # Counter-hash dropout across ALL training dropout sites: each residual
    # tail (dense -> dropout -> LN(residual + .)) fuses into one op whose
    # mask is evaluated in-kernel (ops/layernorm.add_dropout_layer_norm),
    # and the embeddings + XLA-attention-probs sites regenerate their hash
    # masks in the backward pass instead of saving them
    # (ops/attention.hash_dropout). Same Bernoulli statistics as nn.Dropout,
    # different (deterministic counter-based) random stream; measured +13.8
    # MFU points at BERT-Large seq128. False restores the full
    # nn.Dropout-stream behavior at every site (A/B isolation /
    # pre-r5 reproduction). Training only — eval paths are unchanged.
    # Caveat: each site's whole mask derives from ONE 32-bit seed drawn per
    # step, so over a long run a site can (birthday-bound, ~2^16 steps)
    # draw the same seed twice and reuse an identical mask for that step —
    # harmless for training statistics, but not the "fresh bits every
    # element" guarantee of nn.Dropout's threefry stream.
    fused_dropout_ln: bool = True

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "BertConfig":
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})

    @classmethod
    def from_json_file(cls, path: str) -> "BertConfig":
        with open(path, "r", encoding="utf-8") as f:
            return cls.from_dict(json.load(f))

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    def to_json_string(self) -> str:
        return json.dumps(self.to_dict(), indent=2, sort_keys=True) + "\n"

    def replace(self, **kw: Any) -> "BertConfig":
        return dataclasses.replace(self, **kw)

    @property
    def head_dim(self) -> int:
        if self.hidden_size % self.num_attention_heads != 0:
            raise ValueError(
                f"hidden_size ({self.hidden_size}) must be a multiple of "
                f"num_attention_heads ({self.num_attention_heads})"
            )
        return self.hidden_size // self.num_attention_heads


# student presets: `student_<L>l_<H>` names a depth-L, width-H student of
# whatever teacher config it is derived from (training/distill.py). The
# rule, not a table, so any size is nameable; the canonical BERT-Base
# students are student_6l_768 (half depth) and student_4l_512.
_STUDENT_PRESET = re.compile(r"^student_(\d+)l_(\d+)$")


def is_student_preset(name: str) -> bool:
    return bool(_STUDENT_PRESET.match(name or ""))


def student_config(preset: str, teacher: "BertConfig") -> "BertConfig":
    """Derive a student architecture from `teacher` by preset name.

    `student_<L>l_<H>` -> num_hidden_layers=L, hidden_size=H,
    intermediate_size=4H (BERT's MLP ratio), num_attention_heads=H//64
    (BERT's 64-wide heads) lowered until it divides H. Everything else —
    vocab/tokenizer keys, dropout, dtype, fused ops, attention impl,
    parameter layout — is inherited from the teacher, so students train
    and serve through the exact code paths the teacher does (the point
    of the distillation factory: a student is just a checkpoint).
    """
    m = _STUDENT_PRESET.match(preset or "")
    if not m:
        raise ValueError(
            f"unknown student preset {preset!r}; expected student_<L>l_<H> "
            "(e.g. student_6l_768, student_4l_512)")
    layers, hidden = int(m.group(1)), int(m.group(2))
    if layers < 1 or hidden < 1:
        raise ValueError(f"student preset {preset!r}: depth and width "
                         "must be >= 1")
    heads = max(1, hidden // 64)
    while hidden % heads:
        heads -= 1
    return teacher.replace(
        num_hidden_layers=layers,
        hidden_size=hidden,
        num_attention_heads=heads,
        intermediate_size=4 * hidden,
    )


def pad_vocab_size(vocab_size: int, multiple: int = 8) -> int:
    """Pad vocab to a multiple (reference pads to 8 at every load site,
    run_pretraining.py:227-228). On TPU the MXU lane width makes 128 the
    natural multiple for the embedding/decoder matmul; callers pick."""
    return ((vocab_size + multiple - 1) // multiple) * multiple


def explicit_cli_keys(parser: argparse.ArgumentParser,
                      argv: Optional[list] = None) -> set:
    """Which destinations were explicitly given on the command line —
    found by re-parsing with every default suppressed (argparse has no
    public API for this). Shared by merge_args_with_config's CLI-wins
    precedence and run_pretraining's stream-flag validation, so the two
    can never drift on what counts as 'passed'."""
    suppressed = copy.deepcopy(parser)
    for action in suppressed._actions:  # noqa: SLF001
        action.default = argparse.SUPPRESS
    return set(vars(suppressed.parse_args(argv)))


def merge_args_with_config(
    parser: argparse.ArgumentParser,
    argv: Optional[list] = None,
    config_key: str = "config_file",
) -> argparse.Namespace:
    """Three-level precedence: CLI > JSON run config > parser defaults.

    Mirrors the reference's mechanism (run_pretraining.py:152-166): parse once
    normally, then re-parse with all defaults suppressed to learn which flags
    the user explicitly passed; JSON config values override defaults but never
    explicit CLI flags.
    """
    args = parser.parse_args(argv)

    config_path = getattr(args, config_key, None)
    if not config_path:
        return args

    with open(config_path, "r", encoding="utf-8") as f:
        config = json.load(f)

    explicit = explicit_cli_keys(parser, argv)

    for key, value in config.items():
        if key in explicit:
            continue  # CLI wins
        # Keys the entry point doesn't declare (e.g. data-pipeline hints)
        # attach to the namespace rather than crashing.
        setattr(args, key, value)
    return args
