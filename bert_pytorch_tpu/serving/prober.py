"""Synthetic canary prober: known-answer requests through the REAL
frontend, decoded-answer verification, per-task health.

Latency metrics cannot see a silently-corrupted model: a bad checkpoint
swap, a broken quantization scale, or a bit-flipped weight table serves
wrong answers at healthy p99 forever. The prober closes that hole the
way production canaries do — it IS a client:

- one fixed known-answer payload per registered task
  (`KNOWN_ANSWER_PAYLOADS`), POSTed through the live HTTP frontend at a
  low fixed rate (`interval_s`), so the probe exercises the entire
  path: routing, featurization, admission, packing, forward, decode;
- the FIRST successful decode per task is pinned as that task's
  reference answer (the engine is deterministic — packed-vs-single and
  replica bit-identity are proven properties, so the same payload must
  decode identically forever);
- every later probe is verified two ways: schema invariants per task
  (labels count == token count, softmax sums to 1, embedding is
  unit-norm, choice index in range) and an exact-after-rounding match
  against the pinned reference. A mismatch flips THAT task's health;
  the others stay green — which is what localizes a one-task corruption
  (`--slo_inject corrupt_answers` drills exactly this);
- health feeds three consumers: `bert_probe_*` registry families, the
  `prober` block in /healthz, and page-severity alerts merged into the
  SLO engine's /v1/alerts via `alerts()` — an unhealthy probe means
  `status: failing` even though every real request is a fast 200;
- `wait_healthy()` is the machine-checkable pre-swap gate ROADMAP item
  1(c) needs: block until every task has >= 1 verified probe (or a
  deadline), return the verdict.

Stdlib HTTP client on a daemon thread; never raises into the server,
never keeps the process alive.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request
from typing import Any, Callable, Dict, List, Optional, Tuple

# Fixed payloads drawn from the serving fixture's vocab so the canary
# exercises real tokens everywhere (unknown pieces would probe only the
# [UNK] path); any server with a richer vocab still round-trips them.
KNOWN_ANSWER_PAYLOADS: Dict[str, Dict[str, Any]] = {
    "squad": {"question": "who sat on the mat ?",
              "context": "the cat sat on the mat . a dog did run in "
                         "the park"},
    "ner": {"tokens": ["the", "cat", "sat", "on", "the", "mat"]},
    "classify": {"text": "the cat sat on the mat",
                 "text_pair": "a dog did run in the park"},
    "choice": {"question": "who sat on the mat ?",
               "choices": ["the cat", "a dog"]},
    "embed": {"text": "the cat sat on the mat"},
}

# reply fields that legitimately vary probe-to-probe and must not count
# as drift
VOLATILE_KEYS = ("latency_ms",)


def canonicalize(obj: Any, ndigits: int = 4) -> Any:
    """Stable comparable form of a decoded reply: volatile fields
    dropped, floats rounded (bit-identical forwards survive rounding;
    a corrupted forward moves answers far past 1e-4)."""
    if isinstance(obj, dict):
        return {k: canonicalize(v, ndigits) for k, v in sorted(obj.items())
                if k not in VOLATILE_KEYS}
    if isinstance(obj, (list, tuple)):
        return [canonicalize(v, ndigits) for v in obj]
    if isinstance(obj, bool):
        return obj
    if isinstance(obj, float):
        return round(obj, ndigits)
    return obj


def _verify_squad(payload, out) -> Optional[str]:
    if not isinstance(out.get("answer"), str):
        return "answer is not a string"
    if not isinstance(out.get("nbest"), list) or not out["nbest"]:
        return "nbest missing/empty"
    if not out.get("n_windows", 0) >= 1:
        return "n_windows < 1"
    return None


def _verify_ner(payload, out) -> Optional[str]:
    labels = out.get("labels")
    if not isinstance(labels, list) \
            or len(labels) != len(payload["tokens"]):
        return (f"labels count {len(labels or [])} != "
                f"{len(payload['tokens'])} tokens")
    if not all(isinstance(l, str) and l for l in labels):
        return "non-string label"
    return None


def _verify_classify(payload, out) -> Optional[str]:
    scores = out.get("scores")
    if not isinstance(out.get("label"), str):
        return "label is not a string"
    if not isinstance(scores, dict) or not scores:
        return "scores missing"
    total = sum(float(v) for v in scores.values())
    if abs(total - 1.0) > 1e-3:
        return f"scores sum {total:.4f} != 1"
    if out["label"] not in scores:
        return f"label {out['label']!r} not in scores"
    return None


def _verify_choice(payload, out) -> Optional[str]:
    n = len(payload["choices"])
    if not isinstance(out.get("choice"), int) \
            or not 0 <= out["choice"] < n:
        return f"choice {out.get('choice')!r} not in [0, {n})"
    scores = out.get("scores")
    if not isinstance(scores, list) or len(scores) != n:
        return "scores count != choices"
    if abs(sum(float(s) for s in scores) - 1.0) > 1e-3:
        return "scores do not sum to 1"
    return None


def _verify_embed(payload, out) -> Optional[str]:
    emb = out.get("embedding") or (out.get("embeddings") or [None])[0]
    if not isinstance(emb, list) or not emb:
        return "embedding missing"
    if out.get("dim") != len(emb):
        return f"dim {out.get('dim')} != len(embedding) {len(emb)}"
    norm = sum(float(x) ** 2 for x in emb) ** 0.5
    if abs(norm - 1.0) > 1e-2:
        return f"embedding norm {norm:.4f} != 1 (not L2-normalized)"
    return None


VERIFIERS: Dict[str, Callable[[Dict[str, Any], Dict[str, Any]],
                              Optional[str]]] = {
    "squad": _verify_squad,
    "ner": _verify_ner,
    "classify": _verify_classify,
    "choice": _verify_choice,
    "embed": _verify_embed,
}


class CanaryProber:
    """Probe every served task through the live frontend; hold per-task
    health. `start()` launches the daemon loop; `probe_all()` is one
    synchronous round (tests and the pre-swap gate drive it directly)."""

    def __init__(self, url: str, tasks, interval_s: float = 5.0,
                 timeout_s: float = 30.0, registry=None,
                 log: Optional[Callable[[str], None]] = None,
                 time_fn: Callable[[], float] = time.time):
        self.url = url.rstrip("/")
        self.tasks = sorted(tasks)
        unknown = [t for t in self.tasks
                   if t not in KNOWN_ANSWER_PAYLOADS]
        if unknown:
            raise ValueError(
                f"no known-answer payload for task(s) {unknown} — "
                "extend serving/prober.py KNOWN_ANSWER_PAYLOADS when "
                "registering a task")
        self.interval_s = max(0.05, float(interval_s))
        self.timeout_s = float(timeout_s)
        self.log = log
        self.time_fn = time_fn
        self._lock = threading.Lock()
        self._state: Dict[str, Dict[str, Any]] = {
            t: {"healthy": None, "probes": 0, "mismatches": 0,
                "errors": 0, "last_result": None, "last_error": None,
                "baseline_set": False, "last_probe_unix": None,
                "unhealthy_since_unix": None}
            for t in self.tasks}
        self._baseline: Dict[str, Any] = {}
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run,
                                        name="canary-prober", daemon=True)
        if registry is not None:
            self._m_total = registry.counter(
                "bert_probe_total",
                "canary probes by task and result "
                "(ok/mismatch/error)", labels=("task", "result"))
            self._m_healthy = registry.gauge(
                "bert_probe_healthy",
                "1 when the task's last canary probe verified, else 0",
                labels=("task",))
        else:
            self._m_total = self._m_healthy = None

    # -- one probe ------------------------------------------------------------

    def _post(self, task: str,
              payload: Dict[str, Any]) -> Tuple[int, Dict[str, Any]]:
        data = json.dumps(payload).encode("utf-8")
        req = urllib.request.Request(
            f"{self.url}/v1/{task}", data=data,
            headers={"Content-Type": "application/json",
                     "User-Agent": "bert-canary-prober"})
        try:
            with urllib.request.urlopen(req,
                                        timeout=self.timeout_s) as r:
                return r.status, json.loads(r.read().decode("utf-8"))
        except urllib.error.HTTPError as e:
            try:
                body = json.loads(e.read().decode("utf-8"))
            except Exception:
                body = {}
            return e.code, body

    def probe_once(self, task: str) -> Tuple[str, Optional[str]]:
        """One probe of one task -> (result, detail); result is
        ok | mismatch | error. Updates state/metrics."""
        payload = KNOWN_ANSWER_PAYLOADS[task]
        result, detail = "ok", None
        try:
            code, out = self._post(task, payload)
            if code != 200:
                result = "error"
                detail = (f"HTTP {code}: "
                          f"{out.get('error', '')}"[:200] or
                          f"HTTP {code}")
            else:
                detail = VERIFIERS[task](payload, out)
                if detail is not None:
                    result, detail = "mismatch", f"schema: {detail}"
                else:
                    canon = canonicalize(out)
                    ref = self._baseline.get(task)
                    if ref is None:
                        self._baseline[task] = canon
                    elif canon != ref:
                        result = "mismatch"
                        detail = ("decoded answer drifted from the "
                                  "pinned reference (silent model "
                                  "corruption?)")
        except Exception as e:  # timeouts, refused connections, ...
            result, detail = "error", f"{type(e).__name__}: {e}"
        self._note(task, result, detail)
        return result, detail

    def _note(self, task: str, result: str,
              detail: Optional[str]) -> None:
        now = self.time_fn()
        with self._lock:
            st = self._state[task]
            st["probes"] += 1
            st["last_result"] = result
            st["last_probe_unix"] = round(now, 3)
            was_healthy = st["healthy"]
            st["healthy"] = result == "ok"
            if result == "ok":
                st["last_error"] = None
                st["unhealthy_since_unix"] = None
                st["baseline_set"] = task in self._baseline
            else:
                st["mismatches" if result == "mismatch"
                   else "errors"] += 1
                st["last_error"] = detail
                if st["unhealthy_since_unix"] is None:
                    st["unhealthy_since_unix"] = round(now, 3)
        if self._m_total is not None:
            self._m_total.inc(task=task, result=result)
            self._m_healthy.set(1.0 if result == "ok" else 0.0,
                                task=task)
        if result != "ok" and self.log:
            self.log(f"PROBE {result} [{task}]: {detail}")
        elif result == "ok" and was_healthy is False and self.log:
            self.log(f"probe recovered [{task}]")

    def probe_all(self) -> Dict[str, str]:
        """One synchronous round over every task -> {task: result}."""
        return {t: self.probe_once(t)[0] for t in self.tasks}

    # -- background loop ------------------------------------------------------

    def start(self) -> "CanaryProber":
        self._thread.start()
        return self

    def _run(self) -> None:
        # first round immediately: it pins the baselines while the
        # server is provably fresh (a drill's --slo_inject_after_s head
        # start exists exactly for this)
        while True:
            try:
                self.probe_all()
            except Exception:
                pass  # the canary must outlive a bad round
            if self._stop.wait(self.interval_s):
                return

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)

    # -- views ----------------------------------------------------------------

    def status(self) -> Dict[str, Any]:
        """The /healthz `prober` block."""
        with self._lock:
            tasks = {t: dict(st) for t, st in self._state.items()}
        unhealthy = sorted(t for t, st in tasks.items()
                           if st["healthy"] is False)
        return {"tasks": tasks, "interval_s": self.interval_s,
                "healthy": not unhealthy,
                "unhealthy_tasks": unhealthy}

    def alerts(self) -> List[Dict[str, Any]]:
        """Page-severity alerts for unhealthy tasks — wired into
        SLOEngine.add_alert_source so a failed canary flips /healthz to
        `failing` like any other page."""
        out = []
        with self._lock:
            for task, st in self._state.items():
                if st["healthy"] is False:
                    out.append({
                        "slo": f"probe_{task}", "severity": "page",
                        "source": "prober", "task": task,
                        "phase": "serve",
                        "since_unix": st["unhealthy_since_unix"],
                        "description": st["last_error"] or
                        "canary probe failing",
                        "mismatches": st["mismatches"],
                        "errors": st["errors"],
                    })
        return out

    def wait_healthy(self, timeout: float = 60.0,
                     min_probes: int = 1) -> bool:
        """The pre-swap gate: block until EVERY task has >= min_probes
        probes and its last probe verified; False when the deadline
        passes first."""
        deadline = time.monotonic() + float(timeout)
        while time.monotonic() < deadline:
            with self._lock:
                ready = all(st["probes"] >= min_probes
                            and st["healthy"] is True
                            for st in self._state.values())
            if ready:
                return True
            time.sleep(0.05)
        return False
