"""Serving engine: checkpoint restore + AOT bucketed inference forwards.

XLA recompiles on every new input shape, and request lengths are
arbitrary — so a naive server either pads everything to max length
(wasting most of the row on short queries) or eats a multi-second
compile mid-traffic whenever a new length shows up. The TPU-idiomatic
answer is a small set of BUCKETED sequence lengths (default
64/128/256/512): every program the server will ever run is lowered and
compiled ahead of time in `warmup()`, a request rides the smallest
bucket that fits it, and steady-state traffic never touches the
compiler again (CompileWatch pins this: compile count flat after
warmup, tests/test_serving.py).

Each (task, bucket) pair is one `StepProgram`
(training/pretrain.py) — the same AOT lower/compile wrapper the train
step dispatches through, so the compiled executable stays reachable
for the graph lint (tools/graphcheck.py gates a serving forward combo:
zero collectives on a single-device engine, nothing donated).

Checkpoint restore goes through `CheckpointManager.restore_either_layout`
when the checkpoint follows the serving contract ({"params": ...} trees,
scripts/make_serving_fixture.py writes these) — cross-encoder-layout
restores come for free. Full finetune TrainState checkpoints
(run_squad/run_ner output) restore through the raw path with the same
bit-exact layout conversion and a STRICT merge: serving a model whose
head silently fell back to random init is an outage, not a warning.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import numpy as np

DEFAULT_BUCKETS = (64, 128, 256, 512)

# the (B, S) int32 fields every bucketed forward consumes — always the
# packed-batch form (data/packing.py contract); a padded one-request-per-row
# batch is simply the degenerate packing with one segment per row, so BOTH
# scheduler modes execute the identical compiled program
BATCH_FIELDS = ("input_ids", "token_type_ids", "attention_mask",
                "position_ids", "segment_ids")


def select_bucket(length: int,
                  buckets: Sequence[int] = DEFAULT_BUCKETS) -> Optional[int]:
    """Smallest bucket that fits `length` (a request exactly at a bucket
    boundary rides that bucket); None when it exceeds the largest bucket —
    the frontend turns that into HTTP 413."""
    for b in sorted(buckets):
        if length <= b:
            return int(b)
    return None


def zero_batch(batch_rows: int, bucket: int) -> Dict[str, np.ndarray]:
    """The all-pad batch a bucket program is compiled against (segment_ids 0
    everywhere = every slot masked)."""
    return {k: np.zeros((batch_rows, bucket), np.int32)
            for k in BATCH_FIELDS}


def serving_param_shardings(model, bucket: int, mesh) -> Tuple[Any, Any]:
    """(NamedSharding tree, logical-spec tree) for one task model's param
    tree on `mesh`, derived from the logical-axis-rules table
    (parallel/rules.py): each leaf's flax logical annotation resolves
    through `rules.resolve(mesh)`. On a trivial mesh every leaf lands
    replicated; a `--serve_mesh model=K` mesh shards mlp/heads/vocab
    leaves across the model axis. run_server uses the sharding tree to
    place restored params on a replica's device slice, and
    `bucket_input_expectations` below feeds both trees to graphcheck's
    sharding_rules pass."""
    import jax
    import jax.numpy as jnp
    from flax import linen as nn

    from bert_pytorch_tpu.parallel import rules as rules_lib

    sample = jnp.zeros((1, bucket), jnp.int32)
    abstract = jax.eval_shape(
        lambda r: model.init(r, sample, sample, sample),
        jax.random.PRNGKey(0))
    logical = nn.get_partition_spec(abstract["params"])
    shardings = nn.logical_to_mesh_sharding(
        logical, mesh, list(rules_lib.resolve(mesh)))
    return shardings, logical


def bucket_input_expectations(model, bucket: int,
                              mesh=None) -> Tuple[list, list]:
    """(expected shardings, rule labels) for one AOT bucketed forward's
    (params, batch) inputs, flat in tree_leaves order — the engine's
    per-bucket specs, DERIVED from the logical-axis-rules table
    (parallel/rules.py) instead of hand-pinned: param leaves resolve
    their logical annotations through `rules.resolve(mesh)`, batch rows
    ride the table's 'data' rule with no leading accum axis. On the
    default single-device engine every mesh axis is trivial, so the
    table resolves every leaf to a replicated placement; a sharded
    serving mesh (`--serve_mesh model=K`) changes only the `mesh`
    argument. tools/graphcheck.py feeds this into the `sharding_rules`
    pass for the serve combos."""
    import jax
    from jax.sharding import NamedSharding

    from bert_pytorch_tpu.parallel import rules as rules_lib

    if mesh is None:
        from bert_pytorch_tpu.parallel.mesh import make_mesh

        mesh = make_mesh(devices=jax.devices()[:1])
    shardings, logical = serving_param_shardings(model, bucket, mesh)
    is_spec = rules_lib.is_spec_leaf
    expected = list(jax.tree_util.tree_leaves(shardings))
    labels = [rules_lib.label_logical(lg) for lg in
              jax.tree_util.tree_leaves(logical, is_leaf=is_spec)]
    batch_sh = NamedSharding(mesh, rules_lib.batch_spec(0, mesh))
    batch_label = "batch(" + "+".join(rules_lib.batch_axes(mesh)) + ")"
    expected += [batch_sh] * len(BATCH_FIELDS)
    labels += [batch_label] * len(BATCH_FIELDS)
    return expected, labels


def _encoder_layer_count(tree: Any) -> Optional[int]:
    """Encoder depth of a params tree, either layout: unstacked counts
    the encoder/layer_{i} subtrees, stacked reads the leading (L, ...)
    axis of any encoder/layers leaf. None when no encoder is found
    (e.g. a non-BERT tree)."""
    if not isinstance(tree, dict):
        return None
    enc = tree.get("encoder")
    if enc is None and isinstance(tree.get("bert"), dict):
        enc = tree["bert"].get("encoder")
    if not isinstance(enc, dict):
        return None
    idx = [int(k.split("_", 1)[1]) for k in enc
           if isinstance(k, str) and k.startswith("layer_")
           and k.split("_", 1)[1].isdigit()]
    if idx:
        return max(idx) + 1
    layers = enc.get("layers")
    if isinstance(layers, dict):
        import jax

        for leaf in jax.tree_util.tree_leaves(layers):
            shape = np.shape(leaf) or getattr(leaf, "shape", ())
            if shape:
                return int(shape[0])
    return None


def _strict_merge(abstract_params: Any, src: Any) -> Any:
    """Checkpoint tree -> model tree, requiring EVERY model leaf to come
    from the checkpoint with its exact shape. Extra checkpoint subtrees
    (e.g. a pretraining MLM head riding along in a finetune save) are
    ignored; a missing or mis-shaped model leaf raises naming it — and
    when the two trees disagree on encoder DEPTH (the distilled-student-
    checkpoint-under-a-teacher-config mistake, or the reverse) the error
    leads with the expected-vs-found layer counts instead of a wall of
    leaf names."""
    import jax.numpy as jnp

    missing = []

    def merge(dst, src_tree, path=()):
        out = {}
        for k, v in dst.items():
            child = path + (k,)
            if isinstance(v, dict):
                out[k] = merge(v, src_tree.get(k, {})
                               if isinstance(src_tree, dict) else {}, child)
            else:
                cand = (src_tree.get(k)
                        if isinstance(src_tree, dict) else None)
                name = "/".join(child)
                if cand is None:
                    missing.append(name)
                    out[k] = jnp.zeros(v.shape, v.dtype)
                elif tuple(np.shape(cand)) != tuple(v.shape):
                    missing.append(f"{name} (shape {np.shape(cand)} != "
                                   f"{tuple(v.shape)})")
                    out[k] = jnp.zeros(v.shape, v.dtype)
                else:
                    out[k] = jnp.asarray(cand, v.dtype)
        return out

    merged = merge(abstract_params, src)
    if missing:
        msg = ("serving restore is strict — checkpoint is missing "
               f"{len(missing)} required param leaf/leaves: "
               + ", ".join(sorted(missing)[:8])
               + ("..." if len(missing) > 8 else ""))
        want_layers = _encoder_layer_count(abstract_params)
        have_layers = _encoder_layer_count(src)
        if (want_layers is not None and have_layers is not None
                and want_layers != have_layers):
            msg = (f"serving restore: model config expects {want_layers} "
                   f"encoder layer(s) but the checkpoint carries "
                   f"{have_layers} — config/checkpoint depth mismatch. "
                   "If this checkpoint is a distilled student "
                   "(run_distill.py --student), point "
                   "--model_config_file at the student's "
                   "model_config.json (written beside its ckpt), not the "
                   "teacher's. " + msg)
        raise ValueError(msg)
    return merged


def restore_serving_params(init_checkpoint: str, model, max_seq_len: int,
                           log: Callable[[str], None] = print
                           ) -> Tuple[Any, int]:
    """Restore a task model's params for serving. Returns (params, step).

    'dir@step' selects a specific checkpoint step, bare dir = latest (the
    run_squad --init_checkpoint convention). Tries
    `restore_either_layout` first with a {"params": ...} template — the
    params-only serving-checkpoint contract, tolerant of a flipped
    encoder layout; a structure mismatch (full finetune TrainState save)
    falls back to restore_raw + the same bit-exact layout conversion +
    strict merge."""
    import jax
    import jax.numpy as jnp

    from bert_pytorch_tpu.models.pretrained import (convert_tree_layout,
                                                    tree_layout)
    from bert_pytorch_tpu.training.checkpoint import CheckpointManager
    from bert_pytorch_tpu.training.state import unbox

    want_step = None
    ckpt_dir = init_checkpoint
    if "@" in init_checkpoint:
        head, _, tail = init_checkpoint.rpartition("@")
        if tail.isdigit():
            ckpt_dir, want_step = head, int(tail)

    sample = jnp.zeros((1, max_seq_len), jnp.int32)
    abstract = jax.eval_shape(
        lambda r: model.init(r, sample, sample, sample),
        jax.random.PRNGKey(0))
    abstract_params = unbox(abstract["params"])

    mgr = CheckpointManager(ckpt_dir)
    try:
        try:
            state, _extra, step = mgr.restore_either_layout(
                {"params": abstract_params}, step=want_step)
            params = state["params"]
            log(f"serving: restored params-only checkpoint "
                f"{ckpt_dir} step {step}")
        except FileNotFoundError:
            raise
        except Exception:
            raw, step = mgr.restore_raw(step=want_step)
            src = raw.get("params", raw) if isinstance(raw, dict) else raw
            want = tree_layout(abstract_params)
            if want is not None and tree_layout(src) not in (None, want):
                src = convert_tree_layout(src, stacked=(want == "stacked"))
            params = _strict_merge(abstract_params, src)
            log(f"serving: restored finetune checkpoint {ckpt_dir} "
                f"step {step} (strict merge)")
    finally:
        mgr.close()
    return params, int(step)


class ServingEngine:
    """Per-task params + one AOT-compiled forward per sequence bucket.

    `forwards` maps task name -> pure forward fn(params, batch) (the
    tasks/predict.py builders); `params` maps task name -> its param
    tree. All buckets share `batch_rows` rows — the scheduler fills them
    (packed or one-per-row) and the program shape never changes, which is
    what makes the zero-recompile guarantee checkable rather than hoped.

    `mesh` pins the engine to a device slice: params and batches are
    device_put onto it, so N replica engines over disjoint slices never
    contend for a device (`--serve_replicas`), and a multi-device mesh
    shards params per `param_shardings` (`--serve_mesh model=K`, trees
    from `serving_param_shardings`). Default: a one-device mesh on the
    process's first device — exactly the old single-engine placement.
    """

    def __init__(self, forwards: Dict[str, Callable],
                 params: Dict[str, Any],
                 buckets: Sequence[int] = DEFAULT_BUCKETS,
                 batch_rows: int = 8,
                 max_segments: int = 8,
                 compile_watch=None,
                 output_kinds: Optional[Dict[str, str]] = None,
                 mesh=None,
                 param_shardings: Optional[Dict[str, Any]] = None,
                 name: str = "r0"):
        if set(forwards) != set(params):
            raise ValueError(f"forwards tasks {sorted(forwards)} != params "
                             f"tasks {sorted(params)}")
        self._output_kinds = dict(output_kinds or {})
        bad = {t: k for t, k in self._output_kinds.items()
               if k not in ("token", "segment")}
        if bad:
            raise ValueError(f"unknown output kind(s): {bad} "
                             "(want 'token' or 'segment')")
        self.tasks = tuple(sorted(forwards))
        self.buckets = tuple(sorted(int(b) for b in buckets))
        self.batch_rows = int(batch_rows)
        self.max_segments = int(max_segments)
        self.compile_watch = compile_watch
        self.name = str(name)
        import jax
        from jax.sharding import NamedSharding, PartitionSpec

        from bert_pytorch_tpu.parallel import rules as rules_lib

        if mesh is None:
            from bert_pytorch_tpu.parallel.mesh import make_mesh

            mesh = make_mesh(devices=jax.devices()[:1])
        self.mesh = mesh
        self._batch_sharding = NamedSharding(mesh,
                                             rules_lib.batch_spec(0, mesh))
        self._params = {}
        for task in self.tasks:
            sh = (param_shardings or {}).get(task,
                                             NamedSharding(mesh,
                                                           PartitionSpec()))
            # commit every param copy to THIS engine's slice — without it
            # all replicas would silently share jax's default device
            self._params[task] = jax.device_put(params[task], sh)
        self._programs: Dict[Tuple[str, int], Any] = {}
        from bert_pytorch_tpu.training.pretrain import StepProgram

        for task in self.tasks:
            for bucket in self.buckets:
                # params live for the process lifetime: donate nothing
                self._programs[(task, bucket)] = StepProgram(
                    forwards[task], donate_state=False)

    @property
    def max_bucket(self) -> int:
        return self.buckets[-1]

    @property
    def n_devices(self) -> int:
        """Device count behind this replica — the multiplier that turns
        compute wall time into device-seconds for cost accounting."""
        return int(self.mesh.devices.size)

    def output_kind(self, task: str) -> str:
        """'token' (outputs slice per token span) or 'segment' (one
        pooled output per packed segment) — drives the scheduler demux;
        registry TaskSpec.output_kind is the source of truth."""
        return self._output_kinds.get(task, "token")

    def select_bucket(self, length: int) -> Optional[int]:
        return select_bucket(length, self.buckets)

    def _device_batch(self, batch: Dict[str, np.ndarray]):
        import jax

        return jax.device_put(
            {k: np.asarray(batch[k], np.int32) for k in BATCH_FIELDS},
            self._batch_sharding)

    def warmup(self, log: Callable[[str], None] = lambda m: None,
               mark_steady: bool = True) -> int:
        """AOT-compile every (task, bucket) program. Returns the program
        count. After this, `forward` never compiles again — CompileWatch's
        mark_steady() makes any later compile a loud warning.
        `mark_steady=False` defers arming: with N replicas warming up,
        replica K>0's warmup compiles land AFTER replica 0 finished, so
        the caller must arm the shared watch once after ALL replicas
        (run_server does; arming per-engine would fire bogus RECOMPILE
        warnings on every replica but the first)."""
        import time

        n = 0
        for (task, bucket), prog in sorted(self._programs.items()):
            t0 = time.perf_counter()
            prog.compile(self._params[task],
                         self._device_batch(zero_batch(self.batch_rows,
                                                       bucket)))
            n += 1
            log(f"serving[{self.name}]: compiled {task} bucket {bucket} "
                f"({time.perf_counter() - t0:.2f}s)")
        if mark_steady and self.compile_watch is not None:
            self.compile_watch.mark_steady()
        return n

    def forward(self, task: str, batch: Dict[str, np.ndarray]):
        """Run one (batch_rows, bucket) batch; returns host numpy outputs
        (QA: (start, end) each (B, S); NER: (B, S, num_labels))."""
        import jax

        bucket = int(np.shape(batch["input_ids"])[1])
        prog = self._programs.get((task, bucket))
        if prog is None:
            raise KeyError(f"no compiled program for task={task!r} "
                           f"bucket={bucket} (buckets: {self.buckets})")
        out = prog(self._params[task], self._device_batch(batch))
        return jax.device_get(out)

    def programs(self) -> Dict[Tuple[str, int], Any]:
        """The live StepPrograms (graphcheck/tests reach the compiled HLO
        through these)."""
        return dict(self._programs)
