"""Inference & serving stack: checkpoints -> traffic (ROADMAP item 1).

Three layers, each usable on its own:

- `serving.engine`   — restore params from a checkpoint (either encoder
  layout), AOT lower/compile the task forward for a small set of bucketed
  sequence lengths so steady-state traffic never recompiles.
- `serving.batcher`  — bounded request queue + continuous-batching
  scheduler that PACKS multiple short requests into one row using the
  training packer (data/packing.first_fit) + segment-aware attention,
  demuxing per-segment outputs back to their requests.
- `serving.frontend` — stdlib HTTP server: POST /v1/{squad,ner} plus the
  Prometheus /metrics and /healthz every training phase already serves,
  wired through telemetry.init_run(phase="serve").

`run_server.py` at the repo root assembles them; tools/loadtest.py +
scripts/serve_bench.sh measure them; docs/SERVING.md is the operator
guide.
"""

from bert_pytorch_tpu.serving.batcher import (  # noqa: F401
    InferenceRequest, Overloaded, RequestTimeout, Scheduler, TooLong)
from bert_pytorch_tpu.serving.engine import (  # noqa: F401
    ServingEngine, restore_serving_params, select_bucket)
