"""Stdlib HTTP frontend: POST /v1/{squad,ner} + /metrics + /healthz.

Same shape as telemetry/exporter.py (ThreadingHTTPServer on daemon
threads, stdlib-only, never keeps the process alive) with the request
endpoints added: each handler thread featurizes its request (the
tasks/predict.py helpers — the identical code path the eval loops use),
submits the resulting segment(s) to the continuous-batching scheduler,
blocks on the per-request event, and decodes the answer. The Prometheus
/metrics and /healthz a training run serves via `--metrics_port` are
served here on the SAME port, from the same phase="serve" registry the
scheduler publishes into — an orchestrator probes a serving pod exactly
like a training pod.

Status mapping (docs/SERVING.md): 400 malformed JSON / missing fields,
404 unknown route, 413 longer than the largest bucket, 503 queue full
(with Retry-After), 504 admission/result timeout, 500 engine error.

Request tracing (docs/OBSERVABILITY.md): every POST reply carries an
`X-Trace-Id` header naming the trace id(s) the scheduler minted for it
(one per submitted segment — a multi-window squad request lists them
comma-joined), and `GET /v1/traces[?id=a,b][&n=K]` serves the trace
ring's retained span timelines as one Chrome-trace JSON document.

SLO plane (docs/OBSERVABILITY.md): when the server runs with
`--slo_config`, `GET /v1/alerts` serves the burn-rate engine's firing +
recently-resolved alerts and `GET /v1/slo` the budget-remaining view;
/healthz's top-level `status` is the same engine's verdict.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, Optional
from urllib.parse import parse_qs

import numpy as np

from bert_pytorch_tpu.serving.batcher import (Overloaded, RequestTimeout,
                                              TooLong)
from bert_pytorch_tpu.serving.request_trace import collect_trace_ids
from bert_pytorch_tpu.tasks import predict, squad

CONTENT_TYPE_PROM = "text/plain; version=0.0.4; charset=utf-8"
MAX_BODY_BYTES = 1 << 20


class HTTPError(Exception):
    def __init__(self, code: int, message: str,
                 retry_after: Optional[int] = None):
        super().__init__(message)
        self.code = code
        self.message = message
        self.retry_after = retry_after


class _TaskService:
    """Shared scaffolding for the per-task services: scheduler +
    tokenizer, the cross-service tokenizer lock, and the multi-submit
    drain discipline."""

    def __init__(self, scheduler, tokenizer,
                 tok_lock: Optional[threading.Lock] = None):
        self.scheduler = scheduler
        self.tokenizer = tokenizer
        # featurization shares the tokenizer across handler threads; the
        # native C++ encoder's thread safety is not part of its contract.
        # When several services share ONE tokenizer instance (run_server
        # builds exactly one), they must share ONE lock too — a private
        # lock per service would not serialize cross-service access.
        self._tok_lock = tok_lock if tok_lock is not None \
            else threading.Lock()

    def _submit_all(self, submits) -> list:
        """Submit a multi-part request (an iterable of scheduler.submit
        arg tuples). A part shed mid-admission drains the parts already
        queued (they WILL be computed — without a waiter they would be
        orphaned work with no latency/outcome accounting) before
        propagating the shed."""
        reqs = []
        try:
            for args in submits:
                reqs.append(self.scheduler.submit(*args))
        except Exception:
            for req in reqs:
                try:
                    self.scheduler.result(req)
                except Exception:
                    pass
            raise
        return reqs


class SquadService(_TaskService):
    """Featurize -> submit (one request per sliding window) -> n-best
    decode, sharing tasks/squad + tasks/predict with the eval path."""

    def __init__(self, scheduler, tokenizer, answer_cfg=None,
                 doc_stride: int = 128, max_query_length: int = 64,
                 tok_lock: Optional[threading.Lock] = None):
        super().__init__(scheduler, tokenizer, tok_lock=tok_lock)
        self.answer_cfg = answer_cfg or squad.AnswerConfig()
        self.doc_stride = int(doc_stride)
        self.max_query_length = int(max_query_length)

    def __call__(self, body: Dict[str, Any]) -> Dict[str, Any]:
        question = body.get("question")
        context = body.get("context")
        if not isinstance(question, str) or not isinstance(context, str) \
                or not question.strip() or not context.strip():
            raise HTTPError(400, "body must carry non-empty string "
                                 "'question' and 'context'")
        try:
            example = predict.make_squad_example("serve", question, context)
            with self._tok_lock:
                feats = predict.qa_featurize(
                    example, self.tokenizer,
                    max_seq_length=self.scheduler.engine.max_bucket,
                    doc_stride=self.doc_stride,
                    max_query_length=self.max_query_length)
        except ValueError as e:
            raise HTTPError(400, f"featurization failed: {e}")
        reqs = self._submit_all(
            ("squad", np.asarray(feat.input_ids[:ln], np.int32),
             np.asarray(feat.segment_ids[:ln], np.int32))
            for feat, ln in ((f, predict.feature_length(f))
                             for f in feats))
        raws = []
        for feat, req in zip(feats, reqs):
            start, end = self.scheduler.result(req)
            # postprocess indexes logits by in-feature token position;
            # the segment slice is exactly that coordinate system
            raws.append(squad.RawResult(unique_id=feat.unique_id,
                                        start_logits=start.tolist(),
                                        end_logits=end.tolist()))
        out = predict.qa_decode(example, feats, raws, self.answer_cfg)
        out["n_windows"] = len(feats)
        out["real_tokens"] = sum(predict.feature_length(f) for f in feats)
        return out


class NerService(_TaskService):
    """Tokenize pre-split words -> one segment -> per-word label decode."""

    def __init__(self, scheduler, tokenizer, id_to_label: Dict[int, str],
                 tok_lock: Optional[threading.Lock] = None):
        super().__init__(scheduler, tokenizer, tok_lock=tok_lock)
        self.id_to_label = dict(id_to_label)

    def __call__(self, body: Dict[str, Any]) -> Dict[str, Any]:
        tokens = body.get("tokens")
        if isinstance(body.get("text"), str) and tokens is None:
            tokens = body["text"].split()
        if not isinstance(tokens, list) or not tokens \
                or not all(isinstance(t, str) for t in tokens):
            raise HTTPError(400, "body must carry 'tokens' (list of "
                                 "strings) or 'text'")
        try:
            with self._tok_lock:
                ids, piece_word = predict.ner_encode_tokens(
                    tokens, self.tokenizer,
                    max_pieces=self.scheduler.engine.max_bucket)
        except ValueError as e:
            raise HTTPError(413, str(e))
        req = self.scheduler.submit("ner", np.asarray(ids, np.int32))
        logits = self.scheduler.result(req)
        labels = predict.ner_decode(logits, piece_word, self.id_to_label,
                                    n_words=len(tokens))
        return {"tokens": tokens, "labels": labels,
                "real_tokens": len(ids)}


class ClassifyService(_TaskService):
    """GLUE-style pair classification: encode ([CLS] A [SEP] B [SEP])
    through the SAME encode_pair the dataset featurizer uses, submit one
    segment, decode the per-segment pooled logits."""

    def __init__(self, scheduler, tokenizer, class_names,
                 tok_lock: Optional[threading.Lock] = None):
        super().__init__(scheduler, tokenizer, tok_lock=tok_lock)
        self.class_names = list(class_names)

    def __call__(self, body: Dict[str, Any]) -> Dict[str, Any]:
        text = body.get("text")
        pair = body.get("text_pair")
        if not isinstance(text, str) or not text.strip():
            raise HTTPError(400, "body must carry non-empty string 'text' "
                                 "(optional 'text_pair')")
        if pair is not None and not isinstance(pair, str):
            raise HTTPError(400, "'text_pair' must be a string")
        try:
            with self._tok_lock:
                ids, types = predict.encode_pair(
                    self.tokenizer, text, pair or None,
                    max_pieces=self.scheduler.engine.max_bucket)
        except ValueError as e:
            raise HTTPError(400, f"featurization failed: {e}")
        req = self.scheduler.submit("classify",
                                    np.asarray(ids, np.int32),
                                    np.asarray(types, np.int32))
        logits = self.scheduler.result(req)  # (num_labels,)
        out = predict.classify_decode(logits, self.class_names)
        out["real_tokens"] = len(ids)
        return out


class ChoiceService(_TaskService):
    """Multiple choice: one packed segment per (question, choice) pair,
    host-side softmax across the returned per-segment scores."""

    MAX_CHOICES = 16

    def __call__(self, body: Dict[str, Any]) -> Dict[str, Any]:
        question = body.get("question") or ""
        choices = body.get("choices")
        if not isinstance(question, str):
            raise HTTPError(400, "'question' must be a string")
        if not isinstance(choices, list) or len(choices) < 2 \
                or not all(isinstance(c, str) and c.strip()
                           for c in choices):
            raise HTTPError(400, "body must carry 'choices': a list of "
                                 ">=2 non-empty strings")
        if len(choices) > self.MAX_CHOICES:
            raise HTTPError(413, f"{len(choices)} choices > "
                                 f"{self.MAX_CHOICES}")
        encoded = []
        try:
            with self._tok_lock:
                for choice in choices:
                    encoded.append(predict.encode_pair(
                        self.tokenizer, question or choice,
                        choice if question else None,
                        max_pieces=self.scheduler.engine.max_bucket))
        except ValueError as e:
            raise HTTPError(400, f"featurization failed: {e}")
        reqs = self._submit_all(
            ("choice", np.asarray(ids, np.int32),
             np.asarray(types, np.int32))
            for ids, types in encoded)
        scores = [float(np.asarray(self.scheduler.result(req)))
                  for req in reqs]
        out = predict.choice_decode(scores)
        out["real_tokens"] = sum(len(ids) for ids, _ in encoded)
        return out


class EmbedService(_TaskService):
    """Batch-embed endpoint: one segment per text, each returning its
    L2-normalized mean-pooled embedding — the retrieval workload's
    encode path (corpus encoding batches 'texts', query encoding sends
    one 'text')."""

    MAX_TEXTS = 32

    def __call__(self, body: Dict[str, Any]) -> Dict[str, Any]:
        texts = body.get("texts")
        single = body.get("text")
        if texts is None and isinstance(single, str):
            texts = [single]
        if not isinstance(texts, list) or not texts \
                or not all(isinstance(t, str) and t.strip()
                           for t in texts):
            raise HTTPError(400, "body must carry 'text' (string) or "
                                 "'texts' (list of non-empty strings)")
        if len(texts) > self.MAX_TEXTS:
            raise HTTPError(413, f"{len(texts)} texts > {self.MAX_TEXTS} "
                                 "per request; batch client-side")
        encoded = []
        try:
            with self._tok_lock:
                for text in texts:
                    ids, _types = predict.encode_pair(
                        self.tokenizer, text,
                        max_pieces=self.scheduler.engine.max_bucket)
                    encoded.append(ids)
        except ValueError as e:
            raise HTTPError(400, f"featurization failed: {e}")
        reqs = self._submit_all(("embed", np.asarray(ids, np.int32))
                                for ids in encoded)
        embs = [np.asarray(self.scheduler.result(req), np.float32)
                for req in reqs]
        out = {"embeddings": [[round(float(x), 6) for x in e]
                              for e in embs],
               "dim": int(embs[0].shape[-1]),
               "real_tokens": sum(len(ids) for ids in encoded)}
        if isinstance(single, str) and body.get("texts") is None:
            out["embedding"] = out["embeddings"][0]
        return out


class ServingFrontend:
    """One HTTP server for traffic + observability. `services` maps task
    name (any registered task — tasks/registry.py) to a
    callable(body_dict) -> response_dict; `registry`/`healthz_fn` come
    from the phase='serve' TelemetryRun."""

    def __init__(self, services: Dict[str, Callable],
                 registry, healthz_fn: Optional[Callable] = None,
                 port: int = 0, host: str = "0.0.0.0",
                 trace_ring=None, slo_engine=None):
        self.services = dict(services)
        self.registry = registry
        self.healthz_fn = healthz_fn
        self.trace_ring = trace_ring
        self.slo_engine = slo_engine
        # graceful drain (docs/RESILIENCE.md): begin_drain() stops
        # admission (503 + Retry-After so load balancers re-resolve),
        # in-flight requests run to completion, wait_idle() blocks until
        # they have. /metrics and /healthz keep answering throughout —
        # an orchestrator watches the drain via the same probes.
        self._draining = False
        self._inflight = 0
        self._inflight_cv = threading.Condition()
        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def _send(self, code: int, body: str, ctype: str,
                      extra: Optional[Dict[str, str]] = None) -> None:
                payload = body.encode("utf-8")
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(payload)))
                for k, v in (extra or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(payload)

            def _send_json(self, code: int, obj: Dict[str, Any],
                           extra=None) -> None:
                self._send(code, json.dumps(obj, sort_keys=True),
                           "application/json", extra)

            def do_GET(self):  # noqa: N802 (http.server API)
                path = self.path.split("?", 1)[0]
                try:
                    if path == "/metrics":
                        self._send(200, server.registry.render_prometheus(),
                                   CONTENT_TYPE_PROM)
                    elif path == "/healthz":
                        h = (server.healthz_fn()
                             if server.healthz_fn is not None else {})
                        h["draining"] = server._draining
                        h["inflight"] = server.inflight
                        self._send(200, json.dumps(h, sort_keys=True,
                                                   default=str),
                                   "application/json")
                    elif path == "/v1/traces":
                        if server.trace_ring is None:
                            self._send_json(404, {"error": "request "
                                                  "tracing is disabled"})
                        else:
                            q = parse_qs(self.path.partition("?")[2])
                            ids = None
                            if q.get("id"):
                                ids = [t for part in q["id"]
                                       for t in part.split(",") if t]
                            limit = None
                            try:
                                if q.get("n"):
                                    limit = max(1, int(q["n"][0]))
                            except ValueError:
                                pass
                            doc = server.trace_ring.snapshot_events(
                                ids=ids, limit=limit)
                            # strict JSON: a NaN here would be a span
                            # attr bug — fail the export, not the parser
                            self._send(200, json.dumps(doc, sort_keys=True,
                                                       allow_nan=False),
                                       "application/json")
                    elif path == "/v1/alerts":
                        if server.slo_engine is None:
                            self._send_json(404, {
                                "error": "SLO plane is off (start with "
                                         "--slo_config)"})
                        else:
                            self._send_json(
                                200, server.slo_engine.alerts_view())
                    elif path == "/v1/slo":
                        if server.slo_engine is None:
                            self._send_json(404, {
                                "error": "SLO plane is off (start with "
                                         "--slo_config)"})
                        else:
                            self._send_json(
                                200, server.slo_engine.slo_view())
                    else:
                        self._send_json(404, {"error": "not found; try "
                                              "/metrics, /healthz, "
                                              "/v1/traces, /v1/alerts, "
                                              "/v1/slo, or "
                                              "POST /v1/<task>"})
                except BrokenPipeError:
                    pass

            def do_POST(self):  # noqa: N802 (http.server API)
                path = self.path.split("?", 1)[0]
                t0 = time.perf_counter()
                # every scheduler.submit on this thread notes its trace
                # id here; the reply (success OR error) carries them in
                # X-Trace-Id so a slow/failed request can be looked up in
                # /v1/traces by the id the client already holds
                with collect_trace_ids() as trace_ids:
                    self._do_post(path, t0, trace_ids)

            def _do_post(self, path, t0, trace_ids):
                def hdr(extra=None):
                    if trace_ids:
                        extra = dict(extra or {})
                        extra["X-Trace-Id"] = ",".join(trace_ids)
                    return extra

                try:
                    # the body must be consumed BEFORE any error reply:
                    # on a keep-alive connection unread body bytes would
                    # be parsed as the next request line, desyncing every
                    # later request on that socket. An over-size body is
                    # the one case we refuse to read — reply 413 and drop
                    # the connection instead.
                    n = int(self.headers.get("Content-Length") or 0)
                    if n > MAX_BODY_BYTES:
                        self.close_connection = True
                        raise HTTPError(413, f"body {n} bytes > "
                                             f"{MAX_BODY_BYTES}")
                    raw = self.rfile.read(n)
                    service = None
                    if path.startswith("/v1/"):
                        service = server.services.get(path[len("/v1/"):])
                    if service is None:
                        raise HTTPError(
                            404, f"unknown route {path}; serving tasks: "
                            + ", ".join(f"/v1/{t}"
                                        for t in sorted(server.services)))
                    try:
                        body = json.loads(raw.decode("utf-8") or "{}")
                    except ValueError as e:
                        raise HTTPError(400, f"malformed JSON: {e}")
                    if not isinstance(body, dict):
                        raise HTTPError(400, "body must be a JSON object")
                    with server._inflight_cv:
                        if server._draining:
                            # admission stopped: shed with Retry-After so
                            # the client/balancer moves on; requests
                            # admitted before the drain still finish
                            raise HTTPError(503, "draining: this replica "
                                            "is shutting down",
                                            retry_after=5)
                        server._inflight += 1
                    try:
                        out = service(body)
                    finally:
                        with server._inflight_cv:
                            server._inflight -= 1
                            server._inflight_cv.notify_all()
                    out["latency_ms"] = round(
                        (time.perf_counter() - t0) * 1e3, 3)
                    self._send_json(200, out, hdr())
                except HTTPError as e:
                    extra = ({"Retry-After": str(e.retry_after)}
                             if e.retry_after else None)
                    self._send_json(e.code, {"error": e.message},
                                    hdr(extra))
                except TooLong as e:
                    self._send_json(413, {"error": str(e)}, hdr())
                except Overloaded as e:
                    self._send_json(503, {"error": str(e)},
                                    hdr({"Retry-After": "1"}))
                except RequestTimeout as e:
                    self._send_json(504, {"error": str(e)}, hdr())
                except BrokenPipeError:
                    pass
                except Exception as e:
                    self._send_json(500, {"error": f"{type(e).__name__}: "
                                                   f"{e}"}, hdr())

            def log_message(self, fmt, *args):
                pass  # request logs ride the registry, not stdout

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = int(self._httpd.server_address[1])
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="serve-frontend",
            daemon=True)
        self._thread.start()
        self._closed = False

    @property
    def url(self) -> str:
        host = "127.0.0.1" if self.host in ("0.0.0.0", "") else self.host
        return f"http://{host}:{self.port}"

    @property
    def inflight(self) -> int:
        with self._inflight_cv:
            return self._inflight

    @property
    def draining(self) -> bool:
        return self._draining

    def begin_drain(self) -> None:
        """Stop admitting task requests (503 + Retry-After); /metrics,
        /healthz, and requests already past admission are unaffected."""
        with self._inflight_cv:
            self._draining = True

    def wait_idle(self, timeout: Optional[float] = None) -> bool:
        """Block until every in-flight request has completed; returns
        False when `timeout` elapsed first (the caller closes anyway —
        a drain deadline is a deadline)."""
        deadline = (None if timeout is None
                    else time.monotonic() + float(timeout))
        with self._inflight_cv:
            while self._inflight > 0:
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    return False
                self._inflight_cv.wait(timeout=remaining)
        return True

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)
