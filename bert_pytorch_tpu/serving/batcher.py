"""Continuous batching: bounded queue -> packed rows -> per-request demux.

The training packer (data/packing.first_fit) is exactly the multi-tenant
batching primitive an inference server needs ("Boosting Distributed
Training Performance of the Unpadded BERT Model", PAPERS.md 2208.08124):
several short requests share one (S,) row, segment-aware attention keeps
them from seeing each other, and the per-request outputs are plain row
slices because every head this server runs (QA span logits, NER token
logits) is token-local. Packed-vs-one-per-batch responses are
BIT-identical (tests/test_serving.py pins it): cross-segment attention
probabilities are exactly zero on every kernel path, reductions keep the
same length (the row is the bucket either way), and nothing else mixes
tokens.

Flow control, in order:

- `submit()` raises `TooLong` when the request exceeds the largest bucket
  (HTTP 413 — no amount of waiting will ever fit it) and `Overloaded`
  when the bounded queue is full (HTTP 503 + Retry-After: shedding at
  admission keeps tail latency bounded instead of letting the queue grow
  without limit).
- the scheduler thread drains the queue, expires requests older than the
  admission timeout (`RequestTimeout`, HTTP 504 — the client has likely
  given up; computing its answer is pure waste), groups one task per
  batch, picks the bucket of the longest drained request, and first-fits
  requests into `batch_rows` rows. Packing off = the same first_fit with
  max_segments=1, so both modes run the identical compiled program and
  differ only in row occupancy.
- requests that do not fit the current batch stay pending IN ARRIVAL
  ORDER for the next one — continuous batching, not fixed waves.

Every signal lands in the phase="serve" registry: request counters by
task/outcome, end-to-end latency histograms, live queue depth, per-batch
occupancy, and cumulative real/slot token counters (the loadtest derives
batch occupancy per rate sweep from their deltas).
"""

from __future__ import annotations

import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from bert_pytorch_tpu.data.packing import first_fit


class Overloaded(Exception):
    """Queue full — shed at admission (HTTP 503)."""


class RequestTimeout(Exception):
    """Waited longer than the admission timeout (HTTP 504)."""


class TooLong(Exception):
    """Longer than the largest bucket (HTTP 413)."""


# histogram buckets for end-to-end request latency (ms): sub-ms cache-hit
# territory through multi-second overload tails
LATENCY_BUCKETS_MS = (1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
                      500.0, 1000.0, 2500.0, 5000.0, 10000.0, 30000.0)


@dataclass
class InferenceRequest:
    """One queued forward: already-featurized token ids (length L <= the
    largest bucket), resolved to a per-segment output slice."""

    task: str
    input_ids: np.ndarray            # (L,) int32
    token_type_ids: np.ndarray       # (L,) int32
    t_enqueue: float = field(default_factory=time.perf_counter)
    done: threading.Event = field(default_factory=threading.Event)
    result: Any = None               # task-shaped output slices
    error: Optional[Exception] = None

    @property
    def length(self) -> int:
        return int(len(self.input_ids))

    def resolve(self, result: Any = None,
                error: Optional[Exception] = None) -> None:
        self.result = result
        self.error = error
        self.done.set()


class Scheduler:
    """The continuous-batching loop around a ServingEngine."""

    def __init__(self, engine,
                 queue_size: int = 128,
                 admission_timeout_s: float = 10.0,
                 batch_wait_ms: float = 2.0,
                 packing: bool = True,
                 registry=None):
        self.engine = engine
        self.packing = bool(packing)
        self.admission_timeout_s = float(admission_timeout_s)
        self.batch_wait_s = float(batch_wait_ms) / 1e3
        self._q: "queue.Queue[InferenceRequest]" = queue.Queue(
            maxsize=int(queue_size))
        self._pending: List[InferenceRequest] = []
        self._closed = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._init_metrics(registry)

    # -- metrics --------------------------------------------------------------

    def _init_metrics(self, registry) -> None:
        if registry is None:
            from bert_pytorch_tpu.telemetry.registry import MetricsRegistry

            registry = MetricsRegistry(constant_labels={"phase": "serve"})
        self.registry = registry
        self._m_requests = registry.counter(
            "bert_serve_requests_total",
            "requests by task and outcome (ok/too_long/overloaded/"
            "timeout/error)", labels=("task", "outcome"))
        self._m_latency = registry.histogram(
            "bert_serve_request_latency_ms",
            "end-to-end request latency (enqueue -> result), ms",
            labels=("task",), buckets=LATENCY_BUCKETS_MS)
        self._m_depth = registry.gauge(
            "bert_serve_queue_depth",
            "requests admitted but not yet dispatched")
        self._m_batches = registry.counter(
            "bert_serve_batches_total", "forward batches dispatched",
            labels=("task", "bucket"))
        self._m_real_tokens = registry.counter(
            "bert_serve_real_tokens_total",
            "non-pad tokens dispatched to the device")
        self._m_slot_tokens = registry.counter(
            "bert_serve_slot_tokens_total",
            "token slots the device computed (batch_rows x bucket per "
            "batch, pad included)")
        self._m_occupancy = registry.gauge(
            "bert_serve_batch_occupancy",
            "last batch's real tokens / computed slots")
        self._m_segments = registry.gauge(
            "bert_serve_batch_segments",
            "last batch's packed request count")

    def _update_depth(self) -> None:
        self._m_depth.set(self._q.qsize() + len(self._pending))

    # -- client side ----------------------------------------------------------

    def submit(self, task: str, input_ids: np.ndarray,
               token_type_ids: Optional[np.ndarray] = None
               ) -> InferenceRequest:
        """Admit one request (raises TooLong/Overloaded). The caller waits
        on `result(req)`."""
        input_ids = np.asarray(input_ids, np.int32).reshape(-1)
        if token_type_ids is None:
            token_type_ids = np.zeros_like(input_ids)
        token_type_ids = np.asarray(token_type_ids, np.int32).reshape(-1)
        if self.engine.select_bucket(len(input_ids)) is None:
            self._m_requests.inc(task=task, outcome="too_long")
            raise TooLong(
                f"request length {len(input_ids)} exceeds the largest "
                f"bucket {self.engine.max_bucket}")
        req = InferenceRequest(task=task, input_ids=input_ids,
                               token_type_ids=token_type_ids)
        try:
            self._q.put_nowait(req)
        except queue.Full:
            self._m_requests.inc(task=task, outcome="overloaded")
            raise Overloaded(
                f"request queue full ({self._q.maxsize}); shedding — "
                "retry with backoff")
        self._update_depth()
        return req

    def result(self, req: InferenceRequest,
               timeout: Optional[float] = None) -> Any:
        """Block until the request resolves; re-raises its error. The
        latency histogram observes here — the full enqueue->result path
        the client experienced."""
        timeout = (self.admission_timeout_s + 30.0
                   if timeout is None else timeout)
        if not req.done.wait(timeout):
            req.error = RequestTimeout(f"no result within {timeout:.1f}s")
        ms = (time.perf_counter() - req.t_enqueue) * 1e3
        if req.error is not None:
            outcome = ("timeout" if isinstance(req.error, RequestTimeout)
                       else "error")
            self._m_requests.inc(task=req.task, outcome=outcome)
            raise req.error
        self._m_requests.inc(task=req.task, outcome="ok")
        self._m_latency.observe(ms, task=req.task)
        return req.result

    # -- scheduler side -------------------------------------------------------

    def start(self) -> "Scheduler":
        self._thread = threading.Thread(target=self._loop,
                                        name="serve-batcher", daemon=True)
        self._thread.start()
        return self

    def close(self) -> None:
        self._closed.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
        for req in self._drain_all():
            req.resolve(error=RequestTimeout("server shutting down"))

    def _drain_all(self) -> List[InferenceRequest]:
        out, self._pending = list(self._pending), []
        while True:
            try:
                out.append(self._q.get_nowait())
            except queue.Empty:
                return out

    def _expire(self, now: float) -> None:
        """Admission timeout: a request that waited longer than the budget
        resolves with RequestTimeout instead of consuming a batch slot."""
        keep = []
        for req in self._pending:
            if now - req.t_enqueue > self.admission_timeout_s:
                req.resolve(error=RequestTimeout(
                    f"queued {now - req.t_enqueue:.1f}s > admission "
                    f"timeout {self.admission_timeout_s:.1f}s"))
            else:
                keep.append(req)
        self._pending = keep

    def _loop(self) -> None:
        while not self._closed.is_set():
            if not self._pending:
                try:
                    self._pending.append(self._q.get(timeout=0.05))
                except queue.Empty:
                    self._update_depth()
                    continue
            # drain whatever arrived, then give stragglers one batching
            # window to coalesce (continuous batching's only wait)
            self._drain_into_pending()
            if self.batch_wait_s > 0:
                time.sleep(self.batch_wait_s)
                self._drain_into_pending()
            self._expire(time.perf_counter())
            if not self._pending:
                continue
            task = self._pending[0].task
            wave = [r for r in self._pending if r.task == task]
            try:
                placed = self._dispatch(task, wave)
            except Exception as e:
                # engine failures already resolve inside _dispatch; this
                # guards pack/assemble bugs. Fail the HEAD request only —
                # it is the one a broken layout implicates, and dropping
                # it guarantees progress instead of a poison-pill loop
                head = wave[0]
                head.resolve(error=e)
                placed = {id(head)}
            self._pending = [r for r in self._pending
                             if id(r) not in placed]
            self._update_depth()

    def _drain_into_pending(self) -> None:
        cap = self.engine.batch_rows * self.engine.max_segments * 4
        while len(self._pending) < cap:
            try:
                self._pending.append(self._q.get_nowait())
            except queue.Empty:
                return

    def _dispatch(self, task: str, wave: List[InferenceRequest]) -> set:
        """Pack -> forward -> demux one batch; returns the ids of the
        requests actually placed (the rest stay pending, arrival order
        preserved).

        The bucket is the HEAD request's natural bucket, and only
        requests that fit it ride along — sizing by the wave's max would
        drag every short request into the largest bucket under load
        (measured: it inverts the packed-vs-padded win at saturation).
        A longer request waits one round; once it ages to the head, its
        bucket is chosen and shorter traffic packs around it."""
        bucket = self.engine.select_bucket(wave[0].length)
        wave = [r for r in wave if r.length <= bucket]
        max_segments = self.engine.max_segments if self.packing else 1
        bins = first_fit([r.length for r in wave],
                         n_bins=self.engine.batch_rows,
                         capacity=bucket, max_segments=max_segments)
        batch, placements = self._assemble(wave, bins, bucket)
        if not placements:
            return set()
        placed = set(id(req) for req, _, _, _ in placements)
        try:
            outputs = self.engine.forward(task, batch)
        except Exception as e:
            # fail loudly — but ONLY the requests that rode this batch;
            # queued requests that never dispatched stay pending for the
            # next round instead of inheriting a stranger's error
            for req, _, _, _ in placements:
                req.resolve(error=e)
            return placed
        self._note_batch(task, bucket, placements)
        kind = self._output_kind(task)
        for req, row, offset, seg in placements:
            req.resolve(result=self._demux(outputs, row, offset,
                                           req.length, seg, kind))
        return placed

    def _output_kind(self, task: str) -> str:
        getter = getattr(self.engine, "output_kind", None)
        return getter(task) if callable(getter) else "token"

    def _assemble(self, wave: List[InferenceRequest],
                  bins: List[List[int]], bucket: int
                  ) -> Tuple[Dict[str, np.ndarray],
                             List[Tuple[InferenceRequest, int, int, int]]]:
        """Bin layout -> the packed (batch_rows, bucket) arrays
        (data/packing.py field contract minus the training-only labels)
        plus (request, row, offset, segment) placements for the demux."""
        from bert_pytorch_tpu.serving.engine import zero_batch

        batch = zero_batch(self.engine.batch_rows, bucket)
        placements: List[Tuple[InferenceRequest, int, int, int]] = []
        for row, members in enumerate(bins):
            cursor = 0
            for seg, ri in enumerate(members):
                req = wave[ri]
                ln = req.length
                sl = slice(cursor, cursor + ln)
                batch["input_ids"][row, sl] = req.input_ids
                batch["token_type_ids"][row, sl] = req.token_type_ids
                batch["attention_mask"][row, sl] = 1
                batch["segment_ids"][row, sl] = seg + 1
                batch["position_ids"][row, sl] = np.arange(ln,
                                                           dtype=np.int32)
                placements.append((req, row, cursor, seg))
                cursor += ln
        return batch, placements

    def _note_batch(self, task: str, bucket: int,
                    placements: List[Tuple[InferenceRequest, int, int, int]]
                    ) -> None:
        real = sum(req.length for req, _, _, _ in placements)
        slots = self.engine.batch_rows * bucket
        self._m_batches.inc(task=task, bucket=str(bucket))
        self._m_real_tokens.inc(real)
        self._m_slot_tokens.inc(slots)
        self._m_occupancy.set(real / slots)
        self._m_segments.set(len(placements))

    @staticmethod
    def _demux(outputs: Any, row: int, offset: int, length: int,
               seg: int, kind: str = "token") -> Any:
        """Per-request slice of the batch outputs.

        kind='token' (QA span logits, NER token logits): the request's
        tokens live at [row, offset:offset+length] because the head is
        token-local. kind='segment' (pooled heads — classification
        logits (B, G, C), choice scores (B, G), embeddings (B, G, E)):
        the request IS segment `seg` of its row, one pooled output per
        packed segment (registry TaskSpec.output_kind picks the mode)."""
        if kind == "segment":
            if isinstance(outputs, tuple):
                return tuple(np.asarray(o)[row, seg].copy()
                             for o in outputs)
            return np.asarray(outputs)[row, seg].copy()
        sl = slice(offset, offset + length)
        if isinstance(outputs, tuple):
            return tuple(np.asarray(o)[row, sl].copy() for o in outputs)
        return np.asarray(outputs)[row, sl].copy()
