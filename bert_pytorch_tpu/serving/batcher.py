"""Continuous batching: bounded queue -> packed rows -> per-request demux.

The training packer (data/packing.first_fit) is exactly the multi-tenant
batching primitive an inference server needs ("Boosting Distributed
Training Performance of the Unpadded BERT Model", PAPERS.md 2208.08124):
several short requests share one (S,) row, segment-aware attention keeps
them from seeing each other, and the per-request outputs are plain row
slices because every head this server runs (QA span logits, NER token
logits) is token-local. Packed-vs-one-per-batch responses are
BIT-identical (tests/test_serving.py pins it): cross-segment attention
probabilities are exactly zero on every kernel path, reductions keep the
same length (the row is the bucket either way), and nothing else mixes
tokens.

Flow control, in order:

- `submit()` raises `TooLong` when the request exceeds the largest bucket
  (HTTP 413 — no amount of waiting will ever fit it) and `Overloaded`
  when the bounded queue is full (HTTP 503 + Retry-After: shedding at
  admission keeps tail latency bounded instead of letting the queue grow
  without limit).
- the DISPATCHER thread drains the queue, expires requests older than the
  admission timeout (`RequestTimeout`, HTTP 504 — the client has likely
  given up; computing its answer is pure waste), groups one task per
  batch, picks the bucket of the longest drained request, and first-fits
  requests into `batch_rows` rows. Packing off = the same first_fit with
  max_segments=1, so both modes run the identical compiled program and
  differ only in row occupancy.
- a packed wave is handed to a REPLICA queue (shallowest first) and a
  per-replica worker thread executes it on that replica's engine. An
  idle worker steals the OLDEST waiting wave from the DEEPEST other
  queue (work stealing, not static round-robin: mixed-bucket traffic
  makes static assignment lumpy — one replica drowning in 512-bucket
  squad waves while another idles on drained ner traffic). With one
  replica this degenerates to exactly the old single-loop behavior.
  The dispatcher keeps at most ~2 waves per replica outstanding
  (backpressure), so packing still sees a deep pending pool —
  continuous batching, not fixed waves.
- requests that do not fit the current batch stay pending IN ARRIVAL
  ORDER for the next one.

Every signal lands in the phase="serve" registry: request counters by
task/outcome, end-to-end latency histograms, live queue depth (global
plus per-replica `{replica=}` gauges, published on every enqueue/
dequeue/steal transition so scrapes between waves read live depths),
per-batch occupancy, a steal counter, and cumulative real/slot token
counters (the loadtest derives batch occupancy per rate sweep from
their deltas).

Request-path tracing (serving/request_trace.py) rides the same flow:
every admitted request gets a RequestTrace that accumulates host-side
spans (admit/queue_wait/pack/dispatch/compute/demux/respond, terminal
shed/timeout/too_long/error) and retires into the scheduler's TraceRing.
All span recording is host Python on host timestamps — nothing touches
the batch arrays or the compiled program, which is why tracing on/off
cannot perturb packed-vs-single bit-identity. The compute span also
drives the cost layer: wave wall-time x replica device count =
device-seconds, pro-rated to member requests by real tokens and
accumulated into `bert_serve_device_seconds_total` and the per-task
cost-per-1k-tokens gauge at the configured price per device-hour.
"""

from __future__ import annotations

import queue
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from bert_pytorch_tpu.data.packing import first_fit
from bert_pytorch_tpu.serving.request_trace import TraceRing, note_trace_id
from bert_pytorch_tpu.telemetry.stepwatch import resolve_cost_per_device_hour


class Overloaded(Exception):
    """Queue full — shed at admission (HTTP 503)."""


class RequestTimeout(Exception):
    """Waited longer than the admission timeout (HTTP 504)."""


class TooLong(Exception):
    """Longer than the largest bucket (HTTP 413)."""


# histogram buckets for end-to-end request latency (ms): sub-ms cache-hit
# territory through multi-second overload tails
LATENCY_BUCKETS_MS = (1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
                      500.0, 1000.0, 2500.0, 5000.0, 10000.0, 30000.0)


@dataclass
class InferenceRequest:
    """One queued forward: already-featurized token ids (length L <= the
    largest bucket), resolved to a per-segment output slice."""

    task: str
    input_ids: np.ndarray            # (L,) int32
    token_type_ids: np.ndarray       # (L,) int32
    t_enqueue: float = field(default_factory=time.perf_counter)
    done: threading.Event = field(default_factory=threading.Event)
    result: Any = None               # task-shaped output slices
    error: Optional[Exception] = None
    trace: Any = None                # RequestTrace when tracing is on
    t_resolve: float = 0.0           # respond-span start (set by resolve)

    @property
    def length(self) -> int:
        return int(len(self.input_ids))

    def resolve(self, result: Any = None,
                error: Optional[Exception] = None) -> None:
        self.result = result
        self.error = error
        self.t_resolve = time.perf_counter()
        self.done.set()


@dataclass
class _Wave:
    """One packed batch, ready to execute: the dispatcher builds these,
    a replica worker runs them. placements is the (request, row, offset,
    segment) demux layout from `Scheduler._assemble`."""

    task: str
    bucket: int
    batch: Dict[str, np.ndarray]
    placements: List[Tuple[InferenceRequest, int, int, int]]
    t_queued: float = 0.0            # when the dispatcher queued it
    queued_on: int = 0               # replica whose queue received it


class Scheduler:
    """The continuous-batching loop around one or more ServingEngines.

    `engine` is a single engine (the common case, and the pre-replica
    signature every existing caller uses) or a sequence of data-parallel
    replica engines over disjoint device slices (`--serve_replicas`).
    All replicas must share buckets/batch_rows/max_segments — the
    dispatcher packs once and any replica can run the wave."""

    def __init__(self, engine,
                 queue_size: int = 128,
                 admission_timeout_s: float = 10.0,
                 batch_wait_ms: float = 2.0,
                 packing: bool = True,
                 registry=None,
                 trace_ring: Optional[TraceRing] = None,
                 tracing: bool = True,
                 cost_per_device_hour: Optional[float] = None):
        engines = (list(engine) if isinstance(engine, (list, tuple))
                   else [engine])
        if not engines:
            raise ValueError("need at least one engine")
        self.engines = engines
        self.engine = engines[0]
        self.packing = bool(packing)
        # tracing=False is the A/B switch the bit-identity/overhead tests
        # flip; on by default because the per-request cost is microseconds
        if not tracing:
            self.trace_ring: Optional[TraceRing] = None
        else:
            self.trace_ring = (trace_ring if trace_ring is not None
                               else TraceRing())
        self.cost_per_device_hour = resolve_cost_per_device_hour(
            cost_per_device_hour)
        self._cost_lock = threading.Lock()
        self._task_device_seconds: Dict[str, float] = {}
        self._task_real_tokens: Dict[str, float] = {}
        self.admission_timeout_s = float(admission_timeout_s)
        self.batch_wait_s = float(batch_wait_ms) / 1e3
        self._q: "queue.Queue[InferenceRequest]" = queue.Queue(
            maxsize=int(queue_size))
        self._pending: List[InferenceRequest] = []
        self._closed = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._workers: List[threading.Thread] = []
        # per-replica dispatch queues + everything their workers touch,
        # all under one condition: wave handoff, stealing, backpressure
        self._wv = threading.Condition()
        self._waves: List[deque] = [deque() for _ in engines]
        self._inflight = [0] * len(engines)
        self._rstats = [{"dispatched": 0, "steals": 0,
                         "last_dispatch_unix": None} for _ in engines]
        # dispatcher keeps at most this many waves queued fleet-wide so
        # late arrivals still coalesce into deep packs
        self._wave_cap = 2 * len(engines)
        self._init_metrics(registry)

    # -- metrics --------------------------------------------------------------

    def _init_metrics(self, registry) -> None:
        if registry is None:
            from bert_pytorch_tpu.telemetry.registry import MetricsRegistry

            registry = MetricsRegistry(constant_labels={"phase": "serve"})
        self.registry = registry
        self._m_requests = registry.counter(
            "bert_serve_requests_total",
            "requests by task and outcome (ok/too_long/overloaded/"
            "timeout/error)", labels=("task", "outcome"))
        self._m_latency = registry.histogram(
            "bert_serve_request_latency_ms",
            "end-to-end request latency (enqueue -> result), ms",
            labels=("task",), buckets=LATENCY_BUCKETS_MS)
        self._m_depth = registry.gauge(
            "bert_serve_queue_depth",
            "requests admitted but not yet dispatched")
        self._m_batches = registry.counter(
            "bert_serve_batches_total", "forward batches dispatched",
            labels=("task", "bucket"))
        self._m_real_tokens = registry.counter(
            "bert_serve_real_tokens_total",
            "non-pad tokens dispatched to the device")
        self._m_slot_tokens = registry.counter(
            "bert_serve_slot_tokens_total",
            "token slots the device computed (batch_rows x bucket per "
            "batch, pad included)")
        self._m_occupancy = registry.gauge(
            "bert_serve_batch_occupancy",
            "last batch's real tokens / computed slots")
        self._m_segments = registry.gauge(
            "bert_serve_batch_segments",
            "last batch's packed request count")
        self._m_replica_depth = registry.gauge(
            "bert_serve_replica_queue_depth",
            "waves queued on one replica's dispatch queue",
            labels=("replica",))
        self._m_replica_occupancy = registry.gauge(
            "bert_serve_replica_batch_occupancy",
            "one replica's last batch real tokens / computed slots",
            labels=("replica",))
        self._m_steals = registry.counter(
            "bert_serve_steals_total",
            "waves an idle replica stole from another replica's queue",
            labels=("replica",))
        self._m_device_seconds = registry.counter(
            "bert_serve_device_seconds_total",
            "device-seconds of engine compute (wave wall time x the "
            "replica's device count)", labels=("task",))
        self._m_cost = registry.gauge(
            "bert_serve_cost_per_1k_tokens",
            "cumulative device-seconds priced at cost_per_device_hour, "
            "per 1000 real (non-pad) tokens served", labels=("task",))
        self._m_cost_rate = registry.gauge(
            "bert_serve_cost_per_device_hour",
            "the price knob the cost gauges are quoted in "
            "(currency units per device-hour)")
        self._m_cost_rate.set(self.cost_per_device_hour)
        for i in range(len(self.engines)):
            self._m_replica_depth.set(0, replica=str(i))
            self._m_replica_occupancy.set(0.0, replica=str(i))
            self._m_steals.inc(0, replica=str(i))

    def _update_depth(self) -> None:
        with self._wv:
            queued = sum(len(w.placements) for q in self._waves for w in q)
        self._m_depth.set(self._q.qsize() + len(self._pending) + queued)

    def _publish_replica_depth(self, *indices: int) -> None:
        """Publish replica queue-depth gauges. Called (with _wv held) at
        EVERY enqueue/dequeue/steal transition — not only from batching-
        loop iterations — so a /metrics scrape between waves reads the
        live depth, never a stale one."""
        for k in indices:
            self._m_replica_depth.set(len(self._waves[k]), replica=str(k))

    # -- client side ----------------------------------------------------------

    def submit(self, task: str, input_ids: np.ndarray,
               token_type_ids: Optional[np.ndarray] = None
               ) -> InferenceRequest:
        """Admit one request (raises TooLong/Overloaded). The caller waits
        on `result(req)`."""
        input_ids = np.asarray(input_ids, np.int32).reshape(-1)
        if token_type_ids is None:
            token_type_ids = np.zeros_like(input_ids)
        token_type_ids = np.asarray(token_type_ids, np.int32).reshape(-1)
        tr = None
        if self.trace_ring is not None:
            tr = self.trace_ring.new_trace(task)
            note_trace_id(tr.trace_id)
        if self.engine.select_bucket(len(input_ids)) is None:
            self._m_requests.inc(task=task, outcome="too_long")
            if tr is not None:
                self._finish_trace(tr, "too_long",
                                   length=int(len(input_ids)))
            raise TooLong(
                f"request length {len(input_ids)} exceeds the largest "
                f"bucket {self.engine.max_bucket}")
        req = InferenceRequest(task=task, input_ids=input_ids,
                               token_type_ids=token_type_ids)
        try:
            self._q.put_nowait(req)
        except queue.Full:
            self._m_requests.inc(task=task, outcome="overloaded")
            if tr is not None:
                self._finish_trace(tr, "shed",
                                   queue_size=int(self._q.maxsize))
            raise Overloaded(
                f"request queue full ({self._q.maxsize}); shedding — "
                "retry with backoff")
        if tr is not None:
            # admit span: featurized arrays -> a slot in the bounded queue
            tr.span("admit", tr.t_admit, req.t_enqueue,
                    length=req.length)
            req.trace = tr
        self._update_depth()
        return req

    def result(self, req: InferenceRequest,
               timeout: Optional[float] = None) -> Any:
        """Block until the request resolves; re-raises its error. The
        latency histogram observes here — the full enqueue->result path
        the client experienced."""
        timeout = (self.admission_timeout_s + 30.0
                   if timeout is None else timeout)
        if not req.done.wait(timeout):
            req.error = RequestTimeout(f"no result within {timeout:.1f}s")
        ms = (time.perf_counter() - req.t_enqueue) * 1e3
        if req.error is not None:
            outcome = ("timeout" if isinstance(req.error, RequestTimeout)
                       else "error")
            self._m_requests.inc(task=req.task, outcome=outcome)
            if req.trace is not None:
                # no-op when the resolution site already finished it;
                # closes the client-side wait-timeout path otherwise
                self._finish_trace(req.trace, outcome, t0=req.t_enqueue)
            raise req.error
        self._m_requests.inc(task=req.task, outcome="ok")
        self._m_latency.observe(ms, task=req.task)
        if req.trace is not None:
            # respond span: resolved on the worker -> picked up here
            self._finish_trace(req.trace, "ok",
                               t0=req.t_resolve or req.t_enqueue)
        return req.result

    def _finish_trace(self, tr, outcome: str,
                      t0: Optional[float] = None, **attrs: Any) -> None:
        """Record the closing span ('respond' for ok, the terminal name
        otherwise) and retire the trace into the ring. Safe to call from
        racing terminators: finish() is first-wins and the loser's
        ring.add is skipped."""
        now = time.perf_counter()
        tr.span("respond" if outcome == "ok" else outcome,
                tr.t_admit if t0 is None else t0, now, **attrs)
        if tr.finish(outcome, now):
            self.trace_ring.add(tr)

    # -- scheduler side -------------------------------------------------------

    def start(self) -> "Scheduler":
        self._thread = threading.Thread(target=self._loop,
                                        name="serve-batcher", daemon=True)
        self._workers = [
            threading.Thread(target=self._worker, args=(i,),
                             name=f"serve-replica-{i}", daemon=True)
            for i in range(len(self.engines))]
        for w in self._workers:
            w.start()
        self._thread.start()
        return self

    def close(self) -> None:
        self._closed.set()
        with self._wv:
            self._wv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=10)
        for w in self._workers:
            w.join(timeout=10)
        leftovers = self._drain_all()
        with self._wv:
            for q in self._waves:
                while q:
                    leftovers.extend(
                        req for req, _, _, _ in q.popleft().placements)
            self._publish_replica_depth(*range(len(self.engines)))
        for req in leftovers:
            if not req.done.is_set():
                if req.trace is not None:
                    self._finish_trace(req.trace, "timeout",
                                       t0=req.t_enqueue,
                                       reason="shutdown")
                req.resolve(error=RequestTimeout("server shutting down"))

    def wait_idle(self, timeout: float = 30.0) -> bool:
        """Block until every admitted request has resolved — admission
        queue drained, nothing pending, every replica queue empty, no
        wave in flight on any replica. The graceful-drain path calls this
        so ALL replicas finish before the process exits 0."""
        deadline = time.perf_counter() + timeout
        while time.perf_counter() < deadline:
            with self._wv:
                busy = (any(self._waves) or any(self._inflight))
            if not busy and self._q.qsize() == 0 and not self._pending:
                return True
            time.sleep(0.01)
        return False

    def replica_stats(self) -> List[Dict[str, Any]]:
        """Per-replica snapshot for /healthz: dispatch-queue depth,
        in-flight wave count, dispatched/stolen totals, last dispatch
        time, and the engine's compiled bucket set."""
        out = []
        with self._wv:
            for i, eng in enumerate(self.engines):
                st = self._rstats[i]
                out.append({
                    "replica": i,
                    "name": getattr(eng, "name", f"r{i}"),
                    "queue_depth": len(self._waves[i]),
                    "inflight": self._inflight[i],
                    "dispatched": st["dispatched"],
                    "steals": st["steals"],
                    "last_dispatch_unix": st["last_dispatch_unix"],
                    "compiled_buckets": [int(b) for b in
                                         getattr(eng, "buckets", ())],
                })
        return out

    def _drain_all(self) -> List[InferenceRequest]:
        out, self._pending = list(self._pending), []
        while True:
            try:
                out.append(self._q.get_nowait())
            except queue.Empty:
                return out

    def _expire(self, now: float) -> None:
        """Admission timeout: a request that waited longer than the budget
        resolves with RequestTimeout instead of consuming a batch slot."""
        keep = []
        for req in self._pending:
            if now - req.t_enqueue > self.admission_timeout_s:
                if req.trace is not None:
                    self._finish_trace(req.trace, "timeout",
                                       t0=req.t_enqueue,
                                       waited_s=round(
                                           now - req.t_enqueue, 3))
                req.resolve(error=RequestTimeout(
                    f"queued {now - req.t_enqueue:.1f}s > admission "
                    f"timeout {self.admission_timeout_s:.1f}s"))
            else:
                keep.append(req)
        self._pending = keep

    def _loop(self) -> None:
        while not self._closed.is_set():
            if not self._pending:
                try:
                    self._pending.append(self._q.get(timeout=0.05))
                except queue.Empty:
                    self._update_depth()
                    continue
            # drain whatever arrived, then give stragglers one batching
            # window to coalesce (continuous batching's only wait)
            self._drain_into_pending()
            if self.batch_wait_s > 0:
                time.sleep(self.batch_wait_s)
                self._drain_into_pending()
            self._expire(time.perf_counter())
            if not self._pending:
                continue
            # backpressure: with every replica already ~2 waves deep,
            # packing another now would just freeze its contents early —
            # wait a beat (expiry keeps running via the loop) and retry
            with self._wv:
                if sum(map(len, self._waves)) >= self._wave_cap:
                    self._wv.wait(0.02)
                    full = sum(map(len, self._waves)) >= self._wave_cap
                else:
                    full = False
            if full:
                continue
            task = self._pending[0].task
            wave = [r for r in self._pending if r.task == task]
            try:
                placed = self._dispatch(task, wave)
            except Exception as e:
                # replica failures resolve inside the worker; this guards
                # pack/assemble bugs. Fail the HEAD request only — it is
                # the one a broken layout implicates, and dropping it
                # guarantees progress instead of a poison-pill loop
                head = wave[0]
                if head.trace is not None:
                    self._finish_trace(head.trace, "error",
                                       t0=head.t_enqueue, site="pack")
                head.resolve(error=e)
                placed = {id(head)}
            self._pending = [r for r in self._pending
                             if id(r) not in placed]
            self._update_depth()

    def _drain_into_pending(self) -> None:
        cap = self.engine.batch_rows * self.engine.max_segments * 4
        while len(self._pending) < cap:
            try:
                self._pending.append(self._q.get_nowait())
            except queue.Empty:
                return

    def _dispatch(self, task: str, wave: List[InferenceRequest]) -> set:
        """Pack one batch and queue it on the shallowest replica; returns
        the ids of the requests actually placed (the rest stay pending,
        arrival order preserved).

        The bucket is the HEAD request's natural bucket, and only
        requests that fit it ride along — sizing by the wave's max would
        drag every short request into the largest bucket under load
        (measured: it inverts the packed-vs-padded win at saturation).
        A longer request waits one round; once it ages to the head, its
        bucket is chosen and shorter traffic packs around it."""
        t_pack0 = time.perf_counter()
        bucket = self.engine.select_bucket(wave[0].length)
        wave = [r for r in wave if r.length <= bucket]
        max_segments = self.engine.max_segments if self.packing else 1
        bins = first_fit([r.length for r in wave],
                         n_bins=self.engine.batch_rows,
                         capacity=bucket, max_segments=max_segments)
        batch, placements = self._assemble(wave, bins, bucket)
        if not placements:
            return set()
        t_pack1 = time.perf_counter()
        if self.trace_ring is not None:
            for req, _, _, _ in placements:
                if req.trace is not None:
                    req.trace.span("queue_wait", req.t_enqueue, t_pack0)
                    req.trace.span("pack", t_pack0, t_pack1,
                                   bucket=int(bucket),
                                   wave_segments=len(placements))
        placed = set(id(req) for req, _, _, _ in placements)
        with self._wv:
            depths = [len(q) for q in self._waves]
            k = depths.index(min(depths))
            self._waves[k].append(_Wave(task, bucket, batch, placements,
                                        t_queued=time.perf_counter(),
                                        queued_on=k))
            self._publish_replica_depth(k)
            self._wv.notify_all()
        return placed

    def _worker(self, i: int) -> None:
        """One replica's executor: run own queue FIFO; when idle, steal
        the OLDEST wave from the DEEPEST other queue."""
        while True:
            with self._wv:
                if self._closed.is_set():
                    return
                wave, src = None, i
                if self._waves[i]:
                    wave = self._waves[i].popleft()
                else:
                    others = [(len(self._waves[j]), -j) for j
                              in range(len(self._waves)) if j != i]
                    if others:
                        depth, negj = max(others)
                        if depth > 0:
                            src = -negj
                            wave = self._waves[src].popleft()
                            self._rstats[i]["steals"] += 1
                            self._m_steals.inc(replica=str(i))
                if wave is None:
                    self._wv.wait(0.05)
                    continue
                self._publish_replica_depth(src, i)
                self._inflight[i] += 1
                self._rstats[i]["last_dispatch_unix"] = time.time()
                self._wv.notify_all()     # backpressure slot freed
            try:
                self._execute(i, wave)
            finally:
                with self._wv:
                    self._inflight[i] -= 1
                    self._rstats[i]["dispatched"] += 1
                    self._wv.notify_all()
                self._update_depth()

    def _execute(self, i: int, wave: _Wave) -> None:
        """Forward one wave on replica i and demux. Replica choice cannot
        change results: every replica compiled the same program from the
        same params, so packed-vs-single bit-identity holds per replica.

        Tracing here is timestamps around existing calls — the batch
        arrays and the forward are untouched, so tracing on/off cannot
        perturb outputs. The dispatch span records the steal hop
        (queued_on vs the replica that ran it); the compute span carries
        the request's pro-rated share of the wave's device-seconds."""
        tracing = self.trace_ring is not None
        t0 = time.perf_counter()
        if tracing:
            stolen = wave.queued_on != i
            for req, _, _, _ in wave.placements:
                if req.trace is not None:
                    req.trace.span("dispatch", wave.t_queued or t0, t0,
                                   replica=i, queued_on=wave.queued_on,
                                   stolen=stolen)
        try:
            outputs = self.engines[i].forward(wave.task, wave.batch)
        except Exception as e:
            # fail loudly — but ONLY the requests that rode this batch;
            # queued requests that never dispatched stay pending for the
            # next round instead of inheriting a stranger's error
            for req, _, _, _ in wave.placements:
                if req.trace is not None:
                    self._finish_trace(req.trace, "error", t0=t0,
                                       replica=i, site="forward")
                req.resolve(error=e)
            return
        t1 = time.perf_counter()
        real = sum(req.length for req, _, _, _ in wave.placements)
        n_dev = int(getattr(self.engines[i], "n_devices", 1) or 1)
        device_seconds = (t1 - t0) * n_dev
        self._note_batch(i, wave.task, wave.bucket, wave.placements)
        self._note_cost(wave.task, device_seconds, real)
        kind = self._output_kind(wave.task)
        for req, row, offset, seg in wave.placements:
            if req.trace is not None:
                share = req.length / real if real else 0.0
                req.trace.span("compute", t0, t1, replica=i,
                               bucket=int(wave.bucket), n_devices=n_dev,
                               device_seconds=round(
                                   device_seconds * share, 9))
                td0 = time.perf_counter()
                out = self._demux(outputs, row, offset, req.length, seg,
                                  kind)
                req.trace.span("demux", td0, time.perf_counter())
                req.resolve(result=out)
            else:
                req.resolve(result=self._demux(outputs, row, offset,
                                               req.length, seg, kind))

    def _note_cost(self, task: str, device_seconds: float,
                   real_tokens: float) -> None:
        """Accumulate per-task device-seconds and set the cost gauge:
        cumulative device-hours x price, per 1000 real tokens served."""
        with self._cost_lock:
            ds = self._task_device_seconds.get(task, 0.0) + device_seconds
            tk = self._task_real_tokens.get(task, 0.0) + real_tokens
            self._task_device_seconds[task] = ds
            self._task_real_tokens[task] = tk
        self._m_device_seconds.inc(device_seconds, task=task)
        if tk > 0:
            cost = ds / 3600.0 * self.cost_per_device_hour
            self._m_cost.set(cost / (tk / 1000.0), task=task)

    def _output_kind(self, task: str) -> str:
        getter = getattr(self.engine, "output_kind", None)
        return getter(task) if callable(getter) else "token"

    def _assemble(self, wave: List[InferenceRequest],
                  bins: List[List[int]], bucket: int
                  ) -> Tuple[Dict[str, np.ndarray],
                             List[Tuple[InferenceRequest, int, int, int]]]:
        """Bin layout -> the packed (batch_rows, bucket) arrays
        (data/packing.py field contract minus the training-only labels)
        plus (request, row, offset, segment) placements for the demux."""
        from bert_pytorch_tpu.serving.engine import zero_batch

        batch = zero_batch(self.engine.batch_rows, bucket)
        placements: List[Tuple[InferenceRequest, int, int, int]] = []
        for row, members in enumerate(bins):
            cursor = 0
            for seg, ri in enumerate(members):
                req = wave[ri]
                ln = req.length
                sl = slice(cursor, cursor + ln)
                batch["input_ids"][row, sl] = req.input_ids
                batch["token_type_ids"][row, sl] = req.token_type_ids
                batch["attention_mask"][row, sl] = 1
                batch["segment_ids"][row, sl] = seg + 1
                batch["position_ids"][row, sl] = np.arange(ln,
                                                           dtype=np.int32)
                placements.append((req, row, cursor, seg))
                cursor += ln
        return batch, placements

    def _note_batch(self, replica: int, task: str, bucket: int,
                    placements: List[Tuple[InferenceRequest, int, int, int]]
                    ) -> None:
        real = sum(req.length for req, _, _, _ in placements)
        slots = self.engine.batch_rows * bucket
        self._m_batches.inc(task=task, bucket=str(bucket))
        self._m_real_tokens.inc(real)
        self._m_slot_tokens.inc(slots)
        self._m_occupancy.set(real / slots)
        self._m_replica_occupancy.set(real / slots, replica=str(replica))
        self._m_segments.set(len(placements))

    @staticmethod
    def _demux(outputs: Any, row: int, offset: int, length: int,
               seg: int, kind: str = "token") -> Any:
        """Per-request slice of the batch outputs.

        kind='token' (QA span logits, NER token logits): the request's
        tokens live at [row, offset:offset+length] because the head is
        token-local. kind='segment' (pooled heads — classification
        logits (B, G, C), choice scores (B, G), embeddings (B, G, E)):
        the request IS segment `seg` of its row, one pooled output per
        packed segment (registry TaskSpec.output_kind picks the mode)."""
        if kind == "segment":
            if isinstance(outputs, tuple):
                return tuple(np.asarray(o)[row, seg].copy()
                             for o in outputs)
            return np.asarray(outputs)[row, seg].copy()
        sl = slice(offset, offset + length)
        if isinstance(outputs, tuple):
            return tuple(np.asarray(o)[row, sl].copy() for o in outputs)
        return np.asarray(outputs)[row, sl].copy()
