"""Request-path tracing: per-request span timelines for the serving fleet.

Aggregate serving telemetry (queue-depth gauges, latency histograms) says
THAT the tail moved; it cannot say WHY one request was slow. This module
gives every admitted request a trace ID and a host-side span timeline
through its whole life:

    admit -> queue_wait -> pack -> dispatch -> compute -> demux -> respond

with terminal spans on the error exits (`shed` 503, `timeout` 504,
`too_long` 413, `error` 500). The dispatch span carries the steal-hop
evidence (`queued_on` vs `replica`, `stolen`), the compute span carries
the cost attribution (`device_seconds` pro-rated by real tokens across
the wave's members).

Retention is the flight-recorder pattern (telemetry/flight_recorder.py):
a bounded in-memory TraceRing keeps the N slowest traces over the current
and previous rotating time windows — the tail outliers an engineer
actually wants — plus an every-Kth sampled cross-section so the healthy
baseline is visible next to the outliers. Memory is bounded at
2*keep_slowest + keep_sampled traces regardless of traffic.

Export is the Chrome trace event format `telemetry/trace.py` already
parses: complete events (`ph: "X"`, ts/dur in microseconds) named with
the `req/` prefix so `classify()` keeps them out of device-time
summaries, and `summarize_request_events()` / `trace_summary.py
--requests` render per-phase p50/p99 attribution from them. Everything
here is plain host Python (stdlib only, no jax/numpy): span recording is
a tuple append, measured at single-digit microseconds per request — the
overhead budget the bit-identity guarantee rides on is enforced by
tests/test_request_tracing.py.
"""

from __future__ import annotations

import heapq
import itertools
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Any, Dict, Iterable, List, Optional

# span names exported as f"{REQUEST_SPAN_PREFIX}{name}" so they can never
# collide with HLO op names or host/ phases in a merged trace view
REQUEST_SPAN_PREFIX = "req/"

# the lifecycle vocabulary, in request order
REQUEST_PHASES = ("admit", "queue_wait", "pack", "dispatch", "compute",
                  "demux", "respond")

# terminal spans: the error exits; exactly one terminal OR `respond`
# closes a trace
TERMINAL_SPANS = ("shed", "timeout", "too_long", "error")


class RequestTrace:
    """One request's span timeline. Spans are (name, t0, t1, attrs)
    tuples on the perf_counter clock; `finish()` freezes the trace
    (first caller wins — late span/finish calls are no-ops), so a trace
    retained by the ring is immutable from the moment it is exported."""

    __slots__ = ("trace_id", "task", "seq", "t_admit", "spans",
                 "outcome", "total_ms", "finished")

    def __init__(self, trace_id: str, task: str, t_admit: float, seq: int):
        self.trace_id = trace_id
        self.task = task
        self.seq = seq
        self.t_admit = t_admit
        self.spans: List[tuple] = []
        self.outcome: Optional[str] = None
        self.total_ms = 0.0
        self.finished = False

    def span(self, name: str, t0: float, t1: float, **attrs: Any) -> None:
        """Record one closed span. Attr values must be JSON-scalar
        (str/int/float/bool) — the export is strict JSON."""
        if self.finished:
            return
        self.spans.append((name, t0, t1, attrs or None))

    def finish(self, outcome: str, t_end: float) -> bool:
        """Close the trace with its terminal outcome; True only for the
        first caller (racing terminators — client-side wait timeout vs a
        late demux — keep the first outcome, and the loser's ring.add is
        skipped)."""
        if self.finished:
            return False
        self.finished = True
        self.outcome = outcome
        self.total_ms = max(t_end - self.t_admit, 0.0) * 1e3
        return True

    def to_events(self) -> List[Dict[str, Any]]:
        """Chrome trace complete events (ph="X", ts/dur in us). Every
        event's args carry trace_id/task/outcome/total_ms so a single
        span is self-describing when traces are merged into one file."""
        events = []
        for name, t0, t1, attrs in self.spans:
            args: Dict[str, Any] = {
                "trace_id": self.trace_id,
                "task": self.task,
                "outcome": self.outcome or "open",
                "total_ms": round(self.total_ms, 3),
            }
            if attrs:
                args.update(attrs)
            events.append({
                "name": REQUEST_SPAN_PREFIX + name,
                "cat": "request",
                "ph": "X",
                "pid": 1,
                "tid": self.seq,
                "ts": round(t0 * 1e6, 3),
                "dur": round(max(t1 - t0, 0.0) * 1e6, 3),
                "args": args,
            })
        return events


class TraceRing:
    """Bounded flight recorder for finished request traces.

    Keeps the `keep_slowest` slowest traces per rotating `window_s`
    window (current + previous, so a scrape right after rotation still
    sees the recent tail) and an every-`sample_every`-th sampled
    cross-section capped at `keep_sampled`. Thread-safe; `add()` is the
    hot-path cost — one lock, one heap push."""

    def __init__(self, keep_slowest: int = 32, sample_every: int = 16,
                 keep_sampled: int = 64, window_s: float = 60.0,
                 time_fn=time.monotonic):
        self.keep_slowest = max(1, int(keep_slowest))
        self.sample_every = max(1, int(sample_every))
        self.window_s = float(window_s)
        self._time = time_fn
        self._lock = threading.Lock()
        self._cur: List[tuple] = []      # min-heap of (total_ms, seq, trace)
        self._prev: List[tuple] = []
        self._window_start = self._time()
        self._sampled: deque = deque(maxlen=max(1, int(keep_sampled)))
        self._count = 0
        self._by_outcome: Dict[str, int] = {}
        self._seq = itertools.count(1)

    def new_trace(self, task: str,
                  t_admit: Optional[float] = None) -> RequestTrace:
        seq = next(self._seq)
        return RequestTrace(f"{task}-{seq:06x}", task,
                            time.perf_counter() if t_admit is None
                            else t_admit, seq)

    def add(self, trace: RequestTrace) -> None:
        with self._lock:
            now = self._time()
            if now - self._window_start >= self.window_s:
                self._prev = self._cur
                self._cur = []
                self._window_start = now
            self._count += 1
            self._by_outcome[trace.outcome] = \
                self._by_outcome.get(trace.outcome, 0) + 1
            if self._count % self.sample_every == 0:
                self._sampled.append(trace)
            item = (trace.total_ms, trace.seq, trace)
            if len(self._cur) < self.keep_slowest:
                heapq.heappush(self._cur, item)
            elif item > self._cur[0]:
                heapq.heapreplace(self._cur, item)

    def traces(self, ids: Optional[Iterable[str]] = None,
               limit: Optional[int] = None) -> List[RequestTrace]:
        """Retained traces, slowest first, deduped by trace_id (a trace
        can sit in both the slowest heap and the sampled deck)."""
        with self._lock:
            pool = ([t for _, _, t in self._cur]
                    + [t for _, _, t in self._prev]
                    + list(self._sampled))
        seen: Dict[str, RequestTrace] = {}
        for t in pool:
            seen.setdefault(t.trace_id, t)
        out = sorted(seen.values(), key=lambda t: (-t.total_ms, t.seq))
        if ids is not None:
            want = set(ids)
            out = [t for t in out if t.trace_id in want]
        if limit:
            out = out[:limit]
        return out

    def snapshot_events(self, ids: Optional[Iterable[str]] = None,
                        limit: Optional[int] = None) -> Dict[str, Any]:
        """The /v1/traces payload: one Chrome-trace JSON document whose
        traceEvents hold every retained (or requested) trace's spans."""
        retained = self.traces(ids=ids, limit=limit)
        events: List[Dict[str, Any]] = []
        for t in retained:
            events.extend(t.to_events())
        doc = {"traceEvents": events, "displayTimeUnit": "ms"}
        doc["metadata"] = dict(self.stats(), exported=len(retained))
        return doc

    def stats(self) -> Dict[str, Any]:
        """Retention counters for /healthz."""
        with self._lock:
            return {
                "seen": self._count,
                "retained_slowest": len(self._cur) + len(self._prev),
                "retained_sampled": len(self._sampled),
                "by_outcome": dict(self._by_outcome),
                "keep_slowest": self.keep_slowest,
                "sample_every": self.sample_every,
                "window_s": self.window_s,
            }


# -- trace-id handoff to the HTTP layer ---------------------------------------
# The frontend handler thread opens a collection scope around the service
# call; Scheduler.submit notes each new trace id into it; the handler
# stamps the joined ids into the X-Trace-Id response header. Thread-local
# so concurrent handler threads cannot see each other's ids; a no-op
# (one getattr) when no scope is open — e.g. direct Scheduler use.

_collector = threading.local()


@contextmanager
def collect_trace_ids():
    """Collect every trace id created on this thread inside the scope."""
    ids: List[str] = []
    prev = getattr(_collector, "ids", None)
    _collector.ids = ids
    try:
        yield ids
    finally:
        _collector.ids = prev


def note_trace_id(trace_id: str) -> None:
    ids = getattr(_collector, "ids", None)
    if ids is not None:
        ids.append(trace_id)
