"""int8 weight-only serving quantization (`--serve_dtype int8`).

BERT inference at serving batch sizes is weight-bandwidth-bound, so
halving (vs bf16) or quartering (vs f32) the weight bytes is a direct
throughput lever. The scheme is the boring one that works: SYMMETRIC
PER-CHANNEL quantization of every matmul-shaped param (ndim >= 2) —
scale[c] = max|w[..., c]| / 127 over the last ("output channel") axis,
q = round(w / scale) clipped to int8. Quantization happens ONCE,
host-side, at restore time (`quantize_tree`); the quantized tree
replaces each weight leaf with a `{"q8": int8, "scale": f32}` dict, so
the param pytree the AOT programs close over carries int8 in device
memory. Dequantization happens IN-GRAPH (`wrap_forward`): the forward
sees `q8.astype(f32) * scale` cast to the serving compute dtype, which
XLA fuses into the consuming dot — weights stay int8 in HBM,
activations stay bf16, and there is no separate dequantized copy.

Biases, norms, and every other small ndim<2 leaf stay in their restored
float dtype: they are noise in the byte budget and quantizing them
costs accuracy for nothing.

The accuracy contract: serving int8 is only allowed when the decode
delta against the f32 reference forward is under a configurable gate
(`decode_delta` here; tools/quantcheck.py is the offline CLI,
run_server refuses to serve past --int8_max_delta at startup). A broken
quantization (e.g. corrupted scales — `corrupt_scales` injects exactly
that for the gate's own test) must FAIL the gate, not serve garbage.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Tuple

import numpy as np

# leaves smaller than this many elements are never worth quantizing
_MIN_ELEMENTS = 64

_Q_KEY = "q8"
_SCALE_KEY = "scale"


def is_quantized_leaf(x: Any) -> bool:
    """True for the `{"q8": ..., "scale": ...}` dict `quantize_tree`
    substitutes for a weight leaf."""
    return (isinstance(x, dict) and set(x) == {_Q_KEY, _SCALE_KEY})


def quantize_tree(params: Any) -> Tuple[Any, Dict[str, int]]:
    """Host-side symmetric per-channel int8 quantization of a param tree.

    Returns (quantized tree, stats). Every float leaf with ndim >= 2 and
    enough elements becomes {"q8": int8 array, "scale": f32 array
    broadcastable against it (per last-axis channel)}; everything else
    passes through untouched. stats counts leaves and byte totals so the
    server can log what it actually saved."""
    stats = {"quantized_leaves": 0, "passthrough_leaves": 0,
             "bytes_before": 0, "bytes_after": 0}

    def one(leaf):
        w = np.asarray(leaf)
        stats["bytes_before"] += w.nbytes
        if (w.ndim < 2 or w.size < _MIN_ELEMENTS
                or not np.issubdtype(w.dtype, np.floating)):
            stats["passthrough_leaves"] += 1
            stats["bytes_after"] += w.nbytes
            return leaf
        w32 = w.astype(np.float32)
        reduce_axes = tuple(range(w32.ndim - 1))
        amax = np.max(np.abs(w32), axis=reduce_axes, keepdims=True)
        scale = np.maximum(amax / 127.0, 1e-12).astype(np.float32)
        q = np.clip(np.rint(w32 / scale), -127, 127).astype(np.int8)
        stats["quantized_leaves"] += 1
        stats["bytes_after"] += q.nbytes + scale.nbytes
        return {_Q_KEY: q, _SCALE_KEY: scale}

    def walk(node):
        if isinstance(node, dict):
            return {k: walk(v) for k, v in node.items()}
        return one(node)

    return walk(params), stats


def dequantize_tree(qparams: Any, dtype) -> Any:
    """Traceable inverse: q8 * scale in f32, cast to the serving compute
    dtype. Called inside the jitted forward so XLA keeps int8 as the
    stored representation and fuses the convert+scale into the consumer."""
    import jax.numpy as jnp

    def walk(node):
        if is_quantized_leaf(node):
            deq = (node[_Q_KEY].astype(jnp.float32)
                   * node[_SCALE_KEY].astype(jnp.float32))
            return deq.astype(dtype)
        if isinstance(node, dict):
            return {k: walk(v) for k, v in node.items()}
        return node

    return walk(qparams)


def wrap_forward(forward: Callable, dtype) -> Callable:
    """fn(params, batch) -> fn(qparams, batch): dequantize-then-forward,
    jit-composable (the ServingEngine AOT-compiles the wrapped fn, so the
    dequant lives inside the same executable as the matmuls)."""

    def quantized_forward(qparams, batch):
        return forward(dequantize_tree(qparams, dtype), batch)

    return quantized_forward


def corrupt_scales(qparams: Any, factor: float = 37.0) -> Any:
    """Deliberately break the first quantized leaf's scales (multiply by
    `factor`) — the accuracy gate MUST trip on the result. quantcheck's
    --inject broken_scale and the tests use this."""
    done = [False]

    def walk(node):
        if is_quantized_leaf(node) and not done[0]:
            done[0] = True
            return {_Q_KEY: node[_Q_KEY],
                    _SCALE_KEY: np.asarray(node[_SCALE_KEY]) * factor}
        if isinstance(node, dict):
            return {k: walk(v) for k, v in node.items()}
        return node

    out = walk(qparams)
    if not done[0]:
        raise ValueError("corrupt_scales: no quantized leaf found")
    return out


def probe_batch(batch_rows: int, bucket: int, vocab_size: int,
                max_segments: int = 2, seed: int = 0) -> Dict[str, np.ndarray]:
    """Deterministic synthetic packed batch for the accuracy gate: every
    row fully occupied by `max_segments` segments of random in-vocab
    tokens. Same batch every run -> the gate's verdict is reproducible."""
    rng = np.random.RandomState(seed)
    from bert_pytorch_tpu.serving.engine import zero_batch

    batch = zero_batch(batch_rows, bucket)
    seg_len = bucket // max_segments
    for row in range(batch_rows):
        for seg in range(max_segments):
            lo, hi = seg * seg_len, (seg + 1) * seg_len
            batch["input_ids"][row, lo:hi] = rng.randint(
                1, max(2, vocab_size), size=hi - lo)
            batch["attention_mask"][row, lo:hi] = 1
            batch["segment_ids"][row, lo:hi] = seg + 1
            batch["position_ids"][row, lo:hi] = np.arange(hi - lo)
    return batch


def decode_delta(ref_forward: Callable, ref_params: Any,
                 q_forward: Callable, qparams: Any,
                 batch: Dict[str, np.ndarray]) -> Dict[str, float]:
    """Compare the quantized decode against the f32 reference on one
    batch. Returns {"rel_delta": max-abs diff normalized by the reference
    magnitude, "max_abs_delta": raw, "argmax_agreement": fraction of
    positions whose argmax over the trailing axis agrees (1.0 when no
    output has a >1-wide trailing axis)}. rel_delta is what the serving
    gate thresholds."""
    import jax

    ref = jax.device_get(ref_forward(ref_params, batch))
    got = jax.device_get(q_forward(qparams, batch))
    ref_leaves = jax.tree_util.tree_leaves(ref)
    got_leaves = jax.tree_util.tree_leaves(got)
    if len(ref_leaves) != len(got_leaves):
        raise ValueError("reference/quantized outputs differ in structure")
    max_abs = 0.0
    ref_mag = 0.0
    agree_n = agree_total = 0
    for a, b in zip(ref_leaves, got_leaves):
        a = np.asarray(a, np.float32)
        b = np.asarray(b, np.float32)
        if a.shape != b.shape:
            raise ValueError(f"output shape mismatch {a.shape} vs {b.shape}")
        max_abs = max(max_abs, float(np.max(np.abs(a - b))) if a.size else 0.0)
        ref_mag = max(ref_mag, float(np.max(np.abs(a))) if a.size else 0.0)
        if a.ndim >= 1 and a.shape[-1] > 1:
            agree_n += int(np.sum(np.argmax(a, -1) == np.argmax(b, -1)))
            agree_total += int(np.prod(a.shape[:-1]))
    return {
        "max_abs_delta": max_abs,
        "rel_delta": max_abs / (ref_mag + 1e-9),
        "argmax_agreement": (agree_n / agree_total
                             if agree_total else 1.0),
    }
