"""Cross-host perf aggregation: per-host metrics jsonl + process-0 fold.

On a multi-host run every process keeps its own StepWatch, but only
process 0's logger has sinks — so the fleet's perf record describes ONE
host and a straggler (slow disk feeding `data_wait`, a thermally throttled
chip inflating `step_time_ms`) is invisible exactly when it matters:
SPMD training runs at the speed of the slowest host. PAPERS.md "Scalable
Training of Language Models using JAX pjit and TPUv4" calls straggler
attribution table stakes at pod scale; 2008.00177 motivates the per-host
cost accounting.

The mechanism mirrors `flight_recorder.per_host_dir`: a shared directory
(`<output_dir>/metrics_hosts/`) holding one append-only jsonl per process
(`host00000.jsonl`, ...). Every process `publish()`es the numeric fields
of each StepWatch interval record; process 0's `fold()` reads the LAST
record of every host file (a bounded tail read — no file is ever scanned
whole) and folds cross-host min/mean/max of the fold fields
(`step_time_ms`, `data_wait_ms` by default) into its own perf record,
plus a straggler warning when one host's step time z-scores above
`z_threshold` against the fleet.

Files, not collectives, on purpose: a collective in the metrics path would
add a cross-host sync point to every interval (the one thing the
telemetry design rules out), and files keep the aggregation readable
after the run dies — the same postmortem property the flight recorder
has. The cost is folds seeing each host's *latest* interval, which may
lag a step or two behind process 0's; records carry their step id so the
fold reports the spread (`hosts_step_min`/`max`) instead of pretending.

Stdlib-only, no jax import: process index/count are constructor args, so
the two-process gloo harness (tests/multihost_child.py) and plain unit
tests drive it identically.
"""

from __future__ import annotations

import json
import math
import os
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

DEFAULT_FOLD_FIELDS = ("step_time_ms", "data_wait_ms")
_TAIL_BYTES = 65536


def host_file(root_dir: str, process_index: int) -> str:
    return os.path.join(root_dir, f"host{process_index:05d}.jsonl")


def read_last_record(path: str) -> Optional[Dict[str, Any]]:
    """Last complete JSON line of a host file, reading only a bounded tail.
    A torn final line (a concurrent writer mid-append) falls back to the
    previous complete one; missing/empty files return None."""
    try:
        size = os.path.getsize(path)
        with open(path, "rb") as f:
            f.seek(max(0, size - _TAIL_BYTES))
            tail = f.read().decode("utf-8", errors="replace")
    except OSError:
        return None
    for line in reversed(tail.splitlines()):
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue  # torn write: try the line before it
        if isinstance(rec, dict):
            return rec
    return None


class HostMetricsAggregator:
    """Per-host publish + process-0 fold over a shared directory."""

    def __init__(self, root_dir: str, process_index: int,
                 process_count: int, z_threshold: float = 3.0,
                 fold_fields: Sequence[str] = DEFAULT_FOLD_FIELDS):
        self.root_dir = root_dir
        self.process_index = int(process_index)
        self.process_count = int(process_count)
        self.z_threshold = float(z_threshold)
        self.fold_fields = tuple(fold_fields)
        os.makedirs(root_dir, exist_ok=True)
        self.path = host_file(root_dir, self.process_index)
        self._file = open(self.path, "a", encoding="utf-8")

    # -- every process -------------------------------------------------------

    def publish(self, step: int, record: Dict[str, Any]) -> None:
        """Append this host's interval record (numeric fields only — the
        fold needs numbers, and host files should not balloon with
        strings) with host/step/time stamps."""
        rec = {"host": self.process_index, "step": int(step),
               "time": round(time.time(), 3)}
        for k, v in record.items():
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                continue
            if isinstance(v, float) and not math.isfinite(v):
                continue  # a NaN metric must never kill the publish path
            rec[k] = v
        self._file.write(json.dumps(rec, allow_nan=False, default=str)
                         + "\n")
        self._file.flush()

    # -- process 0 -----------------------------------------------------------

    def fold(self) -> Tuple[Dict[str, Any], Optional[str]]:
        """Cross-host aggregate of every host's latest record, plus a
        straggler warning string (or None). Empty dict when fewer than two
        hosts have reported — a fold must never pretend a fleet exists."""
        latest: Dict[int, Dict[str, Any]] = {}
        for i in range(self.process_count):
            rec = read_last_record(host_file(self.root_dir, i))
            if rec is not None:
                latest[i] = rec
        if len(latest) < 2:
            return {}, None

        agg: Dict[str, Any] = {
            "hosts_reporting": len(latest),
            "hosts_step_min": min(r.get("step", 0) for r in latest.values()),
            "hosts_step_max": max(r.get("step", 0) for r in latest.values()),
        }
        warning = None
        for field in self.fold_fields:
            vals = {i: float(r[field]) for i, r in latest.items()
                    if isinstance(r.get(field), (int, float))}
            if len(vals) < 2:
                continue
            xs = list(vals.values())
            mean = sum(xs) / len(xs)
            agg[f"{field}_host_min"] = round(min(xs), 3)
            agg[f"{field}_host_mean"] = round(mean, 3)
            agg[f"{field}_host_max"] = round(max(xs), 3)
            if field == "step_time_ms":
                var = sum((x - mean) ** 2 for x in xs) / len(xs)
                std = var ** 0.5
                if std > 0:
                    worst, worst_val = max(vals.items(),
                                           key=lambda kv: kv[1])
                    z = (worst_val - mean) / std
                    agg["straggler_z"] = round(z, 2)
                    if z > self.z_threshold:
                        agg["straggler_host"] = worst
                        warning = (
                            f"straggler: host {worst} step_time_ms "
                            f"{worst_val:.1f} is z={z:.1f} above the "
                            f"{len(xs)}-host mean {mean:.1f} ms "
                            f"(threshold z={self.z_threshold:g}) — the "
                            "fleet steps at the slowest host's pace")
        return agg, warning

    def hosts_seen(self) -> List[int]:
        """Host indices with a file on disk (diagnostics)."""
        out = []
        for i in range(self.process_count):
            if os.path.exists(host_file(self.root_dir, i)):
                out.append(i)
        return out

    def close(self) -> None:
        if self._file is not None:
            self._file.close()
            self._file = None

    def __enter__(self) -> "HostMetricsAggregator":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close()
