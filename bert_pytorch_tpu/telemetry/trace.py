"""Trace-event summarizer: collective vs compute vs host attribution.

MULTICHIP_r06 measured 0.15–0.21 per-chip scaling efficiency and could not
say WHERE the other 80% went — the bench only had wall clocks. jax.profiler
already writes a Chrome-trace-event JSON (`*.trace.json.gz` under
`<log_dir>/plugins/profile/<run>/`) whose per-op events carry HLO names on
both TPU and the forced-CPU mesh, and the PR-8 host-loop TraceAnnotations
(`host/data_wait`, `host/h2d`, `host/dispatch`, ...) land in the same
stream. This module turns that file into the three numbers a scaling
investigation actually needs, per step:

- **collective**: time in cross-device communication ops (all-gather,
  all-reduce, reduce-scatter, collective-permute, all-to-all — async
  `-start`/`-done` variants and fusions with a collective root included),
- **compute**: every other HLO op (dots, fusions, copies, elementwise),
- **host**: the annotated host-loop phases, reported per annotation.

Durations are bucket-wise interval-merged per thread before summing, so a
collective nested inside another collective (or an op re-reported by a
wrapper event) is never double-counted; framework wrapper events
(`ThunkExecutor::...`, `TfrtCpuExecutable::...`, Python frames) match
neither class and are excluded. On an n-device single-process mesh every
device's ops land in one trace, so bucket totals are device-seconds; the
summary divides by `n_devices` when given to report per-device time.

stdlib-only (gzip + json), no jax import — the summarizer must run on a
login host against a trace scp'd out of a pod job. `tools/trace_summary.py`
is the CLI; bench.py --multichip calls `summarize_trace` directly to land
the breakdown in MULTICHIP_r*.json per variant.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import re
from typing import Any, Dict, Iterable, List, Optional, Tuple

# HLO collective roots. Fusion/async variants keep the root as a prefix of
# the op name ("all-gather-start.3", "all-reduce-scatter" does not exist —
# reduce-scatter is its own root). Order is irrelevant; matching is by
# prefix after stripping nothing.
COLLECTIVE_PREFIXES = (
    "all-gather",
    "all-reduce",
    "reduce-scatter",
    "collective-permute",
    "collective-broadcast",
    "all-to-all",
    "ragged-all-to-all",
    "partition-id",
    "replica-id",
    "send",
    "recv",
)

# an HLO instruction name: lowercase root, optional .N suffix, dashes/
# underscores/digits inside (e.g. "transpose_copy_fusion", "dot.1",
# "all-gather-start.12"). Framework wrappers ("Transpose::Execute",
# "PjitFunction(f)", "$profiler.py:91 ...") all fail this.
_HLO_NAME_RE = re.compile(r"^[a-z][a-z0-9_\-.]*$")

HOST_PREFIX = "host/"

# serving request spans (serving/request_trace.py) ride the same Chrome
# event format under this prefix; classify() excludes them from device
# summaries, summarize_request_events() below aggregates them
REQUEST_PREFIX = "req/"

# request lifecycle phases in request order, + the terminal error spans
REQUEST_PHASE_ORDER = ("admit", "queue_wait", "pack", "dispatch",
                       "compute", "demux", "respond",
                       "shed", "timeout", "too_long", "error")

# the per-kind split (round 15): every collective root maps to one of
# these classes so the summary can say WHICH collective class a variant
# pays for — all-gathers (param gathers), all-reduces (grad/factor/norm
# reductions), reduce-scatters, permutes (ring attention), all-to-alls
# (reshard transitions) — instead of one undifferentiated 'collective'
# bucket. Roots outside the named classes (send/recv, partition/replica
# ids, broadcasts) land in 'other'.
COLLECTIVE_KIND_CLASSES = ("all-gather", "all-reduce", "reduce-scatter",
                           "collective-permute", "all-to-all")


def collective_kind(root: str) -> str:
    """Canonical kind class for one collective root name (the root is the
    op name with any `.N` instance suffix and `-start`/`-done` already
    stripped)."""
    return root if root in COLLECTIVE_KIND_CLASSES else "other"


def classify(name: str) -> Optional[str]:
    """Bucket for one trace-event name: 'collective' | 'compute' | a
    'host/...' phase name | None (framework noise, excluded)."""
    if name.startswith(HOST_PREFIX):
        return name
    if name.startswith(REQUEST_PREFIX):
        return None  # serving request spans: not device time
    if not _HLO_NAME_RE.match(name):
        return None
    for p in COLLECTIVE_PREFIXES:
        if name.startswith(p):
            # "-done" events measure scheduler wait for an async collective
            # already counted from its "-start"; keeping both is correct
            # under interval merge only if they overlap — they do not, so
            # count both: start = issue+transfer, done = the un-hidden tail.
            return "collective"
    return "compute"


def _merged_total_us(intervals: List[Tuple[float, float]]) -> float:
    """Sum of a set of [start, end) intervals with overlaps merged."""
    total = 0.0
    end = -1.0
    for s, e in sorted(intervals):
        if s > end:
            total += e - s
            end = e
        elif e > end:
            total += e - end
            end = e
    return total


def find_trace_file(path: str) -> str:
    """Resolve a profiler log dir (or a direct file) to the newest
    *.trace.json.gz jax wrote under it."""
    if os.path.isfile(path):
        return path
    hits = (glob.glob(os.path.join(path, "plugins", "profile", "*",
                                   "*.trace.json.gz"))
            + glob.glob(os.path.join(path, "*.trace.json.gz")))
    if not hits:
        raise FileNotFoundError(
            f"no *.trace.json.gz under {path} (expected "
            "<log_dir>/plugins/profile/<run>/ from jax.profiler.start_trace)")
    return max(hits, key=os.path.getmtime)


def load_trace_events(trace_file: str) -> List[Dict[str, Any]]:
    opener = gzip.open if trace_file.endswith(".gz") else open
    with opener(trace_file, "rt", encoding="utf-8") as f:
        trace = json.load(f)
    return trace.get("traceEvents", [])


def _per_op_totals(op_iv: Dict[Tuple[Any, Any, str],
                               List[Tuple[float, float]]]) -> Dict[str, float]:
    """Per-root device-time: merge each thread's intervals, then SUM across
    threads — the same aggregation as the bucket totals, so the per-op map
    decomposes collective_ms instead of contradicting it."""
    totals: Dict[str, float] = {}
    for (pid, tid, root), iv in op_iv.items():
        totals[root] = totals.get(root, 0.0) + _merged_total_us(iv)
    return {op: round(us / 1e3, 3) for op, us in sorted(totals.items())}


def summarize_events(events: Iterable[Dict[str, Any]],
                     steps: Optional[int] = None,
                     n_devices: Optional[int] = None) -> Dict[str, Any]:
    """Bucket trace events into collective/compute/host totals.

    Complete ('X') events are the common case; duration pairs are also
    understood — synchronous 'B'/'E' per (pid, tid) stack and async
    'b'/'e' ('S'/'F' legacy) matched by (pid, id, cat, name). A trace cut
    short mid-interval (the run crashed while an op was open — exactly
    when a postmortem reads the trace) leaves unmatched begins: those are
    closed at the trace's end and reported via `truncated: true` +
    `truncated_intervals`, instead of being dropped or raising. An 'E'
    with no matching 'B' began before the capture window — there is no
    start to attribute, so it is skipped.

    `steps`: optimization steps the traced window covered — adds *_ms_per_step.
    `n_devices`: devices whose ops share this trace (single-process mesh) —
    device buckets are additionally reported per device."""
    # per (pid, tid, bucket) interval lists; host annotations keyed by name.
    # op_iv is ALSO keyed per thread — merging a root's intervals across
    # device threads would collapse concurrent same-op collectives into one
    # interval and undercount device-time ~n_devices-fold, making the
    # per-op map inconsistent with collective_ms.
    device_iv: Dict[Tuple[Any, Any, str], List[Tuple[float, float]]] = {}
    host_iv: Dict[str, List[Tuple[float, float]]] = {}
    op_iv: Dict[Tuple[Any, Any, str], List[Tuple[float, float]]] = {}
    n_classified = 0

    def record(pid, tid, name: str, ts: float, end: float) -> bool:
        nonlocal n_classified
        bucket = classify(name)
        if bucket is None:
            return False
        n_classified += 1
        if bucket.startswith(HOST_PREFIX):
            host_iv.setdefault(bucket, []).append((ts, end))
            return True
        device_iv.setdefault((pid, tid, bucket), []).append((ts, end))
        if bucket == "collective":
            # per-root collective map: strip the .N instance suffix and any
            # -start/-done so "all-gather-start.3" aggregates as all-gather
            root = re.sub(r"\.\d+$", "", name)
            root = re.sub(r"-(start|done)$", "", root)
            op_iv.setdefault((pid, tid, root), []).append((ts, end))
        return True

    open_sync: Dict[Tuple[Any, Any], List[Tuple[str, float]]] = {}
    # async opens keep (ts, tid) — the tid must survive to the close (or
    # the truncation pass), or the interval lands under a synthetic thread
    # and can't interval-merge with the same thread's completed ops
    open_async: Dict[Tuple[Any, Any, Any, str],
                     List[Tuple[float, Any]]] = {}
    max_ts = 0.0
    truncated = 0
    for e in events:
        ph = e.get("ph")
        name = e.get("name", "")
        ts = float(e.get("ts", 0.0))
        pid, tid = e.get("pid"), e.get("tid")
        if ph == "X":
            dur = float(e.get("dur", 0.0))
            max_ts = max(max_ts, ts + dur)
            record(pid, tid, name, ts, ts + dur)
        elif ph == "B":
            max_ts = max(max_ts, ts)
            open_sync.setdefault((pid, tid), []).append((name, ts))
        elif ph == "E":
            max_ts = max(max_ts, ts)
            stack = open_sync.get((pid, tid))
            if stack:
                bname, bts = stack.pop()
                record(pid, tid, bname, bts, ts)
        elif ph in ("b", "S"):
            max_ts = max(max_ts, ts)
            key = (pid, e.get("id"), e.get("cat"), name)
            open_async.setdefault(key, []).append((ts, tid))
        elif ph in ("e", "F"):
            max_ts = max(max_ts, ts)
            starts = open_async.get((pid, e.get("id"), e.get("cat"), name))
            if starts:
                bts, btid = starts.pop(0)
                record(pid, btid if btid is not None else tid, name,
                       bts, ts)
    # crashed-run tail: close every still-open interval at the trace end
    # (flagged below) rather than losing it — the op that never completed
    # is usually the one the postmortem is looking for
    for (pid, tid), stack in open_sync.items():
        for name, ts in stack:
            if record(pid, tid, name, ts, max(max_ts, ts)):
                truncated += 1
    for (pid, _id, _cat, name), starts in open_async.items():
        for ts, btid in starts:
            if record(pid, btid, name, ts, max(max_ts, ts)):
                truncated += 1

    def bucket_total(which: str) -> float:
        return sum(_merged_total_us(iv)
                   for (pid, tid, b), iv in device_iv.items() if b == which)

    collective_us = bucket_total("collective")
    compute_us = bucket_total("compute")
    host = {name[len(HOST_PREFIX):]: round(_merged_total_us(iv) / 1e3, 3)
            for name, iv in sorted(host_iv.items())}
    # the per-KIND split: class intervals re-merged per thread (two roots
    # of the same class can overlap under async scheduling, so summing
    # the per-root map would double-count; re-merging keeps each class
    # total consistent with how collective_ms itself is computed). The
    # classes need not sum exactly to collective_ms — cross-class overlap
    # on one thread is attributed to both classes but merged away in the
    # total, by design.
    kind_iv: Dict[Tuple[Any, Any, str], List[Tuple[float, float]]] = {}
    for (pid, tid, root), iv in op_iv.items():
        kind_iv.setdefault((pid, tid, collective_kind(root)),
                           []).extend(iv)
    kind_ms: Dict[str, float] = {}
    for (pid, tid, kind), iv in kind_iv.items():
        kind_ms[kind] = kind_ms.get(kind, 0.0) + _merged_total_us(iv)
    kind_ms = {k: round(us / 1e3, 3) for k, us in sorted(kind_ms.items())}
    out: Dict[str, Any] = {
        "collective_ms": round(collective_us / 1e3, 3),
        "compute_ms": round(compute_us / 1e3, 3),
        "host_ms": host,
        "collective_fraction": round(
            collective_us / max(collective_us + compute_us, 1e-9), 4),
        "collective_by_op_ms": _per_op_totals(op_iv),
        "collective_kind_ms": kind_ms,
        "events_classified": n_classified,
    }
    if truncated:
        out["truncated"] = True
        out["truncated_intervals"] = truncated
    if n_devices:
        out["n_devices"] = int(n_devices)
        out["collective_ms_per_device"] = round(
            collective_us / 1e3 / n_devices, 3)
        out["compute_ms_per_device"] = round(compute_us / 1e3 / n_devices, 3)
    if steps:
        out["steps"] = int(steps)
        div = steps * (n_devices or 1)
        out["collective_ms_per_step_device"] = round(
            collective_us / 1e3 / div, 3)
        out["compute_ms_per_step_device"] = round(compute_us / 1e3 / div, 3)
        out["collective_kind_ms_per_step_device"] = {
            k: round(v / div, 3) for k, v in kind_ms.items()}
    return out


def _pct(sorted_vals: List[float], q: float) -> float:
    """Linear-interpolated percentile of an already-sorted list."""
    if not sorted_vals:
        return 0.0
    if len(sorted_vals) == 1:
        return sorted_vals[0]
    pos = (len(sorted_vals) - 1) * q / 100.0
    lo = int(pos)
    hi = min(lo + 1, len(sorted_vals) - 1)
    return sorted_vals[lo] + (sorted_vals[hi] - sorted_vals[lo]) * (pos - lo)


def _phase_key(name: str) -> Tuple[int, str]:
    try:
        return (REQUEST_PHASE_ORDER.index(name), name)
    except ValueError:
        return (len(REQUEST_PHASE_ORDER), name)


def summarize_request_events(
        events: Iterable[Dict[str, Any]]) -> Dict[str, Any]:
    """Aggregate serving request spans (`req/` complete events from
    /v1/traces) into per-phase latency attribution.

    Groups events by `args.trace_id`, sums each trace's span durations
    per phase, and reports per-phase p50/p99/mean across traces plus a
    tail-cohort attribution: over the traces whose total latency is at
    or above the p99 of totals, the mean time per phase, the DOMINANT
    phase (largest mean), its share of the cohort's mean total, and the
    modal replica the cohort computed on — i.e. the "p99 is 78%
    queue_wait on r0" answer. Non-request events are ignored, so the
    summarizer runs unchanged on a merged device+request trace file."""
    traces: Dict[str, Dict[str, Any]] = {}
    for e in events:
        name = e.get("name", "")
        if e.get("ph") != "X" or not name.startswith(REQUEST_PREFIX):
            continue
        args = e.get("args") or {}
        trace_id = args.get("trace_id")
        if trace_id is None:
            continue
        t = traces.setdefault(trace_id, {
            "phases": {}, "total_ms": 0.0, "task": args.get("task"),
            "outcome": None, "replica": None, "t0": None, "t1": 0.0})
        phase = name[len(REQUEST_PREFIX):]
        ts = float(e.get("ts", 0.0))
        dur = float(e.get("dur", 0.0))
        t["phases"][phase] = t["phases"].get(phase, 0.0) + dur / 1e3
        t["t0"] = ts if t["t0"] is None else min(t["t0"], ts)
        t["t1"] = max(t["t1"], ts + dur)
        if args.get("total_ms"):
            t["total_ms"] = max(t["total_ms"], float(args["total_ms"]))
        if args.get("outcome") not in (None, "open"):
            t["outcome"] = args["outcome"]
        if phase == "compute" and "replica" in args:
            t["replica"] = args["replica"]
        elif t["replica"] is None and "replica" in args:
            t["replica"] = args["replica"]
    out: Dict[str, Any] = {"n_traces": len(traces), "by_outcome": {},
                           "by_task": {}, "phases": {}, "total_ms": {}}
    if not traces:
        return out
    totals: List[float] = []
    phase_samples: Dict[str, List[float]] = {}
    for t in traces.values():
        if not t["total_ms"] and t["t0"] is not None:
            t["total_ms"] = (t["t1"] - t["t0"]) / 1e3
        totals.append(t["total_ms"])
        key = t["outcome"] or "open"
        out["by_outcome"][key] = out["by_outcome"].get(key, 0) + 1
        task = t["task"] or "?"
        out["by_task"][task] = out["by_task"].get(task, 0) + 1
        for phase, ms in t["phases"].items():
            phase_samples.setdefault(phase, []).append(ms)
    totals.sort()
    for phase in sorted(phase_samples, key=_phase_key):
        vals = sorted(phase_samples[phase])
        out["phases"][phase] = {
            "count": len(vals),
            "mean_ms": round(sum(vals) / len(vals), 3),
            "p50_ms": round(_pct(vals, 50.0), 3),
            "p99_ms": round(_pct(vals, 99.0), 3),
        }
    out["total_ms"] = {
        "p50": round(_pct(totals, 50.0), 3),
        "p99": round(_pct(totals, 99.0), 3),
        "mean": round(sum(totals) / len(totals), 3),
        "max": round(totals[-1], 3),
    }
    # tail cohort: everything at/above the p99 total
    p99_total = _pct(totals, 99.0)
    tail = [t for t in traces.values() if t["total_ms"] >= p99_total]
    n_tail = max(len(tail), 1)
    tail_phase: Dict[str, float] = {}
    for t in tail:
        for phase, ms in t["phases"].items():
            tail_phase[phase] = tail_phase.get(phase, 0.0) + ms
    tail_phase = {p: ms / n_tail for p, ms in tail_phase.items()}
    tail_total = sum(t["total_ms"] for t in tail) / n_tail
    dominant_phase, dominant_ms = (
        max(tail_phase.items(), key=lambda kv: kv[1])
        if tail_phase else (None, 0.0))
    replica_votes: Dict[Any, int] = {}
    for t in tail:
        if t["replica"] is not None:
            replica_votes[t["replica"]] = \
                replica_votes.get(t["replica"], 0) + 1
    replica = (f"r{max(replica_votes.items(), key=lambda kv: kv[1])[0]}"
               if replica_votes else None)
    out["p99"] = {
        "total_ms": round(p99_total, 3),
        "n_traces": len(tail),
        "phase_ms": {p: round(ms, 3) for p, ms
                     in sorted(tail_phase.items(),
                               key=lambda kv: _phase_key(kv[0]))},
        "dominant_phase": dominant_phase,
        "dominant_share": round(dominant_ms / tail_total, 4)
        if tail_total > 0 else 0.0,
        "replica": replica,
    }
    return out


def summarize_trace(path: str, steps: Optional[int] = None,
                    n_devices: Optional[int] = None) -> Dict[str, Any]:
    """find_trace_file + load + summarize, with the resolved file recorded
    so the artifact says what it measured."""
    trace_file = find_trace_file(path)
    out = summarize_events(load_trace_events(trace_file), steps=steps,
                           n_devices=n_devices)
    out["trace_file"] = trace_file
    return out
