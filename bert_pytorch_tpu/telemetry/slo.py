"""SLO plane: declarative objectives -> multi-window burn-rate alerts.

Rounds 15-19 made the fleet *recorded* — request outcomes, latency
histograms, cost-per-token, step time, checkpoint freshness all land in
the phase-labeled registry — but nothing *judged* the stream live: an
operator learned a blown p99 from a post-hoc SERVE artifact, and ROADMAP
item 1(c)'s hot-swap is blocked on a machine-checkable "is this healthy"
verdict. This module is the judge:

- `load_slo_config(path)` reads the checked-in `configs/slo.json`:
  per-phase SLO specs (serve: availability, latency bound,
  cost-per-1k-tokens ceiling; train: step-time ceiling, checkpoint
  freshness, nonfinite rate) plus the alerting windows.
- `SLOEngine` evaluates the specs in-process against the EXISTING
  registry families — no second measurement path; the counters the
  scheduler/StepWatch already publish are the ground truth. Each
  evaluation tick folds good/bad deltas into a sliding ring, then runs
  the Google-SRE multi-window multi-burn-rate rule per severity:

      burn = (bad_fraction over window) / error_budget
      fire(severity) iff burn > threshold in BOTH the short and the
      long window of that severity's pair

  Defaults mirror the SRE workbook: page = 5m/1h at 14.4x, ticket =
  30m/6h at 6x. The short window makes alerts RESOLVE fast once the
  burn stops; the long window keeps one bad scrape from paging.
- Alert state is served by the frontend as `/v1/alerts` (firing +
  recently resolved) and `/v1/slo` (budget-remaining view), and folds
  into `/healthz` as the top-level `status: ok|degraded|failing`
  (page firing -> failing, ticket firing -> degraded).
- A firing latency alert carries the trace ids of the slowest
  in-window requests from the TraceRing, so the alert answers "which
  requests" directly (`tools/trace_summary.py --requests --ids ...`).
- `FaultInjector` is the chaos side (docs/RESILIENCE.md drill
  convention): `--slo_inject {error_burst,latency_burst,
  corrupt_answers}` wraps the serving engines' forward host-side so
  `scripts/check_slo.sh` can PROVE each alert fires — and stays silent
  on clean runs. `corrupt_answers` negates one task's logits: every
  request still 200s with healthy latency, which is exactly the
  corruption only the canary prober (serving/prober.py) can see.

Stdlib-only and jax-free like the rest of telemetry/ (the engine must
run in the exporter's probe thread and in jax-free tools); every read
of the registry goes through the public family API. Time is injectable
(`time_fn`) so tests drive the windows deterministically.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

SEVERITIES = ("page", "ticket")
STATUS_BY_SEVERITY = {"page": "failing", "ticket": "degraded"}

# Google SRE workbook's multiwindow multi-burn-rate table: page on a
# fast burn (budget gone in ~2 days), ticket on a slow one (~5 days)
DEFAULT_WINDOWS = {
    "page": {"short_s": 300.0, "long_s": 3600.0, "burn_rate": 14.4},
    "ticket": {"short_s": 1800.0, "long_s": 21600.0, "burn_rate": 6.0},
}

KINDS = ("availability", "latency", "counter_ratio", "threshold")

# outcomes of bert_serve_requests_total that are the SERVER's fault;
# too_long is a 413 client error and burns no budget
DEFAULT_BAD_OUTCOMES = ("error", "timeout", "overloaded")


class SLOSpec:
    """One declarative objective. `budget` is the allowed bad fraction
    (1 - target); burn rate is measured against it."""

    def __init__(self, raw: Dict[str, Any], phase: str):
        if not isinstance(raw, dict):
            raise ValueError(f"SLO spec must be an object, got {raw!r}")
        self.name = raw.get("name")
        self.kind = raw.get("kind")
        self.phase = phase
        self.description = raw.get("description", "")
        if not self.name or not isinstance(self.name, str):
            raise ValueError(f"SLO spec without a 'name': {raw!r}")
        if self.kind not in KINDS:
            raise ValueError(f"SLO {self.name!r}: kind {self.kind!r} not "
                             f"one of {KINDS}")
        if "budget" in raw:
            self.budget = float(raw["budget"])
        else:
            self.budget = 1.0 - float(raw.get("target", 0.99))
        if not (0.0 < self.budget < 1.0):
            raise ValueError(f"SLO {self.name!r}: budget {self.budget} "
                             "must be in (0, 1) — set 'target' or "
                             "'budget'")
        self.min_events = max(1, int(raw.get("min_events", 1)))
        sevs = raw.get("severities", list(SEVERITIES))
        bad = sorted(set(sevs) - set(SEVERITIES))
        if bad:
            raise ValueError(f"SLO {self.name!r}: unknown severities "
                             f"{bad}")
        self.severities = tuple(s for s in SEVERITIES if s in sevs)
        # kind-specific knobs
        self.metric = raw.get("metric")
        if self.kind == "availability":
            self.metric = self.metric or "bert_serve_requests_total"
            self.label = raw.get("label", "outcome")
            self.good_values = tuple(raw.get("good_outcomes", ("ok",)))
            self.bad_values = tuple(raw.get("bad_outcomes",
                                            DEFAULT_BAD_OUTCOMES))
        elif self.kind == "latency":
            self.metric = self.metric or "bert_serve_request_latency_ms"
            self.bound_ms = float(raw["bound_ms"])
        elif self.kind == "counter_ratio":
            self.bad_metric = raw["bad_metric"]
            self.total_metric = raw["total_metric"]
        elif self.kind == "threshold":
            self.source = raw["source"]
            self.bound = float(raw["bound"])
            self.direction = raw.get("direction", "above")
            if self.direction not in ("above", "below"):
                raise ValueError(f"SLO {self.name!r}: direction must be "
                                 "'above' or 'below'")
            self.agg = raw.get("agg", "max")
            self.skip_zero = bool(raw.get("skip_zero", False))


class SLOConfig:
    """Parsed configs/slo.json: windows + per-phase spec lists."""

    def __init__(self, windows: Dict[str, Dict[str, float]],
                 specs: Dict[str, List[SLOSpec]]):
        self.windows = windows
        self.specs = specs

    def specs_for(self, phase: str) -> List[SLOSpec]:
        return list(self.specs.get(phase, []))


def load_slo_config(path: str) -> SLOConfig:
    with open(path, encoding="utf-8") as f:
        raw = json.load(f)
    if not isinstance(raw, dict):
        raise ValueError(f"{path}: SLO config must be a JSON object")
    unknown = sorted(set(raw) - {"comment", "windows", "serve", "train"})
    if unknown:
        raise ValueError(f"{path}: unknown keys {unknown} — spec lists "
                         "go under a phase key ('serve' or 'train')")
    windows: Dict[str, Dict[str, float]] = {}
    for sev, dfl in DEFAULT_WINDOWS.items():
        w = dict(dfl)
        w.update(raw.get("windows", {}).get(sev, {}))
        w = {k: float(w[k]) for k in ("short_s", "long_s", "burn_rate")}
        if not (0 < w["short_s"] <= w["long_s"]):
            raise ValueError(f"{path}: {sev} windows need "
                             f"0 < short_s <= long_s, got {w}")
        if w["burn_rate"] <= 0:
            raise ValueError(f"{path}: {sev} burn_rate must be > 0")
        windows[sev] = w
    specs: Dict[str, List[SLOSpec]] = {}
    for phase in ("serve", "train"):
        phase_specs = [SLOSpec(entry, phase)
                       for entry in raw.get(phase, [])]
        names = [s.name for s in phase_specs]
        if len(set(names)) != len(names):
            raise ValueError(f"{path}: duplicate SLO names in {phase!r}")
        specs[phase] = phase_specs
    return SLOConfig(windows, specs)


class _SpecState:
    __slots__ = ("ring", "prev", "primed", "last_value")

    def __init__(self):
        # ring of (t, good_delta, bad_delta); pruned past the longest
        # window each tick
        self.ring: deque = deque()
        self.prev: Tuple[float, float] = (0.0, 0.0)
        # cumulative sources prime on the first tick so pre-engine
        # history is a baseline, not a burst stamped "now"
        self.primed = False
        self.last_value: Optional[float] = None


class SLOEngine:
    """Evaluate SLO specs against a MetricsRegistry; hold alert state.

    `evaluate()` is one tick (the SLOEvaluator thread or a test calls
    it); everything else is a read of the state it left behind. All
    public methods are thread-safe."""

    def __init__(self, specs: List[SLOSpec],
                 windows: Optional[Dict[str, Dict[str, float]]] = None,
                 registry=None, phase: str = "serve",
                 trace_ring=None, time_fn: Callable[[], float] = time.time,
                 log: Optional[Callable[[str], None]] = None):
        self.specs = list(specs)
        self.windows = {s: dict(w) for s, w in
                        (windows or DEFAULT_WINDOWS).items()}
        self.registry = registry
        self.phase = phase
        self.trace_ring = trace_ring
        self.time_fn = time_fn
        self.log = log
        self._lock = threading.Lock()
        self._state = {s.name: _SpecState() for s in self.specs}
        self._firing: Dict[Tuple[str, str], Dict[str, Any]] = {}
        self._resolved: deque = deque(maxlen=16)
        self._external: List[Callable[[], List[Dict[str, Any]]]] = []
        self._sources: Dict[str, Callable[[], Optional[float]]] = {}
        self._evaluations = 0
        self._last_eval_unix: Optional[float] = None
        self._max_window = max((w["long_s"]
                                for w in self.windows.values()),
                               default=0.0)
        if registry is not None:
            self._m_evals = registry.counter(
                "bert_slo_evaluations_total",
                "SLO engine evaluation ticks")
            self._m_fired = registry.counter(
                "bert_slo_alerts_fired_total",
                "alert firing transitions by SLO and severity",
                labels=("slo", "severity"))
            self._m_firing = registry.gauge(
                "bert_slo_alerts_firing",
                "alerts currently firing by severity",
                labels=("severity",))
            self._m_budget = registry.gauge(
                "bert_slo_budget_remaining",
                "error-budget fraction left over the longest window",
                labels=("slo",))
            for sev in SEVERITIES:
                self._m_firing.set(0.0, severity=sev)
        else:
            self._m_evals = self._m_fired = None
            self._m_firing = self._m_budget = None

    # -- wiring ---------------------------------------------------------------

    def set_source(self, name: str,
                   fn: Callable[[], Optional[float]]) -> None:
        """Register a named value source for `threshold` specs that is
        not a gauge (e.g. train's checkpoint_age_s). Returning None
        means "no data this tick" — the sample is skipped, not bad."""
        self._sources[name] = fn

    def add_alert_source(self,
                         fn: Callable[[], List[Dict[str, Any]]]) -> None:
        """Merge an external producer's firing alerts (the canary
        prober) into alerts()/status(). Each dict needs at least
        'slo' and 'severity'."""
        self._external.append(fn)

    # -- reading the registry -------------------------------------------------

    def _families(self) -> Dict[str, Any]:
        if self.registry is None:
            return {}
        return {m.name: m for m in self.registry.families()}

    def _read_cumulative(self, spec: SLOSpec,
                         fams: Dict[str, Any]
                         ) -> Optional[Tuple[float, float]]:
        """Cumulative (good_total, bad_total) for counter-backed kinds."""
        if spec.kind == "availability":
            m = fams.get(spec.metric)
            if m is None:
                return None
            good = bad = 0.0
            for labels, value in m.labeled_series():
                v = labels.get(spec.label)
                if v in spec.bad_values:
                    bad += value
                elif v in spec.good_values:
                    good += value
            return good, bad
        if spec.kind == "latency":
            m = fams.get(spec.metric)
            if m is None or not hasattr(m, "buckets"):
                return None
            good = total = 0.0
            # largest bucket edge <= bound: conservative when the bound
            # falls between edges (requests in the straddling bucket
            # count bad)
            n_le = sum(1 for b in m.buckets if b <= spec.bound_ms)
            for _labels, s in m.labeled_series():
                total += s.count
                good += sum(s.counts[:n_le])
            return good, total - good
        if spec.kind == "counter_ratio":
            mb = fams.get(spec.bad_metric)
            mt = fams.get(spec.total_metric)
            if mb is None or mt is None:
                return None
            bad = sum(v for _l, v in mb.labeled_series())
            total = sum(v for _l, v in mt.labeled_series())
            return max(total - bad, 0.0), bad
        return None

    def _read_threshold(self, spec: SLOSpec,
                        fams: Dict[str, Any]) -> Optional[float]:
        src = spec.source
        if src.startswith("gauge:"):
            m = fams.get(src[len("gauge:"):])
            if m is None:
                return None
            vals = [v for _l, v in m.labeled_series()
                    if isinstance(v, (int, float))]
            if spec.skip_zero:
                vals = [v for v in vals if v != 0.0]
            if not vals:
                return None
            return min(vals) if spec.agg == "min" else max(vals)
        fn = self._sources.get(src)
        if fn is None:
            return None
        try:
            v = fn()
        except Exception:
            return None  # a broken source must not take the plane down
        return float(v) if isinstance(v, (int, float)) else None

    # -- evaluation -----------------------------------------------------------

    def evaluate(self, now: Optional[float] = None) -> Dict[str, Any]:
        """One tick: fold deltas into each spec's ring, run the
        multi-window rule, transition alerts. Returns the alerts view."""
        with self._lock:
            now = self.time_fn() if now is None else float(now)
            fams = self._families()
            for spec in self.specs:
                st = self._state[spec.name]
                if spec.kind == "threshold":
                    v = self._read_threshold(spec, fams)
                    st.last_value = v
                    if v is None:
                        dg = db = 0.0
                    else:
                        breach = (v > spec.bound
                                  if spec.direction == "above"
                                  else v < spec.bound)
                        dg, db = (0.0, 1.0) if breach else (1.0, 0.0)
                else:
                    tot = self._read_cumulative(spec, fams)
                    if tot is None:
                        dg = db = 0.0
                    elif not st.primed:
                        st.prev, st.primed = tot, True
                        dg = db = 0.0
                    else:
                        dg = max(tot[0] - st.prev[0], 0.0)
                        db = max(tot[1] - st.prev[1], 0.0)
                        st.prev = tot
                st.ring.append((now, dg, db))
                cutoff = now - self._max_window - 1.0
                while st.ring and st.ring[0][0] < cutoff:
                    st.ring.popleft()
                self._judge(spec, st, now)
            self._evaluations += 1
            self._last_eval_unix = now
            if self._m_evals is not None:
                self._m_evals.inc()
                for sev in SEVERITIES:
                    n = sum(1 for (_s, s2) in self._firing if s2 == sev)
                    self._m_firing.set(float(n), severity=sev)
            return self._alerts_view_locked(now)

    def _window_sums(self, st: _SpecState, now: float,
                     window_s: float) -> Tuple[float, float]:
        good = bad = 0.0
        for t, g, b in reversed(st.ring):
            if t < now - window_s:
                break
            good += g
            bad += b
        return good, bad

    def _burn(self, st: _SpecState, now: float, window_s: float,
              budget: float) -> Tuple[float, float]:
        """(burn_rate, events) over the window; burn 0 with no events."""
        good, bad = self._window_sums(st, now, window_s)
        events = good + bad
        if events <= 0:
            return 0.0, 0.0
        return (bad / events) / budget, events

    def _judge(self, spec: SLOSpec, st: _SpecState, now: float) -> None:
        for sev in spec.severities:
            w = self.windows[sev]
            burn_s, ev_s = self._burn(st, now, w["short_s"], spec.budget)
            burn_l, _ev_l = self._burn(st, now, w["long_s"], spec.budget)
            firing = (ev_s >= spec.min_events
                      and burn_s > w["burn_rate"]
                      and burn_l > w["burn_rate"])
            key = (spec.name, sev)
            cur = self._firing.get(key)
            if firing and cur is None:
                alert = {
                    "slo": spec.name, "severity": sev,
                    "phase": self.phase, "kind": spec.kind,
                    "description": spec.description,
                    "budget": spec.budget,
                    "windows": {"short_s": w["short_s"],
                                "long_s": w["long_s"],
                                "burn_threshold": w["burn_rate"]},
                    "since_unix": round(now, 3),
                }
                self._firing[key] = alert
                if self._m_fired is not None:
                    self._m_fired.inc(slo=spec.name, severity=sev)
                if self.log:
                    self.log(f"SLO ALERT firing [{sev}] {spec.name}: "
                             f"burn {burn_s:.1f}x/{burn_l:.1f}x over "
                             f"{w['short_s']:g}s/{w['long_s']:g}s "
                             f"(threshold {w['burn_rate']:g}x, budget "
                             f"{spec.budget:g})")
                cur = alert
            elif not firing and cur is not None:
                cur = self._firing.pop(key)
                cur["resolved_unix"] = round(now, 3)
                self._resolved.append(cur)
                if self.log:
                    self.log(f"SLO alert resolved [{sev}] {spec.name} "
                             f"after {now - cur['since_unix']:.1f}s")
                cur = None
            if cur is not None:
                cur["burn_short"] = round(burn_s, 3)
                cur["burn_long"] = round(burn_l, 3)
                cur["last_eval_unix"] = round(now, 3)
                if spec.kind == "latency" and self.trace_ring is not None:
                    # the slowest retained in-window requests ARE the
                    # alert's evidence — trace_summary --ids takes these
                    try:
                        cur["trace_ids"] = [
                            t.trace_id
                            for t in self.trace_ring.traces(limit=8)]
                    except Exception:
                        pass
                if spec.kind == "threshold" \
                        and st.last_value is not None:
                    cur["value"] = round(st.last_value, 6)
                    cur["bound"] = spec.bound

    # -- views ----------------------------------------------------------------

    def _external_alerts(self) -> List[Dict[str, Any]]:
        out = []
        for fn in self._external:
            try:
                for a in fn() or []:
                    if isinstance(a, dict) and a.get("slo") \
                            and a.get("severity") in SEVERITIES:
                        out.append(dict(a))
            except Exception:
                pass  # an alert source must never take the server down
        return out

    def _alerts_view_locked(self, now: float) -> Dict[str, Any]:
        firing = sorted((dict(a) for a in self._firing.values()),
                        key=lambda a: (a["severity"] != "page",
                                       a["slo"]))
        firing += self._external_alerts()
        sevs = {a["severity"] for a in firing}
        status = ("failing" if "page" in sevs
                  else "degraded" if "ticket" in sevs else "ok")
        return {"status": status, "phase": self.phase,
                "firing": firing,
                "resolved": list(self._resolved),
                "evaluations": self._evaluations,
                "last_eval_unix": self._last_eval_unix}

    def alerts_view(self) -> Dict[str, Any]:
        """The /v1/alerts payload."""
        with self._lock:
            return self._alerts_view_locked(
                self._last_eval_unix or self.time_fn())

    def status(self) -> str:
        """ok | degraded | failing — the /healthz verdict."""
        return self.alerts_view()["status"]

    def page_firing_since(self) -> Optional[float]:
        """Earliest since_unix among firing page-severity alerts (None
        when no page is firing) — run_pretraining's sustained-breach
        halt and the supervisor's restart decision key off this."""
        view = self.alerts_view()
        stamps = [a.get("since_unix") for a in view["firing"]
                  if a.get("severity") == "page"]
        stamps = [s for s in stamps if isinstance(s, (int, float))]
        return min(stamps) if stamps else None

    def slo_view(self) -> Dict[str, Any]:
        """The /v1/slo budget-remaining payload."""
        with self._lock:
            now = self._last_eval_unix or self.time_fn()
            slos: Dict[str, Any] = {}
            for spec in self.specs:
                st = self._state[spec.name]
                longest = max(self.windows[s]["long_s"]
                              for s in spec.severities)
                good, bad = self._window_sums(st, now, longest)
                events = good + bad
                bad_frac = bad / events if events else 0.0
                remaining = max(0.0, 1.0 - bad_frac / spec.budget)
                burns = {}
                for sev in spec.severities:
                    w = self.windows[sev]
                    bs, _ = self._burn(st, now, w["short_s"],
                                       spec.budget)
                    bl, _ = self._burn(st, now, w["long_s"],
                                       spec.budget)
                    burns[sev] = {
                        "short": round(bs, 3), "long": round(bl, 3),
                        "threshold": w["burn_rate"],
                        "firing": (spec.name, sev) in self._firing}
                entry = {
                    "kind": spec.kind,
                    "description": spec.description,
                    "budget": spec.budget,
                    "window_s": longest,
                    "events": round(events, 3),
                    "bad": round(bad, 3),
                    "bad_frac": round(bad_frac, 6),
                    "budget_remaining": round(remaining, 6),
                    "burn": burns,
                    "firing": sorted(s for (n, s) in self._firing
                                     if n == spec.name),
                }
                if spec.kind == "threshold":
                    entry["value"] = st.last_value
                    entry["bound"] = spec.bound
                slos[spec.name] = entry
                if self._m_budget is not None:
                    self._m_budget.set(remaining, slo=spec.name)
            return {"phase": self.phase,
                    "status": self._alerts_view_locked(now)["status"],
                    "windows": self.windows,
                    "evaluations": self._evaluations,
                    "last_eval_unix": self._last_eval_unix,
                    "slos": slos}

    def health_summary(self) -> Dict[str, Any]:
        """Compact block for /healthz (the full views live on /v1/*)."""
        view = self.alerts_view()
        return {
            "status": view["status"],
            "alerts_firing": len(view["firing"]),
            "firing": [f"{a['slo']}:{a['severity']}"
                       for a in view["firing"]],
            "evaluations": view["evaluations"],
            "last_eval_unix": view["last_eval_unix"],
        }


class SLOEvaluator:
    """Daemon thread ticking engine.evaluate() at a fixed interval —
    the serve/train loops never block on SLO math."""

    def __init__(self, engine: SLOEngine, interval_s: float = 1.0):
        self.engine = engine
        self.interval_s = max(0.05, float(interval_s))
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run,
                                        name="slo-evaluator", daemon=True)

    def start(self) -> "SLOEvaluator":
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.engine.evaluate()
            except Exception:
                pass  # the evaluator must outlive a bad tick

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)


def _negate_tree(out):
    """Negate every array leaf (tuples/lists/dicts recursed) without
    importing jax — arrays implement __neg__."""
    if isinstance(out, (tuple, list)):
        return type(out)(_negate_tree(o) for o in out)
    if isinstance(out, dict):
        return {k: _negate_tree(v) for k, v in out.items()}
    return -out


class FaultInjector:
    """The --slo_inject chaos drill: wraps serving engines' HOST-side
    forward so the alert path can be proven live (same convention as
    --chaos / --stream_inject / --inject elsewhere).

    - error_burst:    every wave raises -> outcome=error 500s -> the
                      availability SLO burns -> page within one fast
                      window.
    - latency_burst:  sleep before each wave -> the latency SLO burns.
    - corrupt_answers: negate ONE task's logits -> every request still
                      200s fast, but decoded answers change — the
                      corruption only the canary prober catches.

    Activation is time-based (`after_s` after install) so a drill run
    has a clean head for baselines; tests flip `force(True/False)`
    directly. Wrapping happens AFTER warmup — compiled programs are
    untouched, the fault lives on the host."""

    MODES = ("error_burst", "latency_burst", "corrupt_answers")

    def __init__(self, mode: str, after_s: float = 2.0,
                 task: Optional[str] = None, latency_ms: float = 400.0,
                 time_fn: Callable[[], float] = time.monotonic):
        if mode not in self.MODES:
            raise ValueError(f"--slo_inject {mode!r} not one of "
                             f"{self.MODES}")
        self.mode = mode
        self.task = task
        self.after_s = float(after_s)
        self.latency_ms = float(latency_ms)
        self._time_fn = time_fn
        self._t0 = time_fn()
        self._forced: Optional[bool] = None

    def active(self) -> bool:
        if self._forced is not None:
            return self._forced
        return (self._time_fn() - self._t0) >= self.after_s

    def force(self, active: Optional[bool]) -> None:
        """Override the timer: True/False pins the state, None returns
        to time-based activation (tests drive drills this way)."""
        self._forced = active

    def set_mode(self, mode: str) -> None:
        if mode not in self.MODES:
            raise ValueError(f"mode {mode!r} not one of {self.MODES}")
        self.mode = mode

    def install(self, engine) -> None:
        """Wrap engine.forward(task, batch) in place (idempotent per
        engine instance)."""
        orig = engine.forward

        def forward(task, batch):
            if self.active():
                if self.mode == "error_burst":
                    raise RuntimeError(
                        "slo_inject: synthetic error burst")
                if self.mode == "latency_burst":
                    time.sleep(self.latency_ms / 1e3)
                elif self.mode == "corrupt_answers" and (
                        self.task is None or task == self.task):
                    return _negate_tree(orig(task, batch))
            return orig(task, batch)

        engine.forward = forward
