"""Phase-agnostic metrics registry: counters, gauges, histograms, labels.

Rounds 8-11 grew four telemetry producers (StepWatch, the health pack,
CompileWatch, MetricLogger) whose records end up in per-run sinks — files a
human reads after the fact. ROADMAP item 1 wants the same signals from a
future serving process, and a fleet wants them *while the job runs*, which
means one neutral in-memory representation everything publishes through and
one place an exporter can read. This is that representation — deliberately
shaped like the Prometheus data model (the lingua franca of "Scalable
Training of Language Models using JAX pjit and TPUv4"-style fleet
monitoring) so `render_prometheus()` is a serialization, not a translation:

- `Counter`   — monotonically increasing totals (`steps`, `compiles`,
  `nonfinite steps`). `inc(n)` for event sources, `inc_to(v)` for sampled
  cumulative sources (CompileWatch snapshots a count it did not event).
- `Gauge`     — last-observed values (`step_time_ms`, `mfu`).
- `Histogram` — cumulative-bucket distributions (`step_time_ms` over the
  run), rendered as `_bucket{le=...}` / `_sum` / `_count`.

Every family takes declared label names; a registry may also carry
constant labels (e.g. `phase="pretrain"`) stamped on every series, which is
what makes the SAME instrument code phase-agnostic: run_pretraining,
run_squad, run_ner, bench, and a future server differ only in that one
label. Families are get-or-create (two producers naming the same family
share it); re-declaring a name with a different kind is a loud error.

Stdlib-only and thread-safe (the exporter's http thread reads while the
train loop writes); no jax import — the registry must be constructible in
bench.py's deliberately backend-free parent and in jax-free tools.

telemetry/exporter.py serves `render_prometheus()` over HTTP;
`snapshot()` is the strict-JSON form that rides in flight-recorder
bundle manifests. docs/OBSERVABILITY.md is the operator guide.
"""

from __future__ import annotations

import json
import math
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

# step-time-ish default buckets, in ms: spans a CPU-smoke step (~10 ms)
# through a pod-scale BERT-Large step (~seconds)
DEFAULT_BUCKETS = (1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
                   1000.0, 2500.0, 5000.0, 10000.0, 30000.0)


def _escape_label(value: str) -> str:
    return (str(value).replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def _escape_help(text: str) -> str:
    # HELP lines escape backslash and line feed only (text-format spec);
    # an unescaped newline in a help string would truncate the scrape
    # mid-family
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


def _fmt_value(v: float) -> str:
    if isinstance(v, float) and math.isnan(v):
        return "NaN"
    if isinstance(v, float) and math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if float(v) == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


class _Metric:
    """One metric family: a name, a help string, declared label names, and
    a map of label-value tuples -> series state. Base for the three kinds;
    subclasses define the per-series state and the render shape."""

    kind = "untyped"

    def __init__(self, name: str, help: str, labelnames: Sequence[str],
                 lock: threading.Lock):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = lock
        self._series: Dict[Tuple[str, ...], Any] = {}
        if not self.labelnames:
            # label-less families expose their zero immediately: /metrics
            # must show bert_train_steps_total 0 before the first step,
            # not omit the series until something increments it
            self._series[()] = self._new_series()

    def _new_series(self):
        return 0.0

    def _key(self, labels: Dict[str, str]) -> Tuple[str, ...]:
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"metric {self.name!r} declared labels "
                f"{self.labelnames}, got {tuple(sorted(labels))}")
        return tuple(str(labels[k]) for k in self.labelnames)

    def _get(self, labels: Dict[str, str]):
        key = self._key(labels)
        with self._lock:
            if key not in self._series:
                self._series[key] = self._new_series()
            return key

    def labeled_series(self) -> List[Tuple[Dict[str, str], Any]]:
        with self._lock:
            items = list(self._series.items())
        return [(dict(zip(self.labelnames, key)), value)
                for key, value in items]


class Counter(_Metric):
    kind = "counter"

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        if amount < 0:
            raise ValueError(f"counter {self.name!r}: negative inc")
        key = self._get(labels)
        with self._lock:
            self._series[key] += amount

    def inc_to(self, value: float, **labels: str) -> None:
        """Monotonic set, for sampled cumulative sources (a snapshot of a
        count kept elsewhere). Never decreases the series."""
        key = self._get(labels)
        with self._lock:
            if value > self._series[key]:
                self._series[key] = value

    def value(self, **labels: str) -> float:
        key = self._get(labels)
        with self._lock:
            return self._series[key]


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value: float, **labels: str) -> None:
        key = self._get(labels)
        with self._lock:
            self._series[key] = float(value)

    def value(self, **labels: str) -> float:
        key = self._get(labels)
        with self._lock:
            return self._series[key]


class _HistSeries:
    __slots__ = ("counts", "sum", "count")

    def __init__(self, n_buckets: int):
        self.counts = [0] * n_buckets  # per-bucket (non-cumulative) counts
        self.sum = 0.0
        self.count = 0


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name, help, labelnames, lock,
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        self.buckets = tuple(sorted(float(b) for b in buckets))
        if not self.buckets:
            raise ValueError(f"histogram {name!r}: no buckets")
        super().__init__(name, help, labelnames, lock)

    def _new_series(self):
        return _HistSeries(len(self.buckets) + 1)  # + the +Inf bucket

    def observe(self, value: float, **labels: str) -> None:
        key = self._get(labels)
        value = float(value)
        i = len(self.buckets)
        for j, b in enumerate(self.buckets):
            if value <= b:
                i = j
                break
        with self._lock:
            s = self._series[key]
            s.counts[i] += 1
            s.sum += value
            s.count += 1


class MetricsRegistry:
    """Thread-safe collection of metric families with get-or-create
    declaration and optional constant labels stamped on every series."""

    def __init__(self,
                 constant_labels: Optional[Dict[str, str]] = None):
        self.constant_labels = dict(constant_labels or {})
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    # -- declaration ---------------------------------------------------------

    def _declare(self, cls, name: str, help: str,
                 labelnames: Sequence[str], **kw) -> _Metric:
        with self._lock:
            existing = self._metrics.get(name)
        if existing is not None:
            if not isinstance(existing, cls) \
                    or existing.labelnames != tuple(labelnames):
                raise ValueError(
                    f"metric {name!r} already declared as "
                    f"{existing.kind} with labels {existing.labelnames}")
            return existing
        metric = cls(name, help, labelnames, threading.Lock(), **kw)
        with self._lock:
            # lost a declare race: keep the winner (same kind by check above)
            return self._metrics.setdefault(name, metric)

    def counter(self, name: str, help: str = "",
                labels: Sequence[str] = ()) -> Counter:
        return self._declare(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "",
              labels: Sequence[str] = ()) -> Gauge:
        return self._declare(Gauge, name, help, labels)

    def histogram(self, name: str, help: str = "",
                  labels: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._declare(Histogram, name, help, labels,
                             buckets=buckets)

    def families(self) -> List[_Metric]:
        with self._lock:
            return [self._metrics[k] for k in sorted(self._metrics)]

    # -- export --------------------------------------------------------------

    def _label_str(self, labels: Dict[str, str],
                   extra: Optional[Dict[str, str]] = None) -> str:
        merged = {**self.constant_labels, **labels, **(extra or {})}
        if not merged:
            return ""
        inner = ",".join(f'{k}="{_escape_label(v)}"'
                         for k, v in merged.items())
        return "{" + inner + "}"

    def render_prometheus(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        lines: List[str] = []
        for m in self.families():
            if m.help:
                lines.append(f"# HELP {m.name} {_escape_help(m.help)}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            for labels, value in m.labeled_series():
                if isinstance(m, Histogram):
                    cum = 0
                    for b, c in zip(m.buckets, value.counts):
                        cum += c
                        lines.append(
                            f"{m.name}_bucket"
                            f"{self._label_str(labels, {'le': _fmt_value(b)})}"
                            f" {cum}")
                    cum += value.counts[-1]
                    lines.append(
                        f"{m.name}_bucket"
                        f"{self._label_str(labels, {'le': '+Inf'})} {cum}")
                    lines.append(f"{m.name}_sum{self._label_str(labels)} "
                                 f"{_fmt_value(value.sum)}")
                    lines.append(f"{m.name}_count{self._label_str(labels)} "
                                 f"{value.count}")
                else:
                    lines.append(f"{m.name}{self._label_str(labels)} "
                                 f"{_fmt_value(value)}")
        return "\n".join(lines) + "\n"

    def snapshot(self) -> Dict[str, Any]:
        """Strict-JSON form (no NaN/Inf tokens — non-finite values become
        their repr strings) for bundle manifests and cross-host shipping."""

        def clean(v):
            if isinstance(v, float) and not math.isfinite(v):
                return repr(v)
            return v

        out: Dict[str, Any] = {}
        for m in self.families():
            series = []
            for labels, value in m.labeled_series():
                if isinstance(m, Histogram):
                    val: Any = {
                        "count": value.count,
                        "sum": clean(value.sum),
                        "buckets": {
                            _fmt_value(b): c
                            for b, c in zip(m.buckets, value.counts)},
                        "overflow": value.counts[-1],
                    }
                else:
                    val = clean(value)
                series.append({"labels": {**self.constant_labels,
                                          **labels},
                               "value": val})
            out[m.name] = {"type": m.kind, "help": m.help,
                           "series": series}
        return out

    def snapshot_json(self) -> str:
        return json.dumps(self.snapshot(), sort_keys=True, allow_nan=False)


def parse_prometheus(text: str) -> Dict[str, Dict[str, float]]:
    """Minimal parser of the exposition format — enough for tests and the
    perfboard to assert on a live /metrics payload without a prometheus
    client dependency. Returns {metric_name: {label_str: value}} where
    label_str is the raw '{...}' chunk ('' for label-less series);
    `parse_prometheus_labels` turns a chunk back into the original
    (unescaped) label values for round-trip assertions."""
    out: Dict[str, Dict[str, float]] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        name_labels, _, raw = line.rpartition(" ")
        if not name_labels:
            raise ValueError(f"unparseable exposition line: {line!r}")
        if "{" in name_labels:
            name, _, rest = name_labels.partition("{")
            labels = "{" + rest
        else:
            name, labels = name_labels, ""
        out.setdefault(name, {})[labels] = float(raw)
    return out


def parse_prometheus_labels(chunk: str) -> Dict[str, str]:
    """'{a="x",b="he said \\"hi\\""}' -> {'a': 'x', 'b': 'he said "hi"'}.

    The spec-exact inverse of `_escape_label` (\\\\ -> backslash,
    \\n -> newline, \\" -> quote), tokenized character-wise so a `,`,
    `}`, or `=` INSIDE a quoted value cannot split the chunk — the
    failure mode a naive str.split parser has on hostile label values.
    Raises ValueError on a malformed chunk."""
    s = chunk.strip()
    if not s:
        return {}
    if not (s.startswith("{") and s.endswith("}")):
        raise ValueError(f"label chunk must be braced: {chunk!r}")
    s = s[1:-1]
    out: Dict[str, str] = {}
    i, n = 0, len(s)
    while i < n:
        j = s.index("=", i)
        key = s[i:j].strip()
        if not key:
            raise ValueError(f"empty label name in {chunk!r}")
        i = j + 1
        if i >= n or s[i] != '"':
            raise ValueError(f"label {key!r} value not quoted in "
                             f"{chunk!r}")
        i += 1
        buf: List[str] = []
        while True:
            if i >= n:
                raise ValueError(f"unterminated value for {key!r} in "
                                 f"{chunk!r}")
            c = s[i]
            if c == "\\":
                if i + 1 >= n:
                    raise ValueError(f"dangling escape in {chunk!r}")
                nxt = s[i + 1]
                buf.append({"\\": "\\", "n": "\n", '"': '"'}.get(
                    nxt, "\\" + nxt))
                i += 2
            elif c == '"':
                i += 1
                break
            else:
                buf.append(c)
                i += 1
        out[key] = "".join(buf)
        if i < n:
            if s[i] != ",":
                raise ValueError(f"expected ',' after {key!r} in "
                                 f"{chunk!r}")
            i += 1
    return out
