"""Compile counting + HBM snapshots.

An unexpected XLA recompile mid-run is one of the most expensive silent
failures a jit-based trainer has: a shape or layout that drifts after
warmup stalls every step behind a minutes-long compile, and nothing in the
default logs says so (the ZeRO-1 gate work in round 7 found exactly this
class of problem — warm-cache runs that LOOKED fine). CompileWatch hangs a
listener on jax.monitoring's compile-duration events and keeps counts +
cumulative durations; after `mark_steady()` every further compile fires the
warn callback loudly.

HBM tracking: `hbm_snapshot()` polls `device.memory_stats()` (PJRT exposes
bytes_in_use / peak_bytes_in_use on TPU; CPU returns None) — creep between
snapshots is the "this run will OOM at step 40k" early warning.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional

# jax fires these through jax.monitoring.record_event_duration_secs (the
# names live in jax._src.dispatch; matched by substring so a path shuffle
# in a future jax degrades to "no events seen", never an ImportError)
_COMPILE_EVENT_SUBSTRINGS = ("backend_compile",)


class CompileWatch:
    """Counts XLA compiles via jax.monitoring; loud after warmup.

    install() registers the listener (idempotent); uninstall() detaches it.
    jax.monitoring has no public unregister, so uninstall best-effort uses
    the private helper and otherwise leaves an inert callback behind — the
    `_active` flag makes a stale registration a no-op either way.
    """

    def __init__(self, warn: Optional[Callable[[str], None]] = None,
                 registry=None):
        self._warn = warn
        self._active = False
        self._installed = False
        self._steady = False
        self._lock = threading.Lock()
        self.compiles = 0
        self.compile_secs = 0.0
        self.compiles_after_steady = 0
        self.durations: List[float] = []
        # registry publication (telemetry/registry.py): compiles tick live
        # so a /metrics scrape sees a recompile storm as it happens
        self._compiles_total = self._compile_secs_total = None
        if registry is not None:
            self._compiles_total = registry.counter(
                "bert_xla_compiles_total", "XLA backend compiles")
            self._compile_secs_total = registry.counter(
                "bert_xla_compile_seconds_total",
                "cumulative XLA compile time (s)")

    # -- listener lifecycle -------------------------------------------------

    def install(self) -> "CompileWatch":
        import jax.monitoring

        self._active = True
        if not self._installed:
            jax.monitoring.register_event_duration_secs_listener(
                self._on_duration)
            self._installed = True
        return self

    def uninstall(self) -> None:
        self._active = False
        if not self._installed:
            return
        try:
            from jax._src import monitoring as _m

            _m._unregister_event_duration_listener_by_callback(
                self._on_duration)
            self._installed = False
        except Exception:
            pass  # inert via _active; nothing leaks but a dead callback

    def _on_duration(self, event: str, duration_secs: float, **kw) -> None:
        if not self._active:
            return
        if not any(s in event for s in _COMPILE_EVENT_SUBSTRINGS):
            return
        with self._lock:
            self.compiles += 1
            self.compile_secs += duration_secs
            self.durations.append(duration_secs)
            steady = self._steady
            if steady:
                self.compiles_after_steady += 1
        if self._compiles_total is not None:
            self._compiles_total.inc()
            self._compile_secs_total.inc(duration_secs)
        if steady and self._warn is not None:
            self._warn(
                f"RECOMPILE after warmup: compile #{self.compiles} took "
                f"{duration_secs:.2f}s — a shape/layout/donation drift is "
                "stalling the step pipeline (jax.log_compiles=True to see "
                "which program)")

    # -- policy -------------------------------------------------------------

    def mark_steady(self) -> None:
        """Call once warmup compiles are done (first logged interval);
        compiles after this point warn. Idempotent."""
        self._steady = True

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            return {
                "compiles": self.compiles,
                "compile_secs": round(self.compile_secs, 3),
                "recompiles_after_warmup": self.compiles_after_steady,
            }


def hbm_snapshot(devices=None) -> Dict[str, float]:
    """Max over local devices of PJRT memory_stats; {} where the backend
    exposes none (CPU). Bytes, not GiB — the consumer formats."""
    import jax

    if devices is None:
        devices = jax.local_devices()
    peak, in_use, limit = [], [], []
    for d in devices:
        stats = None
        try:
            stats = d.memory_stats()
        except Exception:
            pass
        if not stats:
            continue
        if "peak_bytes_in_use" in stats:
            peak.append(stats["peak_bytes_in_use"])
        if "bytes_in_use" in stats:
            in_use.append(stats["bytes_in_use"])
        if "bytes_limit" in stats:
            limit.append(stats["bytes_limit"])
    out: Dict[str, float] = {}
    if peak:
        out["hbm_peak_bytes"] = max(peak)
    if in_use:
        out["hbm_bytes_in_use"] = max(in_use)
    if limit:
        out["hbm_bytes_limit"] = max(limit)
    return out
