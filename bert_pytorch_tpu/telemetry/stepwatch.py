"""Host-side step/throughput/MFU accounting.

"Scalable Training of Language Models using JAX pjit and TPUv4" (PAPERS.md)
treats MFU and step-time breakdown as the primary health number of a
pretraining job; the reference framework printed one seq/s line at the END
of the run (run_pretraining.py:574-580), which is exactly when it is no
longer useful. StepWatch keeps per-interval accounting while the job runs:

- wall time per optimization step,
- named host phases (data_wait, h2d, dispatch, metric_flush — where the
  host actually spends its loop time; in steady state `metric_flush` is
  where the one-step-lag readback blocks and therefore approximates the
  device step time),
- seq/s and tokens/s,
- real tokens/s, pad fraction and packing efficiency when the caller feeds
  per-batch real-token counts (`note_tokens`, from the attention mask):
  `tokens_per_sec` counts every slot the device computes — pad included —
  so it measures hardware occupancy, while `real_tokens_per_sec` counts
  only non-pad tokens, i.e. training progress. The gap between them is
  exactly what --packing recovers,
- MFU from the analytic BERT FLOPs-per-step formula below, against the
  device's known peak.

The FLOPs formula is THE shared single source of truth: bench.py imports
`flops_per_seq` / `PEAK_FLOPS` from here, so the bench headline MFU and the
live training MFU can never drift apart.

Everything here is plain host Python — no device work, no added
host-device sync. Timing uses time.perf_counter (injectable for tests).
"""

from __future__ import annotations

import os
import time
from contextlib import contextmanager
from typing import Callable, Dict, Optional

# Peak bf16 FLOP/s per chip by device kind (public figures). Longest
# matching key wins ('TPU v5 lite' must not hit a 'TPU v5' prefix).
# The MXU runs f32 matmuls at half the bf16 rate on every listed
# generation, so the f32 peak is derived rather than tabled —
# lookup_peak_flops(kind, dtype="f32") halves these numbers. MFU must be
# quoted against the peak of the dtype the dots actually run in: dividing
# f32-compute FLOP/s by the bf16 peak under-reports utilization 2x (looks
# like headroom that is not there), and quoting a bf16 run against an f32
# peak inflates it 2x.
PEAK_FLOPS = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,   # v5e reports device_kind "TPU v5 lite"
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,   # v6e / Trillium
    "TPU v6e": 918e12,
}
DEFAULT_PEAK = 275e12
_F32_PEAK_RATIO = 0.5

# Cost accounting price knob, shared by training (StepWatch) and serving
# (serving/batcher.py): device-seconds are priced at this rate per
# device-HOUR. The default of 1.0 makes the cost fields normalized
# device-hours-per-1k-tokens — a hardware-relative efficiency number
# that survives price changes; pass the real $/chip-hour to quote money.
DEFAULT_COST_PER_DEVICE_HOUR = 1.0


def resolve_cost_per_device_hour(value: Optional[float] = None) -> float:
    """Explicit value > BERT_COST_PER_DEVICE_HOUR env > 1.0 default."""
    if value is not None:
        return float(value)
    env = os.environ.get("BERT_COST_PER_DEVICE_HOUR", "").strip()
    if env:
        try:
            return float(env)
        except ValueError:
            pass
    return DEFAULT_COST_PER_DEVICE_HOUR


def lookup_peak_flops(device_kind: str,
                      dtype: str = "bf16") -> Optional[float]:
    """Known peak FLOP/s for a device kind at the given compute dtype
    ("bf16" or "f32"/"float32"), else None (CPU, unknown TPU
    generations). Callers decide the fallback — bench.py uses
    DEFAULT_PEAK so its ratio stays comparable across rounds."""
    kind = device_kind.lower()
    hits = [v for k, v in sorted(PEAK_FLOPS.items(), key=lambda kv: -len(kv[0]))
            if k.lower() in kind]
    if not hits:
        return None
    d = dtype.lower()
    if d in ("f32", "float32", "fp32"):
        return hits[0] * _F32_PEAK_RATIO
    if d in ("bf16", "bfloat16"):
        return hits[0]
    raise ValueError(f"unknown compute dtype for peak lookup: {dtype!r}")


def flops_per_seq(cfg, seq_len: int, vocab: int, n_pred: int) -> float:
    """Analytic fwd+bwd FLOPs for one sequence: 6*params*positions for the
    dense matmuls + 12*L*E*S^2 for attention score/value products. The MLM
    transform + tied decoder run only on the n_pred gathered masked
    positions (models/bert.py BertForPreTraining), so their FLOPs scale
    with n_pred, not S — MFU counts FLOPs actually computed."""
    E, F, L = cfg.hidden_size, cfg.intermediate_size, cfg.num_hidden_layers
    per_layer = 4 * E * E + 2 * E * F          # qkv+proj, mlp in+out
    trunk = L * per_layer * seq_len
    head = (vocab * E + E * E) * n_pred        # tied decoder + mlm transform
    return 6.0 * (trunk + head) + 12.0 * L * E * seq_len * seq_len


class StepWatch:
    """Interval accounting for the host train loop.

    Usage:
        sw = StepWatch(flops_per_step=..., seqs_per_step=..., seq_len=...,
                       peak_flops=..., log_freq=10)
        with sw.phase("data_wait"): batch = next(it)
        with sw.phase("dispatch"):  state, m = jit_step(...)
        rec = sw.step_done()        # dict every log_freq steps, else None

    `flops_per_step` must account for the full optimization step — i.e.
    flops_per_seq(...) * (accum_steps * micro_global). With
    --steps_per_loop > 1 pass n=steps_per_loop to step_done; the interval
    math divides by optimization steps, so MFU/seq_per_sec stay exact.

    `peak_flops=None` (unknown hardware, e.g. the CPU backend) reports
    mfu=0.0 and carries peak_flops=0 in the record so the number is
    self-describing rather than silently wrong.
    """

    def __init__(self, flops_per_step: float, seqs_per_step: float,
                 seq_len: int, peak_flops: Optional[float],
                 log_freq: int = 10,
                 time_fn: Callable[[], float] = time.perf_counter,
                 registry=None,
                 n_devices: int = 1,
                 cost_per_device_hour: Optional[float] = None):
        self.flops_per_step = float(flops_per_step)
        self.seqs_per_step = float(seqs_per_step)
        self.seq_len = int(seq_len)
        self.peak_flops = peak_flops
        # cost accounting: interval wall time x n_devices = the
        # device-seconds this job consumed, priced per device-hour —
        # the serving fleet's cost gauges use the identical formula so
        # train and serve cost-per-token are directly comparable
        self.n_devices = max(1, int(n_devices))
        self.cost_per_device_hour = resolve_cost_per_device_hour(
            cost_per_device_hour)
        self.log_freq = max(1, int(log_freq))
        self._time = time_fn
        self._phases: Dict[str, float] = {}
        # optional fn(name, entering: bool) fired on every phase
        # enter/exit — the hung-step watchdog's feed
        # (resilience/watchdog.py); None costs one attribute load per
        # phase
        self.phase_listener: Optional[Callable[[str, bool], None]] = None
        self._steps = 0
        self._interval_start = self._time()
        self._real_tokens = 0.0
        self._noted_tokens = False
        # registry publication (telemetry/registry.py): the live step
        # counter ticks per step_done call — not per log_freq interval —
        # so a /metrics scrape between intervals still sees progress; the
        # histogram accumulates the per-interval mean step time
        self._steps_total = self._step_hist = None
        if registry is not None:
            self._steps_total = registry.counter(
                "bert_train_steps_total", "optimization steps completed")
            self._step_hist = registry.histogram(
                "bert_step_time_ms_hist",
                "distribution of per-step wall time (ms), sampled per "
                "StepWatch interval")

    @contextmanager
    def phase(self, name: str):
        listener = self.phase_listener
        if listener is not None:
            listener(name, True)
        t0 = self._time()
        try:
            yield
        finally:
            self._phases[name] = (self._phases.get(name, 0.0)
                                  + self._time() - t0)
            if listener is not None:
                listener(name, False)

    def add_phase(self, name: str, seconds: float) -> None:
        self._phases[name] = self._phases.get(name, 0.0) + seconds

    @contextmanager
    def pause(self):
        """Exclude a non-training span (mid-epoch eval, restore) from the
        interval wall clock by advancing the interval start past it —
        without this, an epoch-boundary eval silently inflates the NEXT
        interval's step_time_ms and deflates its seq/s and MFU."""
        t0 = self._time()
        try:
            yield
        finally:
            self._interval_start += self._time() - t0

    def note_tokens(self, real_tokens: float) -> None:
        """Count a dispatched batch's REAL (non-pad) tokens — typically
        `attention_mask.sum()` on the host-side numpy batch, a cost of
        microseconds. Unlocks `real_tokens_per_sec` / `pad_fraction` /
        `packing_efficiency` in the interval record; without any call the
        record carries only the slot-token throughput, as before."""
        self._real_tokens += float(real_tokens)
        self._noted_tokens = True

    def step_done(self, n: int = 1) -> Optional[Dict[str, float]]:
        """Count n optimization steps; at a log_freq boundary, return the
        interval record and reset."""
        self._steps += n
        if self._steps_total is not None:
            self._steps_total.inc(n)
        if self._steps < self.log_freq:
            return None
        return self._emit()

    def flush(self) -> Optional[Dict[str, float]]:
        """Force out the partial interval (None if no steps since the last
        boundary). The crash-safe exit path: a SIGTERM or exception must
        not lose the buffered accounting of up to log_freq-1 steps."""
        if self._steps == 0:
            return None
        return self._emit()

    def _emit(self) -> Dict[str, float]:
        now = self._time()
        wall = max(now - self._interval_start, 1e-9)
        steps = self._steps
        seqs_per_sec = self.seqs_per_step * steps / wall
        achieved = self.flops_per_step * steps / wall
        rec = {
            "steps": steps,
            "step_time_ms": round(wall / steps * 1e3, 3),
            "seq_per_sec": round(seqs_per_sec, 2),
            "tokens_per_sec": round(seqs_per_sec * self.seq_len, 1),
            "model_flops_per_sec": round(achieved, 1),
            "mfu": (round(achieved / self.peak_flops, 6)
                    if self.peak_flops else 0.0),
            "peak_flops": self.peak_flops or 0,
        }
        if self._noted_tokens:
            # slot tokens = everything the device computed (pad included);
            # real tokens = training progress. packing_efficiency is their
            # ratio — with packing off it is simply 1 - pad_fraction of the
            # natural corpus, the number that says what packing would buy
            slot_tokens = self.seqs_per_step * steps * self.seq_len
            eff = self._real_tokens / max(slot_tokens, 1.0)
            rec["real_tokens_per_sec"] = round(self._real_tokens / wall, 1)
            rec["pad_fraction"] = round(max(0.0, 1.0 - eff), 6)
            rec["packing_efficiency"] = round(eff, 6)
        # device-seconds -> cost-per-token, in EVERY record: interval
        # wall x n_devices priced per device-hour, over real tokens when
        # note_tokens fed them (training progress) else slot tokens
        device_seconds = wall * self.n_devices
        cost_tokens = (self._real_tokens if self._noted_tokens
                       else self.seqs_per_step * steps * self.seq_len)
        rec["device_seconds_per_step"] = round(device_seconds / steps, 6)
        cost = device_seconds / 3600.0 * self.cost_per_device_hour
        rec["cost_per_1k_tokens"] = (round(cost / (cost_tokens / 1000.0), 9)
                                     if cost_tokens > 0 else 0.0)
        if self._step_hist is not None:
            self._step_hist.observe(rec["step_time_ms"])
        for name, secs in sorted(self._phases.items()):
            rec[f"{name}_ms"] = round(secs / steps * 1e3, 3)
        self._phases = {}
        self._steps = 0
        self._interval_start = now
        self._real_tokens = 0.0
        return rec
