"""Run provenance stamps.

A BENCH_*.json or logfile found three rounds later is only evidence if it
says WHAT produced it: which commit, which jax, which mesh, which libtpu
flag pack. `collect()` gathers exactly that, tolerating every failure mode
(no git, no backend up yet) by degrading fields to "unknown" rather than
raising — a provenance stamp must never be the thing that kills a run.
"""

from __future__ import annotations

import os
import subprocess
import time
from typing import Any, Dict, Optional


def git_sha(cwd: Optional[str] = None) -> str:
    """Short SHA (+'-dirty' when the tree is modified) of the repo holding
    this file; 'unknown' when git is unavailable."""
    if cwd is None:
        cwd = os.path.dirname(os.path.abspath(__file__))
    try:
        sha = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"], cwd=cwd,
            capture_output=True, text=True, timeout=10).stdout.strip()
        if not sha:
            return "unknown"
        dirty = subprocess.run(
            ["git", "status", "--porcelain", "--untracked-files=no"],
            cwd=cwd, capture_output=True, text=True, timeout=10).stdout
        return sha + ("-dirty" if dirty.strip() else "")
    except Exception:
        return "unknown"


def collect(mesh=None, device: bool = True,
            extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """One provenance dict for log headers and bench JSONs.

    device=False skips every field that would touch the jax backend —
    bench.py's parent process must not initialize the TPU while its
    children try to attach (bench.py platform-probe contract).
    """
    import jax
    import jaxlib

    from bert_pytorch_tpu.parallel.xla_flags import pack_state

    out: Dict[str, Any] = {
        "git_sha": git_sha(),
        "jax_version": jax.__version__,
        "jaxlib_version": jaxlib.__version__,
        "time_unix": round(time.time(), 3),
        **pack_state(),
    }
    if device:
        try:
            d = jax.devices()[0]
            out["platform"] = d.platform
            out["device_kind"] = d.device_kind
            out["device_count"] = jax.device_count()
            out["process_count"] = jax.process_count()
        except Exception:
            out["platform"] = "unknown"
    if mesh is not None:
        out["mesh"] = {k: int(v) for k, v in dict(mesh.shape).items()}
    if extra:
        out.update(extra)
    return out
