"""Live metrics export: /metrics (Prometheus text) + /healthz over HTTP.

The registry makes a run's signals readable in-process; this makes them
readable from OUTSIDE the process while it runs — `curl :9090/metrics`
against a live pretraining job instead of tailing a jsonl, and a
`/healthz` any orchestrator probe can watch (pod-scale training treats
always-on fleet metrics as table stakes — PAPERS.md "Scalable Training of
Language Models using JAX pjit and TPUv4"). Opt-in via `--metrics_port`
on every entry point; a future serving process gets the same endpoints
for free through `telemetry.init_run`.

Deliberately stdlib-only (`http.server` on a daemon thread): the exporter
must never add a dependency, never block the train loop (the registry's
per-family locks are held only for the microseconds a render reads a
series), and never keep the process alive (daemon thread + explicit
`close()` in the run teardown).

- `GET /metrics` — `registry.render_prometheus()`, text/plain; version
  0.0.4. Scrapeable by a stock Prometheus.
- `GET /healthz` — one JSON object from the caller's `healthz_fn`
  (telemetry/run.py supplies the run's last step, last perf interval,
  last health-pack flags incl. the most recent non-finite step, and
  compile counts). 200 always when the server is up — liveness is the
  probe; the payload says *how* alive.

`port=0` binds an ephemeral port; read `.port` after construction (tests
do). Binds 0.0.0.0 by default so a pod-external scraper can reach it;
pass host="127.0.0.1" to keep it loopback-only.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, Optional

CONTENT_TYPE_PROM = "text/plain; version=0.0.4; charset=utf-8"


class MetricsServer:
    """Serve a registry's /metrics + a /healthz JSON on a daemon thread."""

    def __init__(self, registry,
                 healthz_fn: Optional[Callable[[], Dict[str, Any]]] = None,
                 port: int = 0, host: str = "0.0.0.0"):
        self.registry = registry
        self.healthz_fn = healthz_fn
        server = self

        class Handler(BaseHTTPRequestHandler):
            def _send(self, code: int, body: str, ctype: str) -> None:
                payload = body.encode("utf-8")
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def do_GET(self):  # noqa: N802 (http.server API)
                path = self.path.split("?", 1)[0]
                try:
                    if path == "/metrics":
                        self._send(200, server.registry.render_prometheus(),
                                   CONTENT_TYPE_PROM)
                    elif path == "/healthz":
                        h = (server.healthz_fn()
                             if server.healthz_fn is not None else {})
                        self._send(200, json.dumps(h, sort_keys=True,
                                                   default=str),
                                   "application/json")
                    else:
                        self._send(404, "not found: try /metrics or "
                                        "/healthz\n", "text/plain")
                except BrokenPipeError:
                    pass  # scraper went away mid-write; nothing to do

            def log_message(self, fmt, *args):
                pass  # scrapes must not spam the training stdout

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = int(self._httpd.server_address[1])
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="metrics-exporter",
            daemon=True)
        self._thread.start()
        self._closed = False

    @property
    def url(self) -> str:
        host = "127.0.0.1" if self.host in ("0.0.0.0", "") else self.host
        return f"http://{host}:{self.port}"

    def close(self) -> None:
        """Stop serving and release the port. Idempotent."""
        if self._closed:
            return
        self._closed = True
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)
