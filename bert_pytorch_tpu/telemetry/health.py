"""In-graph numerical health pack for the train step.

The reference framework would average a NaN loss straight into
`average_loss` and keep training (run_pretraining.py:528-547 reads
loss.item() with no finiteness check); by the time a human notices, the
optimizer moments are poisoned many checkpoints deep. These signals are
computed ON DEVICE inside the jitted step and returned through the existing
metrics dict, so the host's one-step-lag readback stays non-blocking:

- non-finite element counts for the loss and for each top-level parameter
  group's gradients (a per-group count localizes the blowup: embedding
  scatter vs encoder vs MLM head);
- gradient-norm EMA/variance with a z-score spike flag (catches the
  "loss still finite but the run just went off a cliff" precursor);
- global param norm + relative drift per step (silent divergence and
  frozen-update detection in one number);
- an optional `skip` guard: when the step is bad, params / optimizer
  state / preconditioner state are kept bit-identical to the previous
  step — crucial because the host only LEARNS about the bad step one step
  later, after the poisoned update would already have been applied.

The EMA/drift state (`TelemetryState`) rides in `TrainState.telemetry`. It
is deliberately ephemeral: run_pretraining strips it before checkpointing
(a few warmup steps rebuild it), so checkpoint structure — and restore of
pre-telemetry checkpoints — is unchanged.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
from flax import struct

NONFINITE_ACTIONS = ("log", "skip", "halt")


@dataclasses.dataclass(frozen=True)
class HealthConfig:
    """Static (trace-time) configuration for the health pack.

    `action` mirrors run_pretraining's --nonfinite_action. Only "skip"
    changes the compiled step (the state select); "log" and "halt" are
    host-side policies applied when the flags are read back.
    """

    action: str = "log"
    ema_decay: float = 0.98
    spike_z: float = 6.0
    warmup_steps: int = 10

    def __post_init__(self):
        if self.action not in NONFINITE_ACTIONS:
            raise ValueError(
                f"action must be one of {NONFINITE_ACTIONS}, got "
                f"{self.action!r}")


@struct.dataclass
class TelemetryState:
    """Device-side carry for the health pack (all scalars, ~5 floats).

    `count` is the number of GOOD steps folded into the EMAs — bad
    (non-finite) steps do not update them, so one NaN cannot poison the
    spike detector that is supposed to catch the next one.
    """

    count: jax.Array
    grad_norm_ema: jax.Array
    grad_norm_var: jax.Array
    param_norm_prev: jax.Array


def init_telemetry_state() -> TelemetryState:
    # distinct arrays per field — sharing one zeros buffer across fields
    # trips "donate the same buffer twice" under jit(donate_argnums=(0,))
    return TelemetryState(count=jnp.zeros([], jnp.int32),
                          grad_norm_ema=jnp.zeros([], jnp.float32),
                          grad_norm_var=jnp.zeros([], jnp.float32),
                          param_norm_prev=jnp.zeros([], jnp.float32))


def _nonfinite_count(tree: Any) -> jax.Array:
    leaves = [l for l in jax.tree.leaves(tree)
              if jnp.issubdtype(jnp.asarray(l).dtype, jnp.floating)]
    if not leaves:
        return jnp.zeros([], jnp.int32)
    return sum(jnp.sum(~jnp.isfinite(l)).astype(jnp.int32) for l in leaves)


def global_norm_f32(tree: Any) -> jax.Array:
    """fp32-upcast global L2 norm (bf16 sums of millions of squares
    misreport; same reasoning as training/pretrain._global_norm_f32)."""
    leaves = [jnp.asarray(l).astype(jnp.float32)
              for l in jax.tree.leaves(tree)]
    return jnp.sqrt(sum(jnp.sum(jnp.square(l)) for l in leaves))


def health_signals(loss: jax.Array, grads: Any,
                   grad_norm: jax.Array) -> Tuple[Dict, jax.Array]:
    """Per-step non-finite accounting. Returns (metrics, bad_flag).

    `grads` is the post-accumulation gradient pytree; top-level dict keys
    (bert / cls_predictions / ...) become per-group count metrics so the
    readback localizes which part of the model blew up.
    """
    metrics: Dict[str, jax.Array] = {}
    loss_bad = jnp.sum(~jnp.isfinite(
        jnp.asarray(loss, jnp.float32))).astype(jnp.int32)
    metrics["loss_nonfinite"] = loss_bad
    total = jnp.zeros([], jnp.int32)
    if isinstance(grads, dict):
        for group, sub in grads.items():
            c = _nonfinite_count(sub)
            metrics[f"grad_nonfinite_{group}"] = c
            total = total + c
    else:
        total = _nonfinite_count(grads)
    metrics["grad_nonfinite"] = total
    bad = (loss_bad > 0) | (total > 0) | ~jnp.isfinite(grad_norm)
    return metrics, bad


def health_update(cfg: HealthConfig, telem: TelemetryState,
                  grad_norm: jax.Array, bad: jax.Array,
                  params_after: Any
                  ) -> Tuple[TelemetryState, Dict[str, jax.Array]]:
    """Fold this step into the EMA state; emit spike/drift metrics.

    The z-score is computed against the PRE-update EMA (the spike must be
    judged against history, not against a mean it already moved), gated to
    0 until `warmup_steps` good steps have been observed. All updates are
    `where`-selected on `bad` so a non-finite norm never enters the EMAs.
    """
    if telem is None:
        telem = init_telemetry_state()
    good = ~bad
    gn = jnp.where(good, grad_norm, 0.0).astype(jnp.float32)
    d = jnp.float32(cfg.ema_decay)
    first = telem.count == 0
    warm = telem.count >= cfg.warmup_steps

    # The variance EMA starts at 0 (the mean starts at the first sample),
    # so after k updates only (1 - d^k) of the stationary variance has
    # accumulated — at count=10 with d=0.98 that is ~17%, which would
    # understate sigma ~2.4x and fire false spikes right after every
    # (re)start, since TelemetryState is ephemeral across resumes. Standard
    # bias correction: divide by the accumulated weight.
    var_updates = jnp.maximum(telem.count - 1, 1).astype(jnp.float32)
    var_hat = telem.grad_norm_var / jnp.maximum(1.0 - d ** var_updates,
                                                1e-6)
    z = jnp.where(
        warm & good,
        (gn - telem.grad_norm_ema) / jnp.sqrt(var_hat + 1e-12),
        0.0)
    spike = (z > cfg.spike_z).astype(jnp.int32)

    ema = jnp.where(first, gn, d * telem.grad_norm_ema + (1 - d) * gn)
    var = jnp.where(first, 0.0,
                    d * telem.grad_norm_var + (1 - d) * (gn - ema) ** 2)
    new_ema = jnp.where(good, ema, telem.grad_norm_ema)
    new_var = jnp.where(good, var, telem.grad_norm_var)

    pn = global_norm_f32(params_after)
    drift = jnp.where(telem.param_norm_prev > 0,
                      (pn - telem.param_norm_prev)
                      / jnp.maximum(telem.param_norm_prev, 1e-12),
                      0.0)

    new_telem = TelemetryState(
        count=telem.count + good.astype(jnp.int32),
        grad_norm_ema=new_ema,
        grad_norm_var=new_var,
        param_norm_prev=pn)
    metrics = {
        "grad_norm_ema": new_ema,
        "grad_norm_z": z,
        "grad_spike": spike,
        "param_norm": pn,
        "param_norm_drift": drift,
    }
    return new_telem, metrics


def select_state(bad: jax.Array, old: Any, new: Any) -> Any:
    """Per-leaf where-select: the `skip` guard. When `bad`, every leaf of
    `new` is replaced by its `old` value — params, moments, K-FAC factors
    stay bit-identical, as if the poisoned batch never happened. Costs one
    extra read of the tree, only compiled in under action='skip'."""
    return jax.tree.map(lambda o, n: jnp.where(bad, o, n), old, new)


# metric keys that chain_steps (training/pretrain.py) max-accumulates
# across a device-side multi-step loop: the host only sees the LAST inner
# step's metrics, and a flag raised by any inner step must survive to it
STICKY_METRIC_KEYS = ("loss_nonfinite", "grad_nonfinite", "grad_spike",
                      "skipped_nonfinite", "mlm_dropped")


def is_sticky_metric(key: str) -> bool:
    """True for metrics chain_steps must max-accumulate — the fixed flag
    set plus the dynamic per-group counts (grad_nonfinite_bert, ...), so a
    multi-step loop localizes a blowup to the same group a single step
    would."""
    return key in STICKY_METRIC_KEYS or key.startswith("grad_nonfinite_")
