"""Training telemetry: eyes on a running job.

Four pieces, one per failure mode the ROADMAP's "fast as the hardware
allows" goal keeps hitting blind:

- `health`  — in-graph (device-side) numerical health pack: non-finite
  counts for loss and per-param-group gradients, grad-norm EMA + z-score
  spike flag, param-norm drift, and the ZeRO-safe `skip` update guard.
  Signals ride in the train step's existing metrics dict, so the host's
  one-step-lag readback stays non-blocking.
- `stepwatch` — host-side per-interval accounting: step wall time, data-wait
  vs dispatch vs metric-flush time, seq/s, tokens/s, and MFU from the
  analytic BERT FLOPs formula (shared with bench.py).
- `compile_watch` — jax.monitoring listener counting XLA compiles and their
  durations, loud on recompiles after warmup (the ZeRO-1 gate saga: a
  silent recompile is a silent 2x step time), plus device memory_stats
  snapshots (peak HBM).
- `provenance` — run stamps (git SHA, jax/jaxlib versions, mesh shape,
  xla_flags pack) so every log header and bench JSON is self-describing.
- `flight_recorder` — the black box: a bounded host-side ring of the last
  K batches + RNGs + metric records, dumped as a self-contained repro
  bundle when the health pack flags a step or the process dies;
  tools/replay.py re-executes the offending step from the bundle plus the
  matching checkpoint, bit-identically, and bisects the first non-finite
  model scope.
- `trace` — profiler-trace summarizer: buckets a jax.profiler trace's
  events into collective vs compute vs host time (reusing the host-loop
  TraceAnnotations), the attribution layer under the multichip scaling
  numbers; tools/trace_summary.py is the CLI.
- `registry` / `exporter` / `multihost` / `run` — the phase-agnostic
  metrics plane: one registry (counters/gauges/histograms with labels)
  every producer above publishes through, a stdlib `/metrics` +
  `/healthz` HTTP exporter (`--metrics_port`), per-host metrics jsonl
  with a process-0 cross-host fold + straggler detection, and
  `init_run(phase=...)` — the single wiring path all entry points and
  bench.py construct their telemetry through.

Re-exports resolve LAZILY (PEP 562): `health` pulls in jax+flax at import
time, and consumers like bench.py's parent process import only the pure-
host pieces (stepwatch/provenance) while staying deliberately jax-free
until their children own the backend.

docs/OBSERVABILITY.md is the operator-facing guide.
"""

_EXPORTS = {
    "HealthConfig": ("bert_pytorch_tpu.telemetry.health", "HealthConfig"),
    "TelemetryState": ("bert_pytorch_tpu.telemetry.health",
                       "TelemetryState"),
    "init_telemetry_state": ("bert_pytorch_tpu.telemetry.health",
                             "init_telemetry_state"),
    "StepWatch": ("bert_pytorch_tpu.telemetry.stepwatch", "StepWatch"),
    "flops_per_seq": ("bert_pytorch_tpu.telemetry.stepwatch",
                      "flops_per_seq"),
    "lookup_peak_flops": ("bert_pytorch_tpu.telemetry.stepwatch",
                          "lookup_peak_flops"),
    "CompileWatch": ("bert_pytorch_tpu.telemetry.compile_watch",
                     "CompileWatch"),
    "hbm_snapshot": ("bert_pytorch_tpu.telemetry.compile_watch",
                     "hbm_snapshot"),
    "collect_provenance": ("bert_pytorch_tpu.telemetry.provenance",
                           "collect"),
    "FlightRecorder": ("bert_pytorch_tpu.telemetry.flight_recorder",
                       "FlightRecorder"),
    "validate_bundle": ("bert_pytorch_tpu.telemetry.flight_recorder",
                        "validate_bundle"),
    "summarize_trace": ("bert_pytorch_tpu.telemetry.trace",
                        "summarize_trace"),
    "MetricsRegistry": ("bert_pytorch_tpu.telemetry.registry",
                        "MetricsRegistry"),
    "MetricsServer": ("bert_pytorch_tpu.telemetry.exporter",
                      "MetricsServer"),
    "HostMetricsAggregator": ("bert_pytorch_tpu.telemetry.multihost",
                              "HostMetricsAggregator"),
    "init_run": ("bert_pytorch_tpu.telemetry.run", "init_run"),
    "TelemetryRun": ("bert_pytorch_tpu.telemetry.run", "TelemetryRun"),
    "PERF_RECORD_CORE_KEYS": ("bert_pytorch_tpu.telemetry.run",
                              "PERF_RECORD_CORE_KEYS"),
}

__all__ = sorted(_EXPORTS)


def __getattr__(name):
    try:
        module_name, attr = _EXPORTS[name]
    except KeyError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}") from None
    import importlib

    return getattr(importlib.import_module(module_name), attr)


def __dir__():
    return __all__
