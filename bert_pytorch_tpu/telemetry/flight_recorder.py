"""Flight recorder: the training job's black box.

PR 8 gave a running job eyes (health pack / StepWatch / CompileWatch), but
when a step goes bad the operator still only learns "non-finite grad in
group X", one step late — the batch, RNG, and state that produced it are
gone, and a crash loses the buffered tail of the metrics stream entirely.
Large-scale pjit training reports NaN/divergence triage as a dominant
operational cost ("Scalable Training of Language Models using JAX pjit and
TPUv4", PAPERS.md); the fix production systems use is a black box: record
the last K inputs continuously, dump them when something dies.

`FlightRecorder` is that box, host-side and bounded:

- a ring of the last `window` per-step batch records — the loader-output
  numpy batch (packed fields included), the dispatch PRNG key, and the
  step id. References, not copies: the loader materializes fresh arrays
  per batch, so holding them costs zero extra memcpy and the bound is
  `window * batch_nbytes`;
- a bounded tail of the most recent flushed metric records (the health
  pack's readback), so the bundle says WHAT tripped, not just WITH WHAT;
- `dump()` writes a self-contained repro bundle — `batches.npz` plus a
  `manifest.json` carrying the provenance stamp, the resolved model
  config, and everything `tools/replay.py` needs to rebuild the exact
  train step (accum math, optimizer, schedule, health action, packing,
  mesh) — next to the checkpoints;
- crash handlers: SIGTERM/SIGINT are mapped to `SystemExit(128+sig)` so
  the entry point's except-path can flush metrics and dump before the
  process unwinds, with an atexit backstop for exits that bypass it.

Everything here is plain host Python (numpy + stdlib, no jax import), so
the recorder can never be the thing that kills a run, and the schema
check (`validate_bundle`) runs anywhere.

`tools/replay.py` is the consumer; docs/OBSERVABILITY.md the operator
guide.
"""

from __future__ import annotations

import atexit
import json
import math
import os
import re
import signal
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

import numpy as np

# v2 (round 12): + `metrics_tail_source` (the jsonl sink the metrics tail
# mirrors — the cross-ref from a bundle back to the run's full record
# stream) and `registry` (the metrics-registry snapshot at dump time, so
# the bundle carries the run's cumulative counters — steps, compiles,
# nonfinite totals — not just the last few records).
# v2 extension (round 13, same version — the key is OPTIONAL so round-12
# bundles stay valid): + `program_fingerprint`, the compiled train step's
# structural identity (collective counts + donation-summary hash,
# analysis/hlo.program_fingerprint) recorded at the first dispatch;
# tools/replay.py compares it against the program IT compiles and warns
# on divergence — a replay that silently runs a different program is the
# failure mode this kills.
# v2 extension (round 16, same version, OPTIONAL key): + `stream`, the
# streaming data plane's cursor state at dump time (source list + hash +
# per-source offsets + the cursor of the last yielded batch + recent
# batch->record windows, data/streaming.py stream_info()) — so a bundle
# from a streaming-mode run names the exact corpus records in its window
# and an operator can re-point the plane at the same position.
MANIFEST_SCHEMA_VERSION = 2

# run-manifest keys tools/replay.py needs to rebuild the train step; the
# schema check fails loudly on any absence so a stale bundle errors with
# "missing run key", never with a deep jax shape mismatch
REQUIRED_RUN_KEYS = (
    "accum_steps", "steps_per_loop", "seed", "max_pred_row", "grad_dtype",
    "optimizer", "learning_rate", "lr_decay", "warmup_proportion",
    "max_steps", "previous_phase_end_step", "rng_impl", "health_pack",
    "nonfinite_action", "zero1", "mesh", "seq_len", "packing",
)

REQUIRED_MANIFEST_KEYS = (
    "schema_version", "reason", "trigger_step", "created_unix",
    "provenance", "model_config", "run", "checkpoint", "records",
    "metrics_tail", "metrics_tail_source", "registry",
)


def _npz_key(step: int, field: str) -> str:
    return f"s{step:08d}__{field}"


def per_host_dir(out_dir: str) -> str:
    """Multi-host bundle root: suffix `out_dir` with this process's index.

    Every host's ring holds only ITS loader shard and ITS dispatch keys, so
    on a multi-host run each process must dump its own bundles — two hosts
    dumping the same trigger step into one shared directory race
    `os.makedirs` on the same `stepNNN_reason` path and the loser's
    "_2"-suffixed bundle is indistinguishable from a retry. Single-process
    runs get `out_dir` unchanged (bundle layout identical to round 10), and
    jax is imported lazily so this module stays importable without it
    (the validate_bundle contract)."""
    try:
        import jax

        if jax.process_count() > 1:
            return os.path.join(out_dir, f"host{jax.process_index():05d}")
    except Exception:
        pass
    return out_dir


def _json_strict(obj):
    """Strict-JSON sanitizer: non-finite floats become their repr strings
    ('nan', 'inf', '-inf'). A nonfinite bundle's metrics tail contains
    loss=NaN by construction; bare NaN/Infinity tokens are Python-json-only
    and would make manifest.json unreadable to jq / JS dashboards / strict
    parsers. float('nan') round-trips the strings, which is exactly what
    tools/replay.py does when comparing recorded against replayed."""
    if isinstance(obj, float) and not math.isfinite(obj):
        return repr(obj)
    if isinstance(obj, dict):
        return {k: _json_strict(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_json_strict(v) for v in obj]
    return obj


class FlightRecorder:
    """Bounded black box for the host train loop.

    Usage (run_pretraining.py):
        recorder = FlightRecorder(out_dir, window=8, run_info=...,
                                  model_config=..., checkpoint_dir=...,
                                  provenance=...)
        loader.batch_tap = recorder.capture_batch   # loader boundary
        recorder.install_crash_handlers()
        recorder.arm()
        ...
        recorder.record_dispatch(step, n_steps, rng)  # per jit dispatch
        recorder.note_metrics(step, vals)             # per readback
        path = recorder.dump("nonfinite", trigger_step=step)  # on alarm
        ...
        recorder.disarm(); recorder.close()

    `window` bounds the ring in BATCHES (optimization steps), not
    dispatches: with --steps_per_loop n, one dispatch consumes n slots.
    A dispatch wider than the ring keeps only its trailing steps —
    replay then reports the coverage gap loudly instead of lying.
    """

    def __init__(self, out_dir: str, window: int = 8,
                 metrics_tail: int = 64,
                 run_info: Optional[Dict[str, Any]] = None,
                 model_config: Optional[Dict[str, Any]] = None,
                 checkpoint_dir: Optional[str] = None,
                 provenance: Optional[Dict[str, Any]] = None,
                 checkpoint_step_fn: Optional[Callable[[], Any]] = None,
                 metrics_tail_source: Optional[str] = None,
                 registry=None):
        self.out_dir = out_dir
        self.window = max(1, int(window))
        self.run_info = dict(run_info or {})
        self.model_config = dict(model_config or {})
        self.checkpoint_dir = checkpoint_dir
        self.provenance = dict(provenance or {})
        # cross-refs into the metrics plane (set here or later by
        # TelemetryRun.attach_recorder): the jsonl whose records the tail
        # mirrors, and a MetricsRegistry whose snapshot() rides in every
        # manifest dumped
        self.metrics_tail_source = metrics_tail_source
        self.registry = registry
        # set by the entry point once the first dispatch has compiled
        # (analysis/hlo.program_fingerprint via StepProgram.fingerprint)
        self.program_fingerprint: Optional[Dict[str, Any]] = None
        # streaming-plane runs set this to the loader's stream_info so the
        # manifest's optional `stream` key carries the cursor at dump time
        self.stream_info_fn: Optional[Callable[[], Dict[str, Any]]] = None
        self._checkpoint_step_fn = checkpoint_step_fn
        self._staged: List[Dict[str, np.ndarray]] = []
        self._records: deque = deque()
        self._tail: deque = deque(maxlen=max(1, int(metrics_tail)))
        self.last_dump: Optional[str] = None
        self._armed = False
        self._old_handlers: Dict[int, Any] = {}
        self._atexit_registered = False

    # -- capture --------------------------------------------------------------

    def capture_batch(self, batch: Dict[str, np.ndarray]) -> None:
        """Loader-boundary tap (PretrainingDataLoader.batch_tap): stage one
        yielded batch. The next record_dispatch binds staged batches to
        step ids; stale stages (peeked / never-dispatched batches) are
        dropped there. Called on the consumer thread, so staging order is
        yield order even with prefetch assembly running ahead."""
        self._staged.append({k: np.asarray(v) for k, v in batch.items()})
        if len(self._staged) > max(self.window, 1):
            del self._staged[0]

    def record_dispatch(self, first_step: int, n_steps: int,
                        rng: np.ndarray) -> None:
        """Bind the trailing `n_steps` staged batches to the dispatch that
        just consumed them: steps first_step .. first_step+n_steps-1, all
        sharing the dispatch PRNG key (a --steps_per_loop chunk derives
        inner-step keys by fold_in(rng, pos) — replay reproduces that)."""
        rng = np.asarray(rng)
        take = self._staged[-n_steps:]
        offset = n_steps - len(take)
        for i, batch in enumerate(take):
            pos = offset + i
            self._records.append({
                "step": int(first_step + pos),
                "pos": int(pos),
                "n_steps": int(n_steps),
                "rng": rng,
                "batch": batch,
            })
        self._staged.clear()
        while len(self._records) > self.window:
            self._records.popleft()

    def note_metrics(self, step: int, metrics: Dict[str, Any]) -> None:
        """Append one flushed metric record (already host floats) to the
        bounded tail that rides in the bundle manifest."""
        self._tail.append({"step": int(step),
                           **{k: v for k, v in metrics.items()}})

    def nbytes(self) -> int:
        """Bytes held by the ring + staging — the recorder's whole batch
        footprint (the metrics tail is a few KB of floats)."""
        total = 0
        for rec in self._records:
            total += sum(v.nbytes for v in rec["batch"].values())
        for batch in self._staged:
            total += sum(v.nbytes for v in batch.values())
        return total

    # -- dump -----------------------------------------------------------------

    def dump(self, reason: str, trigger_step: Optional[int] = None) -> str:
        """Write the repro bundle; returns its directory. Never raises into
        the caller's alarm path for cosmetic reasons — but a genuinely
        failed write (disk full) does propagate: a silently-empty black
        box is worse than a second error."""
        reason = re.sub(r"[^A-Za-z0-9_.-]+", "_", str(reason)) or "unknown"
        if trigger_step is None:
            trigger_step = (self._records[-1]["step"] if self._records
                            else 0)
        os.makedirs(self.out_dir, exist_ok=True)
        base = os.path.join(self.out_dir,
                            f"step{int(trigger_step):08d}_{reason}")
        path, n = base, 1
        while os.path.exists(path):
            n += 1
            path = f"{base}_{n}"
        os.makedirs(path)

        arrays: Dict[str, np.ndarray] = {}
        records_meta = []
        for rec in self._records:
            sid = rec["step"]
            for k, v in rec["batch"].items():
                arrays[_npz_key(sid, k)] = v
            arrays[_npz_key(sid, "rng")] = rec["rng"]
            records_meta.append({"step": sid, "pos": rec["pos"],
                                 "n_steps": rec["n_steps"],
                                 "fields": sorted(rec["batch"])})
        np.savez(os.path.join(path, "batches.npz"), **arrays)

        latest_ckpt = None
        if self._checkpoint_step_fn is not None:
            try:
                latest_ckpt = self._checkpoint_step_fn()
            except Exception:
                latest_ckpt = None
        manifest = {
            "schema_version": MANIFEST_SCHEMA_VERSION,
            "reason": reason,
            "trigger_step": int(trigger_step),
            "created_unix": round(time.time(), 3),
            "provenance": self.provenance,
            "model_config": self.model_config,
            "run": self.run_info,
            "checkpoint": {"dir": self.checkpoint_dir,
                           "latest_step": latest_ckpt},
            "records": records_meta,
            "metrics_tail": list(self._tail),
            "metrics_tail_source": self.metrics_tail_source,
            "registry": {},
            "program_fingerprint": self.program_fingerprint,
            "stream": None,
        }
        if self.stream_info_fn is not None:
            try:
                manifest["stream"] = self.stream_info_fn()
            except Exception:
                pass  # cursor snapshot must not kill the alarm path
        if self.registry is not None:
            try:
                manifest["registry"] = self.registry.snapshot()
            except Exception:
                pass  # a broken snapshot must not kill the alarm path
        with open(os.path.join(path, "manifest.json"), "w",
                  encoding="utf-8") as f:
            json.dump(_json_strict(manifest), f, indent=2, allow_nan=False)
        self.last_dump = path
        return path

    # -- crash safety ---------------------------------------------------------

    def arm(self) -> None:
        """Training is in flight: an exit without disarm() is abnormal and
        the atexit backstop will dump."""
        self._armed = True

    def disarm(self) -> None:
        self._armed = False

    def install_crash_handlers(self,
                               signals=(signal.SIGTERM, signal.SIGINT)
                               ) -> None:
        """Map SIGTERM/SIGINT to SystemExit(128+sig) so the train loop's
        except-path flushes metrics and dumps the bundle before the
        process unwinds (bench.py gives the same guarantee for its JSON).
        Also registers an atexit backstop that dumps if the process exits
        while armed with nothing dumped yet. No-op for handlers that
        cannot be installed (non-main thread)."""
        for sig in signals:
            try:
                self._old_handlers[sig] = signal.signal(sig,
                                                        self._on_signal)
            except (ValueError, OSError):
                pass
        if not self._atexit_registered:
            atexit.register(self._atexit_dump)
            self._atexit_registered = True

    def _on_signal(self, signum, frame):
        # minimal work here — the except-path in the entry point does the
        # flushing/dumping with normal (non-async-signal) code
        raise SystemExit(128 + signum)

    def _atexit_dump(self) -> None:
        if self._armed and self.last_dump is None:
            try:
                self.dump("atexit")
            except Exception:
                pass

    def close(self) -> None:
        """Restore signal handlers, unregister atexit, release the ring.
        Idempotent."""
        for sig, old in self._old_handlers.items():
            try:
                signal.signal(sig, old)
            except (ValueError, OSError):
                pass
        self._old_handlers.clear()
        if self._atexit_registered:
            atexit.unregister(self._atexit_dump)
            self._atexit_registered = False
        self._armed = False
        self._records.clear()
        self._staged.clear()


# -- bundle schema validation -------------------------------------------------


def validate_manifest(manifest: Any,
                      npz_keys: Optional[set] = None) -> List[str]:
    """Schema-check a bundle manifest; returns a list of human-readable
    errors (empty = valid). With `npz_keys` (the names inside batches.npz)
    also cross-checks that every record's arrays are actually present —
    the failure mode this kills is a stale/truncated bundle failing
    mysteriously deep inside replay instead of loudly at the door."""
    errors: List[str] = []
    if not isinstance(manifest, dict):
        return ["manifest is not a JSON object"]
    for key in REQUIRED_MANIFEST_KEYS:
        if key not in manifest:
            errors.append(f"missing manifest key '{key}'")
    if errors:
        return errors
    if manifest["schema_version"] != MANIFEST_SCHEMA_VERSION:
        errors.append(
            f"schema_version {manifest['schema_version']!r} != "
            f"{MANIFEST_SCHEMA_VERSION} (this replay tool)")
    run = manifest["run"]
    if not isinstance(run, dict):
        errors.append("'run' is not an object")
    else:
        for key in REQUIRED_RUN_KEYS:
            if key not in run:
                errors.append(f"missing run key '{key}'")
    mc = manifest["model_config"]
    if not isinstance(mc, dict) or "hidden_size" not in mc \
            or "num_hidden_layers" not in mc:
        errors.append("'model_config' is not a BertConfig dict")
    records = manifest["records"]
    if not isinstance(records, list) or not records:
        errors.append("'records' is empty — nothing to replay")
        records = []
    for rec in records:
        if not isinstance(rec, dict) or not {"step", "pos", "n_steps",
                                             "fields"} <= set(rec):
            errors.append(f"malformed record {rec!r}")
            continue
        if not (0 <= rec["pos"] < rec["n_steps"]):
            errors.append(f"record step {rec['step']}: pos {rec['pos']} "
                          f"outside n_steps {rec['n_steps']}")
        if npz_keys is not None:
            for field in list(rec["fields"]) + ["rng"]:
                key = _npz_key(rec["step"], field)
                if key not in npz_keys:
                    errors.append(
                        f"batches.npz missing array '{key}'")
    if not isinstance(manifest["metrics_tail"], list):
        errors.append("'metrics_tail' is not a list")
    if not isinstance(manifest["registry"], dict):
        errors.append("'registry' is not an object (the metrics-registry "
                      "snapshot at dump time)")
    src = manifest["metrics_tail_source"]
    if src is not None and not isinstance(src, str):
        errors.append("'metrics_tail_source' is neither null nor a path")
    fp = manifest.get("program_fingerprint")
    if fp is not None and (not isinstance(fp, dict)
                           or "collective_counts" not in fp
                           or "donation_hash" not in fp):
        errors.append(
            "'program_fingerprint' present but malformed (want the "
            "analysis/hlo.program_fingerprint shape: collective_counts + "
            "donation_hash)")
    stream = manifest.get("stream")
    if stream is not None:
        recent = stream.get("recent_batches") if isinstance(stream, dict) \
            else None
        if not isinstance(stream, dict) \
                or not isinstance(stream.get("sources_hash"), str) \
                or not isinstance(stream.get("sources"), list) \
                or not isinstance(stream.get("cursor"), dict) \
                or not isinstance(recent, (list, type(None))):
            errors.append(
                "'stream' present but malformed (want the "
                "data/streaming.py stream_info shape: sources_hash + "
                "sources + cursor [+ recent_batches list])")
        else:
            for w in recent or []:
                if not isinstance(w, dict) or "record_lo" not in w \
                        or "record_hi" not in w:
                    errors.append(
                        f"'stream.recent_batches' entry malformed: {w!r}")
                    break
    return errors


def validate_bundle(bundle_dir: str) -> List[str]:
    """Validate a bundle directory on disk (manifest + npz cross-check)."""
    manifest_path = os.path.join(bundle_dir, "manifest.json")
    npz_path = os.path.join(bundle_dir, "batches.npz")
    if not os.path.isfile(manifest_path):
        return [f"no manifest.json under {bundle_dir}"]
    try:
        with open(manifest_path, encoding="utf-8") as f:
            manifest = json.load(f)
    except Exception as e:
        return [f"manifest.json unreadable: {e}"]
    if not os.path.isfile(npz_path):
        return [f"no batches.npz under {bundle_dir}"]
    try:
        with np.load(npz_path) as npz:
            keys = set(npz.files)
    except Exception as e:
        return [f"batches.npz unreadable: {e}"]
    return validate_manifest(manifest, npz_keys=keys)
