"""One wiring path for run telemetry: `init_run(phase=...)`.

Before this module, every entry point hand-assembled the same block —
MetricLogger with the right sinks, CompileWatch with a warn hook into the
logger, StepWatch from the shared FLOPs formula, provenance header — four
slightly-different copies (run_pretraining / run_squad / run_ner /
bench.py), and a fifth consumer (a future `serving/` process, ROADMAP
item 1) would have made five. `init_run` is the single construction site:

    tel = telemetry.init_run(phase="pretrain",
                             log_prefix=os.path.join(out, "logfile"),
                             verbose=dist.is_main_process(),
                             tensorboard=True, jsonl=True,
                             metrics_port=args.metrics_port)
    tel.log_header(**collect_provenance(mesh=mesh))
    sw = tel.make_stepwatch(flops_per_step=..., seqs_per_step=..., ...)
    ...
    tel.log_train(step, step_loss=..., loss_nonfinite=..., ...)
    rec = sw.step_done();  tel.log_perf(step, rec) if rec else None
    ...
    tel.close()

What the handle owns:

- `.logger` — the MetricLogger (all sinks, rank-0 gated by `verbose`).
- `.compile_watch` — installed, warn-wired into the logger.
- `.registry` — the phase-labeled MetricsRegistry every piece publishes
  through (StepWatch steps/step-time, CompileWatch compiles, MetricLogger
  record gauges, the nonfinite counters below).
- `.server` — opt-in `/metrics` + `/healthz` exporter (`metrics_port`).
- `.aggregator` — opt-in multi-host fold (`multihost_dir`): every
  process publishes its interval records; process 0's `log_perf` folds
  cross-host min/mean/max and straggler warnings into its record.
- `.stepwatch` / `.recorder` — attached later (`make_stepwatch`,
  `attach_recorder`) because their parameters only exist mid-setup.

`log_train` / `log_perf` are the phase-agnostic record paths: they update
the registry + `/healthz` state, run the multi-host fold, then fan out
through the logger — so a record logged by any phase carries the same
schema and reaches the same places. PERF_RECORD_CORE_KEYS is the
contract every phase's perf record satisfies (asserted per-phase by the
e2e tests).
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, Optional

from bert_pytorch_tpu.telemetry.registry import MetricsRegistry

# every phase's StepWatch interval record carries at least these keys —
# the "identical perf schema" contract the e2e tests pin per entry point
PERF_RECORD_CORE_KEYS = (
    "steps", "step_time_ms", "seq_per_sec", "tokens_per_sec",
    "model_flops_per_sec", "mfu", "peak_flops",
)

# health-pack keys every phase's train record may carry; the subset that
# is present drives the nonfinite counters and /healthz flags
HEALTH_FLAG_KEYS = ("loss_nonfinite", "grad_nonfinite",
                    "skipped_nonfinite", "grad_spike")

# perf-record fields promoted to dedicated gauge families (everything
# else numeric lands in the generic bert_perf{field=...} gauge)
_PERF_GAUGES = {
    "step_time_ms": ("bert_step_time_ms",
                     "wall time per optimization step (ms)"),
    "seq_per_sec": ("bert_seq_per_sec", "sequences per second"),
    "tokens_per_sec": ("bert_tokens_per_sec",
                       "slot tokens per second (pad included)"),
    "mfu": ("bert_mfu", "model FLOPs utilization vs device peak"),
}


class TelemetryRun:
    """The per-run telemetry handle. Construct via `init_run`."""

    def __init__(self, phase: str, logger, compile_watch,
                 registry: MetricsRegistry, server=None, aggregator=None):
        self.phase = phase
        self.logger = logger
        self.compile_watch = compile_watch
        self.registry = registry
        self.server = server
        self.aggregator = aggregator
        self.stepwatch = None
        self.recorder = None
        self.stream_loader = None
        self.ckpt_manager = None
        self.slo = None
        self._closed = False
        # restart lineage: tools/supervise.py stamps the attempt index
        # into the child env so the run (and /healthz, and Prometheus)
        # can report how many lives it has already spent
        try:
            self.supervisor_restarts = int(
                os.environ.get("BERT_SUPERVISOR_RESTARTS", "0"))
        except ValueError:
            self.supervisor_restarts = 0
        self._health: Dict[str, Any] = {
            "phase": phase,
            "started_unix": round(time.time(), 3),
            "last_step": None,
            "last_perf_step": None,
            "last_perf": {},
            "last_health": {},
            "last_nonfinite_step": None,
            "nonfinite_flags": {},
            "compiles": 0,
        }
        # declared up front so /metrics shows the zeros from the first
        # scrape, not only after the first flagged step
        self._nonfinite_steps = registry.counter(
            "bert_nonfinite_steps_total",
            "steps flagged non-finite by the in-graph health pack")
        self._loss_nonfinite = registry.counter(
            "bert_loss_nonfinite_steps_total",
            "steps with a non-finite loss")
        self._grad_nonfinite = registry.counter(
            "bert_grad_nonfinite_steps_total",
            "steps with non-finite gradient elements")
        self._steps_total = registry.counter(
            "bert_train_steps_total", "optimization steps completed")
        self._perf_g = {
            k: registry.gauge(name, help)
            for k, (name, help) in _PERF_GAUGES.items()}
        self._perf_other = registry.gauge(
            "bert_perf", "other StepWatch interval fields", labels=("field",))
        if self.supervisor_restarts or "BERT_SUPERVISOR_RESTARTS" in \
                os.environ:
            registry.gauge(
                "bert_supervisor_restarts",
                "restart count of this process under tools/supervise.py"
            ).set(float(self.supervisor_restarts))
            self._health["supervisor_restarts"] = self.supervisor_restarts

    # -- construction-time helpers -------------------------------------------

    def log_header(self, **fields: Any) -> None:
        self.logger.log_header(**fields)

    def make_stepwatch(self, **kwargs):
        """Build the run's StepWatch wired into the registry; kwargs are
        StepWatch's (flops_per_step, seqs_per_step, seq_len, peak_flops,
        log_freq, ...)."""
        from bert_pytorch_tpu.telemetry.stepwatch import StepWatch

        kwargs.setdefault("registry", self.registry)
        self.stepwatch = StepWatch(**kwargs)
        return self.stepwatch

    def attach_recorder(self, recorder) -> None:
        """Cross-wire the flight recorder: its bundle manifests gain the
        registry snapshot at dump time and a `metrics_tail_source`
        pointing at the jsonl whose records the tail mirrors."""
        self.recorder = recorder
        recorder.registry = self.registry
        if getattr(self.logger, "jsonl_path", None):
            recorder.metrics_tail_source = self.logger.jsonl_path

    def attach_checkpoints(self, manager) -> None:
        """Checkpoint-freshness on /healthz: `last_checkpoint_step` and
        `seconds_since_checkpoint` (training/checkpoint.py freshness()),
        so an external orchestrator can gate restarts/alerts on how much
        work a death right now would cost."""
        self.ckpt_manager = manager

    def attach_slo(self, engine) -> None:
        """SLO plane on /healthz: the engine's ok|degraded|failing
        verdict becomes the payload's top-level `status` and a compact
        `slo` block (telemetry/slo.py; /v1/alerts and /v1/slo carry the
        full views on the serving frontend)."""
        self.slo = engine

    def attach_stream(self, loader) -> None:
        """Streaming-plane runs (data/streaming.py): /healthz names the
        plane's live cursor — epoch / source / record / batches — so an
        operator probing a streaming job sees WHERE in the corpus it is,
        not just that it is stepping."""
        self.stream_loader = loader

    # -- record paths ---------------------------------------------------------

    def log_train(self, step: int, tag: str = "train",
                  **vals: Any) -> None:
        """One per-step record: registry counters + /healthz flags, then
        the logger fan-out. The phase-agnostic replacement for
        `logger.log("train", ...)`."""
        step = int(step)
        self._health["last_step"] = step
        flags = {k: vals[k] for k in HEALTH_FLAG_KEYS
                 if isinstance(vals.get(k), (int, float))}
        if flags:
            self._health["last_health"] = flags
        loss_bad = flags.get("loss_nonfinite", 0) > 0
        grad_bad = flags.get("grad_nonfinite", 0) > 0
        if loss_bad or grad_bad:
            self._health["last_nonfinite_step"] = step
            self._health["nonfinite_flags"] = flags
            self._nonfinite_steps.inc()
            if loss_bad:
                self._loss_nonfinite.inc()
            if grad_bad:
                self._grad_nonfinite.inc()
        self.logger.log(tag, step, **vals)

    def log_perf(self, step: int, record: Dict[str, Any],
                 tag: str = "perf") -> Dict[str, Any]:
        """One StepWatch interval record: multi-host fold (publish this
        host's numbers; on process 0 fold the fleet's into the record),
        registry gauges, /healthz state, then the logger fan-out. Returns
        the (possibly fold-augmented) record actually logged."""
        step = int(step)
        record = dict(record)
        if self.aggregator is not None:
            self.aggregator.publish(step, record)
            if self.aggregator.process_index == 0:
                agg, warning = self.aggregator.fold()
                record.update(agg)
                if warning:
                    self.logger.info("WARNING: " + warning)
        for k, g in self._perf_g.items():
            if isinstance(record.get(k), (int, float)):
                g.set(float(record[k]))
        for k, v in record.items():
            if k in self._perf_g or isinstance(v, bool) \
                    or not isinstance(v, (int, float)):
                continue
            self._perf_other.set(float(v), field=k)
        self._health["last_perf_step"] = step
        self._health["last_perf"] = {
            k: record[k] for k in ("step_time_ms", "seq_per_sec", "mfu",
                                   "data_wait_ms")
            if isinstance(record.get(k), (int, float))}
        if isinstance(record.get("compiles"), (int, float)):
            self._health["compiles"] = int(record["compiles"])
        self.logger.log(tag, step, **record)
        return record

    def healthz(self) -> Dict[str, Any]:
        """The /healthz payload: a consistent snapshot of run liveness."""
        h = dict(self._health)
        h["compiles"] = max(h["compiles"], self.compile_watch.compiles)
        h["uptime_secs"] = round(time.time() - h["started_unix"], 1)
        # machine-readable verdict, ALWAYS present: orchestrators gate
        # on h["status"] without caring whether the SLO plane is on
        if self.slo is not None:
            try:
                h["slo"] = self.slo.health_summary()
                h["status"] = h["slo"]["status"]
            except Exception:
                h["status"] = "ok"  # a probe must never take the run down
        else:
            h["status"] = "ok"
        if self.stream_loader is not None:
            try:
                cursor = dict(self.stream_loader.state_dict())
                cursor.pop("pending", None)  # bulky and not liveness
                h["stream"] = cursor
            except Exception:
                pass  # a probe must never take the run down
        if self.ckpt_manager is not None:
            try:
                step, t = self.ckpt_manager.freshness()
                h["last_checkpoint_step"] = step
                h["seconds_since_checkpoint"] = (
                    round(time.time() - t, 1) if t is not None else None)
            except Exception:
                pass  # a probe must never take the run down
        return h

    # -- teardown -------------------------------------------------------------

    def close(self) -> None:
        """Release everything the handle owns (server first — a scrape
        must not race the logger teardown). Idempotent; each piece is
        guarded so one failing close cannot mask the others."""
        if self._closed:
            return
        self._closed = True
        for fn in ((self.server.close if self.server is not None
                    else None),
                   self.compile_watch.uninstall,
                   (self.aggregator.close if self.aggregator is not None
                    else None),
                   self.logger.close):
            if fn is None:
                continue
            try:
                fn()
            except Exception:
                pass

    def __enter__(self) -> "TelemetryRun":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close()


def init_run(phase: str,
             log_prefix: Optional[str] = None,
             verbose: bool = True,
             stream=None,
             tensorboard: bool = False,
             jsonl: bool = True,
             metrics_port: Optional[int] = None,
             metrics_host: str = "0.0.0.0",
             registry: Optional[MetricsRegistry] = None,
             multihost_dir: Optional[str] = None,
             process_index: int = 0,
             process_count: int = 1,
             straggler_z: float = 3.0) -> TelemetryRun:
    """Build the run's telemetry in one call — THE wiring path every
    entry point (and bench.py) uses; see the module docstring for the
    handle's surface.

    `metrics_port=None` disables the exporter; `0` binds an ephemeral
    port (read `tel.server.port`). `multihost_dir` enables the per-host
    publish + process-0 fold (pass `process_index`/`process_count` from
    dist — this module never imports jax)."""
    from bert_pytorch_tpu.training.metrics import MetricLogger
    from bert_pytorch_tpu.telemetry.compile_watch import CompileWatch

    registry = registry if registry is not None \
        else MetricsRegistry(constant_labels={"phase": phase})
    logger = MetricLogger(log_prefix=log_prefix, verbose=verbose,
                          stream=stream, tensorboard=tensorboard,
                          jsonl=jsonl, registry=registry)
    compile_watch = CompileWatch(
        warn=lambda msg: logger.info("WARNING: " + msg),
        registry=registry).install()

    aggregator = None
    if multihost_dir:
        from bert_pytorch_tpu.telemetry.multihost import \
            HostMetricsAggregator

        aggregator = HostMetricsAggregator(
            multihost_dir, process_index=process_index,
            process_count=process_count, z_threshold=straggler_z)

    tel = TelemetryRun(phase, logger, compile_watch, registry,
                       aggregator=aggregator)
    if metrics_port is not None:
        from bert_pytorch_tpu.telemetry.exporter import MetricsServer

        tel.server = MetricsServer(registry, healthz_fn=tel.healthz,
                                   port=metrics_port, host=metrics_host)
        logger.info(f"metrics: serving /metrics and /healthz on "
                    f"{tel.server.url} (phase={phase})")
    return tel
