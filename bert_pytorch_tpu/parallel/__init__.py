from bert_pytorch_tpu.parallel.mesh import (  # noqa: F401
    DEFAULT_LOGICAL_AXIS_RULES,
    make_mesh,
    logical_rules,
)
from bert_pytorch_tpu.parallel.dist import (  # noqa: F401
    barrier,
    get_rank,
    get_world_size,
    initialize,
    is_main_process,
)
