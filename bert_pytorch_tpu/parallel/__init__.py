from bert_pytorch_tpu.parallel.mesh import (  # noqa: F401
    DEFAULT_LOGICAL_AXIS_RULES,
    make_mesh,
    logical_rules,
)
from bert_pytorch_tpu.parallel.dist import (  # noqa: F401
    barrier,
    get_rank,
    get_world_size,
    initialize,
    is_main_process,
)
from bert_pytorch_tpu.parallel.zero import (  # noqa: F401
    Zero1Plan,
    make_zero1_plan,
    zero1_shardings,
)
from bert_pytorch_tpu.parallel.xla_flags import (  # noqa: F401
    OVERLAP_FLAG_PACK,
    apply_overlap_flags,
)
