"""ZeRO-1 optimizer-state sharding over the data-parallel mesh axis.

Under pure data parallelism every chip holds a full replica of the LAMB
moments and redundantly executes the full once-per-step update — the HBM
floor PERF.md pegs at ~9 MFU points at BERT-Large scale. This module is the
TPU-native analog of the reference's apex `DistributedFusedLAMB` /
K-FAC HYBRID_OPT distributed-optimizer ownership (run_pretraining.py:325-327):
each data-parallel chip owns 1/N of every moment tensor and computes only its
shard of the update.

Mechanically this is three sharding constraints, not a rewrite — GSPMD keeps
the global-view semantics and inserts the collectives:

  1. moments are *born* sharded (training/state.make_sharded_state(zero1=True)
     overrides the opt_state storage shardings with `zero1_shardings`);
  2. the post-accumulation gradient is constrained to the same shard layout
     (training/pretrain.py), so the compiler lowers the gradient sum to a
     reduce-scatter instead of an all-reduce;
  3. the updated params are constrained back to their train-step layout,
     which becomes the all-gather of the 1/N-sized updates.

Same bytes on the wire as an all-reduce (reduce-scatter + all-gather), 1/N
optimizer-state read/write and update FLOPs per chip. LAMB's trust-ratio
norms need no hand-written psum in this formulation: the per-tensor /
per-layer reductions in optim/lamb.py are written against the global shapes,
and the partitioner inserts the (scalar-sized) cross-shard reductions where a
tensor is split — parity is asserted in tests/test_zero1.py.

Spec derivation: for each moment/grad leaf, `zero1_spec` appends the shard
axis to the dimension with the largest *per-shard* extent whose size divides
evenly, composing with whatever fsdp/model sharding the logical rules already
placed (a dim sharded 4-way over fsdp can additionally split over data). A
leaf with no evenly-divisible free dim stays on its base sharding — small
(E,)-norm params are replicated anyway by DEFAULT_LOGICAL_AXIS_RULES, and a
ragged split would cost GSPMD padding on every step. Since round 15 the
derivation itself lives in parallel/rules.py (shard_append_spec /
shard_append_tree) — the one logical-axis-rules table the static
`sharding_rules` gate verifies compiled programs against; the wrappers
here keep the ZeRO-1-named API the training code and tests use.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from bert_pytorch_tpu.parallel import rules as rules_lib


class Zero1Plan(NamedTuple):
    """Shardings a train step needs to run the ZeRO-1 update.

    grad_shardings: param-shaped tree — the shard layout for the reduced
        gradient, the moments, and the per-shard update (the reduce-scatter
        output layout).
    param_shardings: param-shaped tree — the params' train-step layout (the
        all-gather target after the update).
    axis: the mesh axis the update is sharded over.
    gather_on_use: False (the round-7 path) leaves the updated params in
        their train-step layout at the END of the step — one block of
        all-gathers after the optimizer, with no compute left to hide them
        behind. True (--zero1_overlap) keeps the params in their 1/N shard
        layout inside the state and re-constrains them leaf-by-leaf at the
        START of the step, right where the forward consumes them — the
        gathers become per-leaf (per-layer under the unstacked encoder
        layout) ops the latency-hiding scheduler can interleave with
        embedding/encoder compute instead of a post-update barrier. Values
        are bit-identical either way — guaranteed by the deliberate
        program-structure symmetries in training/pretrain.py _zero1_update
        (see its docstring), not by hand-waving about all-gathers moving
        bytes; only the collective schedule changes. Requires state built
        with make_sharded_state(zero1_params=True) so the resting params
        match the shard layout.
    """

    grad_shardings: Any
    param_shardings: Any
    axis: str = "data"
    gather_on_use: bool = False


def zero1_spec(shape, base_spec: PartitionSpec, mesh: Mesh,
               axis: str = "data") -> PartitionSpec:
    """base_spec with `axis` added on the best-splittable dim of `shape`
    — the rules table's appended-axis derivation
    (parallel/rules.shard_append_spec holds the logic and the free-dim-
    first / divisibility-fallback rationale); this wrapper keeps the
    ZeRO-1-named API."""
    return rules_lib.shard_append_spec(shape, base_spec, mesh, axis)


def zero1_shardings(abstract_tree: Any, base_shardings: Any, mesh: Mesh,
                    axis: str = "data") -> Any:
    """Tree of NamedShardings with the ZeRO-1 axis applied per leaf
    (parallel/rules.shard_append_tree). `abstract_tree` supplies shapes
    (ShapeDtypeStructs or concrete arrays), `base_shardings` the matching
    NamedSharding tree (e.g. from nn.logical_to_mesh_sharding).
    Non-NamedSharding leaves and scalars pass through untouched, so this
    maps safely over a whole opt_state — LAMB's step count keeps its
    replicated placement."""
    return rules_lib.shard_append_tree(abstract_tree, base_shardings,
                                       mesh, axis)


def plan_expected_shardings(plan: Zero1Plan) -> list:
    """Flat expected-sharding list for a param-shaped tree under `plan`:
    the grad/moment sharding where the plan actually shards the leaf, None
    (no expectation) where it does not — the `expected` contract of
    analysis/hlo.sharding_leaves, shared by assert_moments_sharded and
    tools/graphcheck.py."""
    return [
        g if (isinstance(g, NamedSharding) and isinstance(p, NamedSharding)
              and g.spec != p.spec) else None
        for g, p in zip(jax.tree.leaves(plan.grad_shardings),
                        jax.tree.leaves(plan.param_shardings))]


def assert_moments_sharded(moments: Any, plan: Zero1Plan,
                           where: str = "") -> None:
    """Assert EVERY moment leaf the plan shards is actually non-replicated.

    An any()-style spot check would pass when a stray constraint (or a
    GSPMD branch merge — the K-FAC lax.cond case) replicates a subset of
    leaves, silently losing most of the 1/N state win; this walks the plan
    so exactly the leaves whose grad spec differs from their param spec are
    required to stay sharded. `moments` is any param-shaped tree (mu or
    nu). Since round 13 this is one instance of the general
    unexpected-replication pass (bert_pytorch_tpu/analysis) — the same
    rule tools/graphcheck.py applies to the whole compiled program's
    inputs.
    """
    from bert_pytorch_tpu.analysis.hlo import sharding_leaves
    from bert_pytorch_tpu.analysis.passes import replication_findings

    leaves = sharding_leaves(moments, expected=plan_expected_shardings(plan))
    bad = replication_findings(leaves, rule="zero1_moments")
    assert not bad, f"zero1 moments replicated {where}:\n" + "\n".join(
        str(f) for f in bad)


def _gather_leaf(p, p_sh: NamedSharding):
    """One leaf's gather-on-use constraint, with an IDENTITY backward.

    with_sharding_constraint's transpose re-applies the forward sharding to
    the cotangent — here that would pin the parameter cotangent to the
    GATHERED layout, forcing the batch grad-sum into an all-reduce that is
    only sliced back down at the zero1 grad constraint. The baseline path
    has no such pin: its cotangent reaches the grad constraint unconstrained
    and the sum lowers straight to a reduce-scatter. The custom VJP passes
    the cotangent through untouched, so the overlap path's backward is the
    SAME program as the baseline's — which is also what makes the two paths
    bit-identical (same reduction order), not just close."""

    @jax.custom_vjp
    def g(x):
        return _materialized(x)

    def _materialized(x):
        # The optimization_barrier pins the GATHERED value as a real
        # intermediate: without it the partitioner may sink the gather
        # into a consuming matmul whose contracting dim the shard layout
        # splits (pooler/MLM-transform kernels under the unstacked
        # layout), computing partial-matmul + psum — a different
        # accumulation grouping than the baseline's local matmul, i.e. an
        # ulp-level fork. Both modes get the same barrier (a no-op cost
        # on an already-gathered value), so both consume a materialized
        # replicated operand and partition identically downstream.
        return jax.lax.optimization_barrier(
            jax.lax.with_sharding_constraint(x, p_sh))

    def fwd(x):
        return _materialized(x), None

    def bwd(_, ct):
        return (ct,)

    g.defvjp(fwd, bwd)
    return g(p)


def gather_params(params: Any, plan: Zero1Plan) -> Any:
    """Re-constrain shard-resident params to their train-step layout,
    LEAF BY LEAF — the gather-on-use half of plan.gather_on_use.

    Each leaf gets its own with_sharding_constraint, so each all-gather is
    an independent node whose only consumer is that parameter's first use:
    under the unstacked encoder layout that is one gather per layer per
    kernel, which the scheduler can prefetch behind the previous layer's
    forward compute; under the stacked layout the (L, ...) scan stacks
    gather as whole leaves (the scan consumes the full stack), still split
    by kernel kind (qkv vs mlp vs norms) rather than fused into one
    end-of-step barrier. Leaves whose grad spec equals their param spec
    (nothing was sharded) pass through without a constraint op. The
    backward is identity per leaf (_gather_leaf), so the gradient program
    is the baseline path's bit for bit."""

    def one(p, g_sh, p_sh):
        if (isinstance(g_sh, NamedSharding) and isinstance(p_sh, NamedSharding)
                and g_sh.spec != p_sh.spec):
            return _gather_leaf(p, p_sh)
        return p

    return jax.tree.map(one, params, plan.grad_shardings,
                        plan.param_shardings)


def make_zero1_plan(params_like: Any, param_shardings: Any,
                    mesh: Optional[Mesh], axis: str = "data",
                    gather_on_use: bool = False
                    ) -> Optional[Zero1Plan]:
    """Build the Zero1Plan a train step consumes, or None when sharding the
    update cannot help (no mesh / trivial axis / nothing splittable).

    `params_like` is the (unboxed) param tree — concrete arrays or abstract
    shapes — and `param_shardings` its NamedSharding tree; the grad/moment
    specs derived here are identical to what make_sharded_state(zero1=True)
    chose for the moments, because mu/nu share their param's shape and base
    spec (flax metadata propagates through tx.init's zeros_like).
    """
    if mesh is None:
        return None
    if mesh.shape.get(axis, 1) <= 1:
        return None
    grads = zero1_shardings(params_like, param_shardings, mesh, axis)
    changed = any(
        isinstance(g, NamedSharding) and isinstance(p, NamedSharding)
        and g.spec != p.spec
        for g, p in zip(jax.tree.leaves(grads),
                        jax.tree.leaves(param_shardings)))
    if not changed:
        return None
    return Zero1Plan(grad_shardings=grads, param_shardings=param_shardings,
                     axis=axis, gather_on_use=gather_on_use)
