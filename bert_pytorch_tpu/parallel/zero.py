"""ZeRO-1 optimizer-state sharding over the data-parallel mesh axis.

Under pure data parallelism every chip holds a full replica of the LAMB
moments and redundantly executes the full once-per-step update — the HBM
floor PERF.md pegs at ~9 MFU points at BERT-Large scale. This module is the
TPU-native analog of the reference's apex `DistributedFusedLAMB` /
K-FAC HYBRID_OPT distributed-optimizer ownership (run_pretraining.py:325-327):
each data-parallel chip owns 1/N of every moment tensor and computes only its
shard of the update.

Mechanically this is three sharding constraints, not a rewrite — GSPMD keeps
the global-view semantics and inserts the collectives:

  1. moments are *born* sharded (training/state.make_sharded_state(zero1=True)
     overrides the opt_state storage shardings with `zero1_shardings`);
  2. the post-accumulation gradient is constrained to the same shard layout
     (training/pretrain.py), so the compiler lowers the gradient sum to a
     reduce-scatter instead of an all-reduce;
  3. the updated params are constrained back to their train-step layout,
     which becomes the all-gather of the 1/N-sized updates.

Same bytes on the wire as an all-reduce (reduce-scatter + all-gather), 1/N
optimizer-state read/write and update FLOPs per chip. LAMB's trust-ratio
norms need no hand-written psum in this formulation: the per-tensor /
per-layer reductions in optim/lamb.py are written against the global shapes,
and the partitioner inserts the (scalar-sized) cross-shard reductions where a
tensor is split — parity is asserted in tests/test_zero1.py.

Spec derivation: for each moment/grad leaf, `zero1_spec` appends the shard
axis to the dimension with the largest *per-shard* extent whose size divides
evenly, composing with whatever fsdp/model sharding the logical rules already
placed (a dim sharded 4-way over fsdp can additionally split over data). A
leaf with no evenly-divisible free dim stays on its base sharding — small
(E,)-norm params are replicated anyway by DEFAULT_LOGICAL_AXIS_RULES, and a
ragged split would cost GSPMD padding on every step. Since round 15 the
derivation itself lives in parallel/rules.py (shard_append_spec /
shard_append_tree) — the one logical-axis-rules table the static
`sharding_rules` gate verifies compiled programs against; the wrappers
here keep the ZeRO-1-named API the training code and tests use.
"""

from __future__ import annotations

import sys
from typing import Any, NamedTuple, Optional, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from bert_pytorch_tpu.parallel import rules as rules_lib


class Zero1Plan(NamedTuple):
    """Shardings a train step needs to run the ZeRO-1 update.

    grad_shardings: param-shaped tree — the shard layout for the reduced
        gradient, the moments, and the per-shard update (the reduce-scatter
        output layout).
    param_shardings: param-shaped tree — the params' train-step layout (the
        all-gather target after the update).
    axis: the mesh axis the update is sharded over.
    replicated_leaves: paths of param leaves the spec derivation left on
        their base layout (no evenly-divisible dim — the divisibility
        fallback). Expected for tiny (E,)-norm params; a LARGE leaf here
        is a layout regression, which is why make_zero1_plan warns loudly
        naming them and run_pretraining exports the count as the
        `bert_zero1_replicated_leaves` gauge.
    gather_on_use: False (the round-7 path) leaves the updated params in
        their train-step layout at the END of the step — one block of
        all-gathers after the optimizer, with no compute left to hide them
        behind. True (--zero1_overlap) keeps the params in their 1/N shard
        layout inside the state and re-constrains them leaf-by-leaf at the
        START of the step, right where the forward consumes them — the
        gathers become per-leaf (per-layer under the unstacked encoder
        layout) ops the latency-hiding scheduler can interleave with
        embedding/encoder compute instead of a post-update barrier. Values
        are bit-identical either way — guaranteed by the deliberate
        program-structure symmetries in training/pretrain.py _zero1_update
        (see its docstring), not by hand-waving about all-gathers moving
        bytes; only the collective schedule changes. Requires state built
        with make_sharded_state(zero1_params=True) so the resting params
        match the shard layout.
    """

    grad_shardings: Any
    param_shardings: Any
    axis: str = "data"
    gather_on_use: bool = False
    replicated_leaves: Tuple[str, ...] = ()
    # fsdp plans only: True = the point-of-use gathers are fused behind
    # ONE whole-tree optimization_barrier (every forward op waits on every
    # gather — the blocking layout), False = independent per-leaf barriers
    # the latency-hiding scheduler can interleave with forward compute.
    # Same gather nodes, same arithmetic, bit-identical values either way
    # (tests/test_zero1.py::test_fsdp_overlap_bit_identical); only the
    # schedulability changes — exactly the zero1_overlap trade restated
    # for the fsdp axis.
    blocking_gather: bool = False
    # --zero1_rs: the fwd/bwd runs inside an explicit shard_map region and
    # each grad leaf EXITS it through psum_scatter on its appended-axis dim
    # (scatter_dims below), landing directly in grad_shardings' layout —
    # no full-gradient all-reduce is ever materialized, so the wire moves
    # half the bytes of the all-reduce-then-slice lowering. Requires
    # gather_on_use (the update is shard-local either way; the params'
    # point-of-use gathers are the return path) and a data-only mesh
    # (rs_supported) — inside shard_map every mesh axis is manual, so a
    # model/seq-sharded forward would need its own collective rewrite.
    reduce_scatter: bool = False
    # Which collective carries each sharded grad leaf out of the shard_map
    # region: "scatter" (psum_scatter — the real path) or "allreduce"
    # (psum + slice of own shard — the 2x-bytes pattern this plan exists
    # to kill, kept as a test arm because it is the SAME program modulo
    # the reduction op and therefore bit-identical on CPU/TPU, which is
    # what lets tests pin rs-vs-allreduce parity exactly rather than
    # allclose; the legacy GSPMD path reassociates sums on its own and is
    # only comparable to tolerance).
    rs_mode: str = "scatter"


def zero1_spec(shape, base_spec: PartitionSpec, mesh: Mesh,
               axis: str = "data") -> PartitionSpec:
    """base_spec with `axis` added on the best-splittable dim of `shape`
    — the rules table's appended-axis derivation
    (parallel/rules.shard_append_spec holds the logic and the free-dim-
    first / divisibility-fallback rationale); this wrapper keeps the
    ZeRO-1-named API."""
    return rules_lib.shard_append_spec(shape, base_spec, mesh, axis)


def zero1_shardings(abstract_tree: Any, base_shardings: Any, mesh: Mesh,
                    axis: str = "data") -> Any:
    """Tree of NamedShardings with the ZeRO-1 axis applied per leaf
    (parallel/rules.shard_append_tree). `abstract_tree` supplies shapes
    (ShapeDtypeStructs or concrete arrays), `base_shardings` the matching
    NamedSharding tree (e.g. from nn.logical_to_mesh_sharding).
    Non-NamedSharding leaves and scalars pass through untouched, so this
    maps safely over a whole opt_state — LAMB's step count keeps its
    replicated placement."""
    return rules_lib.shard_append_tree(abstract_tree, base_shardings,
                                       mesh, axis)


def plan_expected_shardings(plan: Zero1Plan) -> list:
    """Flat expected-sharding list for a param-shaped tree under `plan`:
    the grad/moment sharding where the plan actually shards the leaf, None
    (no expectation) where it does not — the `expected` contract of
    analysis/hlo.sharding_leaves, shared by assert_moments_sharded and
    tools/graphcheck.py."""
    return [
        g if (isinstance(g, NamedSharding) and isinstance(p, NamedSharding)
              and g.spec != p.spec) else None
        for g, p in zip(jax.tree.leaves(plan.grad_shardings),
                        jax.tree.leaves(plan.param_shardings))]


def assert_moments_sharded(moments: Any, plan: Zero1Plan,
                           where: str = "") -> None:
    """Assert EVERY moment leaf the plan shards is actually non-replicated.

    An any()-style spot check would pass when a stray constraint (or a
    GSPMD branch merge — the K-FAC lax.cond case) replicates a subset of
    leaves, silently losing most of the 1/N state win; this walks the plan
    so exactly the leaves whose grad spec differs from their param spec are
    required to stay sharded. `moments` is any param-shaped tree (mu or
    nu). Since round 13 this is one instance of the general
    unexpected-replication pass (bert_pytorch_tpu/analysis) — the same
    rule tools/graphcheck.py applies to the whole compiled program's
    inputs.
    """
    from bert_pytorch_tpu.analysis.hlo import sharding_leaves
    from bert_pytorch_tpu.analysis.passes import replication_findings

    leaves = sharding_leaves(moments, expected=plan_expected_shardings(plan))
    bad = replication_findings(leaves, rule="zero1_moments")
    assert not bad, f"zero1 moments replicated {where}:\n" + "\n".join(
        str(f) for f in bad)


def _gather_leaf(p, p_sh: NamedSharding):
    """One leaf's gather-on-use constraint, with an IDENTITY backward.

    with_sharding_constraint's transpose re-applies the forward sharding to
    the cotangent — here that would pin the parameter cotangent to the
    GATHERED layout, forcing the batch grad-sum into an all-reduce that is
    only sliced back down at the zero1 grad constraint. The baseline path
    has no such pin: its cotangent reaches the grad constraint unconstrained
    and the sum lowers straight to a reduce-scatter. The custom VJP passes
    the cotangent through untouched, so the overlap path's backward is the
    SAME program as the baseline's — which is also what makes the two paths
    bit-identical (same reduction order), not just close."""

    @jax.custom_vjp
    def g(x):
        return _materialized(x)

    def _materialized(x):
        # The optimization_barrier pins the GATHERED value as a real
        # intermediate: without it the partitioner may sink the gather
        # into a consuming matmul whose contracting dim the shard layout
        # splits (pooler/MLM-transform kernels under the unstacked
        # layout), computing partial-matmul + psum — a different
        # accumulation grouping than the baseline's local matmul, i.e. an
        # ulp-level fork. Both modes get the same barrier (a no-op cost
        # on an already-gathered value), so both consume a materialized
        # replicated operand and partition identically downstream.
        return jax.lax.optimization_barrier(
            jax.lax.with_sharding_constraint(x, p_sh))

    def fwd(x):
        return _materialized(x), None

    def bwd(_, ct):
        return (ct,)

    g.defvjp(fwd, bwd)
    return g(p)


def _gather_tree_blocking(leaves, shardings):
    """The blocking counterpart of the per-leaf gather: the same
    with_sharding_constraint per leaf, but ONE optimization_barrier over
    the whole gathered tuple — every consumer of any param now depends on
    every gather, so the scheduler cannot start forward compute until the
    last gather lands (torch-FSDP-without-prefetch semantics). The joint
    identity-backward custom VJP keeps the gradient program untouched,
    exactly like _gather_leaf. Same arithmetic, same nodes, bit-identical
    values to the per-leaf mode; only the dependence structure differs."""

    @jax.custom_vjp
    def g(*xs):
        return _materialized(*xs)

    def _materialized(*xs):
        constrained = [jax.lax.with_sharding_constraint(x, s)
                       for x, s in zip(xs, shardings)]
        out = jax.lax.optimization_barrier(tuple(constrained))
        return tuple(out)

    def fwd(*xs):
        return _materialized(*xs), None

    def bwd(_, cts):
        return tuple(cts)

    g.defvjp(fwd, bwd)
    return g(*leaves)


def gather_params(params: Any, plan: Zero1Plan) -> Any:
    """Re-constrain shard-resident params to their train-step layout,
    LEAF BY LEAF — the gather-on-use half of plan.gather_on_use.

    Each leaf gets its own with_sharding_constraint, so each all-gather is
    an independent node whose only consumer is that parameter's first use:
    under the unstacked encoder layout that is one gather per layer per
    kernel, which the scheduler can prefetch behind the previous layer's
    forward compute; under the stacked layout the (L, ...) scan stacks
    gather as whole leaves (the scan consumes the full stack), still split
    by kernel kind (qkv vs mlp vs norms) rather than fused into one
    end-of-step barrier. Leaves whose grad spec equals their param spec
    (nothing was sharded) pass through without a constraint op. The
    backward is identity per leaf (_gather_leaf), so the gradient program
    is the baseline path's bit for bit.

    plan.blocking_gather=True (the fsdp plans' blocking reference layout)
    routes the same constraint set through ONE whole-tree barrier instead
    — see _gather_tree_blocking."""
    flat, treedef = jax.tree_util.tree_flatten(params)
    g_flat = jax.tree.leaves(plan.grad_shardings)
    p_flat = jax.tree.leaves(plan.param_shardings)
    needs = [
        isinstance(g, NamedSharding) and isinstance(p, NamedSharding)
        and g.spec != p.spec
        for g, p in zip(g_flat, p_flat)]
    if plan.blocking_gather:
        idx = [i for i, n in enumerate(needs) if n]
        gathered = _gather_tree_blocking(
            [flat[i] for i in idx], [p_flat[i] for i in idx])
        out = list(flat)
        for i, x in zip(idx, gathered):
            out[i] = x
        return jax.tree_util.tree_unflatten(treedef, out)
    out = [_gather_leaf(x, p) if n else x
           for x, n, p in zip(flat, needs, p_flat)]
    return jax.tree_util.tree_unflatten(treedef, out)


def _skipped_leaf_paths(params_like: Any, param_shardings: Any,
                        grads: Any) -> Tuple[str, ...]:
    """Paths (with shapes) of the leaves the appended-axis derivation left
    on their base layout — the divisibility fallback's output, surfaced so
    a layout regression (a LARGE leaf silently falling back) cannot
    hide."""
    flat = jax.tree_util.tree_flatten_with_path(params_like)[0]
    g_leaves = jax.tree.leaves(grads)
    p_leaves = jax.tree.leaves(param_shardings)
    out = []
    for (path, leaf), g, p in zip(flat, g_leaves, p_leaves):
        if isinstance(g, NamedSharding) and isinstance(p, NamedSharding) \
                and g.spec == p.spec:
            shape = tuple(getattr(leaf, "shape", ()) or ())
            out.append(f"{jax.tree_util.keystr(path)}{list(shape)}")
    return tuple(out)


def warn_replicated_leaves(leaves: Tuple[str, ...], axis: str,
                           axis_size: int, stream=None) -> None:
    """One counted warning naming every leaf the ZeRO-1 derivation left
    replicated (the silent-skip the round-15 bugfix surfaces). Expected
    for (E,)-norm scales and odd biases; anything big in this list means
    the free-dim-first derivation regressed. run_pretraining additionally
    exports the count as the `bert_zero1_replicated_leaves` gauge."""
    if not leaves:
        return
    stream = stream or sys.stderr
    names = list(leaves)
    shown = names[:12] + ([f"... +{len(names) - 12} more"]
                          if len(names) > 12 else [])
    print(f"WARNING: zero1[{axis}]: {len(names)} param leaves have "
          f"no dim divisible by {axis_size} and stay on their base "
          f"layout (replicated w.r.t. the {axis} axis): "
          + ", ".join(shown), file=stream)


def rs_supported(mesh: Optional[Mesh], axis: str = "data") -> bool:
    """True when the mesh shape admits the shard_map reduce-scatter region:
    a non-trivial `axis` and every OTHER axis trivial. Inside shard_map all
    mesh axes are manual, so a model/seq-sharded forward would silently
    compute garbage without its own collective rewrite — refuse instead."""
    if mesh is None or mesh.shape.get(axis, 1) <= 1:
        return False
    return all(n == 1 for a, n in mesh.shape.items() if a != axis)


def scatter_dims(plan: Zero1Plan) -> list:
    """Per-leaf psum_scatter dimension for the plan's grad tree (flat,
    tree.leaves order): the dim the appended-axis derivation gave to
    plan.axis (parallel/rules.appended_dim — the SAME derivation that
    built grad_shardings, so the scatter provably lands each shard in the
    layout the moments rest in), or None for leaves the divisibility
    fallback left on their base layout (those exit via plain psum)."""
    out = []
    for g, p in zip(jax.tree.leaves(plan.grad_shardings),
                    jax.tree.leaves(plan.param_shardings)):
        if isinstance(g, NamedSharding) and isinstance(p, NamedSharding) \
                and g.spec != p.spec:
            out.append(rules_lib.appended_dim(p.spec, g.spec, plan.axis))
        else:
            out.append(None)
    return out


def make_zero1_plan(params_like: Any, param_shardings: Any,
                    mesh: Optional[Mesh], axis: str = "data",
                    gather_on_use: bool = False,
                    reduce_scatter: bool = False,
                    warn_skipped: bool = True
                    ) -> Optional[Zero1Plan]:
    """Build the Zero1Plan a train step consumes, or None when sharding the
    update cannot help (no mesh / trivial axis / nothing splittable).

    `params_like` is the (unboxed) param tree — concrete arrays or abstract
    shapes — and `param_shardings` its NamedSharding tree; the grad/moment
    specs derived here are identical to what make_sharded_state(zero1=True)
    chose for the moments, because mu/nu share their param's shape and base
    spec (flax metadata propagates through tx.init's zeros_like).

    Leaves the derivation leaves on their base layout (nothing divides)
    are recorded in plan.replicated_leaves and warned about loudly
    (warn_skipped=False silences the print for derivation-only callers;
    the list is always populated).
    """
    if mesh is None:
        return None
    if mesh.shape.get(axis, 1) <= 1:
        return None
    grads = zero1_shardings(params_like, param_shardings, mesh, axis)
    changed = any(
        isinstance(g, NamedSharding) and isinstance(p, NamedSharding)
        and g.spec != p.spec
        for g, p in zip(jax.tree.leaves(grads),
                        jax.tree.leaves(param_shardings)))
    if not changed:
        return None
    if reduce_scatter:
        if not gather_on_use:
            raise ValueError(
                "zero1 reduce_scatter requires gather_on_use: the shard_map "
                "region consumes replicated params and emits sharded grads, "
                "so the params must rest sharded and gather at point of use")
        if not rs_supported(mesh, axis):
            raise ValueError(
                f"zero1 reduce_scatter needs a data-only mesh (axis "
                f"'{axis}' > 1, every other axis == 1); got "
                f"{dict(mesh.shape)}")
    plan = Zero1Plan(grad_shardings=grads, param_shardings=param_shardings,
                     axis=axis, gather_on_use=gather_on_use,
                     replicated_leaves=_skipped_leaf_paths(
                         params_like, param_shardings, grads),
                     reduce_scatter=reduce_scatter)
    if warn_skipped:
        warn_replicated_leaves(plan.replicated_leaves, axis,
                               int(mesh.shape.get(axis, 1)))
    return plan


def make_fsdp_plan(params_like: Any, param_shardings: Any,
                   mesh: Optional[Mesh], zero1: bool = False,
                   blocking: bool = False,
                   warn_skipped: bool = True) -> Optional[Zero1Plan]:
    """Gather-on-use plan for fsdp-RESIDENT params (--fsdp_overlap): the
    round-11 ZeRO-1 overlap pattern extended to the fsdp axis.

    Under plain fsdp the params already rest sharded (that is fsdp's
    memory win) and GSPMD inserts the point-of-use gathers implicitly —
    wherever (and fused however) the partitioner likes. This plan makes
    each gather an EXPLICIT per-leaf node exactly like zero1_overlap:

    - grad_shardings = the storage layout the rules table prescribes
      (the fsdp-sharded base specs, plus the appended data axis when
      `zero1` — one derivation with make_sharded_state, so grads
      reduce-scatter into, and the update computes in, the layout the
      state actually rests in);
    - param_shardings = the USE layout: the storage spec with the fsdp
      axis stripped (parallel/rules.strip_axis_spec — whole over fsdp,
      still model-sharded where the table says so). gather_params
      constrains each leaf to it behind the identity-backward VJP +
      optimization_barrier, so each all-gather is an independent,
      overlap-schedulable node whose backward is untouched;
    - axis = 'fsdp'; gather_on_use is always True (there is no "params
      rest gathered" mode for fsdp — resting gathered would simply not
      be fsdp). `blocking` instead selects the BLOCKING reference
      layout: the same gather nodes fused behind one whole-tree barrier
      (every forward op waits on every gather) — what an FSDP
      implementation without prefetch does, and the baseline the
      overlap mode is measured and bit-parity-pinned against
      (tests/test_zero1.py::test_fsdp_overlap_bit_identical).

    The explicit gather-then-compute structure deliberately differs from
    the implicit-GSPMD no-plan program (which is free to sink gathers
    into contracting-dim matmuls as partial-matmul + psum — a different
    accumulation grouping): blocking and overlap share every node and
    are bit-identical to each other; versus the no-plan program the
    values agree to reduction-reorder tolerance only, which the test
    pins as allclose.

    With `zero1` the plan composes both overlaps (requires
    make_sharded_state(zero1=True, zero1_params=True) so params rest in
    the data-appended layout the post-update pin restores). Returns None
    when the mesh has no non-trivial fsdp axis or nothing is
    fsdp-sharded.
    """
    if mesh is None or mesh.shape.get("fsdp", 1) <= 1:
        return None
    rest = param_shardings
    if zero1:
        rest = zero1_shardings(params_like, param_shardings, mesh)
    use = rules_lib.strip_axis_tree(param_shardings, mesh)
    changed = any(
        isinstance(g, NamedSharding) and isinstance(p, NamedSharding)
        and g.spec != p.spec
        for g, p in zip(jax.tree.leaves(rest), jax.tree.leaves(use)))
    if not changed:
        return None
    skipped = (_skipped_leaf_paths(params_like, param_shardings, rest)
               if zero1 else ())
    plan = Zero1Plan(grad_shardings=rest, param_shardings=use,
                     axis="fsdp", gather_on_use=True,
                     replicated_leaves=skipped, blocking_gather=blocking)
    if warn_skipped and zero1:
        warn_replicated_leaves(skipped, "data",
                               int(mesh.shape.get("data", 1)))
    return plan
