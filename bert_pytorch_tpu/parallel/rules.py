"""The logical-axis-rules table: ONE source of truth for every sharding
spec in the repo.

Before round 15 the specs lived scattered — the flax rules tuple in
parallel/mesh.py, the ZeRO-1 free-dim-first derivation in
parallel/zero.py, the K-FAC stacked-factor placement in optim/kfac.py,
the batch-input layout in mesh.batch_sharding, and the serving engine's
implicit single-device placement — which meant every collective
optimization (MULTICHIP_r07: 75-94% of multichip wall time is
collectives) had to reason about specs it could not see in one place.
This module is that one place:

- `BASE_RULES`: the logical-axis -> mesh-axis table (each entry carries
  the WHY next to the mapping). `resolve(mesh)` turns it into the
  flax-style pair list, applying any per-mesh-config override from
  `CONFIG_OVERRIDES` — dp-only, dp x fsdp, dp x mp, and dp x seq meshes
  all compose through the same table.
- derivation helpers every consumer routes through:
  `shard_append_spec` (the ZeRO-1 moment/grad layout — free-dim-first
  with a divisibility fallback, formerly parallel/zero.zero1_spec),
  `stacked_spec` (the K-FAC distributed-factor layout, formerly
  KFAC._stacked_sharding), `batch_spec` (the activation/input layout the
  step builders and the serving engine consume), and
  `train_state_expectations` (the full TrainState storage layout plus a
  per-leaf rule LABEL, consumed by training/state.make_sharded_state for
  construction and by tools/graphcheck.py's `sharding_rules` pass for
  verification — the same derivation on both sides is what makes the
  static check meaningful: any ad-hoc constraint site that diverges from
  the table shows up as a compiled in-sharding that the table did not
  derive).

The table is declarative and the check is static: tools/graphcheck.py
compiles every production program combo and verifies each input leaf's
compiled in-sharding against the spec derived here (docs/SHARDING.md is
the operator guide; docs/OBSERVABILITY.md "Static graph analysis" covers
the gate). Fingerprint neutrality of the round-15 refactor — every
pre-existing combo's collective counts + donation hash byte-identical —
is pinned in tests/test_sharding_rules.py.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

MESH_AXES = ("data", "fsdp", "model", "seq")

# a rule's mesh_axes: None (replicated), one axis name, or a tuple of
# axis names whose product shards the dimension
Axes = Union[None, str, Tuple[str, ...]]


@dataclasses.dataclass(frozen=True)
class Rule:
    """One row of the table: a logical axis name (what model code
    annotates via nn.with_logical_partitioning) mapped to the mesh
    axis/axes that shard it, with the reason pinned next to the
    mapping."""

    logical: str
    mesh_axes: Axes
    note: str = ""


BASE_RULES: Tuple[Rule, ...] = (
    # -- params ------------------------------------------------------------
    Rule("vocab", ("model", "fsdp"),
         "embedding rows / MLM decoder cols: splitting the big (V, E) "
         "table on its vocab axis over BOTH model and fsdp keeps the ZeRO "
         "memory win while leaving the embed axis replicated — an "
         "embed-sharded table makes every lookup emit a "
         "replicate-then-repartition against the batch-sharded "
         "activations (SPMD 'involuntary full rematerialization')"),
    Rule("embed", "fsdp",
         "hidden dim of params -> ZeRO sharding"),
    Rule("mlp", "model",
         "FFN inner dim -> megatron column/row split"),
    Rule("heads", "model",
         "attention heads"),
    Rule("kv", None, "per-head dim stays whole"),
    Rule("embed_out", None, "output embed dim of row-split kernels"),
    Rule("embed_head", None,
         "embed-dim of the small post-pooler heads (pooler dense, "
         "NSP/classifier kernels): replicated — an fsdp-sharded "
         "contracting dim on a few-KB kernel forces GSPMD to reshard the "
         "batch-sharded (B, E) pooled activations embed-major, an "
         "involuntary full rematerialization on (data x fsdp) meshes "
         "(tests/test_zero1.py 2x2-mesh gate)"),
    Rule("norm", None,
         "(E,)-shaped norm scales/biases and the small "
         "position/token-type tables: sharding a few KB forces XLA into "
         "replicate-then-repartition transitions against the "
         "batch-sharded activations, so they stay replicated by design"),
    Rule("layers", None,
         "scan-stacked layer axis stays replicated. This logical axis "
         "only exists under the stacked layout (config.stacked_params="
         "True, where nn.scan prepends it via PARTITION_NAME); the "
         "unstacked per-layer layout has no leading L dim anywhere, so "
         "its leaves resolve through the remaining rules unchanged — "
         "same mesh placement per layer"),
    # -- activations -------------------------------------------------------
    Rule("data", ("data", "fsdp"),
         "batch shards over data AND fsdp (fsdp devices are data "
         "parallel for activations; only params/moments split on fsdp)"),
    Rule("seq", "seq",
         "sequence axis -> ring-attention seq sharding"),
    Rule("embed_act", None, "activation embed dim stays whole"),
)

# The named production config (round 15): the collective-time feature
# pack — packing + ring attention + ZeRO-1 overlap + fsdp gather-on-use
# — promoted to a first-class CONFIG_OVERRIDES entry so "what the
# production mesh runs" is a name in the rules table, not a flag recipe
# scattered across launch scripts. Its RULE rows are identical to
# BASE_RULES (empty override tuple: every production mesh composes
# through the base table — measured, not assumed, by the
# dp_seq_packing_overlap MULTICHIP variant); what the name carries is
# the feature set `production_features(mesh)` derives per mesh shape.
PRODUCTION_CONFIG = "production"

# Per-mesh-config overrides: config name (see `mesh_config`) -> extra
# Rule rows that REPLACE the base row for the same logical axis on that
# config only. The only named entry today is `production` (rule rows ==
# base — the override hook stays load-bearing for the ROADMAP item-1b
# sharded serving mesh and is exercised by tests/test_sharding_rules.py).
CONFIG_OVERRIDES: Dict[str, Tuple[Rule, ...]] = {
    PRODUCTION_CONFIG: (),
}

# K-FAC distributed factor ownership splits the stacked layer axis over
# these mesh axes (optim/kfac.py KFAC.shard_axes default) — part of the
# table so the audit/gate derivations and the live placement agree.
KFAC_SHARD_AXES: Tuple[str, ...] = ("data", "fsdp")

# The ZeRO-1 update shards over this axis (parallel/zero.Zero1Plan.axis
# default).
ZERO1_AXIS = "data"


def mesh_config(mesh=None) -> str:
    """Short name of a mesh's parallelism config: the non-trivial axes in
    MESH_AXES order, joined — 'dp', 'dp_fsdp', 'dp_mp', 'dp_seq',
    'dp_fsdp_mp', ... 'replicated' when every axis is trivial or there is
    no mesh. This is the CONFIG_OVERRIDES key."""
    if mesh is None:
        return "replicated"
    short = {"data": "dp", "fsdp": "fsdp", "model": "mp", "seq": "seq"}
    sizes = dict(mesh.shape)
    parts = [short[a] for a in MESH_AXES if sizes.get(a, 1) > 1]
    return "_".join(parts) if parts else "replicated"


def resolve(mesh=None, overrides: Optional[Dict[str, Tuple[Rule, ...]]]
            = None, config: Optional[str] = None
            ) -> Tuple[Tuple[str, Axes], ...]:
    """The flax-style ((logical, mesh_axes), ...) pair list for `mesh`:
    BASE_RULES with this mesh config's overrides applied row-by-row
    (an override row replaces the base row with the same logical name;
    a new logical name appends). mesh=None returns the base table —
    exactly the tuple parallel/mesh.DEFAULT_LOGICAL_AXIS_RULES re-exports
    for flax contexts that are mesh-agnostic. `config` selects a NAMED
    override entry (e.g. PRODUCTION_CONFIG) instead of the mesh-derived
    key — how run_pretraining resolves the rules when --mesh_config
    picked the production pack."""
    rows = list(BASE_RULES)
    table = CONFIG_OVERRIDES if overrides is None else overrides
    key = config if config is not None else mesh_config(mesh)
    for over in table.get(key, ()):
        for i, row in enumerate(rows):
            if row.logical == over.logical:
                rows[i] = over
                break
        else:
            rows.append(over)
    return tuple((r.logical, r.mesh_axes) for r in rows)


def production_features(mesh=None) -> Dict[str, bool]:
    """The feature set the `production` config turns on for THIS mesh —
    each entry only where the mesh shape can express it:

    - packing: always (unpadded rows are a pure win on any shape);
    - zero1 / zero1_overlap: the data axis is non-trivial (ZeRO-1 shards
      the update over `data`; overlap moves its all-gathers to the point
      of use);
    - fsdp_overlap: the fsdp axis is non-trivial (gather-on-use for
      fsdp-resident params — parallel/zero.make_fsdp_plan);
    - ring_attention: the seq axis is non-trivial (ops/ring_attention.py;
      the default attention impl already routes there — recorded so the
      resolved config names the whole composition).

    run_pretraining consumes this when --mesh_config resolves to
    `production`; bench.py's `dp_seq_packing_overlap` variant measures
    the full composition so the default is backed by a number."""
    sizes = dict(mesh.shape) if mesh is not None else {}
    data = sizes.get("data", 1) > 1
    return {
        "packing": True,
        "zero1": data,
        "zero1_overlap": data,
        "fsdp_overlap": sizes.get("fsdp", 1) > 1,
        "ring_attention": sizes.get("seq", 1) > 1,
    }


def production_qualifies(mesh=None) -> bool:
    """Does this mesh have any axis the production feature pack can use?
    (A single-device / replicated mesh gains nothing — --mesh_config=auto
    keeps the base config there.)"""
    if mesh is None:
        return False
    sizes = dict(mesh.shape)
    return any(sizes.get(a, 1) > 1 for a in ("data", "fsdp", "seq"))


def rule_for(logical: str, mesh=None) -> Axes:
    """The mesh axes the table assigns to one logical axis (None =
    replicated). Raises KeyError on an unknown logical name — a typo in
    a model annotation must not silently replicate."""
    for name, axes in resolve(mesh):
        if name == logical:
            return axes
    raise KeyError(f"no rule for logical axis {logical!r}")


# -- derivation: extra-axis append (the ZeRO-1 layout) -------------------------


def _entry_axes(entry) -> tuple:
    if entry is None:
        return ()
    if isinstance(entry, (tuple, list)):
        return tuple(entry)
    return (entry,)


def shard_append_spec(shape, base_spec, mesh, axis: str = ZERO1_AXIS):
    """base_spec with `axis` added on the best-splittable dim of `shape`
    — the ZeRO-1 moment/grad layout derivation (formerly
    parallel/zero.zero1_spec; zero.py now delegates here).

    Preference order: the largest UNSHARDED dim that divides evenly by
    the axis size; only if no free dim qualifies, stack onto an
    already-sharded dim (largest per-shard extent divisible by the extra
    factor). Free dims first is not just cosmetic — stacking `data` onto
    a dim another mesh axis already shards (e.g. the (model, fsdp)-
    sharded vocab dim of the tied embedding) creates a grad layout
    sharded over every axis at once, which the loss/backward residuals
    can only reach by involuntary full rematerialization (reshard gate,
    tests/test_zero1.py). Returns base_spec unchanged when the axis is
    trivial, already used, or nothing divides (the divisibility
    fallback — prime-sized leaves stay on their base layout instead of
    paying GSPMD ragged-split padding every step). `mesh` only needs a
    `.shape` mapping, so tests can probe prime shard counts without
    devices."""
    from jax.sharding import PartitionSpec

    n = mesh.shape.get(axis, 1) if hasattr(mesh.shape, "get") \
        else dict(mesh.shape)[axis]
    if n <= 1 or not shape:
        return base_spec
    entries = list(tuple(base_spec))
    entries += [None] * (len(shape) - len(entries))
    if any(axis in _entry_axes(e) for e in entries):
        return base_spec

    def shard_factor(entry) -> int:
        f = 1
        for a in _entry_axes(entry):
            f *= mesh.shape[a]
        return f

    best, best_local, best_free = -1, 0, False
    for d, size in enumerate(shape):
        cur = shard_factor(entries[d])
        if size == 0 or size % (cur * n):
            continue
        free = cur == 1
        local = size // cur  # per-shard extent before the new split
        if (free, local) > (best_free, best_local):
            best, best_local, best_free = d, local, free
    if best < 0:
        return base_spec
    prior = _entry_axes(entries[best])
    entries[best] = prior + (axis,) if prior else axis
    return PartitionSpec(*entries)


def shard_append_tree(abstract_tree: Any, base_shardings: Any, mesh,
                      axis: str = ZERO1_AXIS) -> Any:
    """Tree of NamedShardings with the appended axis applied per leaf
    (formerly parallel/zero.zero1_shardings — zero.py delegates here).
    `abstract_tree` supplies shapes (ShapeDtypeStructs or concrete
    arrays), `base_shardings` the matching NamedSharding tree.
    Non-NamedSharding leaves and scalars pass through untouched, so this
    maps safely over a whole opt_state — LAMB's step count keeps its
    replicated placement."""
    import jax
    from jax.sharding import NamedSharding

    def one(ab, sh):
        if not isinstance(sh, NamedSharding):
            return sh
        shape = getattr(ab, "shape", None)
        if not shape:
            return sh
        return NamedSharding(mesh, shard_append_spec(shape, sh.spec, mesh,
                                                     axis))

    return jax.tree.map(one, abstract_tree, base_shardings)


def appended_dim(base_spec, appended_spec, axis: str = ZERO1_AXIS
                 ) -> Optional[int]:
    """The dim index where shard_append_spec placed `axis` — i.e. the one
    entry of `appended_spec` that carries `axis` while the matching
    `base_spec` entry does not — or None for a leaf the divisibility
    fallback left on its base layout. This is the reduce-scatter
    dimension derivation: the ZeRO-1 rs gradient path psum-scatters each
    per-device gradient along exactly this dim, so the scattered local
    block lands in the SAME layout shard_append_spec derived for the
    moments (one derivation serving the plan construction, the scatter,
    and the sharding_rules pass)."""
    a_entries = list(tuple(appended_spec))
    b_entries = list(tuple(base_spec))
    b_entries += [None] * (len(a_entries) - len(b_entries))
    for d, (ae, be) in enumerate(zip(a_entries, b_entries)):
        if axis in _entry_axes(ae) and axis not in _entry_axes(be):
            return d
    return None


# -- derivation: axis strip (the fsdp gather-on-use USE layout) ----------------


FSDP_AXIS = "fsdp"


def strip_axis_spec(base_spec, axis: str = FSDP_AXIS):
    """base_spec with every occurrence of `axis` removed — the USE-layout
    derivation behind fsdp gather-on-use (--fsdp_overlap). Params REST in
    the table's storage layout (which shards their fsdp-ruled dims); at
    the point of use the forward wants them whole over fsdp, and this
    spec is the explicit per-leaf gather target parallel/zero.
    gather_params constrains to. Deriving it here (rather than in
    zero.py) keeps construction (make_sharded_state), the point-of-use
    gather, and the sharding_rules verification reading ONE source: the
    use layout is a pure function of the storage layout the table
    already owns. Entries that shard over `axis` jointly with other
    axes keep the others ((model, fsdp) vocab stays model-sharded at
    use — only the fsdp factor gathers)."""
    from jax.sharding import PartitionSpec

    if base_spec is None:
        return None
    out = []
    for entry in tuple(base_spec):
        axes = tuple(a for a in _entry_axes(entry) if a != axis)
        out.append(axes if len(axes) > 1 else (axes[0] if axes else None))
    while out and out[-1] is None:
        out.pop()
    return PartitionSpec(*out)


def strip_axis_tree(base_shardings: Any, mesh,
                    axis: str = FSDP_AXIS) -> Any:
    """Tree of NamedShardings with `axis` stripped per leaf
    (strip_axis_spec) — the whole-params use layout an fsdp gather-on-use
    plan gathers to. Non-NamedSharding leaves pass through untouched."""
    import jax
    from jax.sharding import NamedSharding

    def one(sh):
        if not isinstance(sh, NamedSharding):
            return sh
        return NamedSharding(mesh, strip_axis_spec(sh.spec, axis))

    return jax.tree.map(one, base_shardings)


# -- derivation: stacked-layer-axis split (the K-FAC factor layout) ------------


def shard_count(mesh, axes: Sequence[str] = KFAC_SHARD_AXES) -> int:
    """Product of the named axes' sizes; missing axes count as 1 so
    custom meshes degrade to the replicated layout instead of raising."""
    if mesh is None:
        return 1
    sizes = dict(mesh.shape)
    return int(np.prod([sizes.get(a, 1) for a in axes]))


def stacked_spec(mesh, n_stacked: int,
                 axes: Sequence[str] = KFAC_SHARD_AXES):
    """NamedSharding splitting a leading stacked-layer axis of size
    `n_stacked` over `axes`, or None when there is no mesh / the axis
    does not divide evenly over the shards (uneven layouts are rejected
    by jax for donated/jitted state; a replicated fallback is always
    correct). Formerly KFAC._stacked_sharding — optim/kfac.py delegates
    here, and so do the shard-audit/gate expectations, which is what
    retires their private copies."""
    shards = shard_count(mesh, axes)
    if shards <= 1 or n_stacked % shards != 0:
        return None
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P(tuple(axes)))


# -- derivation: batch/activation layout ---------------------------------------


def batch_axes(mesh=None) -> Tuple[str, ...]:
    """The mesh axes the table assigns to the batch ('data' logical)
    axis."""
    return tuple(_entry_axes(rule_for("data", mesh)))


def batch_spec(n_leading: int = 1, mesh=None):
    """PartitionSpec for input batches: `n_leading` unsharded leading
    axes (accum, or steps+accum) before the batch axis, which rides the
    table's 'data' rule. n_leading=0 is a flat (batch, ...) array (the
    serving engine's bucketed forwards)."""
    from jax.sharding import PartitionSpec as P

    return P(*([None] * n_leading), batch_axes(mesh))


# -- derivation: whole-TrainState expectations ---------------------------------


def label_logical(spec) -> str:
    """Human label for a leaf's logical annotation: 'logical(vocab,embed)'
    with '-' for unsharded dims, 'replicated' when nothing is annotated."""
    entries = tuple(spec) if spec is not None else ()
    if not any(e is not None for e in entries):
        return "replicated"
    return "logical(" + ",".join(
        "-" if e is None else
        ("+".join(e) if isinstance(e, (tuple, list)) else str(e))
        for e in entries) + ")"


def is_spec_leaf(x) -> bool:
    from jax.sharding import PartitionSpec

    return x is None or isinstance(x, PartitionSpec)


def train_state_shardings(abstract_state: Any, mesh,
                          zero1: bool = False, zero1_params: bool = False,
                          table=None) -> Any:
    """The STORAGE NamedSharding tree the rules table prescribes for a
    TrainState (abstract, with flax Partitioned metadata still boxed —
    training/state.abstract_train_state builds one): logical annotations
    -> mesh axes via `resolve(mesh)`, then the ZeRO-1 appended axis on
    the moments (zero1=True) and on the resting params
    (zero1_params=True, the --zero1_overlap layout).
    training/state.make_sharded_state CONSTRUCTS the state from this
    derivation and tools/graphcheck.py VERIFIES compiled programs
    against it — one derivation, two consumers."""
    from flax import linen as nn

    rules = list(table) if table is not None else list(resolve(mesh))
    logical = nn.get_partition_spec(abstract_state)
    shardings = nn.logical_to_mesh_sharding(logical, mesh, rules)
    unboxed = _unbox(abstract_state)
    if zero1:
        shardings = shardings.replace(opt_state=shard_append_tree(
            unboxed.opt_state, shardings.opt_state, mesh))
    if zero1_params:
        shardings = shardings.replace(params=shard_append_tree(
            unboxed.params, shardings.params, mesh))
    return shardings


def train_state_expectations(abstract_state: Any, mesh,
                             zero1: bool = False,
                             zero1_params: bool = False,
                             table=None) -> Tuple[List[Any], List[str]]:
    """(expected shardings, rule labels), FLAT in tree_leaves order, for
    every leaf of a TrainState — the `sharding_rules` static-analysis
    contract (analysis/passes.py, tools/graphcheck.py). The expected
    sharding is exactly `train_state_shardings`; the label names the
    logical axes the table resolved plus any appended-axis derivation
    ('logical(-,embed)+zero1[data]'), so a gate finding can say WHICH
    rule the compiled program violated."""
    import jax
    from flax import linen as nn
    from jax.sharding import NamedSharding

    base = train_state_shardings(abstract_state, mesh, zero1=False,
                                 table=table)
    final = train_state_shardings(abstract_state, mesh, zero1=zero1,
                                  zero1_params=zero1_params, table=table)
    logical = nn.get_partition_spec(abstract_state)

    # flatten all three with None-as-leaf so the structural Nones
    # (TrainState.precond_state / .telemetry) line the trees up, then
    # drop them — program args flatten without them too
    none_leaf = {"is_leaf": lambda x: x is None}
    flat_logical = jax.tree.leaves(logical, is_leaf=is_spec_leaf)
    flat_base = jax.tree.leaves(base, **none_leaf)
    flat_final = jax.tree.leaves(final, **none_leaf)
    if not (len(flat_logical) == len(flat_base) == len(flat_final)):
        raise ValueError(
            f"rules: logical/base/final leaf counts diverge "
            f"({len(flat_logical)}/{len(flat_base)}/{len(flat_final)})")
    expected, labels = [], []
    for lg, b, f in zip(flat_logical, flat_base, flat_final):
        if b is None and f is None:
            continue  # structural None — not a program input leaf
        label = label_logical(lg)
        if isinstance(f, NamedSharding) and isinstance(b, NamedSharding) \
                and f.spec != b.spec:
            label += f"+zero1[{ZERO1_AXIS}]"
        expected.append(f)
        labels.append(label)
    return expected, labels


def _unbox(tree: Any) -> Any:
    """Local copy of training/state.unbox (strip flax Partitioned boxes)
    to keep the parallel package import-independent of training/."""
    import jax
    from flax import linen as nn

    return jax.tree.map(
        lambda x: x.unbox() if isinstance(x, nn.Partitioned) else x,
        tree,
        is_leaf=lambda x: isinstance(x, nn.Partitioned),
    )
