"""Coalesced cross-device reductions: the scalar-all-reduce storm, bucketed.

MULTICHIP_r07 attribution and the graph lint agree on where multichip
wall time goes: collectives. The largest *count* contributor in the
compiled train steps is not the gradient traffic (a handful of large,
bandwidth-bound ops) but the reduction STORM of tiny scalars — LAMB's
per-tensor trust-ratio norms alone compile to two `f32[]`/`f32[L]`
all-reduces per parameter leaf (88 of `kfac_zero1_dp8`'s 161 all-reduces
per graph_report), each paying full collective latency to move four
bytes. Latency, not bandwidth, is the bill; batching is the fix — the
same amortization PAPERS.md "Multi-node BERT-pretraining" (2008.00177)
applies to gradient communication.

`NormReducer` coalesces them: per-leaf LOCAL partial sums computed under
`shard_map` (the identical local reduce GSPMD's partial-sum lowering
performs), flattened into deterministic size-capped buckets, ONE `psum`
per bucket, then split back per leaf. Summation grouping is preserved —
local block reduce, then one cross-device sum per element, exactly the
two-level grouping of the per-tensor all-reduces — so the coalesced
update is BIT-IDENTICAL to the per-tensor one (pinned in
tests/test_kfac.py::test_kfac_bucketed_reduction_parity). Leaves whose
layout the reducer cannot bucket fall back to the per-tensor path,
loudly and countably:

- leaves replicated on the mesh need no cross-device reduction at all
  ('local'),
- leaves whose KEPT (per-layer trust ratio) axes are themselves sharded
  would need a sharded output layout ('kept-axis-sharded' — left to
  GSPMD, counted in `summary()`).

The bucket assignment is a pure function of the parameter tree and the
rules-table layout (parallel/rules.py) — deterministic, recorded in the
run header via `summary()` so a bundle/replay can see exactly which
leaves shared a reduction. optim/lamb.py consumes this for the trust
norms (`lamb(norm_reducer=...)`); optim/kfac.py applies the same idea to
the factor-statistic reductions (its own buckets — factor tensors, not
scalars). Both are opt-in: without a reducer the compiled programs are
byte-identical to round 15's.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from bert_pytorch_tpu.parallel.rules import _entry_axes

DEFAULT_BUCKET_BYTES = 4 << 20


def spec_sharded_dims(spec, mesh_sizes: Dict[str, int]) -> Dict[int, tuple]:
    """dim index -> non-trivial mesh axes sharding it, for one
    PartitionSpec (axes of size 1 shard nothing and are ignored)."""
    out: Dict[int, tuple] = {}
    for d, entry in enumerate(tuple(spec) if spec is not None else ()):
        axes = tuple(a for a in _entry_axes(entry)
                     if mesh_sizes.get(a, 1) > 1)
        if axes:
            out[d] = axes
    return out


def _bucketize(sizes: Sequence[int], cap_bytes: int,
               itemsize: int = 4) -> List[List[int]]:
    """Deterministic greedy bucket assignment: walk entries in order,
    start a new bucket when the running payload would exceed the cap.
    Returns index lists; every entry lands in exactly one bucket."""
    buckets: List[List[int]] = []
    cur: List[int] = []
    cur_bytes = 0
    for i, n in enumerate(sizes):
        b = int(n) * itemsize
        if cur and cur_bytes + b > cap_bytes:
            buckets.append(cur)
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_bytes += b
    if cur:
        buckets.append(cur)
    return buckets


class NormReducer:
    """Bucketed trust-ratio/global-norm reductions for one parameter
    layout.

    `param_shardings` is the param-shaped tree of NamedShardings (or bare
    PartitionSpecs) the norm inputs will be constrained to when the norms
    are computed — for a ZeRO-1 step that is the plan's grad/shard layout
    (parallel/zero.Zero1Plan.grad_shardings), the layout `_zero1_update`
    pins `norm_params` and the updates to. Deriving the reducer from the
    same tree the plan derived keeps one source of truth: a layout change
    re-derives the buckets.
    """

    def __init__(self, param_shardings: Any, mesh,
                 bucket_bytes: int = DEFAULT_BUCKET_BYTES):
        import jax

        self.mesh = mesh
        self.bucket_bytes = int(bucket_bytes)
        self._specs = [getattr(s, "spec", s)
                       for s in jax.tree.leaves(param_shardings)]
        self._sizes = dict(mesh.shape)
        self._summary: Optional[Dict[str, Any]] = None

    # -- classification -----------------------------------------------------

    def _classify(self, flat_shapes: Sequence[tuple],
                  flat_nbatch: Sequence[int]):
        """(groups, plain): groups maps a sorted tuple of reduction axes
        to the leaf indices bucketed under it; plain lists (index, why)
        for leaves computed per-tensor."""
        groups: Dict[tuple, List[int]] = {}
        plain: List[Tuple[int, str]] = []
        for i, (shape, nb) in enumerate(zip(flat_shapes, flat_nbatch)):
            spec = self._specs[i] if i < len(self._specs) else None
            sd = spec_sharded_dims(spec, self._sizes)
            if not sd:
                plain.append((i, "local"))
            elif any(d < nb for d in sd):
                plain.append((i, "kept-axis-sharded"))
            else:
                key = tuple(sorted({a for axes in sd.values()
                                    for a in axes}))
                groups.setdefault(key, []).append(i)
        return groups, plain

    # -- the coalesced trust norms ------------------------------------------

    def trust_norms(self, pf_tree: Any, u_tree: Any, nbatch_tree: Any,
                    paths: Optional[Sequence[str]] = None
                    ) -> Tuple[Any, Any]:
        """(pn_tree, un_tree): per-leaf L2 norms of `pf_tree` / `u_tree`
        reduced over all but the first nbatch axes (keepdims, like
        optim/lamb.per_tensor computes them), with every cross-device
        reduction bucketed. Bit-identical values to the per-tensor path:
        same local reduce, same per-element cross-device sum, sqrt after
        the reduction in both."""
        import jax
        import jax.numpy as jnp

        from bert_pytorch_tpu.ops.shard_map_compat import shard_map

        flat_pf, treedef = jax.tree_util.tree_flatten(pf_tree)
        flat_u = jax.tree.leaves(u_tree)
        flat_nb = [int(n) for n in jax.tree.leaves(nbatch_tree)]
        shapes = [tuple(x.shape) for x in flat_pf]
        groups, plain = self._classify(shapes, flat_nb)

        def kept_keepdims(shape, nb):
            return tuple(shape[:nb]) + (1,) * (len(shape) - nb)

        def local_sq(x, nb):
            return jnp.sum(jnp.square(x),
                           axis=tuple(range(nb, x.ndim)))

        pn_out: List[Any] = [None] * len(flat_pf)
        un_out: List[Any] = [None] * len(flat_pf)

        for i, _why in plain:
            nb = flat_nb[i]
            axes = tuple(range(nb, flat_pf[i].ndim))
            pn_out[i] = jnp.sqrt(jnp.sum(jnp.square(flat_pf[i]), axis=axes,
                                         keepdims=True))
            un_out[i] = jnp.sqrt(jnp.sum(jnp.square(flat_u[i]), axis=axes,
                                         keepdims=True))

        summary: Dict[str, Any] = {
            "bucket_bytes": self.bucket_bytes,
            "n_local": len([p for p in plain if p[1] == "local"]),
            "fallback": [
                (paths[i] if paths is not None and i < len(paths)
                 else f"leaf_{i}")
                for i, why in plain if why == "kept-axis-sharded"],
            "groups": [],
        }

        for key in sorted(groups):
            idxs = groups[key]
            # per-leaf partial widths: pn and un contribute kept-size each
            kept_sizes = [int(np.prod(shapes[i][:flat_nb[i]] or (1,)))
                          for i in idxs]
            buckets = _bucketize([2 * k for k in kept_sizes],
                                 self.bucket_bytes)
            summary["groups"].append({
                "axes": list(key),
                "n_leaves": len(idxs),
                "buckets": [
                    {"n_leaves": len(b),
                     "elems": sum(2 * kept_sizes[j] for j in b)}
                    for b in buckets],
            })
            in_specs = tuple(self._specs[i] for i in idxs) * 2
            from jax.sharding import PartitionSpec

            def reduce_group(*blocks, _idxs=idxs, _buckets=buckets,
                             _key=key):
                n = len(_idxs)
                pf_blocks, u_blocks = blocks[:n], blocks[n:]
                partials = []
                for j, i in enumerate(_idxs):
                    nb = flat_nb[i]
                    partials.append(jnp.concatenate([
                        local_sq(pf_blocks[j], nb).reshape(-1),
                        local_sq(u_blocks[j], nb).reshape(-1)]))
                reduced = []
                for b in _buckets:
                    vec = (jnp.concatenate([partials[j] for j in b])
                           if len(b) > 1 else partials[b[0]])
                    red = jax.lax.psum(vec, _key)
                    off = 0
                    for j in b:
                        w = partials[j].shape[0]
                        reduced.append((j, red[off:off + w]))
                        off += w
                reduced.sort(key=lambda t: t[0])
                return tuple(r for _, r in reduced)

            outs = shard_map(
                reduce_group, mesh=self.mesh,
                in_specs=in_specs,
                out_specs=tuple(PartitionSpec() for _ in idxs),
                check_rep=False,
            )(*[flat_pf[i] for i in idxs], *[flat_u[i] for i in idxs])
            for j, i in enumerate(idxs):
                nb = flat_nb[i]
                k = int(np.prod(shapes[i][:nb] or (1,)))
                kd = kept_keepdims(shapes[i], nb)
                pn_out[i] = jnp.sqrt(outs[j][:k].reshape(kd))
                un_out[i] = jnp.sqrt(outs[j][k:].reshape(kd))

        self._summary = summary
        return (jax.tree_util.tree_unflatten(treedef, pn_out),
                jax.tree_util.tree_unflatten(treedef, un_out))

    # -- the coalesced global norm ------------------------------------------

    def global_norm_f32(self, tree: Any) -> Any:
        """fp32-upcast global L2 norm with the cross-device reductions
        bucketed — the drop-in for telemetry/health.global_norm_f32 and
        LAMB's optax.global_norm pre-normalization (both compile one
        scalar all-reduce PER LEAF; this compiles one vector all-reduce
        per reduction-axis group). Bit-identical: same per-leaf local
        reduce, same per-element cross-device sum, and the per-leaf
        totals fold in the same tree-leaves order before the sqrt."""
        import jax
        import jax.numpy as jnp

        from jax.sharding import PartitionSpec

        from bert_pytorch_tpu.ops.shard_map_compat import shard_map

        flat = [jnp.asarray(x).astype(jnp.float32)
                for x in jax.tree.leaves(tree)]
        shapes = [tuple(x.shape) for x in flat]
        groups, plain = self._classify(shapes, [0] * len(flat))
        totals: List[Any] = [None] * len(flat)
        for i, _why in plain:
            totals[i] = jnp.sum(jnp.square(flat[i]))
        for key in sorted(groups):
            idxs = groups[key]

            def group_sums(*blocks, _key=key):
                vec = jnp.stack([jnp.sum(jnp.square(b)) for b in blocks])
                return jax.lax.psum(vec, _key)

            vec = shard_map(
                group_sums, mesh=self.mesh,
                in_specs=tuple(self._specs[i] for i in idxs),
                out_specs=PartitionSpec(),
                check_rep=False)(*[flat[i] for i in idxs])
            for j, i in enumerate(idxs):
                totals[i] = vec[j]
        return jnp.sqrt(sum(totals))

    def summary(self) -> Optional[Dict[str, Any]]:
        """Deterministic bucket-assignment record (run-header material):
        per reduction-axis group, the bucket layout; plus the fallback
        leaves the reducer left to GSPMD. None until the first traced
        use."""
        return self._summary
