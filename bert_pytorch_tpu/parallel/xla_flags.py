"""Collective/compute overlap flag pack for the TPU XLA/libtpu runtime.

"Scalable Training of Language Models using JAX pjit and TPUv4" (PAPERS.md)
attributes multichip efficiency to sharding annotations *plus* XLA's
latency-hiding scheduler: the compiler splits each collective into an async
start/done pair and schedules independent compute between them. On current
libtpu that scheduler and the async collective lowering are controlled by
flags, consumed from the LIBTPU_INIT_ARGS environment variable at backend
initialization — the same pack production JAX trainers (MaxText et al.) ship.

What each flag buys the data-parallel/ZeRO-1 step (parallel/zero.py):

  - async_collective_fusion(+fuse_all_gather, +multiple_steps): the gradient
    reduce-scatter and the post-update param all-gather become async pairs
    that XLA fuses into neighbouring compute regions instead of serial
    barriers at the end of the step;
  - overlap_compute_collective_tc + latency-hiding scheduling: the
    TensorCore keeps executing (e.g. the next microbatch's backward under
    grad accumulation) while ICI traffic is in flight;
  - data_parallel_all_reduce_opt / different_sized_ops: the classic DP
    gradient-bucket reorderings, still profitable for the per-tensor
    collectives the unstacked per-layer layout (round 6) produces — each
    layer's params are separate leaves, so under fsdp the all-gathers are
    layer-granular and the scheduler can prefetch layer i+1's gather behind
    layer i's compute.

These are libtpu flags: on CPU/GPU backends LIBTPU_INIT_ARGS is simply never
read, so applying the pack is a safe no-op off-TPU (the multichip CPU-mesh
bench and the tests run with it applied). Must be called BEFORE the first
jax device/backend touch in the process; importing jax is fine, initializing
the backend is not.
"""

from __future__ import annotations

import os
from typing import List, MutableMapping, Optional

OVERLAP_FLAG_PACK = (
    "--xla_tpu_enable_data_parallel_all_reduce_opt=true",
    "--xla_tpu_data_parallel_opt_different_sized_ops=true",
    "--xla_tpu_enable_async_collective_fusion=true",
    "--xla_tpu_enable_async_collective_fusion_fuse_all_gather=true",
    "--xla_tpu_enable_async_collective_fusion_multiple_steps=true",
    "--xla_tpu_overlap_compute_collective_tc=true",
    "--xla_enable_async_all_gather=true",
)

_ENV_VAR = "LIBTPU_INIT_ARGS"


def _flag_name(flag: str) -> str:
    return flag.split("=", 1)[0]


def apply_overlap_flags(env: Optional[MutableMapping[str, str]] = None
                        ) -> List[str]:
    """Append the overlap pack to LIBTPU_INIT_ARGS; returns what was added.

    Flags whose name the user already set (either polarity) are left alone —
    an operator's explicit choice wins over the pack. Idempotent.
    """
    if env is None:
        env = os.environ
    existing = env.get(_ENV_VAR, "")
    present = {_flag_name(f) for f in existing.split() if f}
    added = [f for f in OVERLAP_FLAG_PACK if _flag_name(f) not in present]
    if added:
        env[_ENV_VAR] = " ".join(([existing] if existing else []) + added)
    return added


def overlap_flags_active(env: Optional[MutableMapping[str, str]] = None
                         ) -> bool:
    """True when every flag in the pack is present (any polarity counts as
    'operator decided')."""
    if env is None:
        env = os.environ
    present = {_flag_name(f) for f in env.get(_ENV_VAR, "").split() if f}
    return all(_flag_name(f) in present for f in OVERLAP_FLAG_PACK)


def pack_state(env: Optional[MutableMapping[str, str]] = None) -> dict:
    """Provenance view of the runtime flag state (telemetry/provenance.py):
    the full LIBTPU_INIT_ARGS value plus which pack flags are present —
    enough to reproduce the collective-overlap configuration of a run from
    its log header or bench JSON alone."""
    if env is None:
        env = os.environ
    value = env.get(_ENV_VAR, "")
    present = {_flag_name(f) for f in value.split() if f}
    n_present = sum(
        1 for f in OVERLAP_FLAG_PACK if _flag_name(f) in present)
    return {
        "libtpu_init_args": value,
        # active == every pack flag present (overlap_flags_active semantics)
        "overlap_pack_active": n_present == len(OVERLAP_FLAG_PACK),
        "overlap_pack_present": n_present,
        "overlap_pack_size": len(OVERLAP_FLAG_PACK),
    }
