"""Multi-host process helpers.

The reference wrapped torch.distributed rank/world/barrier calls
(src/utils.py:22-74) around an NCCL process group initialized from env://
rendezvous (run_pretraining.py:175). On TPU-VM the runtime already knows the
topology: `jax.distributed.initialize()` (no-op on a single host) and the
process_* APIs replace the whole launcher layer (SURVEY §5.8).
"""

from __future__ import annotations

import jax


def _cluster_env_present() -> bool:
    """True when this process is on a multi-worker TPU pod slice (GCE/GKE
    metadata present). Deliberately restricted to the TPU cluster detectors:
    auto-init on Slurm/MPI/K8s envs would make a plain single-process
    `python run_pretraining.py` inside an unrelated allocation block in
    jax.distributed.initialize() waiting for peers that never start. Those
    clusters keep the explicit-args path. BPT_NO_AUTO_DIST=1 opts out
    entirely."""
    import os

    if os.environ.get("BPT_NO_AUTO_DIST") == "1":
        return False
    try:
        from jax._src.clusters.cluster import ClusterEnv

        return any(
            "tpu" in env.__name__.lower() and env.is_env_present()
            for env in ClusterEnv._cluster_types)
    except Exception as e:  # private API moved: fall back to explicit-args only
        import warnings

        # Loud, not silent: on a pod slice this fallback means
        # jax.distributed NEVER initializes (orbax cross-process checkpoint
        # coordination and process_index() are then wrong), and the run
        # would fail in confusing ways far from the cause. Single-host runs
        # can ignore this. Re-verify the private import on JAX upgrades.
        warnings.warn(
            "bert_pytorch_tpu.parallel.dist: probing jax's private cluster "
            f"detection API failed ({type(e).__name__}: {e}); multi-host "
            "TPU auto-init is DISABLED. If this is a multi-worker pod "
            "slice, pass coordinator_address/num_processes/process_id "
            "explicitly to dist.initialize() or fix the probe for this "
            "JAX version. Set BPT_NO_AUTO_DIST=1 to silence.",
            RuntimeWarning, stacklevel=2)
        return False


def is_initialized() -> bool:
    """True once jax.distributed is up. jax >= 0.5 exposes
    jax.distributed.is_initialized(); on older versions the global client
    object is the source of truth (private, but the only probe there is —
    covered by tests/test_multihost.py so an API move fails loudly)."""
    probe = getattr(jax.distributed, "is_initialized", None)
    if probe is not None:
        return bool(probe())
    from jax._src import distributed as _dist  # jax < 0.5

    state = getattr(_dist, "global_state", None)
    return state is not None and state.client is not None


def initialize(coordinator_address=None, num_processes=None, process_id=None):
    """Bring up the multi-host runtime.

    The reference initialized its NCCL process group unconditionally
    (run_pretraining.py:175); the equivalent here is: on a multi-worker TPU
    pod slice (and ONLY there — see _cluster_env_present), call
    jax.distributed.initialize() argless and let it auto-discover
    coordinator/rank — so orbax's cross-process checkpoint coordination and
    process_index() are always correct on a pod without any CLI plumbing.
    Slurm/MPI/K8s and CPU/DCN clusters use the explicit-args path
    (e.g. tests/test_multihost.py). Plain single-host runs no-op."""
    if is_initialized():
        return
    if num_processes is not None and num_processes > 1:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id)
    elif num_processes is None and _cluster_env_present():
        jax.distributed.initialize()


def get_rank() -> int:
    """Host (process) index — reference src/utils.py:29-35 semantics."""
    return jax.process_index()


def get_world_size() -> int:
    """Host count — reference src/utils.py:37-43 semantics."""
    return jax.process_count()


def is_main_process() -> bool:
    """rank == 0 gate used for logging/checkpoint writes
    (reference src/utils.py:45-47)."""
    return jax.process_index() == 0


def barrier() -> None:
    """Cross-host sync. The reference used dist.barrier (src/utils.py:49-51);
    here a tiny all-reduce across hosts forces a rendezvous."""
    if jax.process_count() > 1:
        x = jax.numpy.ones((jax.local_device_count(),))
        jax.block_until_ready(
            jax.pmap(lambda v: jax.lax.psum(v, "i"), axis_name="i")(x))
