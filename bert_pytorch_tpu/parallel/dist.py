"""Multi-host process helpers.

The reference wrapped torch.distributed rank/world/barrier calls
(src/utils.py:22-74) around an NCCL process group initialized from env://
rendezvous (run_pretraining.py:175). On TPU-VM the runtime already knows the
topology: `jax.distributed.initialize()` (no-op on a single host) and the
process_* APIs replace the whole launcher layer (SURVEY §5.8).
"""

from __future__ import annotations

import jax
import numpy as np


def initialize(coordinator_address=None, num_processes=None, process_id=None):
    """Bring up the multi-host runtime. Safe to call on a single host (no-op).
    Args mirror jax.distributed.initialize for DCN clusters where the TPU
    runtime can't auto-discover."""
    if num_processes is not None and num_processes > 1:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id)


def get_rank() -> int:
    """Host (process) index — reference src/utils.py:29-35 semantics."""
    return jax.process_index()


def get_world_size() -> int:
    """Host count — reference src/utils.py:37-43 semantics."""
    return jax.process_count()


def is_main_process() -> bool:
    """rank == 0 gate used for logging/checkpoint writes
    (reference src/utils.py:45-47)."""
    return jax.process_index() == 0


def barrier() -> None:
    """Cross-host sync. The reference used dist.barrier (src/utils.py:49-51);
    here a tiny all-reduce across hosts forces a rendezvous."""
    if jax.process_count() > 1:
        x = jax.numpy.ones((jax.local_device_count(),))
        jax.block_until_ready(
            jax.pmap(lambda v: jax.lax.psum(v, "i"), axis_name="i")(x))
