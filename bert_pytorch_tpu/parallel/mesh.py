"""Device mesh construction + the flax-facing view of the rules table.

This replaces the reference's entire launcher/DDP layer (torch.distributed
NCCL process groups, SSH/mpirun fan-out — SURVEY §2.2, §5.8) with the JAX
SPMD model: one `Mesh` whose axes express every parallelism the framework
supports, and a table of rules mapping the *logical* axis names annotated on
model params/activations (models/bert.py) to mesh axes.

Axes:
  data   — data parallelism (gradient psum rides ICI; reference: DDP allreduce)
  fsdp   — parameter/optimizer sharding (ZeRO-style; reference had none)
  model  — tensor parallelism (reference had none; SURVEY §2.2 row "TP absent")
  seq    — sequence/context parallelism for ring attention (SURVEY §5.7 asks
           the mesh to reserve this axis so long-context lands without breaks)

The rules themselves live in parallel/rules.py — the single source of
truth every spec in the repo is derived from (docs/SHARDING.md);
DEFAULT_LOGICAL_AXIS_RULES below is its resolved flax-style view, kept
as the import point model/training code already uses.

Multi-host: axis order puts `data` outermost so cross-slice DCN traffic is
data-parallel gradient reduction only; fsdp/model/seq stay inside an ICI slice.
"""

from __future__ import annotations

import contextlib
from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from flax import linen as nn
from jax.sharding import Mesh

from bert_pytorch_tpu.parallel import rules as rules_lib
from bert_pytorch_tpu.parallel.rules import MESH_AXES

# logical axis -> mesh axis (None = replicated); the resolved base view
# of parallel/rules.BASE_RULES (per-entry rationale lives there).
DEFAULT_LOGICAL_AXIS_RULES: Tuple[Tuple[str, Optional[str]], ...] = \
    rules_lib.resolve()


def make_mesh(
    shape: Optional[Dict[str, int]] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a Mesh over all devices.

    shape maps axis name -> size; unspecified axes get 1, and if no shape is
    given everything lands on `data` (pure DP — the reference's only strategy).
    Axis sizes must multiply to the device count.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    shape = dict(shape or {})
    sizes = [shape.get(ax, 1) for ax in MESH_AXES]
    specified = int(np.prod([s for s in sizes if s > 0]))
    if "data" not in shape:
        # data absorbs whatever is left
        rest = int(np.prod([shape.get(ax, 1) for ax in MESH_AXES if ax != "data"]))
        if n % rest != 0:
            raise ValueError(f"{n} devices not divisible by non-data axes {shape}")
        sizes[MESH_AXES.index("data")] = n // rest
    elif specified != n:
        raise ValueError(f"mesh shape {shape} != device count {n}")
    dev_array = np.asarray(devices).reshape(sizes)
    return Mesh(dev_array, MESH_AXES)


def data_shard_count(mesh: Mesh) -> int:
    """Number of ways the batch is split (data * fsdp axes)."""
    return mesh.shape["data"] * mesh.shape["fsdp"]


def batch_sharding(mesh: Mesh, stacked: bool = True, n_leading: int = None):
    """NamedSharding for input batches: batch axis over (data, fsdp).
    n_leading = number of unsharded leading axes BEFORE the batch axis:
    1 for the (accum, batch, ...) microbatch layout (stacked=True), 2 for
    the --steps_per_loop (steps, accum, batch, ...) chunk layout, 0 for a
    flat (batch, ...) array."""
    from jax.sharding import NamedSharding

    if n_leading is None:
        n_leading = 1 if stacked else 0
    # the batch axis rides the rules table's 'data' rule — one source of
    # truth with the activation constraints the graph lint verifies
    return NamedSharding(mesh, rules_lib.batch_spec(n_leading, mesh))


def host_to_device_batch(mesh: Mesh, batch, stacked: bool = True,
                         n_leading: int = None):
    """Per-host numpy batch -> global sharded jax.Arrays.

    Each host feeds its contiguous chunk (HostShardSampler keyed by
    process_index); jax.make_array_from_process_local_data assembles the
    global array without gathering — the TPU replacement for the reference's
    per-rank DataLoader + batch.to(device) (run_pretraining.py:384,527).

    Every leaf must carry the same leading layout: n_leading unsharded axes
    (accum, or steps+accum) followed by the per-host batch axis; trailing
    axes (seq, ...) are optional per leaf.
    """
    import jax as _jax

    if n_leading is None:
        n_leading = 1 if stacked else 0
    sharding = batch_sharding(mesh, n_leading=n_leading)

    def put(x):
        x = np.asarray(x)
        if x.ndim < n_leading + 1:
            raise ValueError(
                f"batch leaf rank {x.ndim} < n_leading+1 ({n_leading + 1}); "
                "all leaves need the (leading..., batch, ...) layout")
        return _jax.make_array_from_process_local_data(sharding, x)

    return {k: put(v) for k, v in batch.items()}


@contextlib.contextmanager
def logical_rules(rules=DEFAULT_LOGICAL_AXIS_RULES):
    """Context installing the logical->mesh rules consumed by
    nn.with_logical_partitioning / nn.with_logical_constraint."""
    with nn.logical_axis_rules(rules):
        yield


def param_shardings(mesh: Mesh, abstract_variables,
                    rules=DEFAULT_LOGICAL_AXIS_RULES):
    """Logical annotations (from nn.get_partition_spec on an eval_shape'd
    variable tree) -> concrete NamedShardings on `mesh`."""
    logical_spec = nn.get_partition_spec(abstract_variables)
    return nn.logical_to_mesh_sharding(logical_spec, mesh, rules)
