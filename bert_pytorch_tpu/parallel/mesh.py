"""Device mesh construction and logical-axis sharding rules.

This replaces the reference's entire launcher/DDP layer (torch.distributed
NCCL process groups, SSH/mpirun fan-out — SURVEY §2.2, §5.8) with the JAX
SPMD model: one `Mesh` whose axes express every parallelism the framework
supports, and a table of rules mapping the *logical* axis names annotated on
model params/activations (models/bert.py) to mesh axes.

Axes:
  data   — data parallelism (gradient psum rides ICI; reference: DDP allreduce)
  fsdp   — parameter/optimizer sharding (ZeRO-style; reference had none)
  model  — tensor parallelism (reference had none; SURVEY §2.2 row "TP absent")
  seq    — sequence/context parallelism for ring attention (SURVEY §5.7 asks
           the mesh to reserve this axis so long-context lands without breaks)

Multi-host: axis order puts `data` outermost so cross-slice DCN traffic is
data-parallel gradient reduction only; fsdp/model/seq stay inside an ICI slice.
"""

from __future__ import annotations

import contextlib
from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from flax import linen as nn
from jax.sharding import Mesh

MESH_AXES = ("data", "fsdp", "model", "seq")

# logical axis -> mesh axis (None = replicated).
DEFAULT_LOGICAL_AXIS_RULES: Tuple[Tuple[str, Optional[str]], ...] = (
    # params
    # embedding rows / MLM decoder cols: splitting the big (V, E) table on
    # its vocab axis over BOTH model and fsdp keeps the ZeRO memory win
    # while leaving the embed axis replicated — an embed-sharded table makes
    # every lookup emit a replicate-then-repartition against the
    # batch-sharded activations (SPMD "involuntary full rematerialization")
    ("vocab", ("model", "fsdp")),
    ("embed", "fsdp"),        # hidden dim of params -> ZeRO sharding
    ("mlp", "model"),         # FFN inner dim -> megatron column/row split
    ("heads", "model"),       # attention heads
    ("kv", None),
    ("embed_out", None),
    # embed-dim of the small post-pooler heads (pooler dense, NSP/classifier
    # kernels): replicated — an fsdp-sharded contracting dim on a few-KB
    # kernel forces GSPMD to reshard the batch-sharded (B, E) pooled
    # activations embed-major, an involuntary full rematerialization on
    # (data x fsdp) meshes (tests/test_zero1.py 2x2-mesh gate)
    ("embed_head", None),
    # (E,)-shaped norm scales/biases and the small position/token-type
    # tables: sharding a few KB forces XLA into replicate-then-repartition
    # transitions against the batch-sharded activations (SPMD "involuntary
    # full rematerialization"), so they stay replicated by design
    ("norm", None),
    # scan-stacked layer axis stays replicated. This logical axis only
    # exists under the stacked layout (config.stacked_params=True, where
    # nn.scan prepends it via PARTITION_NAME); the unstacked per-layer
    # layout has no leading L dim anywhere, so its leaves resolve through
    # the remaining rules unchanged — same mesh placement per layer.
    ("layers", None),
    # activations — batch shards over data AND fsdp (fsdp devices are data
    # parallel for activations; only params/moments split on fsdp)
    ("data", ("data", "fsdp")),
    ("seq", "seq"),
    ("embed_act", None),
)


def make_mesh(
    shape: Optional[Dict[str, int]] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a Mesh over all devices.

    shape maps axis name -> size; unspecified axes get 1, and if no shape is
    given everything lands on `data` (pure DP — the reference's only strategy).
    Axis sizes must multiply to the device count.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    shape = dict(shape or {})
    sizes = [shape.get(ax, 1) for ax in MESH_AXES]
    specified = int(np.prod([s for s in sizes if s > 0]))
    if "data" not in shape:
        # data absorbs whatever is left
        rest = int(np.prod([shape.get(ax, 1) for ax in MESH_AXES if ax != "data"]))
        if n % rest != 0:
            raise ValueError(f"{n} devices not divisible by non-data axes {shape}")
        sizes[MESH_AXES.index("data")] = n // rest
    elif specified != n:
        raise ValueError(f"mesh shape {shape} != device count {n}")
    dev_array = np.asarray(devices).reshape(sizes)
    return Mesh(dev_array, MESH_AXES)


def data_shard_count(mesh: Mesh) -> int:
    """Number of ways the batch is split (data * fsdp axes)."""
    return mesh.shape["data"] * mesh.shape["fsdp"]


def batch_sharding(mesh: Mesh, stacked: bool = True, n_leading: int = None):
    """NamedSharding for input batches: batch axis over (data, fsdp).
    n_leading = number of unsharded leading axes BEFORE the batch axis:
    1 for the (accum, batch, ...) microbatch layout (stacked=True), 2 for
    the --steps_per_loop (steps, accum, batch, ...) chunk layout, 0 for a
    flat (batch, ...) array."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    if n_leading is None:
        n_leading = 1 if stacked else 0
    batch_axes = ("data", "fsdp")
    spec = P(*([None] * n_leading), batch_axes)
    return NamedSharding(mesh, spec)


def host_to_device_batch(mesh: Mesh, batch, stacked: bool = True,
                         n_leading: int = None):
    """Per-host numpy batch -> global sharded jax.Arrays.

    Each host feeds its contiguous chunk (HostShardSampler keyed by
    process_index); jax.make_array_from_process_local_data assembles the
    global array without gathering — the TPU replacement for the reference's
    per-rank DataLoader + batch.to(device) (run_pretraining.py:384,527).

    Every leaf must carry the same leading layout: n_leading unsharded axes
    (accum, or steps+accum) followed by the per-host batch axis; trailing
    axes (seq, ...) are optional per leaf.
    """
    import jax as _jax

    if n_leading is None:
        n_leading = 1 if stacked else 0
    sharding = batch_sharding(mesh, n_leading=n_leading)

    def put(x):
        x = np.asarray(x)
        if x.ndim < n_leading + 1:
            raise ValueError(
                f"batch leaf rank {x.ndim} < n_leading+1 ({n_leading + 1}); "
                "all leaves need the (leading..., batch, ...) layout")
        return _jax.make_array_from_process_local_data(sharding, x)

    return {k: put(v) for k, v in batch.items()}


@contextlib.contextmanager
def logical_rules(rules=DEFAULT_LOGICAL_AXIS_RULES):
    """Context installing the logical->mesh rules consumed by
    nn.with_logical_partitioning / nn.with_logical_constraint."""
    with nn.logical_axis_rules(rules):
        yield


def param_shardings(mesh: Mesh, abstract_variables,
                    rules=DEFAULT_LOGICAL_AXIS_RULES):
    """Logical annotations (from nn.get_partition_spec on an eval_shape'd
    variable tree) -> concrete NamedShardings on `mesh`."""
    logical_spec = nn.get_partition_spec(abstract_variables)
    return nn.logical_to_mesh_sharding(logical_spec, mesh, rules)
