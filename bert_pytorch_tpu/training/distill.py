"""Teacher->student distillation riding the shared finetune driver.

The reference framework has no compression path: serving cost per request
is whatever the finetuned BERT costs. This module turns any registered
task (tasks/registry.py) into a distillation target: a student —
`BertConfig` preset `student_<L>l_<H>` (config.student_config) — trains
against a frozen teacher inside the SAME jitted finetune step
(training/pretrain.build_pretrain_step), so telemetry, packing, the
preemption guard, the watchdog, and checkpointing all come for free, and
the resulting checkpoint serves through run_server.py unchanged
(a student is just a checkpoint).

Losses, per the task's own loss shape:

- soft-target KD: temperature-scaled KL(teacher || student) on the head
  logits — per-segment for pooled heads, per-token for token heads, with
  per-segment softmax windows for QA spans;
- hard-label CE: the task's own loss on the gold labels;
- layer-matched tap losses: per-token MSE between student and teacher
  `debug_taps` sows (attention_out / mlp_out, models/bert.py) under a
  configurable layer map, through a learned linear projection when the
  widths differ (the 'distill_proj' params subtree — trained by the same
  optimizer, ignored by the serving restore's strict merge).

Every packed reduction follows models/losses.py's bit-equality
discipline (segment_onehot masking, segment-first contraction,
_ordered_sum): a packed distillation batch's loss equals the same
examples one-example-per-row bit-for-bit (tests/test_distill.py pins it,
the PR 13 standard). The teacher runs under jax.lax.stop_gradient in the
same step — no second dispatch path — and a batch carrying precomputed
`teacher_logits` (or `teacher_start_logits`/`teacher_end_logits`) skips
the teacher forward with bit-identical student gradients.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from bert_pytorch_tpu.models import losses

# tap-loss knob -> the models/bert.py debug_taps sow it matches on
TAP_KINDS = (("attention_out", "alpha_attn"), ("mlp_out", "alpha_hidden"))


@dataclasses.dataclass(frozen=True)
class DistillConfig:
    """Loss mix + layer map for one distillation run."""

    temperature: float = 2.0
    alpha_kd: float = 1.0        # soft-target KL weight
    alpha_ce: float = 0.5        # hard-label task-loss weight
    alpha_hidden: float = 0.0    # layer-matched mlp_out MSE weight
    alpha_attn: float = 0.0      # layer-matched attention_out MSE weight
    layer_map: Tuple[Tuple[int, int], ...] = ()  # (student, teacher) pairs
    max_segments: int = 8

    @property
    def needs_taps(self) -> bool:
        return self.alpha_hidden > 0 or self.alpha_attn > 0


def default_layer_map(student_layers: int,
                      teacher_layers: int) -> Tuple[Tuple[int, int], ...]:
    """Evenly-spaced map: student layer i <- teacher layer
    ((i+1) * Lt) // Ls - 1 — for a 6L student of a 12L teacher that is
    (0,1) (1,3) (2,5) (3,7) (4,9) (5,11), i.e. student i <- teacher 2i+1
    (every second teacher layer, ending on the top one)."""
    if student_layers < 1 or teacher_layers < 1:
        raise ValueError("layer counts must be >= 1")
    return tuple((i, (i + 1) * teacher_layers // student_layers - 1)
                 for i in range(student_layers))


def parse_layer_map(text: Optional[str], student_layers: int,
                    teacher_layers: int) -> Tuple[Tuple[int, int], ...]:
    """'s:t,s:t,...' -> ((s, t), ...), validated against both depths;
    None/empty -> default_layer_map."""
    if not text:
        return default_layer_map(student_layers, teacher_layers)
    pairs = []
    for item in text.split(","):
        s, _, t = item.partition(":")
        try:
            si, ti = int(s), int(t)
        except ValueError:
            raise ValueError(f"bad layer-map entry {item!r}; want "
                             "'student:teacher' ints, e.g. '0:1,1:3'")
        if not (0 <= si < student_layers):
            raise ValueError(f"layer map student index {si} out of range "
                             f"[0, {student_layers})")
        if not (0 <= ti < teacher_layers):
            raise ValueError(f"layer map teacher index {ti} out of range "
                             f"[0, {teacher_layers})")
        pairs.append((si, ti))
    return tuple(pairs)


# -- KD losses (models/losses.py bit-equality discipline) ---------------------


def _kl_terms(s_logits: jax.Array, t_logits: jax.Array,
              temperature: float) -> jax.Array:
    """Per-slot temperature-scaled KL(teacher_T || student_T) * T^2, fp32,
    reduced over the class axis only (same-length last-axis reduction —
    per-slot bit-identical across batch shapes, like log_softmax in the
    packed task losses)."""
    t = float(temperature)
    s = s_logits.astype(jnp.float32) / t
    tt = t_logits.astype(jnp.float32) / t
    s_logp = jax.nn.log_softmax(s, axis=-1)
    t_logp = jax.nn.log_softmax(tt, axis=-1)
    p = jnp.exp(t_logp)
    return (p * (t_logp - s_logp)).sum(-1) * (t * t)


def kd_segment_loss(s_logits: jax.Array, t_logits: jax.Array,
                    labels: jax.Array, temperature: float) -> jax.Array:
    """Soft-target KD for pooled heads: (B, G, C) logits against (B, G)
    labels (-1 = empty slot), or plain (B, C)/(B,). Empty slots contribute
    exactly 0.0 before the order-canonical sum, so packed and
    one-example-per-row batches agree bit-for-bit."""
    kl = _kl_terms(s_logits, t_logits, temperature)
    valid = labels != -1
    kl = jnp.where(valid, kl, 0.0)
    return losses._ordered_sum(kl) / jnp.maximum(valid.sum(), 1)


def kd_token_loss(s_logits: jax.Array, t_logits: jax.Array,
                  labels: jax.Array, segment_ids: jax.Array,
                  max_segments: int, temperature: float,
                  ignore_index: int = -100) -> jax.Array:
    """Per-token KD for token heads on packed rows, reduced SEGMENT-FIRST
    exactly like losses.packed_token_loss: per-token KL contracted
    against the segment one-hot, then the tiny (B, G) ordered sum."""
    kl = _kl_terms(s_logits, t_logits, temperature)
    valid = labels != ignore_index
    kl = jnp.where(valid, kl, 0.0)
    onehot = losses.segment_onehot(
        segment_ids, max_segments).astype(jnp.float32)
    seg_kl = jnp.einsum("bgs,bs->bg", onehot, kl)
    return losses._ordered_sum(seg_kl) / jnp.maximum(valid.sum(), 1)


def kd_plain_token_loss(s_logits: jax.Array, t_logits: jax.Array,
                        labels: jax.Array, temperature: float,
                        ignore_index: int = -100) -> jax.Array:
    """Unpacked per-token KD: masked mean over supervised positions."""
    kl = _kl_terms(s_logits, t_logits, temperature)
    valid = labels != ignore_index
    kl = jnp.where(valid, kl, 0.0)
    return kl.sum() / jnp.maximum(valid.sum(), 1)


def kd_qa_loss(s_start: jax.Array, s_end: jax.Array,
               t_start: jax.Array, t_end: jax.Array,
               segment_ids: jax.Array, max_segments: int,
               temperature: float) -> jax.Array:
    """Span KD for packed QA rows: each segment's softmax window covers
    ITS OWN positions only (-inf elsewhere, like losses.packed_qa_loss),
    the KL is masked back to in-segment positions (0 * -inf would be
    NaN), and the (B, G) aggregate takes the ordered sum."""
    seg_mask = losses.segment_onehot(segment_ids, max_segments)  # (B, G, S)
    t = float(temperature)

    def one(s_logits, t_logits):
        s = s_logits.astype(jnp.float32)[:, None, :] / t
        tt = t_logits.astype(jnp.float32)[:, None, :] / t
        s_logp = jax.nn.log_softmax(jnp.where(seg_mask, s, -jnp.inf), -1)
        t_logp = jax.nn.log_softmax(jnp.where(seg_mask, tt, -jnp.inf), -1)
        p = jnp.exp(t_logp)
        kl = jnp.where(seg_mask, p * (t_logp - s_logp), 0.0).sum(-1)
        kl = kl * (t * t)                                        # (B, G)
        valid = seg_mask.any(-1)
        kl = jnp.where(valid, kl, 0.0)
        return losses._ordered_sum(kl) / jnp.maximum(valid.sum(), 1)

    return (one(s_start, t_start) + one(s_end, t_end)) / 2.0


def kd_plain_qa_loss(s_start: jax.Array, s_end: jax.Array,
                     t_start: jax.Array, t_end: jax.Array,
                     temperature: float) -> jax.Array:
    """Unpacked span KD: full-row softmax windows (the losses.qa_loss
    shape), mean over the batch."""
    kl_s = _kl_terms(s_start, t_start, temperature)
    kl_e = _kl_terms(s_end, t_end, temperature)
    return (kl_s.mean() + kl_e.mean()) / 2.0


# -- debug_taps layer normalization + tap losses ------------------------------


def layer_taps(taps: Dict[str, Any], config) -> List[Dict[str, jax.Array]]:
    """Normalize a `debug_taps` collection to a per-layer list of
    {tap_name: (B, S, H)} dicts, for BOTH encoder layouts.

    Stacked scan (config.stacked_params=True): the sows live under
    encoder/layers/layer with a leading (L, ...) axis (nn.scan
    variable_axes 'debug_taps': 0, models/bert.py). Unstacked: under
    encoder/layer_{i}, no leading axis. Task heads nest the trunk under
    'bert'. Flax sow stores tuples — the single element is unwrapped.
    This is the contract the distillation layer map rides
    (tests/test_distill.py pins names + shapes for both layouts)."""
    tree = taps.get("bert", taps)
    enc = tree.get("encoder", {})
    n = config.num_hidden_layers

    def leaf(v):
        return v[0] if isinstance(v, (tuple, list)) else v

    if config.stacked_params:
        per = enc.get("layers", {}).get("layer", {})
        return [{k: leaf(v)[i] for k, v in per.items()} for i in range(n)]
    return [{k: leaf(v) for k, v in enc.get(f"layer_{i}", {}).items()}
            for i in range(n)]


def tap_match_loss(s_tap: jax.Array, t_tap: jax.Array,
                   proj: Optional[Dict[str, jax.Array]],
                   attention_mask: jax.Array,
                   segment_ids: Optional[jax.Array],
                   max_segments: int) -> jax.Array:
    """Per-token MSE between a student tap (optionally projected to the
    teacher width) and the mapped teacher tap, masked to real tokens and
    normalized by (real tokens * teacher width). Packed rows reduce
    segment-first + ordered-sum, so the tap terms keep the packed
    bit-equality the KD terms have."""
    s = s_tap.astype(jnp.float32)
    if proj is not None:
        s = s @ proj["kernel"].astype(jnp.float32)
    t = t_tap.astype(jnp.float32)
    err = ((s - t) ** 2).sum(-1)                       # (B, S)
    mask = attention_mask > 0
    err = jnp.where(mask, err, 0.0)
    denom = jnp.maximum(mask.sum(), 1) * t_tap.shape[-1]
    if segment_ids is not None:
        onehot = losses.segment_onehot(
            segment_ids, max_segments).astype(jnp.float32)
        seg = jnp.einsum("bgs,bs->bg", onehot, err)
        return losses._ordered_sum(seg) / denom
    return err.sum() / denom


def init_projections(rng: jax.Array, dcfg: DistillConfig,
                     student_cfg, teacher_cfg) -> Dict[str, Any]:
    """'distill_proj' params subtree: one (H_student, H_teacher) kernel
    per mapped student layer per enabled tap kind. Empty when the widths
    already match or no tap loss is on. Rides beside the model params —
    trained by the same optimizer, dropped by the serving restore
    (extra checkpoint subtrees are ignored by the strict merge)."""
    if (not dcfg.needs_taps
            or student_cfg.hidden_size == teacher_cfg.hidden_size):
        return {}
    shape = (student_cfg.hidden_size, teacher_cfg.hidden_size)
    out: Dict[str, Any] = {}
    for si, _ti in dcfg.layer_map:
        r = jax.random.fold_in(rng, si)
        layer = {}
        for j, (kind, alpha_name) in enumerate(TAP_KINDS):
            if getattr(dcfg, alpha_name) <= 0:
                continue
            layer[kind] = {"kernel": (
                jax.random.normal(jax.random.fold_in(r, j), shape,
                                  jnp.float32)
                * teacher_cfg.initializer_range)}
        out[f"layer_{si}"] = layer
    return out


# -- the loss builder run_task compiles ---------------------------------------


def _apply_head(model, params, batch, rng, deterministic, packed, taps):
    kwargs: Dict[str, Any] = dict(deterministic=deterministic)
    if packed:
        kwargs["position_ids"] = batch["position_ids"]
        kwargs["segment_ids"] = batch["segment_ids"]
    if not deterministic:
        kwargs["rngs"] = {"dropout": rng}
    if taps:
        kwargs["mutable"] = ["debug_taps"]
    return model.apply({"params": params}, batch["input_ids"],
                       batch.get("token_type_ids"),
                       batch["attention_mask"], **kwargs)


def _precomputed_teacher(batch):
    if "teacher_start_logits" in batch:
        return (batch["teacher_start_logits"], batch["teacher_end_logits"])
    return batch.get("teacher_logits")


def _head_losses(s_out, t_out, batch, dcfg: DistillConfig,
                 output_kind: str, packed: bool,
                 label_ignore: Dict[str, int]):
    """(kd, hard) for the task's head shape: QA tuples, token heads,
    pooled segment heads (incl. the multiple-choice regroup)."""
    if isinstance(s_out, (tuple, list)):
        sp, ep = batch["start_positions"], batch["end_positions"]
        if packed:
            kd = kd_qa_loss(s_out[0], s_out[1], t_out[0], t_out[1],
                            batch["segment_ids"], dcfg.max_segments,
                            dcfg.temperature)
            hard = losses.packed_qa_loss(s_out[0], s_out[1], sp, ep,
                                         batch["segment_ids"],
                                         dcfg.max_segments)
        else:
            kd = kd_plain_qa_loss(s_out[0], s_out[1], t_out[0], t_out[1],
                                  dcfg.temperature)
            hard = losses.qa_loss(s_out[0], s_out[1], sp, ep)
        return kd, hard

    labels = batch["labels"]
    if output_kind == "token":
        ignore = label_ignore.get("labels", -100)
        if packed:
            kd = kd_token_loss(s_out, t_out, labels, batch["segment_ids"],
                               dcfg.max_segments, dcfg.temperature, ignore)
            hard = losses.packed_token_loss(s_out, labels,
                                            batch["segment_ids"],
                                            dcfg.max_segments, ignore)
        else:
            kd = kd_plain_token_loss(s_out, t_out, labels,
                                     dcfg.temperature, ignore)
            hard = losses.token_classification_loss(s_out, labels, ignore)
        return kd, hard

    if s_out.ndim == labels.ndim and s_out.shape[-1] != labels.shape[-1]:
        # packed multiple-choice: (B, G) per-segment scores against
        # (B, G/C) chosen indices — regroup like losses.choice_loss
        n_choices = s_out.shape[-1] // labels.shape[-1]
        s_out = s_out.reshape(*s_out.shape[:-1], -1, n_choices)
        t_out = t_out.reshape(*t_out.shape[:-1], -1, n_choices)
    kd = kd_segment_loss(s_out, t_out, labels, dcfg.temperature)
    hard = losses.segment_classification_loss(s_out, labels)
    return kd, hard


def make_distill_loss_builder(*, teacher_model, teacher_params,
                              dcfg: DistillConfig, output_kind: str,
                              packed: bool,
                              label_ignore: Optional[Dict[str, int]] = None):
    """A loss_fn_builder for build_pretrain_step: student forward (+taps),
    teacher forward under stop_gradient IN THE SAME STEP (skipped when the
    batch carries precomputed teacher logits and no tap loss is on), KD +
    hard + layer-matched tap losses. `teacher_params` are closed over as
    read-only device constants — they are never part of the trained
    pytree, so no gradient ever reaches them."""
    ignore = dict(label_ignore or {})

    def builder(student_model):
        def loss_fn(params, batch, rng, deterministic=False):
            proj = (params.get("distill_proj")
                    if isinstance(params, dict) else None)
            s_params = ({k: v for k, v in params.items()
                         if k != "distill_proj"}
                        if proj is not None else params)
            taps_on = dcfg.needs_taps

            s_res = _apply_head(student_model, s_params, batch, rng,
                                deterministic, packed, taps_on)
            if taps_on:
                s_out, s_vars = s_res
                s_taps = s_vars["debug_taps"]
            else:
                s_out, s_taps = s_res, None

            pre = _precomputed_teacher(batch)
            if pre is not None and not taps_on:
                t_out, t_taps = pre, None
            else:
                t_res = _apply_head(teacher_model, teacher_params, batch,
                                    rng, True, packed, taps_on)
                if taps_on:
                    t_out, t_vars = t_res
                    t_taps = jax.lax.stop_gradient(t_vars["debug_taps"])
                else:
                    t_out, t_taps = t_res, None
                t_out = jax.lax.stop_gradient(t_out)

            kd, hard = _head_losses(s_out, t_out, batch, dcfg,
                                    output_kind, packed, ignore)
            total = jnp.zeros((), jnp.float32)
            if dcfg.alpha_kd:
                total = total + dcfg.alpha_kd * kd
            if dcfg.alpha_ce:
                total = total + dcfg.alpha_ce * hard

            if taps_on:
                s_layers = layer_taps(s_taps, student_model.config)
                t_layers = layer_taps(t_taps, teacher_model.config)
                seg_ids = batch["segment_ids"] if packed else None
                for si, ti in dcfg.layer_map:
                    for kind, alpha_name in TAP_KINDS:
                        alpha = getattr(dcfg, alpha_name)
                        if alpha <= 0:
                            continue
                        p = (proj or {}).get(f"layer_{si}", {}).get(kind)
                        total = total + alpha * tap_match_loss(
                            s_layers[si][kind], t_layers[ti][kind], p,
                            batch["attention_mask"], seg_ids,
                            dcfg.max_segments)
            return total, {}
        return loss_fn
    return builder
