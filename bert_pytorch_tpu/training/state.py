"""Train state: one pytree carrying params + optimizer state + step.

The reference scattered this across the DDP-wrapped module, the apex optimizer
object, the GradScaler, and the scheduler (run_pretraining.py:223-348); on TPU
the whole thing is a single pytree so `jit` can donate it, shard it over the
mesh, and orbax can checkpoint it atomically. There is no GradScaler field at
all — bf16 needs no loss scaling (reference carried scaler state in ckpts,
run_pretraining.py:501-511).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import optax
from flax import linen as nn
from flax import struct
from jax.sharding import Mesh

from bert_pytorch_tpu.parallel.mesh import DEFAULT_LOGICAL_AXIS_RULES


@struct.dataclass
class TrainState:
    """step is the global optimization step (phase-global on resume, matching
    the reference's ckpt_{global_step} naming, run_pretraining.py:497-500).
    precond_state carries the K-FAC factors/inverses when --kfac is on (the
    reference checkpointed the preconditioner dict the same way,
    run_pretraining.py:501-511); None otherwise.

    telemetry carries the health pack's EMA scalars
    (telemetry/health.TelemetryState) when the step was built with a
    HealthConfig; None otherwise. It is EPHEMERAL by contract: checkpoint
    writers strip it (run_pretraining saves state.replace(telemetry=None)),
    so checkpoint structure is identical with or without the health pack
    and pre-telemetry checkpoints restore unchanged."""

    step: jax.Array
    params: Any
    opt_state: Any
    precond_state: Any = None
    telemetry: Any = None


def unbox(tree: Any) -> Any:
    """Strip flax Partitioned metadata boxes from a pytree (after init the
    boxes have served their purpose — sharding specs are derived from the
    abstract tree, and raw arrays flow through the train step)."""
    return jax.tree.map(
        lambda x: x.unbox() if isinstance(x, nn.Partitioned) else x,
        tree,
        is_leaf=lambda x: isinstance(x, nn.Partitioned),
    )


def make_sharded_state(
    rng: jax.Array,
    init_fn: Callable[[jax.Array], Any],
    tx: optax.GradientTransformation,
    mesh: Optional[Mesh] = None,
    rules=DEFAULT_LOGICAL_AXIS_RULES,
    zero1: bool = False,
    zero1_params: bool = False,
):
    """Initialize a TrainState directly into its mesh sharding.

    init_fn(rng) -> variables (with flax logical-partitioning metadata).
    Returns (state, state_shardings); state_shardings is None off-mesh.

    The flow is the standard JAX SPMD recipe (scaling-book): eval_shape the
    whole state (metadata boxes propagate through tx.init's zeros_like),
    logical->mesh the partition specs, then jit the initializer with
    out_shardings so parameters are *born* sharded — no host-side full
    materialization (the reference instead materialized on one GPU and
    broadcast via DDP, run_pretraining.py:257-260).

    zero1=True additionally shards every param-shaped optimizer slot (LAMB/
    Adam mu+nu) over the mesh's `data` axis (parallel/zero.py — the TPU
    analog of apex DistributedFusedLAMB ownership): the moments are *born*
    1/N-per-chip instead of replicated. The train step must then run with
    the matching Zero1Plan (build_pretrain_step(zero1=...)) so the gradient
    reduce-scatters into — and the update computes in — that same layout.
    No-op when the mesh's data axis is trivial.

    zero1_params=True (the --zero1_overlap gather-on-use mode) makes the
    PARAMS rest in the same 1/N shard layout as the moments; the train step
    (built with make_zero1_plan(..., gather_on_use=True)) then re-gathers
    them leaf-by-leaf at the point of use so the all-gathers overlap
    forward compute instead of trailing the update. The returned
    `state_shardings` tree still carries the BASE (train-step) param
    layout — it is what make_zero1_plan derives both layouts from; the
    state's actual storage layout is the zero1_shardings of it.
    """

    def make(rng):
        params = init_fn(rng)["params"]
        # tx.init runs on the *boxed* params so the Partitioned metadata
        # propagates (via tree-mapped zeros_like) into the optimizer moments —
        # mu/nu then shard exactly like their parameters.
        return TrainState(
            step=jax.numpy.zeros([], jax.numpy.int32),
            params=params,
            opt_state=tx.init(params),
        )

    if mesh is None:
        return unbox(jax.jit(make)(rng)), None

    abstract = jax.eval_shape(make, rng)
    logical_spec = nn.get_partition_spec(abstract)
    shardings = nn.logical_to_mesh_sharding(logical_spec, mesh, list(rules))
    if zero1:
        from bert_pytorch_tpu.parallel.zero import zero1_shardings

        # unbox first: the abstract tree still carries flax Partitioned
        # nodes, the shardings tree has them collapsed to NamedSharding
        # leaves — the zip only lines up on the unboxed structure
        shardings = shardings.replace(opt_state=zero1_shardings(
            unbox(abstract.opt_state), shardings.opt_state, mesh))
    with mesh:
        state = jax.jit(make, out_shardings=shardings)(rng)
    state = unbox(state)
    if zero1_params:
        from bert_pytorch_tpu.parallel.zero import zero1_shardings

        # params REST in the shard layout (`shardings` — the return
        # value — keeps the base layout, the plan's gather target). The
        # re-layout happens AFTER the init jit, as pure data movement
        # (device_put replicated -> sharded is a local slice): jitting
        # the initializer straight into the shard layout would let XLA
        # partition the init computation itself, and a partitioned
        # initializer does not produce bit-identical values to the
        # replicated one for every leaf — which would silently break the
        # overlap path's bit-parity contract before the first step ran
        # (tests/test_zero1.py pins it).
        state = state.replace(params=jax.device_put(
            state.params,
            zero1_shardings(state.params, shardings.params, mesh)))
    return state, shardings
