"""Train state: one pytree carrying params + optimizer state + step.

The reference scattered this across the DDP-wrapped module, the apex optimizer
object, the GradScaler, and the scheduler (run_pretraining.py:223-348); on TPU
the whole thing is a single pytree so `jit` can donate it, shard it over the
mesh, and orbax can checkpoint it atomically. There is no GradScaler field at
all — bf16 needs no loss scaling (reference carried scaler state in ckpts,
run_pretraining.py:501-511).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import optax
from flax import linen as nn
from flax import struct
from jax.sharding import Mesh


@struct.dataclass
class TrainState:
    """step is the global optimization step (phase-global on resume, matching
    the reference's ckpt_{global_step} naming, run_pretraining.py:497-500).
    precond_state carries the K-FAC factors/inverses when --kfac is on (the
    reference checkpointed the preconditioner dict the same way,
    run_pretraining.py:501-511); None otherwise.

    telemetry carries the health pack's EMA scalars
    (telemetry/health.TelemetryState) when the step was built with a
    HealthConfig; None otherwise. It is EPHEMERAL by contract: checkpoint
    writers strip it (run_pretraining saves state.replace(telemetry=None)),
    so checkpoint structure is identical with or without the health pack
    and pre-telemetry checkpoints restore unchanged."""

    step: jax.Array
    params: Any
    opt_state: Any
    precond_state: Any = None
    telemetry: Any = None


def unbox(tree: Any) -> Any:
    """Strip flax Partitioned metadata boxes from a pytree (after init the
    boxes have served their purpose — sharding specs are derived from the
    abstract tree, and raw arrays flow through the train step)."""
    return jax.tree.map(
        lambda x: x.unbox() if isinstance(x, nn.Partitioned) else x,
        tree,
        is_leaf=lambda x: isinstance(x, nn.Partitioned),
    )


def _make_train_state(init_fn: Callable[[jax.Array], Any],
                      tx: optax.GradientTransformation
                      ) -> Callable[[jax.Array], "TrainState"]:
    """The ONE fresh-TrainState constructor closure: eval_shape'd by
    abstract_train_state (the tree every storage spec derives from) and
    jitted by make_sharded_state (the state actually built) — one
    definition, so the verified abstract structure and the constructed
    state cannot drift apart."""

    def make(r):
        params = init_fn(r)["params"]
        # tx.init runs on the *boxed* params so the Partitioned metadata
        # propagates (via tree-mapped zeros_like) into the optimizer
        # moments — mu/nu then shard exactly like their parameters.
        return TrainState(
            step=jax.numpy.zeros([], jax.numpy.int32),
            params=params,
            opt_state=tx.init(params),
        )

    return make


def abstract_train_state(rng: jax.Array,
                         init_fn: Callable[[jax.Array], Any],
                         tx: optax.GradientTransformation) -> Any:
    """The eval_shape'd TrainState with flax Partitioned metadata still
    boxed — the tree parallel/rules.train_state_shardings derives every
    storage spec from. Shared by make_sharded_state (construction) and
    tools/graphcheck.py (verification): both sides of the sharding_rules
    gate read the SAME abstract tree, so they can only disagree when the
    compiled program actually diverged from the table."""
    return jax.eval_shape(_make_train_state(init_fn, tx), rng)


def make_sharded_state(
    rng: jax.Array,
    init_fn: Callable[[jax.Array], Any],
    tx: optax.GradientTransformation,
    mesh: Optional[Mesh] = None,
    rules=None,
    zero1: bool = False,
    zero1_params: bool = False,
):
    """Initialize a TrainState directly into its mesh sharding.

    init_fn(rng) -> variables (with flax logical-partitioning metadata).
    Returns (state, state_shardings); state_shardings is None off-mesh.
    `rules` defaults to the rules table resolved FOR THIS MESH
    (parallel/rules.resolve(mesh)) — the same per-config resolution the
    sharding_rules gate verifies against, so a CONFIG_OVERRIDES entry
    applies to construction and verification alike; pass an explicit
    flax-style pair list only to deviate from the table deliberately.

    The flow is the standard JAX SPMD recipe (scaling-book): eval_shape the
    whole state (metadata boxes propagate through tx.init's zeros_like),
    logical->mesh the partition specs, then jit the initializer with
    out_shardings so parameters are *born* sharded — no host-side full
    materialization (the reference instead materialized on one GPU and
    broadcast via DDP, run_pretraining.py:257-260).

    zero1=True additionally shards every param-shaped optimizer slot (LAMB/
    Adam mu+nu) over the mesh's `data` axis (parallel/zero.py — the TPU
    analog of apex DistributedFusedLAMB ownership): the moments are *born*
    1/N-per-chip instead of replicated. The train step must then run with
    the matching Zero1Plan (build_pretrain_step(zero1=...)) so the gradient
    reduce-scatters into — and the update computes in — that same layout.
    No-op when the mesh's data axis is trivial.

    zero1_params=True (the --zero1_overlap gather-on-use mode) makes the
    PARAMS rest in the same 1/N shard layout as the moments; the train step
    (built with make_zero1_plan(..., gather_on_use=True)) then re-gathers
    them leaf-by-leaf at the point of use so the all-gathers overlap
    forward compute instead of trailing the update. The returned
    `state_shardings` tree still carries the BASE (train-step) param
    layout — it is what make_zero1_plan derives both layouts from; the
    state's actual storage layout is the zero1_shardings of it.
    """

    make = _make_train_state(init_fn, tx)

    if mesh is None:
        return unbox(jax.jit(make)(rng)), None

    from bert_pytorch_tpu.parallel import rules as rules_lib

    # every storage spec is DERIVED from the logical-axis-rules table
    # (parallel/rules.py) — the same derivation tools/graphcheck.py's
    # sharding_rules pass later verifies the compiled program against
    abstract = abstract_train_state(rng, init_fn, tx)
    shardings = rules_lib.train_state_shardings(abstract, mesh,
                                                zero1=zero1, table=rules)
    with mesh:
        state = jax.jit(make, out_shardings=shardings)(rng)
    state = unbox(state)
    if zero1_params:
        from bert_pytorch_tpu.parallel.zero import zero1_shardings

        # params REST in the shard layout (`shardings` — the return
        # value — keeps the base layout, the plan's gather target). The
        # re-layout happens AFTER the init jit, as pure data movement
        # (device_put replicated -> sharded is a local slice): jitting
        # the initializer straight into the shard layout would let XLA
        # partition the init computation itself, and a partitioned
        # initializer does not produce bit-identical values to the
        # replicated one for every leaf — which would silently break the
        # overlap path's bit-parity contract before the first step ran
        # (tests/test_zero1.py pins it).
        state = state.replace(params=jax.device_put(
            state.params,
            zero1_shardings(state.params, shardings.params, mesh)))
    return state, shardings
