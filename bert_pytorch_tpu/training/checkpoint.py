"""Checkpoint / auto-resume on top of orbax.

Parity targets (SURVEY §5.4):
- checkpoint dict {model, optimizer, sampler, epoch} — here {state, extra}
  where state is the TrainState pytree and extra is JSON (sampler cursor,
  epoch, config echo) (reference run_pretraining.py:501-511);
- rank-0-coordinated multi-host write, every `num_steps_per_checkpoint`
  optimization steps (reference :484-492) — orbax handles the multi-host
  coordination natively;
- rolling window of the most recent 3 (reference :513-516);
- auto-resume: newest step found in the directory wins (reference scans for
  ckpt_*.pt and takes max, run_pretraining.py:236-255);
- two-phase handoff: checkpoints are named by *global* step
  (ckpt_{global+previous_phase_end}, reference :497-500). Phase 2 restores
  phase-1 state and keeps the optimizer moments; the new phase's schedule
  takes `offset=previous_phase_end_step` (optim/schedulers.py) instead of the
  reference's in-place rewrite of optimizer hyperparameters (:288-299).

Resilience layer (round 17, bert_pytorch_tpu/resilience/manifest.py,
docs/RESILIENCE.md): every committed checkpoint gains a jax-free
`integrity.json` sidecar (per-item content digests + provenance +
sampler/stream-cursor echo + program fingerprint), written AFTER the
async commit lands; `restore` verifies digests BEFORE deserializing and
raises CorruptCheckpointError on mismatch; `restore_with_fallback`
quarantines a corrupt newest checkpoint (renamed `<step>.corrupt`, loud
warning naming the failed item) and walks `all_steps()` newest→oldest
instead of crashing. Save/restore health is published through the
optional registry (`bert_ckpt_saves_total` / `bert_ckpt_failures_total`)
and `freshness()` feeds /healthz `last_checkpoint_step` /
`seconds_since_checkpoint`.
"""

from __future__ import annotations

import os
import time
from typing import Any, Callable, Dict, Optional, Tuple

import orbax.checkpoint as ocp

from bert_pytorch_tpu.resilience.manifest import (CorruptCheckpointError,
                                                  quarantine_step,
                                                  step_dir_path,
                                                  verify_step_dir,
                                                  write_step_manifest)


class CheckpointManager:
    """Thin wrapper over ocp.CheckpointManager with the reference's policy."""

    def __init__(self, directory: str, max_to_keep: int = 3,
                 save_interval_steps: int = 1,
                 registry=None, log: Callable[[str], None] = print):
        directory = os.path.abspath(directory)
        self.directory = directory
        self._max_to_keep = max_to_keep
        self._save_interval_steps = save_interval_steps
        self._log = log
        self._mgr = self._open()
        # context stamped into every integrity sidecar; the entry point
        # fills provenance at setup and the program fingerprint when the
        # first dispatch's HLO parse lands (run_pretraining.py)
        self.manifest_context: Dict[str, Any] = {}
        # steps saved but (possibly) not yet committed: their sidecars are
        # written at the next wait()/save() once the async commit is final
        # (save() hands the digesting to a daemon worker; wait() drains
        # synchronously)
        self._pending_manifests: Dict[int, Any] = {}
        self._manifest_worker = None
        # freshness for /healthz (telemetry/run.py attach_checkpoints)
        self.last_saved_step: Optional[int] = None
        self.last_saved_time: Optional[float] = None
        self._saves_total = self._failures_total = None
        if registry is not None:
            self._saves_total = registry.counter(
                "bert_ckpt_saves_total", "checkpoint saves issued")
            self._failures_total = registry.counter(
                "bert_ckpt_failures_total",
                "checkpoint save/commit/sidecar failures")

    def _open(self):
        options = ocp.CheckpointManagerOptions(
            max_to_keep=self._max_to_keep,
            save_interval_steps=self._save_interval_steps,
            create=True,
            enable_async_checkpointing=True,
        )
        return ocp.CheckpointManager(self.directory, options=options)

    def _reopen(self) -> None:
        """Rebuild the underlying manager after an external directory
        mutation (quarantine rename): orbax caches its step scan, and a
        stale cache would make the rolling-window GC or latest_step()
        chase a renamed directory."""
        try:
            self._mgr.close()
        except Exception:
            pass
        self._mgr = self._open()

    def save(self, step: int, state: Any,
             extra: Optional[Dict[str, Any]] = None) -> bool:
        """Async save; returns False if skipped by save_interval policy.
        Sidecar manifests for previously-issued saves are flushed here
        (their commits are final once the previous async save drains) —
        on a BACKGROUND thread: digesting a multi-GB checkpoint must not
        stall the train loop inside the watchdog-watched 'checkpoint'
        phase (a slow filesystem would read as a device hang)."""
        if self._pending_manifests:
            try:
                self._mgr.wait_until_finished()
            except Exception:
                if self._failures_total is not None:
                    self._failures_total.inc()
                raise
            self._spawn_manifest_flush()
        args = {"state": ocp.args.StandardSave(state)}
        if extra is not None:
            args["extra"] = ocp.args.JsonSave(extra)
        try:
            saved = self._mgr.save(step, args=ocp.args.Composite(**args))
        except Exception:
            if self._failures_total is not None:
                self._failures_total.inc()
            raise
        if saved:
            self._pending_manifests[int(step)] = extra
            self.last_saved_step = int(step)
            self.last_saved_time = time.time()
            if self._saves_total is not None:
                self._saves_total.inc()
        return saved

    def _spawn_manifest_flush(self) -> None:
        """Hand the pending sidecars to a daemon worker. Caller must have
        waited out the async commit first — digesting an in-flight write
        would freeze a lie into the sidecar."""
        import threading

        self._join_manifest_worker()
        pending, self._pending_manifests = self._pending_manifests, {}
        self._manifest_worker = threading.Thread(
            target=self._write_manifests, args=(pending,),
            name="ckpt-integrity-sidecars", daemon=True)
        self._manifest_worker.start()

    def _join_manifest_worker(self, timeout: Optional[float] = None
                              ) -> None:
        worker = self._manifest_worker
        if worker is not None:
            worker.join(timeout=timeout)
            self._manifest_worker = None

    def _flush_manifests(self) -> None:
        """Synchronous drain: join any in-flight worker, then write the
        remaining sidecars on THIS thread — wait()/close() and the
        emergency-save path need them on disk before the process exits."""
        self._join_manifest_worker()
        pending, self._pending_manifests = self._pending_manifests, {}
        self._write_manifests(pending)

    def _write_manifests(self, pending: Dict[int, Any]) -> None:
        for step, extra in sorted(pending.items()):
            sd = step_dir_path(self.directory, step)
            if not os.path.isdir(sd):
                continue  # evicted by the rolling window before commit
            try:
                write_step_manifest(
                    sd, step, extra_echo=extra,
                    provenance=self.manifest_context.get("provenance"),
                    program_fingerprint=self.manifest_context.get(
                        "program_fingerprint"))
            except Exception as e:
                if self._failures_total is not None:
                    self._failures_total.inc()
                self._log(f"WARNING: integrity sidecar for checkpoint "
                          f"step {step} failed: {e} (checkpoint itself "
                          "is committed; it will restore unverified)")

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def all_steps(self, read: bool = False) -> list:
        """Every completed checkpoint step, ascending. tools/replay.py uses
        this to pick the newest checkpoint whose gap to the target step
        the flight-recorder bundle's records actually cover. read=True
        forces a directory re-scan (the fallback walk needs fresh truth
        after a quarantine rename)."""
        if read:
            try:
                self._mgr.reload()
            except AttributeError:  # older orbax: read kwarg instead
                return sorted(int(s)
                              for s in self._mgr.all_steps(read=True))
        return sorted(int(s) for s in self._mgr.all_steps())

    def verify(self, step: int) -> Optional[list]:
        """Integrity-check one committed step against its sidecar:
        None = no sidecar (legacy checkpoint, unverifiable), [] = clean,
        list of errors = corrupt. Never raises for a missing sidecar;
        a torn sidecar IS corruption (manifest.read_step_manifest)."""
        return verify_step_dir(step_dir_path(self.directory, step))

    def restore(self, abstract_state: Any, step: Optional[int] = None
                ) -> Tuple[Any, Dict[str, Any], int]:
        """Restore (state, extra, step). abstract_state (e.g. from
        jax.eval_shape, with shardings attached) drives sharded restore —
        arrays land directly on their devices, no host bounce.

        Digests are verified BEFORE deserialization: a corrupt
        checkpoint raises CorruptCheckpointError naming the failed item,
        never a tensorstore stack trace."""
        if step is None:
            step = self.latest_step()
        if step is None:
            raise FileNotFoundError(
                f"no checkpoint found under {self.directory}")
        errors = self.verify(step)
        if errors:
            raise CorruptCheckpointError(step, errors)
        restored = self._mgr.restore(
            step,
            args=ocp.args.Composite(
                state=ocp.args.StandardRestore(abstract_state)),
        )
        extra = self._read_extra(step)
        return restored["state"], extra, step

    def restore_either_layout(self, abstract_state: Any,
                              step: Optional[int] = None
                              ) -> Tuple[Any, Dict[str, Any], int]:
        """Restore like `restore`, but tolerate a checkpoint written under
        the OTHER encoder parameter layout (config.stacked_params flipped
        between save and resume): on a structure mismatch, retry with the
        template converted to the alternate layout and convert the restored
        state back. The conversion is bit-exact (models/pretrained.py
        stack_layer_tree/unstack_layer_tree), so a stacked-era checkpoint
        resumes into an unstacked run — and vice versa — with identical
        values."""
        from bert_pytorch_tpu.models.pretrained import (convert_tree_layout,
                                                        tree_layout)

        try:
            return self.restore(abstract_state, step)
        except FileNotFoundError:
            raise
        except CorruptCheckpointError:
            # bugfix (round 17): a digest-mismatched checkpoint is NOT a
            # layout mismatch — short-circuit before the layout retry, or
            # the retry's confusing structure complaint masks the real,
            # actionable corruption error until first_err surfaces
            raise
        except Exception as first_err:
            want = tree_layout(getattr(abstract_state, "params",
                                       abstract_state))
            if want is None:
                raise
            alt = convert_tree_layout(abstract_state,
                                      stacked=(want == "unstacked"))
            try:
                state, extra, step = self.restore(alt, step)
            except Exception:
                # the alternate layout fails too: this was never a layout
                # mismatch (shape/dtype drift, ...) — surface the
                # ORIGINAL, actionable error, not the second attempt's
                # confusing structure complaint
                raise first_err
            return (convert_tree_layout(state, stacked=(want == "stacked")),
                    extra, step)

    def restore_with_fallback(self, abstract_state: Any
                              ) -> Tuple[Any, Dict[str, Any], int]:
        """Auto-resume that survives a torn/corrupt newest checkpoint:
        walk `all_steps()` newest→oldest; a step that fails integrity
        verification (or fails to deserialize while unverifiable) is
        QUARANTINED (renamed `<step>.corrupt`) with a loud warning naming
        the failed item, and the walk continues. A checkpoint whose
        digests VERIFY but whose restore still raises is surfaced as-is:
        intact data + failing restore means config/shape drift, i.e. an
        operator error quarantining would silently destroy evidence of.

        Raises CorruptCheckpointError when every checkpoint was
        quarantined, FileNotFoundError when there were none to begin
        with."""
        steps = self.all_steps(read=True)
        if not steps:
            raise FileNotFoundError(
                f"no checkpoint found under {self.directory}")
        quarantined = []
        deferred = []   # sidecar-less restore failures, quarantine pending
        first_err: Optional[BaseException] = None
        for step in reversed(steps):
            errors = None
            try:
                # verify INSIDE the try: a torn/unreadable sidecar raises
                # CorruptCheckpointError itself and must quarantine + walk
                # like any other corruption, not crash the resume
                errors = self.verify(step)
                if errors:
                    raise CorruptCheckpointError(step, errors)
                result = self.restore_either_layout(abstract_state, step)
            except CorruptCheckpointError as e:
                dst = quarantine_step(self.directory, step)
                quarantined.append(step)
                self._log(
                    f"WARNING: checkpoint step {step} is CORRUPT — "
                    f"{'; '.join(e.errors)}. Quarantined to {dst}; "
                    "auto-resume falling back to the next-newest "
                    "checkpoint")
                self._reopen()
                continue
            except Exception as e:
                if errors is None:
                    # unverifiable (no sidecar) AND undeserializable:
                    # PROBABLY torn — but an environmental failure
                    # (config/mesh drift, transient FS error) looks the
                    # same and would hit every legacy checkpoint in the
                    # walk. Defer the quarantine until a deeper
                    # checkpoint proves the environment can restore at
                    # all; if nothing restores, surface the error and
                    # rename NOTHING.
                    first_err = first_err or e
                    deferred.append(step)
                    self._log(
                        f"WARNING: checkpoint step {step} failed to "
                        f"restore ({type(e).__name__}: {e}) and has no "
                        "integrity sidecar to verify against — falling "
                        "back (quarantine deferred until an older "
                        "checkpoint restores)")
                    continue
                # digests verified clean: the data is intact and the
                # failure is structural (config drift) — surface it
                raise
            # success: the environment restores fine, so the deferred
            # failures really were torn checkpoints — quarantine them now
            for dstep in deferred:
                dst = quarantine_step(self.directory, dstep)
                quarantined.append(dstep)
                self._log(
                    f"WARNING: checkpoint step {dstep} (unverifiable, "
                    f"failed to restore) quarantined to {dst} — step "
                    f"{step} restored cleanly, so the failure was the "
                    "checkpoint, not the environment")
            if deferred:
                self._reopen()
            return result
        if first_err is not None:
            # nothing restored and at least one failure was unverifiable:
            # this smells like config drift or an environmental fault —
            # surface the newest error, destroy no evidence
            raise first_err
        raise CorruptCheckpointError(
            None, [f"every checkpoint under {self.directory} failed "
                   f"verification; quarantined steps: {quarantined}"])

    def restore_raw(self, step: Optional[int] = None) -> Tuple[Any, int]:
        """Restore the state tree exactly as saved (no abstract template, no
        shape enforcement). For transfer-style loads — e.g. finetuning pulls
        encoder weights out of a pretraining checkpoint whose head shapes
        differ (reference loads ckpt['model'] with strict=False,
        run_squad.py:961)."""
        if step is None:
            step = self.latest_step()
        if step is None:
            raise FileNotFoundError(
                f"no checkpoint found under {self.directory}")
        restored = self._mgr.restore(
            step, args=ocp.args.Composite(state=ocp.args.StandardRestore()))
        return restored["state"], step

    def _read_extra(self, step: int) -> Dict[str, Any]:
        # Distinguish "saved without extra" (fine, return {}) from "extra is
        # present but unreadable" (corrupt ckpt — surface it rather than
        # silently resetting the sampler and re-reading consumed data).
        try:
            items = self._mgr.item_metadata(step)
            has_extra = "extra" in items
        except Exception:
            has_extra = True  # metadata unreadable: attempt restore, let it raise
        if not has_extra:
            return {}
        restored = self._mgr.restore(
            step, args=ocp.args.Composite(extra=ocp.args.JsonRestore()))
        return restored.get("extra") or {}

    def freshness(self) -> Tuple[Optional[int], Optional[float]]:
        """(last checkpoint step, unix time it landed) for /healthz
        checkpoint-freshness gating. Falls back to the on-disk newest
        step + its directory mtime when this process has not saved yet
        (a freshly-resumed run reports the checkpoint it restored)."""
        if self.last_saved_step is not None:
            return self.last_saved_step, self.last_saved_time
        step = self.latest_step()
        if step is None:
            return None, None
        try:
            t = os.path.getmtime(step_dir_path(self.directory, step))
        except OSError:
            t = None
        return step, t

    def wait(self) -> None:
        try:
            self._mgr.wait_until_finished()
        except Exception:
            if self._failures_total is not None:
                self._failures_total.inc()
            raise
        self._flush_manifests()

    def close(self) -> None:
        self.wait()
        self._mgr.close()
