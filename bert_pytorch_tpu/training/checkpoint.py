"""Checkpoint / auto-resume on top of orbax.

Parity targets (SURVEY §5.4):
- checkpoint dict {model, optimizer, sampler, epoch} — here {state, extra}
  where state is the TrainState pytree and extra is JSON (sampler cursor,
  epoch, config echo) (reference run_pretraining.py:501-511);
- rank-0-coordinated multi-host write, every `num_steps_per_checkpoint`
  optimization steps (reference :484-492) — orbax handles the multi-host
  coordination natively;
- rolling window of the most recent 3 (reference :513-516);
- auto-resume: newest step found in the directory wins (reference scans for
  ckpt_*.pt and takes max, run_pretraining.py:236-255);
- two-phase handoff: checkpoints are named by *global* step
  (ckpt_{global+previous_phase_end}, reference :497-500). Phase 2 restores
  phase-1 state and keeps the optimizer moments; the new phase's schedule
  takes `offset=previous_phase_end_step` (optim/schedulers.py) instead of the
  reference's in-place rewrite of optimizer hyperparameters (:288-299).
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional, Tuple

import orbax.checkpoint as ocp


class CheckpointManager:
    """Thin wrapper over ocp.CheckpointManager with the reference's policy."""

    def __init__(self, directory: str, max_to_keep: int = 3,
                 save_interval_steps: int = 1):
        directory = os.path.abspath(directory)
        options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep,
            save_interval_steps=save_interval_steps,
            create=True,
            enable_async_checkpointing=True,
        )
        self._mgr = ocp.CheckpointManager(directory, options=options)
        self.directory = directory

    def save(self, step: int, state: Any,
             extra: Optional[Dict[str, Any]] = None) -> bool:
        """Async save; returns False if skipped by save_interval policy."""
        args = {"state": ocp.args.StandardSave(state)}
        if extra is not None:
            args["extra"] = ocp.args.JsonSave(extra)
        return self._mgr.save(step, args=ocp.args.Composite(**args))

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def all_steps(self) -> list:
        """Every completed checkpoint step, ascending. tools/replay.py uses
        this to pick the newest checkpoint whose gap to the target step
        the flight-recorder bundle's records actually cover."""
        return sorted(int(s) for s in self._mgr.all_steps())

    def restore(self, abstract_state: Any, step: Optional[int] = None
                ) -> Tuple[Any, Dict[str, Any], int]:
        """Restore (state, extra, step). abstract_state (e.g. from
        jax.eval_shape, with shardings attached) drives sharded restore —
        arrays land directly on their devices, no host bounce."""
        if step is None:
            step = self.latest_step()
        if step is None:
            raise FileNotFoundError(
                f"no checkpoint found under {self.directory}")
        restored = self._mgr.restore(
            step,
            args=ocp.args.Composite(
                state=ocp.args.StandardRestore(abstract_state)),
        )
        extra = self._read_extra(step)
        return restored["state"], extra, step

    def restore_either_layout(self, abstract_state: Any,
                              step: Optional[int] = None
                              ) -> Tuple[Any, Dict[str, Any], int]:
        """Restore like `restore`, but tolerate a checkpoint written under
        the OTHER encoder parameter layout (config.stacked_params flipped
        between save and resume): on a structure mismatch, retry with the
        template converted to the alternate layout and convert the restored
        state back. The conversion is bit-exact (models/pretrained.py
        stack_layer_tree/unstack_layer_tree), so a stacked-era checkpoint
        resumes into an unstacked run — and vice versa — with identical
        values."""
        from bert_pytorch_tpu.models.pretrained import (convert_tree_layout,
                                                        tree_layout)

        try:
            return self.restore(abstract_state, step)
        except FileNotFoundError:
            raise
        except Exception as first_err:
            want = tree_layout(getattr(abstract_state, "params",
                                       abstract_state))
            if want is None:
                raise
            alt = convert_tree_layout(abstract_state,
                                      stacked=(want == "unstacked"))
            try:
                state, extra, step = self.restore(alt, step)
            except Exception:
                # the alternate layout fails too: this was never a layout
                # mismatch (corrupt checkpoint, shape/dtype drift, ...) —
                # surface the ORIGINAL, actionable error, not the second
                # attempt's confusing structure complaint
                raise first_err
            return (convert_tree_layout(state, stacked=(want == "stacked")),
                    extra, step)

    def restore_raw(self, step: Optional[int] = None) -> Tuple[Any, int]:
        """Restore the state tree exactly as saved (no abstract template, no
        shape enforcement). For transfer-style loads — e.g. finetuning pulls
        encoder weights out of a pretraining checkpoint whose head shapes
        differ (reference loads ckpt['model'] with strict=False,
        run_squad.py:961)."""
        if step is None:
            step = self.latest_step()
        if step is None:
            raise FileNotFoundError(
                f"no checkpoint found under {self.directory}")
        restored = self._mgr.restore(
            step, args=ocp.args.Composite(state=ocp.args.StandardRestore()))
        return restored["state"], step

    def _read_extra(self, step: int) -> Dict[str, Any]:
        # Distinguish "saved without extra" (fine, return {}) from "extra is
        # present but unreadable" (corrupt ckpt — surface it rather than
        # silently resetting the sampler and re-reading consumed data).
        try:
            items = self._mgr.item_metadata(step)
            has_extra = "extra" in items
        except Exception:
            has_extra = True  # metadata unreadable: attempt restore, let it raise
        if not has_extra:
            return {}
        restored = self._mgr.restore(
            step, args=ocp.args.Composite(extra=ocp.args.JsonRestore()))
        return restored.get("extra") or {}

    def wait(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.wait_until_finished()
        self._mgr.close()
