"""Metric logging with multiple sinks, rank-0 gated.

Re-implements the interface the reference got from the external `loggerplus`
lib — four simultaneous handlers: stream, append-mode text file, TensorBoard,
CSV (reference run_pretraining.py:181-194) — plus the dllogger-style JSON
stream SQuAD used (run_squad.py:891-895). One subsystem serves all entry
points (SURVEY §5.5 asked for exactly this consolidation).
"""

from __future__ import annotations

import csv
import json
import os
import sys
import time
from typing import Any, Dict, Optional, TextIO


class MetricLogger:
    """logger.log(tag, step, **metrics) fans out to every enabled sink.

    verbose=False (non-main processes) turns every sink off — same gating as
    the reference's verbose=is_main_process() (run_pretraining.py:186).
    """

    # header fields that legitimately differ between a run and its resume
    # (wall-clock stamps); excluded from the resume-dedup fingerprint
    VOLATILE_HEADER_KEYS = ("time", "time_unix")

    def __init__(
        self,
        log_prefix: Optional[str] = None,
        verbose: bool = True,
        stream: Optional[TextIO] = None,
        tensorboard: bool = False,
        jsonl: bool = False,
        registry=None,
    ):
        self.verbose = verbose
        self._closed = False
        self._stream = stream if stream is not None else sys.stdout
        self._file: Optional[TextIO] = None
        self._csv_path: Optional[str] = None
        self._csv_fields: Optional[list] = None
        self._csv_file: Optional[TextIO] = None
        self._jsonl: Optional[TextIO] = None
        self.jsonl_path: Optional[str] = None
        self._tb = None
        # telemetry/registry.py publication: every numeric metric also
        # lands in the phase-labeled registry (gauge per tag+key), so a
        # /metrics scrape sees what the sinks see. Deliberately BEFORE the
        # verbose gate in log(): worker hosts keep a live registry even
        # though their file sinks are rank-0-gated off.
        self._registry = registry
        self._reg_gauge = self._reg_step = None
        if registry is not None:
            self._reg_gauge = registry.gauge(
                "bert_metric", "last logged value per record tag + key",
                labels=("tag", "name"))
            self._reg_step = registry.gauge(
                "bert_last_logged_step", "last step logged per record tag",
                labels=("tag",))
        self._last_header = None  # lazily seeded from the jsonl sink
        if not verbose:
            return
        if log_prefix:
            os.makedirs(os.path.dirname(os.path.abspath(log_prefix)) or ".",
                        exist_ok=True)
            self._file = open(f"{log_prefix}.txt", "a", encoding="utf-8")
            self._csv_path = f"{log_prefix}_metrics.csv"
            if jsonl:
                self.jsonl_path = f"{log_prefix}.jsonl"
                self._jsonl = open(self.jsonl_path, "a",
                                   encoding="utf-8")
            if tensorboard:
                try:
                    from torch.utils.tensorboard import SummaryWriter

                    self._tb = SummaryWriter(log_dir=f"{log_prefix}_tb")
                except Exception:  # tensorboard not installed — optional sink
                    self._tb = None

    # -- structured metric records -----------------------------------------

    def log(self, tag: str, step: int, **metrics: Any) -> None:
        if self._reg_gauge is not None and not self._closed:
            self._reg_step.set(step, tag=tag)
            for k, v in metrics.items():
                if isinstance(v, bool) or not isinstance(v, (int, float)):
                    continue
                self._reg_gauge.set(float(v), tag=tag, name=k)
        if not self.verbose or self._closed:
            return
        record = {"tag": tag, "step": step, "time": time.time(), **metrics}
        line = f"[{tag}] step {step} " + " ".join(
            f"{k}={_fmt(v)}" for k, v in metrics.items())
        print(line, file=self._stream, flush=True)
        if self._file:
            print(line, file=self._file, flush=True)
        if self._jsonl:
            self._jsonl.write(json.dumps(record) + "\n")
            self._jsonl.flush()
        if self._csv_path:
            self._append_csv(record)
        if self._tb is not None:
            for k, v in metrics.items():
                if isinstance(v, (int, float)):
                    self._tb.add_scalar(f"{tag}/{k}", v, step)

    def _append_csv(self, record: Dict[str, Any]) -> None:
        if self._csv_fields is None:
            # resuming into an existing file: adopt its header so appended
            # rows stay aligned
            if os.path.exists(self._csv_path):
                with open(self._csv_path, newline="", encoding="utf-8") as f:
                    first = f.readline().strip()
                self._csv_fields = first.split(",") if first else []
            else:
                self._csv_fields = []

        new_keys = [k for k in record if k not in self._csv_fields]
        if new_keys:
            # Expand the header: rewrite existing rows under the union of
            # columns so no metric is ever silently dropped. Rare by design —
            # a sink logging a genuinely variable key set would make this
            # quadratic; steady-state appends below never rewrite.
            if self._csv_file is not None:
                self._csv_file.close()
                self._csv_file = None
            rows = []
            if os.path.exists(self._csv_path):
                with open(self._csv_path, newline="", encoding="utf-8") as f:
                    rows = list(csv.DictReader(f))
            self._csv_fields = self._csv_fields + new_keys
            with open(self._csv_path, "w", newline="",
                      encoding="utf-8") as f:
                w = csv.DictWriter(f, fieldnames=self._csv_fields)
                w.writeheader()
                for r in rows:
                    w.writerow({k: r.get(k, "") for k in self._csv_fields})

        if self._csv_file is None:
            self._csv_file = open(self._csv_path, "a", newline="",
                                  encoding="utf-8")
            if self._csv_file.tell() == 0 and self._csv_fields:
                csv.writer(self._csv_file).writerow(self._csv_fields)
        row = {k: record.get(k, "") for k in self._csv_fields}
        csv.DictWriter(self._csv_file,
                       fieldnames=self._csv_fields).writerow(row)
        self._csv_file.flush()

    # -- run header (provenance stamp) --------------------------------------

    @classmethod
    def _header_norm(cls, fields: Dict[str, Any]) -> Dict[str, str]:
        """Normalized header identity: wall-clock stamps excluded, values
        JSON-canonicalized (a resume re-collects provenance in-memory
        while the comparison target round-tripped through the jsonl sink
        — `default=str` on both sides makes tuple-vs-list and similar
        type drift compare equal)."""
        return {k: json.dumps(v, sort_keys=True, default=str)
                for k, v in fields.items()
                if k not in cls.VOLATILE_HEADER_KEYS}

    @staticmethod
    def _header_covered(new: Dict[str, str],
                        last: Optional[Dict[str, str]]) -> bool:
        """True when `new` carries no information the LAST header lacks:
        equal, or an item-subset of it. The subset case is the base
        provenance stamp re-logged on resume AFTER the run's
        program-fingerprint extension (base fields + extras) landed — it
        must dedup. A header with any CHANGED or new value (different git
        SHA, new fingerprint) is not covered and lands; comparing only
        against the last header (not all history) keeps a flip-back
        (sha A -> B -> A across resumes) recorded, per this method's
        caller's contract."""
        if last is None:
            return False
        return all(last.get(k) == v for k, v in new.items())

    def _existing_last_header(self) -> Optional[Dict[str, str]]:
        """Normalized fields of the LAST header record already in the
        jsonl sink (None when there is none) — the resume-append case."""
        if not self.jsonl_path or not os.path.exists(self.jsonl_path):
            return None
        last = None
        try:
            with open(self.jsonl_path, encoding="utf-8") as f:
                for line in f:
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    if isinstance(rec, dict) and rec.get("tag") == "header":
                        last = rec
        except OSError:
            return None
        if last is None:
            return None
        return self._header_norm(
            {k: v for k, v in last.items() if k != "tag"})

    def log_header(self, **fields: Any) -> None:
        """One self-describing record at the top of a run: git SHA, library
        versions, mesh, flag pack (telemetry/provenance.py). Goes to the
        stream/text/jsonl sinks only — header fields are mostly strings and
        logged once, so forcing them into the CSV schema (or TensorBoard
        scalars) would pollute every later row for no queryable value.

        Resume-dedup: a resumed run re-collects provenance and would append
        a second identical header block into the same files. When the new
        header is COVERED by the last header in the jsonl sink — equal to
        it, or an item-subset of it (the base provenance stamp re-logged
        after that same run's program-fingerprint extension) — nothing is
        appended. A header with any changed or new value (new git SHA,
        different mesh, new fingerprint) still lands, because that
        difference is exactly what the header exists to record — including
        a flip-back to an older value across resumes (sha A -> B -> A
        appends all three, which is why coverage is judged against the
        LAST header only, never the whole history)."""
        if not self.verbose:
            return
        if self._closed:
            return
        norm = self._header_norm(fields)
        if self._last_header is None:
            self._last_header = self._existing_last_header()
        if self._header_covered(norm, self._last_header):
            print("[header] unchanged on resume (not re-appended)",
                  file=self._stream, flush=True)
            return
        self._last_header = norm
        line = "[header] " + " ".join(
            f"{k}={_fmt(v)}" for k, v in fields.items())
        print(line, file=self._stream, flush=True)
        if self._file:
            print(line, file=self._file, flush=True)
        if self._jsonl:
            self._jsonl.write(json.dumps(
                {"tag": "header", "time": time.time(), **fields},
                default=str) + "\n")
            self._jsonl.flush()

    # -- freeform info (reference logger.info) ------------------------------

    def info(self, msg: str) -> None:
        if not self.verbose or self._closed:
            return
        print(msg, file=self._stream, flush=True)
        if self._file:
            print(msg, file=self._file, flush=True)

    def close(self) -> None:
        """Close every sink. Idempotent; a log()/info() after close is a
        consistent no-op across ALL sinks (rather than, say, the CSV path
        silently reopening its file while the text sink drops the record)."""
        self._closed = True
        for f in (self._file, self._jsonl, self._csv_file):
            if f:
                f.close()
        self._file = self._jsonl = self._csv_file = None
        self._csv_path = None
        if self._tb is not None:
            self._tb.close()
            self._tb = None

    # context manager: `with MetricLogger(...) as logger:` guarantees the
    # sinks flush/close on the exception path too (the logger/trace-leak
    # fix — a crashed run must still land its csv/jsonl tail on disk)
    def __enter__(self) -> "MetricLogger":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close()


def _fmt(v: Any) -> str:
    if isinstance(v, float):
        return f"{v:.6g}"
    return str(v)
