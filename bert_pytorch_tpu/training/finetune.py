"""Shared finetune driver: one loop, N registered tasks.

Before this module, run_squad.py and run_ner.py each carried a private
copy of the same machinery — featurize, shuffle/batch, jitted step,
StepWatch perf records, preemption guard + emergency save, watchdog,
checkpoint save, eval loop. Five registered tasks (tasks/registry.py)
would have meant five copies. This driver owns the loop once; a task
contributes only what is genuinely task-shaped (model head, loss,
featurizer, eval/predict) through the `TaskRun` contract its
`TaskSpec.setup` returns.

What every task inherits from the loop, for free:

- telemetry via the single `init_run(phase=<task>)` wiring path —
  jsonl/csv sinks, live /metrics + /healthz, CompileWatch, and StepWatch
  perf records carrying `real_tokens_per_sec` / `pad_fraction` /
  `packing_efficiency` end to end (tools/perfboard.py indexes them);
- the survival kit (docs/RESILIENCE.md): SIGTERM/SIGINT emergency
  checkpoint of the in-progress state, optional hung-step watchdog;
- **packed training** (`--packing`): the greedy first-fit packer
  (data/packing.first_fit generalized to multi-segment units) assembles
  fixed-shape rows from several short examples, with per-segment labels
  for span/token/classification heads — finetune corpora pad far worse
  than pretraining ones ("Boosting Distributed Training Performance of
  the Unpadded BERT Model", PAPERS.md 2208.08124). Packed loss is
  pinned bit-equal to the same examples one-segment-per-row
  (tests/test_finetune_packing.py);
- **length-bucketed eval**: eval batches ride the smallest bucket that
  fits their longest example instead of always padding to
  max_seq_length — a handful of compiles, most of the pad FLOPs gone;
- a final orbax checkpoint (`<output_dir>/ckpt`) in the finetune save
  layout run_server.py restores, and an optional FINETUNE perf artifact
  (`--perf_artifact`) for the perfboard gate.
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

# -- default eval buckets: powers of two up to the task's max_seq_len ---------


def eval_buckets(max_seq_len: int, floor: int = 32) -> Tuple[int, ...]:
    """Length buckets for eval batching: 32/64/128/... up to (and always
    including) max_seq_len."""
    out = []
    b = int(floor)
    while b < max_seq_len:
        out.append(b)
        b *= 2
    out.append(int(max_seq_len))
    return tuple(sorted(set(out)))


# -- shared CLI pieces --------------------------------------------------------


def add_common_finetune_flags(p) -> None:
    """Flags every task's parser carries (run_squad/run_ner append these
    to their historical CLIs; the base parser below includes them)."""
    p.add_argument("--packing", action="store_true",
                   help="pack several short examples per row with "
                        "segment-aware attention and per-segment labels "
                        "(data/packing.py; packed loss is bit-equal to "
                        "one-example-per-row — docs/TASKS.md)")
    p.add_argument("--packing_max_segments", type=int, default=8,
                   help="max packed examples (segments) per row")
    p.add_argument("--perf_artifact", type=str, default=None,
                   help="merge this run's finetune perf summary "
                        "(real_tokens_per_sec, pad_fraction, ...) into "
                        "the given FINETUNE_*.json artifact "
                        "(tools/perfboard.py indexes + gates it)")


def base_finetune_parser(description: str):
    """The shared CLI for registry tasks without a historical entry
    point (classify / choice / embed): run_ner-style flags plus the
    common packing/perf knobs."""
    import argparse

    p = argparse.ArgumentParser(description=description)
    p.add_argument("--train_file", type=str, default=None)
    p.add_argument("--val_file", type=str, default=None)
    p.add_argument("--test_file", type=str, default=None)
    p.add_argument("--model_config_file", type=str, required=True)
    p.add_argument("--init_checkpoint", type=str, default=None,
                   help="pretraining checkpoint dir (orbax), TF release, "
                        "or reference torch save; optional")
    p.add_argument("--vocab_file", default=None, type=str)
    p.add_argument("--uppercase", action="store_true", default=None,
                   help="force cased tokenization (default: follow the "
                        "model config's `lowercase`, exactly like the "
                        "serving tokenizer — run_server.py)")
    p.add_argument("--epochs", type=int, default=3)
    p.add_argument("--lr", type=float, default=3e-5)
    p.add_argument("--warmup_proportion", type=float, default=0.1)
    p.add_argument("--clip_grad", type=float, default=1.0)
    p.add_argument("--batch_size", type=int, default=16)
    p.add_argument("--max_seq_len", type=int, default=128)
    p.add_argument("--max_steps", type=int, default=-1,
                   help="cap total optimization steps (benchmarking)")
    p.add_argument("--seed", type=int, default=42)
    p.add_argument("--output_dir", type=str, required=True)
    p.add_argument("--log_prefix", type=str, default=None)
    p.add_argument("--metrics_port", type=int, default=None)
    p.add_argument("--dtype", type=str, default="bfloat16",
                   choices=["bfloat16", "float32"])
    p.add_argument("--watchdog_timeout", type=float, default=0.0)
    p.add_argument("--watchdog_action", type=str, default="abort",
                   choices=["abort", "warn"])
    add_common_finetune_flags(p)
    return p


# -- shared task-setup scaffolding (classify / choice / embed) ----------------


def resolve_tokenizer(args, config):
    """The finetune-side tokenizer, case-matched to the serving side:
    run_server.py builds `uppercase=not config.lowercase`, so when
    --uppercase is unset the training featurizer follows the model config
    too — a cased checkpoint must not lowercase its training data while
    live traffic keeps case (they would hit different wordpiece ids)."""
    from bert_pytorch_tpu.data.tokenization import get_wordpiece_tokenizer

    vocab_file = args.vocab_file or config.vocab_file
    if not vocab_file:
        raise SystemExit("vocab_file required (CLI or model config)")
    upper = getattr(args, "uppercase", None)
    if upper is None:
        upper = not config.lowercase
    return get_wordpiece_tokenizer(vocab_file, uppercase=upper)


def dataset_splits(args, build) -> Dict[str, Dict[str, np.ndarray]]:
    """{split: build(path).arrays()} over the train/val/test CLI flags."""
    return {split: build(path)
            for split, path in (("train", args.train_file),
                                ("val", args.val_file),
                                ("test", args.test_file)) if path}


def epoch_steps(train: Optional[Dict[str, np.ndarray]], args,
                group_size: int = 1) -> Tuple[int, int]:
    """(steps_per_epoch, total_steps) with the --max_steps cap applied.

    Packed runs count the actual per-epoch first-fit stream
    (packed_epoch_step_counts) so total_steps — and therefore the LR
    schedule built over it — matches the steps that really execute; the
    unpacked batch count would be ~avg_segments× too large."""
    if train is None:
        return 0, 0
    if getattr(args, "packing", False):
        counts = packed_epoch_step_counts(
            train, n_rows=args.batch_size, seq_len=args.max_seq_len,
            max_segments=getattr(args, "packing_max_segments", 8),
            seed=args.seed, epochs=args.epochs, group_size=group_size)
        steps_per_epoch = counts[0] if counts else 0
        total_steps = sum(counts)
    else:
        steps_per_epoch = max(1, -(-len(train["input_ids"])
                                   // args.batch_size))
        total_steps = steps_per_epoch * args.epochs
    if args.max_steps and args.max_steps > 0:
        total_steps = min(total_steps, int(args.max_steps))
    return steps_per_epoch, total_steps


def finetune_optimizer(args, total_steps: int):
    """(schedule, tx): linear-warmup fused_adam + optional global-norm
    clip — the one finetune recipe every registry task trains with."""
    import optax

    from bert_pytorch_tpu.optim import schedulers
    from bert_pytorch_tpu.optim.adam import fused_adam
    from bert_pytorch_tpu.optim.lamb import default_weight_decay_mask

    sched = schedulers.linear_warmup_schedule(
        args.lr, max(total_steps, 1), warmup=args.warmup_proportion)
    tx = fused_adam(sched, weight_decay=0.01,
                    weight_decay_mask=default_weight_decay_mask,
                    bias_correction=False)
    if args.clip_grad and args.clip_grad > 0:
        tx = optax.chain(optax.clip_by_global_norm(args.clip_grad), tx)
    return sched, tx


def accuracy_evals(datasets, batch_size: int, buckets: Sequence[int],
                   logits_fn) -> Dict[str, Callable]:
    """{split: run(params) -> accuracy} for the val/test splits present.
    `logits_fn(params, feats)` returns the (N, ...) per-example scores
    argmaxed against the 'labels' field (length-bucketed batching)."""
    from bert_pytorch_tpu.data import glue

    def make(split):
        arrays = datasets[split]

        def run(params):
            import jax.numpy as jnp

            outs, labels = [], []
            for batch, idx, _bucket in bucketed_eval_batches(
                    arrays, batch_size, buckets,
                    label_ignore={"labels": -1}):
                feats = {k: jnp.asarray(v) for k, v in batch.items()
                         if k != "labels"}
                outs.append(np.asarray(logits_fn(params, feats))[:len(idx)])
                labels.append(arrays["labels"][idx])
            return glue.accuracy(np.concatenate(outs),
                                 np.concatenate(labels))

        return run

    return {s: make(s) for s in ("val", "test") if s in datasets}


def eval_closures(evals: Dict[str, Callable], tel, metric: str = "accuracy"
                  ) -> Tuple[Optional[Callable], Callable]:
    """(epoch_eval, finalize) over accuracy_evals' split runners —
    epoch_eval logs val accuracy per epoch (None when no val split),
    finalize logs/returns test accuracy."""

    def epoch_eval(params, epoch):
        acc = evals["val"](params)
        tel.logger.log("val", epoch, epoch=epoch, **{metric: acc})
        return {"val_accuracy": acc}

    def finalize(params, results):
        out = {}
        if "test" in evals:
            acc = evals["test"](params)
            tel.logger.log("test", 0, **{metric: acc})
            out["test_accuracy"] = acc
        return out

    return (epoch_eval if "val" in evals else None), finalize


# -- checkpoint seeding (moved from run_squad.py; run_ner/run_squad alias it) --


def _is_tf_source(path: str) -> bool:
    """Does `path` name an external weight source — a Google TF release
    (registry name, URL, zip, extracted dir, bare ckpt prefix) or a
    reference torch checkpoint (ckpt_*.pt) — rather than one of this
    framework's orbax checkpoints?"""
    from bert_pytorch_tpu.models.pretrained import PRETRAINED_ARCHIVE_MAP

    if path in PRETRAINED_ARCHIVE_MAP or "://" in path \
            or path.endswith((".zip", ".ckpt", ".pt", ".pth", ".bin")):
        return True
    if os.path.isdir(path):
        for _root, _dirs, files in os.walk(path):
            if "bert_config.json" in files \
                    or any(f.endswith(".ckpt.index") for f in files):
                return True
        return False
    return os.path.exists(path + ".index")


def load_pretrained_params(init_checkpoint: str, current_params,
                           log=None):
    """Load encoder weights from a pretraining checkpoint — this framework's
    orbax checkpoints, a Google TF BERT release (zip / URL / extracted dir /
    registry name), or a reference torch save — returning the FINAL param
    tree: loaded leaves replace current ones (placed with their
    dtype/sharding), everything else keeps its current init. Tolerant of
    missing/extra heads
    (reference loads ckpt['model'] with strict=False, run_squad.py:961; TF
    import parity: src/modeling.py:58-116).

    Every subtree that does NOT come from the checkpoint is reported loudly:
    a wrong --init_checkpoint must not silently train from scratch. Raises if
    nothing at all matches (that checkpoint is certainly not a BERT encoder
    for this config)."""
    import jax

    if _is_tf_source(init_checkpoint):
        from bert_pytorch_tpu.models.pretrained import from_pretrained

        vocab = int(np.shape(jax.tree.leaves(
            current_params["bert"]["embeddings"]["word_embeddings"])[0])[0])
        _, src = from_pretrained(init_checkpoint, next_sentence=True,
                                 vocab_pad_multiple=1)
        # re-pad the release vocab to this model's padded size
        emb = src["bert"]["embeddings"]["word_embeddings"]["embedding"]
        if emb.shape[0] < vocab:
            from bert_pytorch_tpu.models.pretrained import (
                PADDED_VOCAB_BIAS, _pad_vocab)

            src["bert"]["embeddings"]["word_embeddings"]["embedding"] = \
                _pad_vocab(emb, vocab, 0.0)
            src["cls_predictions"]["bias"] = _pad_vocab(
                src["cls_predictions"]["bias"], vocab, PADDED_VOCAB_BIAS)
        step = ("torch-ckpt" if init_checkpoint.endswith(
            (".pt", ".pth", ".bin")) else "tf-release")
    else:
        from bert_pytorch_tpu.training.checkpoint import CheckpointManager

        # 'dir@step' selects a specific checkpoint step (finetune curves
        # against intermediate pretraining checkpoints); bare dir = latest
        want_step = None
        ckpt_dir = init_checkpoint
        if "@" in init_checkpoint:
            head, _, tail = init_checkpoint.rpartition("@")
            if tail.isdigit():
                ckpt_dir, want_step = head, int(tail)
        mgr = CheckpointManager(ckpt_dir)
        state, step = mgr.restore_raw(step=want_step)
        mgr.close()
        src = state["params"]

    # align the source's encoder layer layout (scan-stacked vs per-layer)
    # with the target model's before the path-wise merge — a stacked-era
    # checkpoint must seed an unstacked model and vice versa
    from bert_pytorch_tpu.models.pretrained import (convert_tree_layout,
                                                    tree_layout)

    want_layout = tree_layout(current_params)
    if want_layout is not None and tree_layout(src) not in (None, want_layout):
        src = convert_tree_layout(src, stacked=(want_layout == "stacked"))

    loaded, fresh = [], []

    def merge(dst, src_tree, path=()):
        out = {}
        for k, v in dst.items():
            child_path = path + (k,)
            if isinstance(v, dict):
                out[k] = merge(v, src_tree.get(k, {}) if isinstance(
                    src_tree, dict) else {}, child_path)
            else:
                cand = src_tree.get(k) if isinstance(src_tree, dict) else None
                name = "/".join(child_path)
                if cand is not None and tuple(np.shape(cand)) == tuple(v.shape):
                    out[k] = jax.numpy.asarray(cand, v.dtype)
                    loaded.append(name)
                else:
                    out[k] = None  # keep fresh init
                    fresh.append(name + ("" if cand is None
                                         else f" (shape {np.shape(cand)} != "
                                              f"{tuple(v.shape)})"))
        return out

    merged = merge(current_params, src)
    emit = log if log is not None else print
    emit(f"init_checkpoint step {step}: loaded {len(loaded)} param leaves, "
         f"{len(fresh)} fresh-initialized")
    if fresh:
        emit("WARNING: fresh-initialized (not found in checkpoint or shape "
             "mismatch): " + ", ".join(sorted(fresh)))
    if not loaded:
        raise ValueError(
            f"checkpoint {init_checkpoint} (step {step}) shares no "
            "same-shaped parameters with this model — wrong checkpoint?")

    # apply the merge here so every caller gets final params: a loaded leaf
    # is placed with the current leaf's dtype/sharding, a fresh leaf IS the
    # current (initialized) leaf object
    def take(cur, new):
        if new is None:
            return cur
        if isinstance(cur, jax.Array) and hasattr(cur, "sharding"):
            return jax.device_put(new, cur.sharding)
        return new

    return jax.tree.map(take, current_params, merged)


# -- packed finetune batch assembly -------------------------------------------


@dataclass(frozen=True)
class UnitPlacement:
    """Where one training unit landed in a packed batch. A unit is one
    example — `group_size` sub-rows (1 for single-sequence tasks, C for
    multiple choice, whose C choices must stay CONSECUTIVE segments of
    one row so the loss can regroup (B, G) -> (B, G/C, C))."""

    unit: int                 # index into the per-example arrays
    row: int                  # packed batch row
    seg0: int                 # first segment slot (0-based)
    offsets: Tuple[int, ...]  # per-sub-row token offset within the row
    lengths: Tuple[int, ...]  # per-sub-row real token count


def _unit_lengths(attention_mask: np.ndarray) -> np.ndarray:
    """(N, S) or (N, C, S) masks -> (N,) total real tokens per unit."""
    mask = np.asarray(attention_mask, np.int64)
    return mask.sum(axis=tuple(range(1, mask.ndim)))


def segment_scalar_pack_labels(arrays: Dict[str, np.ndarray],
                               placements: Sequence[UnitPlacement],
                               n_rows: int, seq_len: int,
                               max_segments: int) -> Dict[str, np.ndarray]:
    """Per-segment scalar labels for pooled heads: (n_rows, G), -1 = empty
    slot. The `pack_labels` hook for any task whose label is one int per
    example (classify, embed)."""
    labels = np.full((n_rows, max_segments), -1, np.int32)
    for p in placements:
        labels[p.row, p.seg0] = arrays["labels"][p.unit]
    return {"labels": labels}


def pack_finetune_batch(arrays: Dict[str, np.ndarray],
                        unit_indices: Sequence[int],
                        n_rows: int, seq_len: int, max_segments: int,
                        group_size: int = 1
                        ) -> Tuple[Dict[str, np.ndarray],
                                   List[UnitPlacement]]:
    """First-fit `unit_indices` (arrival order) into an (n_rows, seq_len)
    packed batch. Returns the base packed fields (data/packing.py
    contract: input_ids / token_type_ids / attention_mask / segment_ids /
    position_ids) plus the placements a task's label packer consumes;
    units that did not fit are simply not placed (their indices stay
    pending with the caller)."""
    from bert_pytorch_tpu.data.packing import first_fit

    ids = arrays["input_ids"]
    types = arrays.get("token_type_ids")
    lengths = _unit_lengths(arrays["attention_mask"])
    sub_lengths = np.asarray(arrays["attention_mask"], np.int64).sum(axis=-1)

    # the ONE greedy first-fit packer — the same function the pretraining
    # loader and the serving batcher bin with, so training and serving
    # packing cannot drift; segs_per_unit packs whole C-segment
    # multiple-choice groups as one unit
    bins = first_fit([lengths[i] for i in unit_indices],
                     n_bins=n_rows, capacity=seq_len,
                     max_segments=max_segments,
                     segs_per_unit=group_size)
    batch = {k: np.zeros((n_rows, seq_len), np.int32)
             for k in ("input_ids", "token_type_ids", "attention_mask",
                       "segment_ids", "position_ids")}
    placements: List[UnitPlacement] = []
    for row, members in enumerate(bins):
        cursor, seg = 0, 0
        for local in members:
            unit = int(unit_indices[local])
            offsets, lens = [], []
            for c in range(group_size):
                if group_size == 1:
                    row_ids = ids[unit]
                    row_types = None if types is None else types[unit]
                    ln = int(sub_lengths[unit])
                else:
                    row_ids = ids[unit, c]
                    row_types = None if types is None else types[unit, c]
                    ln = int(sub_lengths[unit, c])
                sl = slice(cursor, cursor + ln)
                batch["input_ids"][row, sl] = row_ids[:ln]
                if row_types is not None:
                    batch["token_type_ids"][row, sl] = row_types[:ln]
                batch["attention_mask"][row, sl] = 1
                batch["segment_ids"][row, sl] = seg + 1
                batch["position_ids"][row, sl] = np.arange(ln,
                                                           dtype=np.int32)
                offsets.append(cursor)
                lens.append(ln)
                cursor += ln
                seg += 1
            placements.append(UnitPlacement(
                unit=unit, row=row, seg0=seg - group_size,
                offsets=tuple(offsets), lengths=tuple(lens)))
    return batch, placements


# -- plain + packed training batch iterators ----------------------------------


def plain_train_batches(arrays: Dict[str, np.ndarray], batch_per_step: int,
                        accum_steps: int, shuffle: bool, seed: int,
                        label_ignore: Optional[Dict[str, int]] = None):
    """Fixed-shape per-step batches, tail padded to full by repeating
    index 0 with its labels forced to the ignore value (so duplicated
    rows contribute zero loss — the run_squad pad_to_full convention).
    Yields ((accum, micro, ...) stacked batch, real_token_count,
    real_example_count)."""
    from bert_pytorch_tpu.training.pretrain import stack_microbatches

    n = len(arrays["input_ids"])
    order = (np.random.RandomState(seed).permutation(n) if shuffle
             else np.arange(n))
    for lo in range(0, n, batch_per_step):
        idx = order[lo:lo + batch_per_step]
        pad = batch_per_step - len(idx)
        full = (np.concatenate([idx, np.zeros(pad, np.int64)]) if pad
                else idx)
        batch = {k: np.asarray(v[full]).copy() for k, v in arrays.items()}
        if pad:
            for fld, ign in (label_ignore or {}).items():
                batch[fld][len(idx):] = ign
        real = int(np.asarray(
            arrays["attention_mask"][idx], np.int64).sum())
        yield stack_microbatches(batch, accum_steps), real, len(idx)


def _packable_lengths(arrays: Dict[str, np.ndarray],
                      seq_len: int) -> np.ndarray:
    """(N,) per-unit token counts, validated to fit one packed row."""
    lengths = _unit_lengths(arrays["attention_mask"])
    too_long = [int(i) for i in np.nonzero(lengths > seq_len)[0]]
    if too_long:
        raise ValueError(
            f"{len(too_long)} unit(s) exceed seq_len {seq_len} (e.g. unit "
            f"{too_long[0]}: {int(lengths[too_long[0]])} tokens) — a "
            "multi-choice group must fit one row to pack; raise "
            "--max_seq_len or disable --packing")
    return lengths


def packed_epoch_step_counts(arrays: Dict[str, np.ndarray], n_rows: int,
                             seq_len: int, max_segments: int, seed: int,
                             epochs: float,
                             group_size: int = 1) -> List[int]:
    """Per-epoch step counts `packed_train_batches` will dispatch.

    The epoch-e shuffle is a pure function of seed+e, so the first-fit
    stream can be replayed placement-only BEFORE training: total_steps
    and the LR schedule built over it are sized to the packed stream. A
    packed step consumes ~n_rows*avg_segments examples, so sizing from
    the unpacked batch count instead would leave epoch-bound runs ending
    near peak LR and step-bound runs training avg_segments× the data
    passes. A fractional final epoch contributes round(frac * count).
    """
    from bert_pytorch_tpu.data.packing import first_fit

    n = len(arrays["input_ids"])
    if n == 0 or epochs <= 0:
        return []
    lengths = _packable_lengths(arrays, seq_len)
    window = max(1, n_rows * max_segments * 2)
    full = int(epochs)
    frac = float(epochs) - full
    counts: List[int] = []
    for e in range(full + (1 if frac > 0 else 0)):
        pending = list(np.random.RandomState(seed + e).permutation(n))
        steps = 0
        while pending:
            head = pending[:window]
            bins = first_fit([lengths[i] for i in head], n_bins=n_rows,
                             capacity=seq_len, max_segments=max_segments,
                             segs_per_unit=group_size)
            placed = {int(head[local]) for b in bins for local in b}
            if not placed:
                raise RuntimeError("packer failed to place the head unit")
            pending = [i for i in pending if i not in placed]
            steps += 1
        counts.append(steps)
    if frac > 0:
        counts[-1] = max(1, int(round(frac * counts[-1])))
    return counts


def packed_train_batches(arrays: Dict[str, np.ndarray], n_rows: int,
                         seq_len: int, max_segments: int,
                         pack_labels: Callable, shuffle: bool, seed: int,
                         group_size: int = 1):
    """Packed per-step batches: shuffle once, then first-fit the pending
    stream in arrival order; units that do not fit a batch stay pending
    for the next (continuous packing, the data/packing.py discipline).
    Yields ((1, n_rows, ...) stacked packed batch, real_token_count,
    placed_example_count)."""
    n = len(arrays["input_ids"])
    _packable_lengths(arrays, seq_len)  # reject units that cannot fit
    order = (np.random.RandomState(seed).permutation(n) if shuffle
             else np.arange(n))
    pending: List[int] = list(order)
    window = max(1, n_rows * max_segments * 2)
    while pending:
        batch, placements = pack_finetune_batch(
            arrays, pending[:window], n_rows, seq_len, max_segments,
            group_size=group_size)
        if not placements:  # cannot happen (head always fits an empty row)
            raise RuntimeError("packer failed to place the head unit")
        labels = pack_labels(arrays, placements, n_rows, seq_len,
                             max_segments)
        batch.update(labels)
        placed = {p.unit for p in placements}
        pending = [i for i in pending if i not in placed]
        real = int(sum(sum(p.lengths) for p in placements))
        yield ({k: v[None] for k, v in batch.items()}, real,
               len(placements))


# -- length-bucketed eval -----------------------------------------------------


def bucketed_eval_batches(arrays: Dict[str, np.ndarray], batch_size: int,
                          buckets: Sequence[int],
                          label_ignore: Optional[Dict[str, int]] = None):
    """Length-bucketed eval batching: examples group by the smallest
    bucket that fits their longest sub-row, every sequence-shaped field
    is TRIMMED to the bucket, and tails pad to full batch_size by
    repeating index 0 with ignored labels. Pad keys beyond a real
    example's length carry the exact-zero attention bias either way, so
    trimming changes FLOPs, not answers. Yields
    (np_batch, real_indices, bucket)."""
    mask = np.asarray(arrays["attention_mask"], np.int64)
    sub_len = mask.sum(axis=-1)
    max_len = sub_len.max(axis=-1) if sub_len.ndim > 1 else sub_len
    buckets = sorted(set(int(b) for b in buckets))
    by_bucket: Dict[int, List[int]] = {}
    for i, ln in enumerate(max_len):
        for b in buckets:
            if ln <= b:
                by_bucket.setdefault(b, []).append(i)
                break
        else:
            by_bucket.setdefault(buckets[-1], []).append(i)
    seq_fields = {k for k, v in arrays.items()
                  if np.asarray(v).ndim >= 2
                  and np.asarray(v).shape[-1] == mask.shape[-1]}
    for bucket in sorted(by_bucket):
        idx_all = by_bucket[bucket]
        for lo in range(0, len(idx_all), batch_size):
            idx = np.asarray(idx_all[lo:lo + batch_size])
            pad = batch_size - len(idx)
            full = (np.concatenate([idx, np.zeros(pad, np.int64)]) if pad
                    else idx)
            batch = {}
            for k, v in arrays.items():
                picked = np.asarray(v[full]).copy()
                if k in seq_fields:
                    picked = picked[..., :bucket].copy()
                batch[k] = picked
            if pad:
                for fld, ign in (label_ignore or {}).items():
                    batch[fld][len(idx):] = ign
            yield batch, idx, bucket


# -- the TaskRun contract + the loop ------------------------------------------


@dataclass
class TaskRun:
    """Everything task-shaped the driver loop needs, built by a
    TaskSpec.setup(args, config, tel). `train_arrays=None` skips
    training (predict/eval-only invocations)."""

    model: Any
    tx: Any
    init_fn: Callable                     # rng -> model variables
    schedule: Callable[[int], float]      # lr metric (optimizer owns its own)
    seq_len: int
    batch_size: int                       # units per optimization step
    accum_steps: int = 1
    total_steps: int = 0
    epochs: Optional[int] = None          # None = loop until total_steps
    train_arrays: Optional[Dict[str, np.ndarray]] = None
    loss_builder: Optional[Callable] = None         # plain batches
    packed_loss_builder: Optional[Callable] = None  # --packing batches
    pack_labels: Optional[Callable] = None
    group_size: int = 1                   # sub-rows per unit (MC: C)
    label_ignore: Dict[str, int] = field(default_factory=dict)
    rows_per_step: Optional[int] = None   # FLOPs basis (MC: batch*C)
    log_every: int = 50
    perf_log_freq: int = 50
    init_checkpoint: Optional[str] = None
    epoch_eval: Optional[Callable] = None  # (params, epoch) -> dict|None
    finalize: Optional[Callable] = None    # (params, results) -> dict|None
    log_epoch_metrics: bool = False        # per-epoch train record (run_ner)


def write_finetune_artifact(path: str, task: str,
                            record: Dict[str, Any]) -> None:
    """Merge one task's finetune perf summary into a FINETUNE_*.json
    artifact (tools/perfboard.py indexes these; several tasks accumulate
    into one file)."""
    doc: Dict[str, Any] = {"schema_version": 1, "kind": "finetune",
                           "tasks": {}}
    try:
        with open(path, encoding="utf-8") as f:
            prev = json.load(f)
        if isinstance(prev, dict) and isinstance(prev.get("tasks"), dict):
            doc = prev
    except (OSError, ValueError):
        pass
    doc["schema_version"] = 1
    doc["kind"] = "finetune"
    doc["time_unix"] = round(time.time(), 3)
    doc["tasks"][task] = record
    os.makedirs(os.path.dirname(os.path.abspath(path)) or ".",
                exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1, sort_keys=True, allow_nan=False)
        f.write("\n")


def run_task(spec, args) -> Dict[str, Any]:
    """The shared finetune entry body: telemetry + survival kit + train
    loop (plain or packed) + checkpoint + per-task eval, for any
    registered TaskSpec. run_finetune.py (and the run_squad.py /
    run_ner.py aliases) call this."""
    if not getattr(args, "output_dir", None):
        raise SystemExit("--output_dir is required")
    os.makedirs(args.output_dir, exist_ok=True)

    import jax
    import jax.numpy as jnp

    from bert_pytorch_tpu.config import BertConfig, pad_vocab_size
    from bert_pytorch_tpu.parallel import dist
    from bert_pytorch_tpu.resilience import PreemptionGuard
    from bert_pytorch_tpu.resilience.preemption import \
        finetune_emergency_save
    from bert_pytorch_tpu.resilience.watchdog import arm_watchdog
    from bert_pytorch_tpu.telemetry import (collect_provenance,
                                            flops_per_seq, init_run,
                                            lookup_peak_flops)
    from bert_pytorch_tpu.telemetry.stepwatch import DEFAULT_PEAK
    from bert_pytorch_tpu.training import TrainState, make_sharded_state
    from bert_pytorch_tpu.training.checkpoint import CheckpointManager
    from bert_pytorch_tpu.training.pretrain import build_pretrain_step

    np.random.seed(args.seed)
    config = BertConfig.from_json_file(args.model_config_file)
    config = config.replace(vocab_size=pad_vocab_size(config.vocab_size, 8))

    log_prefix = getattr(args, "log_prefix", None) or f"{spec.name}_log"
    tel = init_run(phase=spec.name,
                   log_prefix=os.path.join(args.output_dir, log_prefix),
                   verbose=dist.is_main_process(), jsonl=True,
                   metrics_port=getattr(args, "metrics_port", None))
    logger = tel.logger
    compile_watch = tel.compile_watch
    guard = PreemptionGuard(registry=tel.registry, log=logger.info)
    guard.install()
    watchdog = None
    survival: Dict[str, Any] = {}
    try:
        tel.log_header(**collect_provenance())
        run: TaskRun = spec.setup(args, config, tel)
        packing = bool(getattr(args, "packing", False))
        if packing and run.pack_labels is None:
            raise SystemExit(f"task '{spec.name}' does not support "
                             "--packing")
        if packing and run.accum_steps > 1:
            raise SystemExit(
                "--packing is incompatible with gradient accumulation "
                f"(accum_steps={run.accum_steps}): the packer owns the "
                "per-step example budget, so accumulation would silently "
                "change the effective batch and LR-schedule basis. Drop "
                "one of the two flags.")
        results: Dict[str, Any] = {}
        last_perf: Optional[Dict[str, float]] = None

        do_train = run.train_arrays is not None and run.total_steps > 0
        if do_train:
            loss_builder = (run.packed_loss_builder if packing
                            else run.loss_builder)
            accum = run.accum_steps
            step_fn = build_pretrain_step(
                run.model, run.tx, schedule=run.schedule,
                accum_steps=accum, loss_fn_builder=loss_builder)
            state, _ = make_sharded_state(jax.random.PRNGKey(args.seed),
                                          run.init_fn, run.tx)
            if run.init_checkpoint:
                params = load_pretrained_params(run.init_checkpoint,
                                                state.params,
                                                log=logger.info)
                state = TrainState(step=state.step, params=params,
                                   opt_state=state.opt_state)
                logger.info(f"loaded pretrained weights from "
                            f"{run.init_checkpoint}")
            jit_step = jax.jit(step_fn, donate_argnums=(0,))

            # StepWatch's flops/slot basis is DEVICE ROWS per step: a
            # packed step dispatches exactly batch_size rows (accum > 1
            # is rejected with --packing above),
            # a plain step batch*accum*group rows (multiple choice
            # computes C rows per example). Getting this wrong skews the
            # perfboard-gated MFU/pad_fraction (seq_per_sec therefore
            # counts rows, not examples; results[
            # "training_sequences_per_second"] below counts examples
            # actually consumed, both modes).
            if packing:
                rows = run.batch_size
            else:
                rows = run.rows_per_step or (
                    run.batch_size * run.accum_steps * run.group_size)
            peak = lookup_peak_flops(
                jax.devices()[0].device_kind,
                dtype=getattr(args, "dtype", None) or config.dtype)
            sw = tel.make_stepwatch(
                flops_per_step=flops_per_seq(
                    config, run.seq_len, config.vocab_size, 0) * rows,
                seqs_per_step=rows,
                seq_len=run.seq_len,
                peak_flops=(peak or DEFAULT_PEAK) * jax.device_count(),
                log_freq=run.perf_log_freq,
                n_devices=jax.device_count())
            watchdog = arm_watchdog(
                getattr(args, "watchdog_timeout", 0.0),
                getattr(args, "watchdog_action", "abort"), sw,
                registry=tel.registry, log=logger.info,
                out_dir=args.output_dir)

            logger.info(
                f"finetune[{spec.name}]: {run.total_steps} step(s), "
                f"batch {run.batch_size} x accum {run.accum_steps}, "
                f"seq {run.seq_len}, packing "
                f"{'on' if packing else 'off'}"
                + (f" (max_segments "
                   f"{getattr(args, 'packing_max_segments', 8)})"
                   if packing else ""))

            rng = jax.random.PRNGKey(args.seed)
            t0 = time.time()
            step, epoch, examples_done = 0, 0, 0
            metrics = None
            while step < run.total_steps:
                if packing:
                    batches = packed_train_batches(
                        run.train_arrays, n_rows=run.batch_size,
                        seq_len=run.seq_len,
                        max_segments=getattr(args, "packing_max_segments",
                                             8),
                        pack_labels=run.pack_labels, shuffle=True,
                        seed=args.seed + epoch,
                        group_size=run.group_size)
                else:
                    batches = plain_train_batches(
                        run.train_arrays,
                        run.batch_size * run.accum_steps,
                        run.accum_steps, shuffle=True,
                        seed=args.seed + epoch,
                        label_ignore=run.label_ignore)
                for batch_np, real_tokens, n_examples in batches:
                    if step >= run.total_steps:
                        break
                    with sw.phase("data_prep"):
                        batch = {k: jnp.asarray(v)
                                 for k, v in batch_np.items()}
                        sw.note_tokens(float(real_tokens))
                    rng, srng = jax.random.split(rng)
                    with sw.phase("dispatch"):
                        state, metrics = jit_step(state, batch, srng)
                    step += 1
                    examples_done += n_examples
                    survival["state"], survival["step"] = state, step
                    if not run.log_epoch_metrics and (
                            step % run.log_every == 0
                            or step == run.total_steps):
                        with sw.phase("metric_flush"):
                            tel.log_train(
                                step, loss=float(metrics["loss"]),
                                learning_rate=float(
                                    metrics["learning_rate"]))
                    perf = sw.step_done()
                    if perf is not None:
                        tel.log_perf(step, perf)
                        last_perf = perf
                if run.log_epoch_metrics and metrics is not None:
                    with sw.phase("metric_flush"):
                        tel.log_train(step, epoch=epoch,
                                      loss=float(metrics["loss"]),
                                      learning_rate=float(
                                          metrics["learning_rate"]))
                if run.epoch_eval is not None and step > 0:
                    with sw.pause():  # eval must not pollute the interval
                        extra = run.epoch_eval(state.params, epoch)
                    if extra:
                        results.update(extra)
                epoch += 1
                if run.epochs is not None and epoch >= run.epochs:
                    break
            perf = sw.flush()  # partial interval: short runs still get one
            if perf is not None:
                tel.log_perf(step, perf)
                last_perf = perf
            train_time = time.time() - t0
            results["e2e_train_time"] = train_time
            # examples ACTUALLY consumed: a packed step trains a
            # data-dependent number of examples (never batch*accum — the
            # packed path forces accum to 1) and a plain tail batch pads
            # with zero-loss repeats that must not count
            results["training_sequences_per_second"] = (
                examples_done / max(train_time, 1e-9))

            mgr = CheckpointManager(os.path.join(args.output_dir, "ckpt"))
            mgr.save(step, state, extra={"task": spec.name,
                                         "config": config.to_dict()})
            mgr.close()
            final_params = state.params

            artifact = getattr(args, "perf_artifact", None)
            if artifact and last_perf is not None:
                rec = {k: last_perf[k] for k in
                       ("real_tokens_per_sec", "pad_fraction",
                        "packing_efficiency", "seq_per_sec",
                        "step_time_ms", "mfu") if k in last_perf}
                rec["packing"] = packing
                rec["steps"] = step
                write_finetune_artifact(artifact, spec.name, rec)
                logger.info(f"finetune[{spec.name}]: perf artifact -> "
                            f"{artifact}")
        else:
            state, _ = make_sharded_state(jax.random.PRNGKey(args.seed),
                                          run.init_fn, run.tx)
            if run.init_checkpoint:
                final_params = load_pretrained_params(
                    run.init_checkpoint, state.params, log=logger.info)
            else:
                final_params = state.params

        if run.finalize is not None:
            extra = run.finalize(final_params, results)
            if extra:
                results.update(extra)

        if results:
            logger.log("final", 0, **{
                k: v for k, v in results.items()
                if isinstance(v, (int, float))})
        logger.info(json.dumps(results, default=str))
        logger.info(f"compiles: {compile_watch.snapshot()}")
        return results
    except BaseException as exc:
        # preemption-safe finetuning: SIGTERM/SIGINT mid-epoch saves the
        # in-progress state (the reference lost the whole finetune run)
        finetune_emergency_save(guard, exc, survival,
                                os.path.join(args.output_dir, "ckpt"),
                                spec.name, registry=tel.registry,
                                log=logger.info)
        raise
    finally:
        for closeable in (watchdog, guard):
            if closeable is not None:
                try:
                    closeable.close()
                except Exception:
                    pass
        tel.close()
