"""Jitted pretraining step: forward, loss, grad, accumulation, update.

The reference split this across forward_backward_pass / take_optimizer_step
with DDP no_sync() gymnastics to suppress NCCL allreduce during accumulation
(run_pretraining.py:395-451, :525-535). Under SPMD there is nothing to
suppress: microbatches accumulate grads inside a `lax.scan` carry, and the
single grad (p)sum the compiler inserts happens once per optimization step by
construction. The whole step — N microbatch fwd/bwd, optimizer, schedule — is
one XLA program; donation makes it in-place.

Batch layout contract: every array arrives shaped (accum_steps, micro_batch,
...). accum_steps == 1 is the plain path (no scan). Loss is averaged over
microbatches (reference pre-divided by accumulation count,
run_pretraining.py:436 — same result, computed exactly).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from bert_pytorch_tpu.models import losses
from bert_pytorch_tpu.telemetry.health import (HealthConfig,
                                               global_norm_f32,
                                               health_signals, health_update,
                                               is_sticky_metric,
                                               select_state)
from bert_pytorch_tpu.training.state import TrainState

Batch = Dict[str, jax.Array]


def _apply_health(health: Optional[HealthConfig], state: TrainState,
                  loss, grads, grad_norm, params, opt_state, metrics,
                  precond_state=None):
    """Shared health-pack tail for both step builders: non-finite signals,
    EMA/z-score/drift update, and — under action='skip' — the in-graph
    state guard. Returns (params, opt_state, precond_state, telemetry).

    The skip select must live IN the compiled step: the host reads metrics
    one step late (the non-blocking readback contract), so by the time it
    could react, a poisoned update would already be applied. Step-count
    semantics of a skip: TrainState.step (and so the LOGGED learning_rate
    metric, and the K-FAC builder's schedule argument) still advances, but
    the reverted opt_state includes the optimizer's internal count — the
    optax schedule the update actually consumes counts only APPLIED steps,
    exactly as if the poisoned batch never reached the optimizer. After k
    skips the applied lr therefore trails the logged one by k schedule
    steps; with rare skips (the intended regime) the drift is noise, and it
    is the price of keeping the skip bit-exact.
    """
    if health is None:
        return params, opt_state, precond_state, state.telemetry
    hmetrics, bad = health_signals(loss, grads, grad_norm)
    if health.action == "skip":
        params = select_state(bad, state.params, params)
        opt_state = select_state(bad, state.opt_state, opt_state)
        if precond_state is not None:
            precond_state = select_state(bad, state.precond_state,
                                         precond_state)
        hmetrics["skipped_nonfinite"] = bad.astype(jnp.int32)
    telemetry, ema_metrics = health_update(health, state.telemetry,
                                           grad_norm, bad, params)
    metrics.update(hmetrics)
    metrics.update(ema_metrics)
    return params, opt_state, precond_state, telemetry


def inject_nonfinite(params: Any, bad) -> Any:
    """Fault-injection drill (--inject_nonfinite_step, tools/replay.py):
    when `bad` is true, set one element of the first encoder kernel — in
    canonical (sorted-path) order that is layer 0's attention output
    projection, in either parameter layout — to NaN, so a real NaN
    propagates attention -> loss -> gradients exactly the way a hardware
    or data blowup would, and the whole alarm -> flight-recorder ->
    replay -> bisect pipeline can be exercised end to end on a live run.
    Because the poison is a pure function of the traced step counter it
    replays deterministically from the recorded manifest. Compiled in
    only when the flag is set; `bad` false is an exact no-op value-wise.
    """
    done = [False]

    def maybe(path, leaf):
        keys = "/".join(str(getattr(p, "key", p)) for p in path)
        if done[0] or "encoder" not in keys or "kernel" not in keys \
                or not jnp.issubdtype(leaf.dtype, jnp.floating):
            return leaf
        done[0] = True
        flat = jnp.asarray(leaf).reshape(-1)  # tolerate numpy leaves (replay)
        flat = flat.at[0].set(jnp.where(bad,
                                        jnp.asarray(jnp.nan, leaf.dtype),
                                        flat[0]))
        return flat.reshape(leaf.shape)

    return jax.tree_util.tree_map_with_path(maybe, params)


def _param_caster(grad_dtype):
    """tree-cast fp params to grad_dtype (bf16 grads against fp32 masters,
    the apex-O2-equivalent scheme); identity when grad_dtype is None."""
    def cast(params):
        if grad_dtype is None:
            return params
        return jax.tree.map(
            lambda p: p.astype(grad_dtype)
            if jnp.issubdtype(p.dtype, jnp.floating) else p, params)
    return cast


def _accum_zeros(gparams, accum_steps: int):
    """Gradient-accumulator init: carry dtype follows the per-micro grad
    dtype up to depth 128 — worst-case bf16 accumulation rounding
    (~sqrt(N)*2^-9, ~2% relative at N=128) stays far below microbatch
    gradient noise, matching the reference's apex-O2 fp16 accumulation at
    its typical depths (run_pretraining.py:438-448). Beyond 128 the carry
    switches to fp32: the bf16 ulp approaches a whole microbatch
    contribution (catastrophic at N>~500) and the fp32 carry's constant
    extra traffic is amortized by the long scan."""
    deep = accum_steps > 128
    return jax.tree.map(
        lambda p: jnp.zeros(
            p.shape, jnp.float32
            if deep and jnp.issubdtype(p.dtype, jnp.floating) else p.dtype),
        gparams)


# global_norm with fp32 leaf upcast (bf16 sums of millions of squares
# misreport the norm) — single implementation shared with the health pack
# so the logged grad_norm and param_norm can never diverge in method
_global_norm_f32 = global_norm_f32


def gather_masked_labels(masked_lm_labels: jax.Array, max_predictions: int
                         ) -> Tuple[jax.Array, jax.Array]:
    """(B, S) dense labels (-1 = unmasked) -> ((B, P) positions, (B, P)
    labels) with the masked positions first in original order.

    Rows with fewer than P masked tokens fill the tail with positions whose
    gathered label is -1, which the loss ignores — the gathered path then
    computes the exact same CE as the dense path. P must be >= the data
    pipeline's max_predictions_per_seq or excess masked positions silently
    drop out of the loss.
    """
    unmasked = masked_lm_labels == -1
    positions = jnp.argsort(unmasked, axis=-1, stable=True)
    positions = positions[:, :max_predictions].astype(jnp.int32)
    labels = jnp.take_along_axis(masked_lm_labels, positions, axis=-1)
    return positions, labels


def _packed_kwargs(batch: Batch) -> Dict[str, Any]:
    """The packed-sequence fields (data/packing.py batch contract), passed
    through to the model only when the loader emitted them — an unpacked
    batch traces the exact pre-packing program."""
    return {k: batch[k] for k in ("position_ids", "segment_ids",
                                  "nsp_positions") if k in batch}


def _pretrain_loss_fn(model, max_predictions: Optional[int] = None
                      ) -> Callable:
    def loss_fn(params, batch: Batch, dropout_rng,
                deterministic: bool = False) -> Tuple[jax.Array, Dict]:
        mlm_labels = batch["masked_lm_labels"]
        masked_positions = None
        dropped = jnp.zeros([], jnp.int32)
        if max_predictions is not None:
            dense_total = jnp.sum(mlm_labels != -1).astype(jnp.int32)
            masked_positions, mlm_labels = gather_masked_labels(
                mlm_labels, max_predictions)
            # rows with > max_predictions masks lose the excess; surface it
            dropped = dense_total - jnp.sum(mlm_labels != -1).astype(jnp.int32)
        mlm_logits, nsp_logits = model.apply(
            {"params": params},
            batch["input_ids"],
            batch.get("token_type_ids"),
            batch.get("attention_mask"),
            deterministic=deterministic,
            masked_positions=masked_positions,
            rngs=None if deterministic else {"dropout": dropout_rng},
            **_packed_kwargs(batch),
        )
        loss = losses.pretraining_loss(
            mlm_logits, mlm_labels,
            nsp_logits, batch.get("next_sentence_labels"))
        correct, total = losses.mlm_accuracy(mlm_logits, mlm_labels)
        return loss, {"mlm_correct": correct, "mlm_total": total,
                      "mlm_dropped": dropped}

    return loss_fn


def _zero1_update(tx, grads, state, zero1):
    """The optimizer tail shared by both step builders, with the optional
    ZeRO-1 sharding constraints (parallel/zero.py) around it.

    With a Zero1Plan: the post-accumulation gradient is constrained into its
    shard layout (GSPMD lowers the batch psum to a reduce-scatter), the
    moments/update compute shard-local against the sharded-at-init opt_state,
    and the updated params are constrained back to their train-step layout
    (the all-gather). Without a plan this is exactly the old update.

    gather_on_use plans instead leave the updated params IN the shard
    layout: the all-gather moves to the start of the next step
    (_use_params), where it overlaps forward compute instead of trailing
    the update as a barrier. state.params arrive shard-resident there
    (make_sharded_state(zero1_params=True)), so apply_updates is
    shard-local end to end.

    Bit-identity between the two modes is a PROGRAM-STRUCTURE property,
    not a given — a reduction's rounding depends on its grouping, and
    GSPMD regroups freely when the two programs differ anywhere. Three
    deliberate symmetries hold it (each was empirically necessary; drop
    one and the paths drift ~1e-9/step):
      1. the params handed to tx.update are constrained to the SHARD
         layout in both modes (free local slice vs no-op), so LAMB's
         trust-ratio norms reduce in the same partial+psum order;
      2. the updated params are pinned to the SHARD layout in both modes
         right after apply_updates — the non-overlap mode then appends
         its trailing all-gather as a pure output-layout materialization,
         the only node the two programs do not share;
      3. the point-of-use gather node exists in both modes too
         (_use_params), a no-op re-statement in the non-overlap one.
    Net collective count is identical (one gather per planned leaf per
    step, verified against the compiled HLO in tests/test_zero1.py);
    only WHERE it sits differs — trailing the update (a barrier with no
    compute left to hide it) vs leading the forward (interleavable)."""
    if zero1 is not None:
        grads = jax.lax.with_sharding_constraint(grads, zero1.grad_shardings)
        norm_params = jax.lax.with_sharding_constraint(
            state.params, zero1.grad_shardings)
    else:
        norm_params = state.params
    updates, opt_state = tx.update(grads, state.opt_state, norm_params)
    if zero1 is not None:
        updates = jax.lax.with_sharding_constraint(
            updates, zero1.grad_shardings)
    params = optax.apply_updates(state.params, updates)
    if zero1 is not None:
        params = jax.lax.with_sharding_constraint(
            params, zero1.grad_shardings)
        if not zero1.gather_on_use:
            params = jax.lax.with_sharding_constraint(
                params, zero1.param_shardings)
    return params, opt_state, grads


def _use_params(state, zero1, cast_params):
    """The params the forward/backward consume: cast to the grad dtype and —
    for a gather-on-use Zero1Plan — re-constrained from the 1/N resting
    layout to the train-step layout, leaf by leaf (parallel/zero.py
    gather_params). Cast-then-gather order matters for traffic, not values:
    the all-gather then moves the bf16 copy (half the bytes of the fp32
    masters) while the masters stay shard-resident for the update. With
    grad_dtype=None the cast is identity and the gather moves fp32 —
    exactly what the non-overlap path's end-of-step gather moved."""
    gparams = cast_params(state.params)
    if zero1 is not None:
        from bert_pytorch_tpu.parallel.zero import gather_params

        # BOTH modes get the same per-leaf constraint node: in overlap mode
        # it is the all-gather from the 1/N resting layout, in the baseline
        # it is a no-op re-statement of the layout the params already rest
        # in. Keeping the node in both programs is what makes them the SAME
        # program to the SPMD partitioner (modulo the resting layout), and
        # therefore bit-identical — with the node present on one side only,
        # GSPMD partitions the backward's wgrad reductions differently and
        # the paths drift ~1e-9/step.
        gparams = gather_params(gparams, zero1)
    return gparams


def _build_rs_micro(model, zero1, max_predictions=None,
                    kfac=None, zeros_perts=None):
    """One-microbatch fwd/bwd inside an EXPLICIT shard_map region whose
    gradients leave through `psum_scatter` — the --zero1_rs path.

    The legacy lowering all-reduces every full gradient and only then
    slices out the shard the ZeRO-1 update consumes: 2x the bytes the
    update needs. Here each grad leaf exits the region through
    psum_scatter on the dim the appended-axis derivation gave plan.axis
    (parallel/zero.scatter_dims — literally parallel/rules.appended_dim
    over the SAME specs that built plan.grad_shardings, so the scatter,
    the layout the moments rest in, and the sharding_rules pass all read
    one derivation), landing each device exactly its shard and nothing
    else. Leaves the divisibility fallback left replicated exit via
    plain psum.

    Value-parity design (each point was empirically necessary):
    - the masked-token / NSP counts are label-only, so they are psum'd
      BEFORE the differentiated function: the backward stays psum-free,
      and dividing the LOCAL nll sums by the GLOBAL counts seeds every
      position's cotangent with the baseline's exact 1/count;
    - the logged loss is psum(local sums)/count — the same
      sum-then-divide grouping GSPMD lowers losses.pretraining_loss to,
      so the metric is bit-identical to the legacy path;
    - model.apply runs under nn.logical_axis_rules(()): inside shard_map
      every mesh axis is manual, so the model's with_logical_constraint
      annotations must dissolve (the data-only-mesh guard in
      make_zero1_plan is what makes that safe — nothing was
      model/seq-sharded to begin with);
    - plan.rs_mode="allreduce" swaps each psum_scatter for
      psum + slice-own-shard — the 2x-bytes pattern this path exists to
      kill, kept because it is the SAME program modulo the reduction op
      and therefore bit-identical, which is what lets
      tests/test_zero1.py pin scatter-vs-allreduce parity EXACTLY (the
      legacy GSPMD program reassociates reductions on its own and is
      only comparable to tolerance);
    - dropout draws from fold_in(rng, axis_index): valid training (each
      device gets independent bits) but not bit-matched to the legacy
      path's global-shape masks — parity gates run with dropout 0, where
      the rng folds prune away entirely.

    With `kfac` (must be bucketed — factor_bucket_bytes set), the region
    also returns K-FAC factor statistics: kfac.local_partial_stats' local
    contractions exit with their leading partial axis mapped back onto
    the batch axes, exactly the layout `kfac.step`'s coalesced
    _reduce_stats consumes. `zeros_perts` is the zero perturbation tree
    (an explicit shard_map operand, replicated).

    Returns one_micro with the step builders' usual signature:
    (params, micro, rng) -> (loss, aux, grads[, stats]).
    """
    import flax.linen as nn
    from jax.sharding import NamedSharding, PartitionSpec as P

    from bert_pytorch_tpu.ops.shard_map_compat import shard_map
    from bert_pytorch_tpu.parallel import rules as rules_lib
    from bert_pytorch_tpu.parallel import zero as zero_lib

    if kfac is not None and not kfac.bucketed:
        raise ValueError(
            "zero1 reduce_scatter + K-FAC requires bucketed factor "
            "reductions (factor_bucket_bytes): the region emits PARTIAL "
            "factor statistics only _reduce_stats knows how to consume")

    mesh = next(s.mesh for s in jax.tree.leaves(zero1.grad_shardings)
                if isinstance(s, NamedSharding))
    axis = zero1.axis
    ax_entry = rules_lib.batch_axes(mesh)
    n_shards = int(mesh.shape[axis])
    sdims = zero_lib.scatter_dims(zero1)
    grad_specs = jax.tree.map(
        lambda s: s.spec if isinstance(s, NamedSharding) else P(),
        zero1.grad_shardings)
    rep = P()

    def reduce_grads(grads):
        flat, tdef = jax.tree_util.tree_flatten(grads)
        out = []
        for g, d in zip(flat, sdims):
            if d is None:
                out.append(jax.lax.psum(g, axis))
            elif zero1.rs_mode == "allreduce":
                full = jax.lax.psum(g, axis)
                shard = g.shape[d] // n_shards
                start = jax.lax.axis_index(axis) * shard
                out.append(jax.lax.dynamic_slice_in_dim(
                    full, start, shard, d))
            else:
                out.append(jax.lax.psum_scatter(
                    g, axis, scatter_dimension=d, tiled=True))
        return jax.tree_util.tree_unflatten(tdef, out)

    def prep_labels(micro):
        mlm_labels = micro["masked_lm_labels"]
        masked_positions = None
        dropped = jnp.zeros([], jnp.int32)
        if max_predictions is not None:
            dense_total = jnp.sum(mlm_labels != -1).astype(jnp.int32)
            masked_positions, mlm_labels = gather_masked_labels(
                mlm_labels, max_predictions)
            dropped = dense_total - jnp.sum(
                mlm_labels != -1).astype(jnp.int32)
        return mlm_labels, masked_positions, dropped

    def global_counts(mlm_labels, nsp_labels):
        # label-only, psum'd OUTSIDE the differentiated function — exact
        # int sums, and the backward never sees a collective
        c_mlm = jnp.maximum(
            jax.lax.psum(jnp.sum(mlm_labels != -1), ax_entry), 1)
        c_nsp = (jnp.maximum(
            jax.lax.psum(jnp.sum(nsp_labels != -1), ax_entry), 1)
            if nsp_labels is not None else None)
        return c_mlm, c_nsp

    def terms_to_loss(mlm_logits, nsp_logits, mlm_labels, nsp_labels,
                      c_mlm, c_nsp):
        (mlm_sum, _), nsp = losses.pretraining_loss_terms(
            mlm_logits, mlm_labels, nsp_logits, nsp_labels)
        lloc = mlm_sum / c_mlm
        nsp_sum = jnp.zeros([], jnp.float32)
        if nsp is not None:
            nsp_sum = nsp[0]
            lloc = lloc + nsp_sum / c_nsp
        correct, total = losses.mlm_accuracy(mlm_logits, mlm_labels)
        return lloc, mlm_sum, nsp_sum, correct, total

    def metric_loss(mlm_sum, nsp_sum, nsp_labels, c_mlm, c_nsp):
        loss = jax.lax.psum(mlm_sum, ax_entry) / c_mlm
        if nsp_labels is not None:
            loss = loss + jax.lax.psum(nsp_sum, ax_entry) / c_nsp
        return loss

    def local_micro(params, micro, rng):
        mlm_labels, masked_positions, dropped = prep_labels(micro)
        nsp_labels = micro.get("next_sentence_labels")
        c_mlm, c_nsp = global_counts(mlm_labels, nsp_labels)
        rng = jax.random.fold_in(rng, jax.lax.axis_index(axis))

        def local_loss(p):
            with nn.logical_axis_rules(()):
                mlm_logits, nsp_logits = model.apply(
                    {"params": p}, micro["input_ids"],
                    micro.get("token_type_ids"),
                    micro.get("attention_mask"),
                    deterministic=False,
                    masked_positions=masked_positions,
                    rngs={"dropout": rng},
                    **_packed_kwargs(micro))
            lloc, mlm_sum, nsp_sum, correct, total = terms_to_loss(
                mlm_logits, nsp_logits, mlm_labels, nsp_labels,
                c_mlm, c_nsp)
            return lloc, (mlm_sum, nsp_sum, correct, total)

        (_, (mlm_sum, nsp_sum, correct, total)), grads = \
            jax.value_and_grad(local_loss, has_aux=True)(params)
        loss = metric_loss(mlm_sum, nsp_sum, nsp_labels, c_mlm, c_nsp)
        aux = {"mlm_correct": jax.lax.psum(correct, ax_entry),
               "mlm_total": jax.lax.psum(total, ax_entry),
               "mlm_dropped": jax.lax.psum(dropped, ax_entry)}
        return loss, aux, reduce_grads(grads)

    def local_micro_kfac(params, perts, micro, rng):
        mlm_labels, masked_positions, _ = prep_labels(micro)
        nsp_labels = micro.get("next_sentence_labels")
        c_mlm, c_nsp = global_counts(mlm_labels, nsp_labels)
        rng = jax.random.fold_in(rng, jax.lax.axis_index(axis))

        def local_loss(p, pe):
            with nn.logical_axis_rules(()):
                (mlm_logits, nsp_logits), mut = model.apply(
                    {"params": p, "perturbations": pe},
                    micro["input_ids"], micro.get("token_type_ids"),
                    micro.get("attention_mask"),
                    deterministic=False,
                    masked_positions=masked_positions,
                    rngs={"dropout": rng}, mutable=["kfac_in"],
                    **_packed_kwargs(micro))
            lloc, mlm_sum, nsp_sum, correct, total = terms_to_loss(
                mlm_logits, nsp_logits, mlm_labels, nsp_labels,
                c_mlm, c_nsp)
            return lloc, (mlm_sum, nsp_sum, correct, total,
                          mut["kfac_in"])

        (_, (mlm_sum, nsp_sum, correct, total, acts)), \
            (pgrads, pert_grads) = jax.value_and_grad(
                local_loss, argnums=(0, 1), has_aux=True)(params, perts)
        stats = kfac.local_partial_stats(acts, pert_grads)
        loss = metric_loss(mlm_sum, nsp_sum, nsp_labels, c_mlm, c_nsp)
        aux = {"mlm_correct": jax.lax.psum(correct, ax_entry),
               "mlm_total": jax.lax.psum(total, ax_entry)}
        return loss, aux, reduce_grads(pgrads), stats

    def _stats_probe(params, micro, rng):
        # shapes only (jax.eval_shape): the stats tree STRUCTURE and per-
        # leaf ranks the region's out_specs need. Collective-free and
        # traced OUTSIDE shard_map on global shapes — ranks match the
        # local ones, and record_norms=False keeps the (8x-wrong) global
        # row counts out of the normalization bookkeeping.
        mlm_labels, masked_positions, _ = prep_labels(micro)

        def local_loss(p, pe):
            (mlm_logits, nsp_logits), mut = model.apply(
                {"params": p, "perturbations": pe},
                micro["input_ids"], micro.get("token_type_ids"),
                micro.get("attention_mask"),
                deterministic=False, masked_positions=masked_positions,
                rngs={"dropout": rng}, mutable=["kfac_in"],
                **_packed_kwargs(micro))
            return losses.pretraining_loss(
                mlm_logits, mlm_labels, nsp_logits,
                micro.get("next_sentence_labels")), mut["kfac_in"]

        (_, acts), (_, pert_grads) = jax.value_and_grad(
            local_loss, argnums=(0, 1), has_aux=True)(params, zeros_perts)
        return kfac.local_partial_stats(acts, pert_grads,
                                        record_norms=False)

    def one_micro(params, micro, rng):
        p_specs = jax.tree.map(lambda _: rep, params)
        m_specs = jax.tree.map(
            lambda v: P(ax_entry, *([None] * (v.ndim - 1))), micro)
        if kfac is None:
            fn = shard_map(
                local_micro, mesh=mesh,
                in_specs=(p_specs, m_specs, rep),
                out_specs=(rep, {"mlm_correct": rep, "mlm_total": rep,
                                 "mlm_dropped": rep}, grad_specs),
                check_rep=False)
            return fn(params, micro, rng)
        # perturbation taps are activation-shaped: batch rides dim 0, or
        # dim 1 under the nn.scan-stacked encoder ([L, B, ...] 'layers'
        # leaves) — enter the region sliced like the microbatch so the
        # in-model `x + perturb` sees local shapes
        def pe_spec(path, v):
            keys = [getattr(k, "key", str(k)) for k in path]
            if "layers" in keys:
                return P(None, ax_entry, *([None] * (v.ndim - 2)))
            return P(ax_entry, *([None] * (v.ndim - 1)))

        pe_specs = jax.tree_util.tree_map_with_path(pe_spec, zeros_perts)
        stats_struct = jax.eval_shape(_stats_probe, params, micro, rng)
        s_specs = jax.tree.map(
            lambda sd: P(ax_entry, *([None] * (sd.ndim - 1))),
            stats_struct)
        fn = shard_map(
            local_micro_kfac, mesh=mesh,
            in_specs=(p_specs, pe_specs, m_specs, rep),
            out_specs=(rep, {"mlm_correct": rep, "mlm_total": rep},
                       grad_specs, s_specs),
            check_rep=False)
        return fn(params, zeros_perts, micro, rng)

    return one_micro


def build_pretrain_step(
    model,
    tx: optax.GradientTransformation,
    schedule: Optional[optax.Schedule] = None,
    accum_steps: int = 1,
    loss_fn_builder: Optional[Callable] = None,
    max_predictions: Optional[int] = None,
    grad_dtype: Optional[Any] = None,
    zero1: Optional[Any] = None,
    health: Optional[HealthConfig] = None,
    nan_inject_step: Optional[int] = None,
    norm_reducer: Optional[Any] = None,
) -> Callable[[TrainState, Batch, jax.Array], Tuple[TrainState, Dict]]:
    """Returns train_step(state, batch, rng) -> (state, metrics).

    `schedule` is only consulted for the lr metric (the optimizer owns its
    own schedule). `max_predictions` (pretraining only; ignored when a custom
    loss_fn_builder is given) turns on the gathered MLM head: logits are
    computed for at most that many masked positions per sequence instead of
    the full (B, S, V) tensor. For K-FAC use build_kfac_pretrain_step.

    `grad_dtype` (e.g. jnp.bfloat16): compute the forward/backward against a
    params copy cast to this dtype, so gradients — including the encoder
    grad buffers, the dominant non-matmul HBM traffic at BERT-Large scale —
    live in the compute dtype instead of fp32. (Under the stacked layout
    those buffers are the scan's (L, ...) stacks filled by
    dynamic_update_slice; under config.stacked_params=False they are
    per-layer leaves written directly — either way this halves their
    bytes.) The fp32 master params still receive the update (the optimizer
    upcasts); the reference's apex-O2 path likewise kept fp16 grads against
    fp32 masters. None = grads in param dtype (fp32).

    The accumulation scan below is layout-agnostic: the carry mirrors
    whatever pytree the grads arrive as (stacked (L, ...) leaves or
    per-layer subtrees), so both encoder layouts share this step builder
    unchanged.

    `zero1` (a parallel.zero.Zero1Plan, from make_zero1_plan): shard the
    optimizer update ZeRO-1-style over the data axis — reduce-scatter the
    accumulated gradient, update 1/N of the moments/params per chip,
    all-gather the result. Requires state built with
    make_sharded_state(zero1=True) so the moments' storage layout matches.
    LAMB trust-ratio semantics are unchanged: the per-tensor/per-layer norm
    reductions are global-view, so GSPMD adds the scalar cross-shard psums
    (parity: tests/test_zero1.py). A plan with gather_on_use=True
    (--zero1_overlap) additionally keeps the params shard-resident between
    steps and re-gathers them per-leaf at the point of use — bit-identical
    values, overlap-schedulable gathers; requires
    make_sharded_state(zero1_params=True).

    `health` (telemetry/health.HealthConfig): compile the in-graph health
    pack into the step — non-finite counts for loss and per-group grads,
    grad-norm EMA/z-score spike flag, param-norm drift, all returned in
    `metrics`; with health.action='skip' a non-finite step leaves params /
    optimizer state bit-identical. Requires state.telemetry populated
    (telemetry.init_telemetry_state()); the returned state carries the
    updated TelemetryState.

    `nan_inject_step` (fault-injection drill): poison layer 0's attention
    output kernel with one NaN on exactly that global step (state.step+1
    numbering, like the logged metrics) — see inject_nonfinite. None (the
    default) compiles nothing extra.

    `norm_reducer` (parallel/coalesce.NormReducer built from the plan's
    grad layout): route the logged grad_norm's cross-device reductions
    through the bucketed path — one vector all-reduce per axis group
    instead of one scalar per leaf, bit-identical value. Pass the same
    instance to lamb(norm_reducer=...) so the whole step shares one
    deterministic bucket assignment. None = the per-leaf program,
    byte-identical to round 15.
    """
    rs = zero1 is not None and getattr(zero1, "reduce_scatter", False)
    if loss_fn_builder is None:
        loss_fn = _pretrain_loss_fn(model, max_predictions)
    else:
        if rs:
            raise ValueError(
                "zero1 reduce_scatter supports only the built-in "
                "pretraining loss: the shard_map region owns the loss "
                "decomposition (losses.pretraining_loss_terms), so a "
                "custom loss_fn_builder cannot ride it")
        loss_fn = loss_fn_builder(model)
    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

    cast_params = _param_caster(grad_dtype)

    if rs:
        one_micro = _build_rs_micro(model, zero1, max_predictions)
    else:
        def one_micro(params, micro: Batch, rng):
            (loss, aux), grads = grad_fn(params, micro, rng)
            return loss, aux, grads

    def train_step(state: TrainState, batch: Batch, rng: jax.Array):
        rngs = jax.random.split(rng, accum_steps)
        gparams = _use_params(state, zero1, cast_params)
        if nan_inject_step is not None:
            gparams = inject_nonfinite(
                gparams, state.step + 1 == nan_inject_step)

        if accum_steps == 1:
            micro = jax.tree.map(lambda x: x[0], batch)
            loss, aux, grads = one_micro(gparams, micro, rngs[0])
        else:
            zeros = _accum_zeros(gparams, accum_steps)

            def body(carry, inp):
                grads_acc, loss_acc, aux_acc = carry
                micro, r = inp
                loss, aux, grads = one_micro(gparams, micro, r)
                carry = (
                    jax.tree.map(lambda a, g: a + g.astype(a.dtype),
                                 grads_acc, grads),
                    loss_acc + loss,
                    jax.tree.map(jnp.add, aux_acc, aux),
                )
                return carry, None

            micro0 = jax.tree.map(lambda x: x[0], batch)
            aux_shape = jax.eval_shape(
                lambda p, m, r: one_micro(p, m, r)[1],
                gparams, micro0, rngs[0])
            aux_zeros = jax.tree.map(
                lambda sd: jnp.zeros(sd.shape, sd.dtype), aux_shape)
            init = (zeros, jnp.zeros([], jnp.float32), aux_zeros)
            (grads, loss, aux), _ = jax.lax.scan(body, init, (batch, rngs))
            grads = jax.tree.map(lambda g: g / accum_steps, grads)
            loss = loss / accum_steps

        params, opt_state, grads = _zero1_update(tx, grads, state, zero1)
        grad_norm = (norm_reducer.global_norm_f32(grads)
                     if norm_reducer is not None
                     else _global_norm_f32(grads))

        metrics = {
            "loss": loss,
            "grad_norm": grad_norm,
        }
        params, opt_state, _, telemetry = _apply_health(
            health, state, loss, grads, grad_norm, params, opt_state,
            metrics)
        new_state = state.replace(step=state.step + 1, params=params,
                                  opt_state=opt_state, telemetry=telemetry)
        if "mlm_correct" in aux and "mlm_total" in aux:
            metrics["mlm_accuracy"] = (
                aux["mlm_correct"] / jnp.maximum(aux["mlm_total"], 1))
        if "mlm_dropped" in aux:
            # masked positions beyond max_predictions lose supervision; a
            # nonzero value means the data pipeline and step config disagree
            metrics["mlm_dropped"] = aux["mlm_dropped"]
        if schedule is not None:
            metrics["learning_rate"] = schedule(state.step)
        return new_state, metrics

    return train_step


def chain_steps(step_fn: Callable, n_steps: int,
                per_step_batch: bool = False) -> Callable:
    """Wrap a train step into a device-side n-step loop (one host dispatch).

    chained(state, batch, rng) runs `step_fn` n_steps times. With
    per_step_batch=True, `batch` carries a leading (n_steps, ...) axis of
    fresh data per inner step (run_pretraining's --steps_per_loop path);
    with False, the single (accum, micro, ...) batch is reused every step
    (bench steady-state). The per-step rng derives from fold_in(rng, i).
    Returns (state, metrics_of_last_step) — except health/anomaly flags
    (telemetry.health.STICKY_METRIC_KEYS), which are max-accumulated across
    the inner steps so a NaN or spike in ANY of them survives to the one
    readback the host gets per loop.

    This is the TPU-idiomatic "host out of the loop" structure: the host
    only feeds data and reads metrics every n_steps, so per-step dispatch
    latency (micro-seconds on a directly-attached TPU VM, ~24 ms through a
    remote relay) amortizes away.
    """
    if n_steps == 1:
        return step_fn

    def chained(state, batch, rng):
        def select(i):
            return (jax.tree.map(lambda x: x[i], batch) if per_step_batch
                    else batch)

        def body(i, carry):
            state, prev_metrics = carry
            state, metrics = step_fn(state, select(i),
                                     jax.random.fold_in(rng, i))
            for k in metrics:
                if is_sticky_metric(k) and k in prev_metrics:
                    metrics[k] = jnp.maximum(metrics[k], prev_metrics[k])
            return state, metrics

        # one real step builds the metrics pytree structure for the carry
        carry = step_fn(state, select(0), jax.random.fold_in(rng, 0))
        return jax.lax.fori_loop(1, n_steps, body, carry)

    return chained


class StepProgram:
    """AOT dispatch wrapper around a built train step — the lowering hook
    the static graph analyzer (bert_pytorch_tpu/analysis, tools/
    graphcheck.py) and the program-fingerprint plumbing hang off.

    jit-and-call hides the executable: once `jitted(args)` has compiled,
    there is no public route back to the HLO the run is actually
    executing. This wrapper makes the compile explicit — the first
    dispatch lowers and compiles (one XLA compile, same cost jit would
    have paid) and keeps the jax.stages.Compiled object, so
    `as_text()` / `fingerprint()` can report the live program's structure.
    Dispatches whose avals/shardings do not match the compiled signature
    (tail chunks, sharding drift on an uncommitted input) fall back to the
    plain jit cache — exactly the behavior the entry points had before,
    verified cheap because AOT argument validation raises BEFORE any
    donation or execution happens.

    The wrapped callable is positional-arity-agnostic: train steps call it
    as (state, batch, rng), the serving engine's bucketed inference
    forwards as (params, batch) — same AOT lifecycle either way
    (serving/engine.py compiles one StepProgram per sequence-length
    bucket so steady-state traffic never recompiles).
    """

    def __init__(self, step_fn: Callable, donate_state: bool = True):
        self.jitted = jax.jit(step_fn,
                              donate_argnums=(0,) if donate_state else ())
        self.lowered = None
        self.compiled = None
        self._aot_broken = False

    def lower(self, *args):
        """Trace only (cheap); keeps the lowered StableHLO for the dtype
        lint."""
        self.lowered = self.jitted.lower(*args)
        return self.lowered

    def compile(self, *args):
        """Lower (if needed) + XLA-compile; keeps the Compiled object."""
        if args or self.lowered is None:
            self.lower(*args)
        self.compiled = self.lowered.compile()
        return self.compiled

    def __call__(self, *args):
        if self.compiled is None and not self._aot_broken:
            try:
                self.compile(*args)
            except Exception as e:
                # fall back to plain jit, but never silently: a broken AOT
                # compile also means no program fingerprint for this run's
                # headers/bundles — the operator should see why
                import sys

                print(f"WARNING: StepProgram AOT compile failed "
                      f"({type(e).__name__}: {e}); dispatching through "
                      "the jit cache — program fingerprint unavailable",
                      file=sys.stderr)
                self._aot_broken = True
        if self.compiled is not None:
            try:
                return self.compiled(*args)
            except (ValueError, TypeError):
                # aval/sharding mismatch — raised during argument
                # validation, before donation or execution, so retrying
                # through the jit cache is safe (and compiles the new
                # signature exactly as the pre-wrapper code did)
                pass
        return self.jitted(*args)

    def as_text(self) -> Optional[str]:
        return self.compiled.as_text() if self.compiled is not None else None

    def fingerprint(self) -> Optional[Dict[str, Any]]:
        """Structural identity (collective counts + donation hash) of the
        compiled program, or None if nothing AOT-compiled (fallback mode).
        """
        if self.compiled is None:
            return None
        from bert_pytorch_tpu.analysis.hlo import program_fingerprint

        return program_fingerprint(self.compiled)


def step_input_expectations(abstract_state, state, batch, mesh,
                            zero1: bool = False,
                            zero1_params: bool = False,
                            n_leading: int = 1,
                            kfac_shard_axes=None):
    """(expected shardings, rule labels) for EVERY input leaf of a
    compiled train step's (state, batch, rng) argument tuple, flat in
    tree_leaves order — the `sharding_rules` static-analysis contract
    (analysis/passes.py; tools/graphcheck.py feeds this into
    program_report and the pass verifies each compiled in-sharding
    against it). Everything is DERIVED from the logical-axis-rules table
    (parallel/rules.py), never hand-written per leaf:

    - TrainState leaves: rules.train_state_expectations — params and
      moments through the logical annotations, plus the ZeRO-1 appended
      axis (zero1) and the --zero1_overlap resting layout (zero1_params);
    - K-FAC precond leaves (state.precond_state is not None):
      optim/kfac.state_shardings placements — stacked factor/inverse
      leaves the table distributes carry their L-axis spec; leaves the
      table deliberately leaves unplaced (2D sites, non-divisible
      stacks) carry NO expectation, because their in-sharding is GSPMD's
      choice rather than a rule;
    - batch leaves: the table's 'data' rule with `n_leading` unsharded
      leading axes (the (accum, micro, ...) contract);
    - the rng key: no expectation (pruned from the program entirely when
      dropout is off).

    `abstract_state` is training/state.abstract_train_state's tree;
    `state` the built TrainState (for the precond structure); `batch`
    the device batch dict; `kfac_shard_axes` the KFAC instance's
    configured axes when it deviates from the table's KFAC_SHARD_AXES
    default — the expectations must mirror the derivation that actually
    placed the state.
    """
    from jax.sharding import NamedSharding

    from bert_pytorch_tpu.optim import kfac as kfac_lib
    from bert_pytorch_tpu.parallel import rules as rules_lib

    expected, labels = rules_lib.train_state_expectations(
        abstract_state, mesh, zero1=zero1, zero1_params=zero1_params)
    if state.precond_state is not None:
        axes = (tuple(kfac_shard_axes) if kfac_shard_axes is not None
                else rules_lib.KFAC_SHARD_AXES)
        kfac_axes = "+".join(axes)
        for sh in kfac_lib.state_shardings(state.precond_state, mesh,
                                           axes):
            expected.append(sh)
            labels.append(f"kfac_stacked[{kfac_axes}]" if sh is not None
                          else "kfac_unplaced")
    n_batch = len(jax.tree_util.tree_leaves(batch))
    batch_sh = NamedSharding(mesh, rules_lib.batch_spec(n_leading, mesh))
    batch_label = "batch(" + "+".join(rules_lib.batch_axes(mesh)) + ")"
    expected += [batch_sh] * n_batch
    labels += [batch_label] * n_batch
    expected.append(None)
    labels.append("rng")
    return expected, labels


def init_kfac_state(model, kfac, state, sample_inputs: Tuple):
    """Attach a freshly-initialized KFACState to `state`.

    Shapes come from eval_shape only — no forward pass runs. `sample_inputs`
    is one microbatch's (input_ids, token_type_ids, attention_mask). Returns
    (new_state, pert_template); pert_template is what
    build_kfac_pretrain_step needs. Single source of truth for the tap-shape
    bootstrap used by run_pretraining, the multi-chip dryrun, and the tests.
    """
    from bert_pytorch_tpu.training.state import TrainState

    ids, types, mask = (jnp.asarray(x) for x in sample_inputs)
    variables = jax.eval_shape(
        lambda r: model.init(r, ids, types, mask), jax.random.PRNGKey(0))
    pert_template = jax.tree.map(
        lambda sd: jnp.zeros(sd.shape, sd.dtype), variables["perturbations"])
    acts_shape = jax.eval_shape(
        lambda p, pe: model.apply(
            {"params": p, "perturbations": pe}, ids, types, mask,
            mutable=["kfac_in"])[1]["kfac_in"],
        state.params, pert_template)
    acts0 = jax.tree.map(lambda sd: jnp.zeros(sd.shape, sd.dtype), acts_shape,
                         is_leaf=lambda x: hasattr(x, "shape"))
    new_state = TrainState(step=state.step, params=state.params,
                           opt_state=state.opt_state,
                           precond_state=kfac.init(acts0, pert_template))
    return new_state, pert_template


def build_kfac_pretrain_step(
    model,
    tx: optax.GradientTransformation,
    kfac,
    pert_template: Any,
    schedule: Optional[optax.Schedule] = None,
    accum_steps: int = 1,
    max_predictions: Optional[int] = None,
    grad_dtype: Optional[Any] = None,
    zero1: Optional[Any] = None,
    health: Optional[HealthConfig] = None,
    nan_inject_step: Optional[int] = None,
    norm_reducer: Optional[Any] = None,
):
    """K-FAC variant of the train step (model built with
    config.kfac_taps=True; `kfac` is optim.kfac.KFAC; `pert_template` the
    'perturbations' collection from model.init on a microbatch).

    Order matches the reference's take_optimizer_step (run_pretraining.py:
    395-407): factor stats from this step's fwd/bwd -> preconditioner ->
    optimizer on the preconditioned grads. TrainState.precond_state carries
    the KFACState pytree so it checkpoints/restores with everything else.

    `zero1` shards the trailing LAMB update exactly as in
    build_pretrain_step; the constraint lands AFTER kfac.step because
    preconditioning contracts the full grad tensors against the factor
    inverses (sharding its input would force a gather inside the
    preconditioner instead of a reduce-scatter into the optimizer).

    `health` as in build_pretrain_step; under action='skip' the K-FAC
    factor/inverse state is guarded too — a poisoned batch's NaN statistics
    must not survive in the preconditioner. `nan_inject_step` as in
    build_pretrain_step (the fault-injection drill covers the K-FAC path
    too — its factor statistics are exactly the kind of state a NaN
    poisons silently).
    """
    from bert_pytorch_tpu.models import losses as _losses

    def loss_fn(params, perts, micro: Batch, rng):
        mlm_labels = micro["masked_lm_labels"]
        masked_positions = None
        if max_predictions is not None:
            masked_positions, mlm_labels = gather_masked_labels(
                mlm_labels, max_predictions)
        (mlm_logits, nsp_logits), mut = model.apply(
            {"params": params, "perturbations": perts},
            micro["input_ids"], micro.get("token_type_ids"),
            micro.get("attention_mask"),
            deterministic=False, masked_positions=masked_positions,
            rngs={"dropout": rng},
            mutable=["kfac_in"],
            **_packed_kwargs(micro))
        loss = _losses.pretraining_loss(
            mlm_logits, mlm_labels,
            nsp_logits, micro.get("next_sentence_labels"))
        correct, total = _losses.mlm_accuracy(mlm_logits, mlm_labels)
        return loss, ({"mlm_correct": correct, "mlm_total": total},
                      mut["kfac_in"])

    grad_fn = jax.value_and_grad(loss_fn, argnums=(0, 1), has_aux=True)
    zeros_perts = jax.tree.map(jnp.zeros_like, pert_template)

    # factor statistics are unaffected by bf16 grads (compute_stats
    # upcasts to fp32)
    cast_params = _param_caster(grad_dtype)

    rs = zero1 is not None and getattr(zero1, "reduce_scatter", False)
    if rs:
        one_micro = _build_rs_micro(model, zero1, max_predictions,
                                    kfac=kfac, zeros_perts=zeros_perts)
    else:
        def one_micro(params, micro, rng):
            (loss, (aux, acts)), (pgrads, pert_grads) = grad_fn(
                params, zeros_perts, micro, rng)
            stats = kfac.compute_stats(acts, pert_grads)
            return loss, aux, pgrads, stats

    def train_step(state: TrainState, batch: Batch, rng: jax.Array):
        rngs = jax.random.split(rng, accum_steps)
        gparams = _use_params(state, zero1, cast_params)
        if nan_inject_step is not None:
            gparams = inject_nonfinite(
                gparams, state.step + 1 == nan_inject_step)

        if accum_steps == 1:
            micro = jax.tree.map(lambda x: x[0], batch)
            loss, aux, grads, stats = one_micro(gparams, micro, rngs[0])
        else:
            def body(carry, inp):
                g_acc, s_acc, loss_acc, c_acc, t_acc = carry
                micro, r = inp
                loss, aux, g, s = one_micro(gparams, micro, r)
                return (jax.tree.map(lambda a, g_: a + g_.astype(a.dtype),
                                     g_acc, g),
                        jax.tree.map(jnp.add, s_acc, s),
                        loss_acc + loss,
                        c_acc + aux["mlm_correct"],
                        t_acc + aux["mlm_total"]), None

            zeros_g = _accum_zeros(gparams, accum_steps)
            micro0 = jax.tree.map(lambda x: x[0], batch)
            stats_shape = jax.eval_shape(
                lambda p, m, r: one_micro(p, m, r)[3],
                gparams, micro0, rngs[0])
            zeros_s = jax.tree.map(
                lambda sd: jnp.zeros(sd.shape, sd.dtype), stats_shape)
            init = (zeros_g, zeros_s, jnp.zeros([], jnp.float32),
                    jnp.zeros([], jnp.int32), jnp.zeros([], jnp.int32))
            (grads, stats, loss, correct, total), _ = jax.lax.scan(
                body, init, (batch, rngs))
            grads = jax.tree.map(lambda g: g / accum_steps, grads)
            stats = jax.tree.map(lambda s: s / accum_steps, stats)
            loss = loss / accum_steps
            aux = {"mlm_correct": correct, "mlm_total": total}

        lr = (schedule(state.step) if schedule is not None
              else kfac.config.learning_rate)
        if rs:
            # preconditioning contracts FULL grad tensors against the
            # factor inverses; the region's grads arrive reduce-scattered,
            # so gather them at the point of use (same per-leaf all-gather
            # economics as gather_on_use params) — _zero1_update re-pins
            # the preconditioned output to the shard layout
            grads = jax.lax.with_sharding_constraint(
                grads, zero1.param_shardings)
        kstate, grads = kfac.step(state.precond_state, stats, grads, lr)
        params, opt_state, grads = _zero1_update(tx, grads, state, zero1)
        grad_norm = (norm_reducer.global_norm_f32(grads)
                     if norm_reducer is not None
                     else _global_norm_f32(grads))
        metrics = {
            "loss": loss,
            "grad_norm": grad_norm,
            "mlm_accuracy": aux["mlm_correct"] / jnp.maximum(aux["mlm_total"], 1),
        }
        params, opt_state, kstate, telemetry = _apply_health(
            health, state, loss, grads, grad_norm, params, opt_state,
            metrics, precond_state=kstate)
        new_state = state.replace(step=state.step + 1, params=params,
                                  opt_state=opt_state, precond_state=kstate,
                                  telemetry=telemetry)
        if schedule is not None:
            metrics["learning_rate"] = schedule(state.step)
        return new_state, metrics

    return train_step


def build_debug_forward(model, max_predictions: Optional[int] = None
                        ) -> Callable:
    """Forward probe for tools/replay.py --bisect: fwd(params, micro, rng)
    -> (loss, taps) runs ONE microbatch's forward exactly as the train
    step's loss_fn would — same masked-position gathering, same packed-
    field threading (_packed_kwargs), same dropout rng plumbing — on a
    model built with config.debug_taps=True, returning the 'debug_taps'
    collection (embeddings / per-layer attention & mlp / pooler / heads)
    alongside the loss. Sharing this preprocessing with _pretrain_loss_fn
    is what keeps bisect from ever drifting from what training computed.
    `rng` is the per-microbatch key, i.e. jax.random.split(step_rng,
    accum_steps)[i] for microbatch i — the same derivation the step uses.
    """

    def fwd(params, micro: Batch, rng):
        mlm_labels = micro["masked_lm_labels"]
        masked_positions = None
        if max_predictions is not None:
            masked_positions, mlm_labels = gather_masked_labels(
                mlm_labels, max_predictions)
        (mlm_logits, nsp_logits), mut = model.apply(
            {"params": params},
            micro["input_ids"], micro.get("token_type_ids"),
            micro.get("attention_mask"),
            deterministic=False, masked_positions=masked_positions,
            rngs={"dropout": rng},
            mutable=["debug_taps"],
            **_packed_kwargs(micro))
        loss = losses.pretraining_loss(
            mlm_logits, mlm_labels,
            nsp_logits, micro.get("next_sentence_labels"))
        return loss, mut.get("debug_taps", {})

    return fwd


def build_eval_step(model, loss_fn_builder: Callable = _pretrain_loss_fn):
    """eval_step(params, batch) -> metrics; batch unstacked (no accum axis).
    Uses the same loss_fn_builder contract as build_pretrain_step
    (loss_fn(params, batch, rng, deterministic) -> (loss, aux))."""
    loss_fn = loss_fn_builder(model)

    def eval_step(params, batch: Batch):
        dummy_rng = jax.random.PRNGKey(0)
        loss, aux = loss_fn(params, batch, dummy_rng, deterministic=True)
        metrics = {"loss": loss}
        if "mlm_total" in aux:
            metrics["mlm_accuracy"] = (
                aux["mlm_correct"] / jnp.maximum(aux["mlm_total"], 1))
        return metrics

    return eval_step


def stack_microbatches(batch: Dict[str, Any], accum_steps: int
                       ) -> Dict[str, Any]:
    """Host-side: (B, ...) numpy batch -> (accum, B/accum, ...). The loader
    delivers flat per-host batches; this reshapes for the scan contract."""
    import numpy as np

    def split(x):
        x = np.asarray(x)
        if x.shape[0] % accum_steps:
            raise ValueError(
                f"batch dim {x.shape[0]} not divisible by accum {accum_steps}")
        return x.reshape(accum_steps, x.shape[0] // accum_steps, *x.shape[1:])

    return {k: split(v) for k, v in batch.items()}
