"""Training subsystem: sharded train-step builders, checkpointing, logging.

The reference's training machinery lived inline in run_pretraining.py
(setup_training/prepare_*/take_optimizer_step/forward_backward_pass,
run_pretraining.py:170-451). Here it is a library layer so pretraining,
SQuAD, and NER share one implementation of the jitted step, the checkpoint
manager, and the metric logger.
"""

from bert_pytorch_tpu.training.state import (  # noqa: F401
    TrainState,
    make_sharded_state,
    unbox,
)
from bert_pytorch_tpu.training.pretrain import (  # noqa: F401
    build_pretrain_step,
    build_eval_step,
    init_kfac_state,
)
from bert_pytorch_tpu.training.checkpoint import CheckpointManager  # noqa: F401
from bert_pytorch_tpu.training.metrics import MetricLogger  # noqa: F401
