"""Structured reports over lowered/compiled XLA programs.

The worst regressions this repo has hit were *program-structure* bugs that
no unit test could see until a multichip bench ran: fail-open sharding
gates (round 7), GSPMD forking the ZeRO-1 gather into extra all-gathers
(round 11, until now guarded only by one ad-hoc regex in
tests/test_zero1.py), 75-94%-collective-time meshes (MULTICHIP_r07). The
compiled program is a perfectly inspectable artifact — `jit(f).lower(...)
.compile().as_text()` is stable HLO text — so this module parses it into a
structured report the rule framework (analysis/passes.py) and the CI gate
(tools/graphcheck.py) consume:

- collective inventory: all-gather / all-reduce / reduce-scatter /
  collective-permute / all-to-all counts, result shapes, bytes, replica
  group sizes, and an estimated bytes-moved figure per kind;
- copy/transpose/fusion/dot op counts (the layout-regression smells the
  round-6 kernel work was chasing);
- the input→output buffer-donation table: which donated parameters XLA
  actually aliased (`input_output_alias`) vs accepted-but-never-aliased
  (`buffer_donor` — the double-HBM miss `donate_argnums` silently allows);
- per-input leaf table (paths from the argument pytree, compiled
  in-shardings, expected shardings from the parallel plan) for the
  unexpected-replication pass;
- a `fingerprint` (collective counts + donation summary hash) small enough
  to ride in flight-recorder manifests and MetricLogger run headers, so
  tools/replay.py can warn when a replayed program's structure diverges
  from the recorded one.

Everything that parses TEXT is stdlib-only and importable without jax
(tools/graphcheck.py --validate-budgets relies on this, mirroring
tools/perfboard.py); the helpers that touch compiled objects or pytrees
import jax lazily inside the function.
"""

from __future__ import annotations

import hashlib
import json
import re
from typing import Any, Dict, List, Optional, Sequence

COLLECTIVE_KINDS = ("all-gather", "all-reduce", "reduce-scatter",
                    "collective-permute", "all-to-all")

# layout/fusion smells tracked alongside the collectives
TRACKED_OPS = ("copy", "transpose", "fusion", "dot", "dynamic-update-slice")

_DTYPE_BYTES = {
    "pred": 1, "s2": 1, "u2": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1,
    "f8e5m2": 1, "f8e4m3": 1, "f8e4m3fn": 1, "f8e4m3b11fnuz": 1,
    "f8e5m2fnuz": 1, "f8e4m3fnuz": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

# one HLO instruction: `%name = <result-shape> opcode(operands...)`.
# The result shape is either a tuple `(f32[..]{..}, ...)` (no nested
# parens in HLO shape syntax — layouts use braces) or a single
# `dtype[dims]{layout}`.
_INSTR_RE = re.compile(
    r"=\s*(?:\([^)]*\)|[a-z0-9]+\[[0-9,]*\](?:\{[^}]*\})?)\s+"
    r"(?P<op>[a-z][a-z0-9-]*)\(")

_SHAPE_RE = re.compile(r"([a-z][a-z0-9]*)\[([0-9,]*)\]")

# `replica_groups=[8,1]<=[8]` (iota form: [n_groups, group_size]) or the
# explicit `replica_groups={{0,1},{2,3}}` form
_GROUPS_IOTA_RE = re.compile(r"replica_groups=\[(\d+),(\d+)\]")
_GROUPS_LIST_RE = re.compile(r"replica_groups=\{(\{[^}]*\})")

_ALIAS_ENTRY_RE = re.compile(
    r"\{[0-9,\s]*\}:\s*\(\s*(\d+)\s*,\s*\{[0-9,\s]*\}\s*(?:,\s*[\w-]+\s*)?\)")
_DONOR_ENTRY_RE = re.compile(r"\(\s*(\d+)\s*,\s*\{[0-9,\s]*\}\s*\)")


def _result_shapes(line: str, async_start: bool = False) -> list:
    """(dtype, dims) pairs of the instruction's result shape(s) — the
    text between '=' and the opcode. `async_start`: an async collective's
    tuple result is `(operand_buffer, output)` — only the LAST element is
    the collective's output; counting the whole tuple would double-count
    the traffic (~2x on all-reduce-start)."""
    m = _INSTR_RE.search(line)
    lhs = (line[line.index("=") + 1:m.start("op")] if m is not None
           else line.split("=", 1)[1])
    shapes = _SHAPE_RE.findall(lhs)
    if async_start and len(shapes) > 1:
        shapes = shapes[-1:]
    return shapes


def _shapes_bytes(shapes: list) -> int:
    total = 0
    for dt, dims in shapes:
        if dt not in _DTYPE_BYTES:
            continue
        n = 1
        for d in dims.split(","):
            if d.strip():
                n *= int(d)
        total += n * _DTYPE_BYTES[dt]
    return total


def _group_size(line: str, num_partitions: Optional[int]) -> Optional[int]:
    m = _GROUPS_IOTA_RE.search(line)
    if m:
        return int(m.group(2))
    m = _GROUPS_LIST_RE.search(line)
    if m:
        return len([t for t in m.group(1).strip("{}").split(",") if t.strip()])
    return num_partitions


def _braced_segment(text: str, opener: str) -> Optional[str]:
    """The balanced-brace body following `opener` (which ends with '{'),
    or None when the opener is absent. Entries inside the module-header
    tables contain nested braces (`{0}: (0, {}, may-alias)`), so a split
    on '}' under-reads — count depth instead."""
    start = text.find(opener)
    if start < 0:
        return None
    depth, i = 1, start + len(opener)
    while i < len(text) and depth:
        if text[i] == "{":
            depth += 1
        elif text[i] == "}":
            depth -= 1
        i += 1
    return text[start + len(opener):i - 1]


def _est_bytes_moved(kind: str, bytes_out: int, group_size: Optional[int]
                     ) -> int:
    """Rough per-participant wire bytes for one collective (ring algorithm
    estimates — attribution fodder, not a profiler): all-gather receives
    (g-1)/g of its output, all-reduce moves ~2x that (reduce-scatter +
    gather phases), reduce-scatter's input is g x its output, a permute
    moves its full payload."""
    g = group_size or 2
    if g <= 1:
        return 0
    if kind == "all-gather":
        return bytes_out * (g - 1) // g
    if kind == "all-reduce":
        return 2 * bytes_out * (g - 1) // g
    if kind == "reduce-scatter":
        return bytes_out * (g - 1)
    return bytes_out  # collective-permute / all-to-all


def parse_hlo_module(text: str) -> Dict[str, Any]:
    """Compiled HLO text -> the structural summary (stdlib only).

    Counts opcodes (async `-start` forms count once; `-done` halves are
    skipped so nothing double-counts), sizes collective results, and parses
    the module header's donation tables. Deterministic for fixed input.
    """
    counts: Dict[str, int] = {k: 0 for k in COLLECTIVE_KINDS}
    op_counts: Dict[str, int] = {k: 0 for k in TRACKED_OPS}
    coll_bytes: Dict[str, int] = {k: 0 for k in COLLECTIVE_KINDS}
    est_moved: Dict[str, int] = {k: 0 for k in COLLECTIVE_KINDS}
    shapes: Dict[str, int] = {}
    num_partitions = None
    header = ""
    for line in text.splitlines():
        if not header and line.startswith("HloModule"):
            header = line
            m = re.search(r"num_partitions=(\d+)", line)
            if m:
                num_partitions = int(m.group(1))
            continue
        m = _INSTR_RE.search(line)
        if m is None:
            continue
        op = m.group("op")
        if op.endswith("-done"):
            continue
        base = op[:-6] if op.endswith("-start") else op
        if base in counts:
            counts[base] += 1
            out_shapes = _result_shapes(line, async_start=(base != op))
            b = _shapes_bytes(out_shapes)
            coll_bytes[base] += b
            gs = _group_size(line, num_partitions)
            est_moved[base] += _est_bytes_moved(base, b, gs)
            if out_shapes:
                dt, dims = out_shapes[0]
                key = f"{base} {dt}[{dims}]"
            else:
                key = base
            shapes[key] = shapes.get(key, 0) + 1
        elif base in op_counts:
            op_counts[base] += 1

    donation = {"aliased": [], "donated_unaliased": []}
    seg = _braced_segment(header, "input_output_alias={")
    if seg is not None:
        donation["aliased"] = sorted(
            {int(p) for p in _ALIAS_ENTRY_RE.findall(seg)})
    seg = _braced_segment(header, "buffer_donor={")
    if seg is not None:
        donation["donated_unaliased"] = sorted(
            {int(p) for p in _DONOR_ENTRY_RE.findall(seg)})
    donation["n_aliased"] = len(donation["aliased"])
    donation["n_donated_unaliased"] = len(donation["donated_unaliased"])

    return {
        "num_partitions": num_partitions,
        "collective_counts": counts,
        "collective_bytes": coll_bytes,
        "collective_est_bytes_moved": est_moved,
        "collective_shapes": dict(sorted(shapes.items())),
        "op_counts": op_counts,
        "donation": donation,
    }


def collective_counts(text: str) -> Dict[str, int]:
    """Just the per-kind collective counts of an HLO text — the one
    counter tests/test_zero1.py, bench.py --multichip, and the budget pass
    all share (replacing the ad-hoc per-test regexes)."""
    return parse_hlo_module(text)["collective_counts"]


def collective_inventory(text: str) -> Dict[str, Any]:
    """Counts + bytes + estimated wire traffic, the per-variant block
    bench.py --multichip embeds next to its time_breakdown."""
    rep = parse_hlo_module(text)
    return {
        "counts": {k: v for k, v in rep["collective_counts"].items() if v},
        "bytes_out": {k: v for k, v in rep["collective_bytes"].items() if v},
        "est_bytes_moved": {
            k: v for k, v in rep["collective_est_bytes_moved"].items() if v},
        "shapes": rep["collective_shapes"],
    }


def stablehlo_dot_dtypes(lowered_text: str) -> Dict[str, int]:
    """Result element types of every dot/convolution in the LOWERED
    (StableHLO) program. The dtype lint must read the pre-optimization
    text: backends legally rewrite dtypes after this point (the CPU
    backend upcasts bf16 matmuls to f32 wholesale), so only the lowering
    reflects what the model code asked for."""
    out: Dict[str, int] = {}
    pat = re.compile(
        r"stablehlo\.(?:dot_general|dot|convolution)\b[^\n]*->\s*"
        r"tensor<([^>]*)>")
    for m in pat.finditer(lowered_text):
        elem = m.group(1).split("x")[-1]
        out[elem] = out.get(elem, 0) + 1
    return out


# -- jax-side report assembly -------------------------------------------------


def sharding_leaves(tree: Any, expected: Optional[Sequence] = None,
                    ) -> List[Dict[str, Any]]:
    """Per-leaf sharding table of a pytree of concrete arrays, Shape-
    DtypeStructs-with-sharding, or NamedShardings: path, shape, bytes,
    actual spec + replicated flag, per-device bytes, and (optionally) the
    expected sharding. `expected` is a flat sequence aligned with the
    tree's flatten order — entries are NamedShardings (what the plan says
    this leaf's layout should be) or None (no expectation). This is the
    one leaf walk behind parallel/zero.assert_moments_sharded, the K-FAC
    shard audit, and the compiled-program replication pass."""
    import jax

    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    if expected is not None and len(expected) != len(flat):
        raise ValueError(
            f"expected-sharding list has {len(expected)} entries for "
            f"{len(flat)} tree leaves — derive it from the same tree")
    rows: List[Dict[str, Any]] = []
    for i, (path, leaf) in enumerate(flat):
        sh = getattr(leaf, "sharding", None) \
            if not _is_sharding(leaf) else leaf
        shape = tuple(getattr(leaf, "shape", ()) or ())
        dtype = getattr(leaf, "dtype", None)
        try:
            import numpy as np

            itemsize = np.dtype(dtype).itemsize if dtype is not None else 0
        except TypeError:
            itemsize = getattr(dtype, "itemsize", 0) or 0
        nbytes = itemsize
        for d in shape:
            nbytes *= d
        row: Dict[str, Any] = {
            "path": jax.tree_util.keystr(path),
            "shape": list(shape),
            "dtype": str(dtype) if dtype is not None else None,
            "bytes": int(nbytes),
            "spec": None,
            "replicated": None,
            "per_device_bytes": int(nbytes),
        }
        if sh is not None and hasattr(sh, "is_fully_replicated"):
            row["replicated"] = bool(sh.is_fully_replicated)
            if hasattr(sh, "spec"):
                row["spec"] = str(sh.spec)
            if shape and hasattr(sh, "shard_shape"):
                try:
                    local = sh.shard_shape(shape)
                    per = itemsize
                    for d in local:
                        per *= d
                    row["per_device_bytes"] = int(per)
                except Exception:
                    pass
        if expected is not None:
            exp = expected[i]
            if exp is not None and hasattr(exp, "is_fully_replicated"):
                row["expected_spec"] = str(getattr(exp, "spec", exp))
                row["expected_sharded"] = not exp.is_fully_replicated
                if sh is not None:
                    # the sharding_rules pass contract: True/False when a
                    # comparison happened, absent otherwise. Equivalence,
                    # not string equality — trivial mesh axes and trailing
                    # None entries must not count as violations.
                    try:
                        row["matches_expected"] = bool(
                            sh.is_equivalent_to(exp, len(shape)))
                    except Exception:
                        row["matches_expected"] = (
                            str(getattr(sh, "spec", sh))
                            == str(getattr(exp, "spec", exp)))
            else:
                row["expected_spec"] = None
                row["expected_sharded"] = False
        rows.append(row)
    return rows


def _is_sharding(x: Any) -> bool:
    return type(x).__name__.endswith("Sharding")


def program_report(compiled: Any, args: Optional[tuple] = None,
                   expected: Optional[Sequence] = None,
                   lowered_text: Optional[str] = None,
                   label: Optional[str] = None,
                   rules: Optional[Sequence[Optional[str]]] = None
                   ) -> Dict[str, Any]:
    """Full structured report of one compiled program.

    `compiled` is a jax.stages.Compiled (from jit(f).lower(...).compile()).
    `args` (the example args the program was lowered with) adds the
    per-input leaf table with paths + compiled in-shardings; `expected` is
    the flat expected-sharding list for those args (sharding_leaves
    contract — each comparison lands as the row's `matches_expected`
    bool, what the sharding_rules pass gates). `rules`, aligned with
    `expected`, stamps each row with the rules-table label that derived
    its expectation (parallel/rules.py), so a finding can name the rule.
    `lowered_text` (lowered.as_text(), StableHLO) adds the dot-dtype
    census the dtype lint reads.
    """
    rep = parse_hlo_module(compiled.as_text())
    rep["label"] = label
    if lowered_text is not None:
        rep["dot_dtypes"] = stablehlo_dot_dtypes(lowered_text)
    try:
        ma = compiled.memory_analysis()
        rep["memory"] = {
            k: int(getattr(ma, k)) for k in (
                "argument_size_in_bytes", "output_size_in_bytes",
                "temp_size_in_bytes", "alias_size_in_bytes",
                "generated_code_size_in_bytes")
            if hasattr(ma, k)}
    except Exception:
        rep["memory"] = None
    if args is not None:
        import jax

        # align the executable's input shardings with the arg tree.
        # Two kinds of None complicate this: keep_unused=False PRUNES
        # unused args (their slot in input_shardings is None while the arg
        # tree has a real leaf), and structural Nones (empty optional
        # fields, e.g. TrainState.precond_state) appear in BOTH trees.
        # Flatten both with None-as-leaf, drop the structural pairs, and
        # what remains lines up 1:1 with the default tree_leaves order —
        # the order `expected` is derived in.
        none_leaf = {"is_leaf": lambda x: x is None}
        in_sh = jax.tree_util.tree_leaves(compiled.input_shardings[0],
                                          **none_leaf)
        flat = jax.tree_util.tree_flatten_with_path(args, **none_leaf)[0]
        if len(in_sh) == len(flat):
            triples = [(p, a, s) for (p, a), s in zip(flat, in_sh)
                       if a is not None]
            rows = []
            for i, (path, a, s) in enumerate(triples):
                row_tree = jax.ShapeDtypeStruct(
                    getattr(a, "shape", ()), getattr(a, "dtype", None),
                    sharding=s)
                row = sharding_leaves(
                    [row_tree],
                    expected=[expected[i]] if expected is not None
                    else None)[0]
                row["path"] = jax.tree_util.keystr(path)
                if rules is not None and i < len(rules) \
                        and rules[i] is not None:
                    row["rule"] = rules[i]
                rows.append(row)
            aliased = set(rep["donation"]["aliased"])
            unaliased = set(rep["donation"]["donated_unaliased"])
            # executable parameter numbers count only the KEPT args
            # (pruned ones have a None sharding slot)
            param = 0
            for row, (_, _, s) in zip(rows, triples):
                if s is None:
                    row["pruned"] = True
                    continue
                row["param"] = param
                row["aliased"] = param in aliased
                if param in unaliased:
                    row["donated_unaliased"] = True
                param += 1
            rep["inputs"] = rows
    return rep


# -- fingerprint ---------------------------------------------------------------


def _short_hash(obj: Any) -> str:
    return hashlib.sha256(
        json.dumps(obj, sort_keys=True).encode()).hexdigest()[:16]


def fingerprint_of(report: Dict[str, Any]) -> Dict[str, Any]:
    """Compact structural identity of a program report: collective counts
    plus a donation-summary hash. Small enough for a flight-recorder
    manifest or a MetricLogger run header; tools/replay.py compares the
    recorded one against the replayed program's."""
    donation = report.get("donation", {})
    dsum = {"aliased": donation.get("aliased", []),
            "donated_unaliased": donation.get("donated_unaliased", [])}
    counts = {k: v for k, v in
              report.get("collective_counts", {}).items() if v}
    return {
        "collective_counts": counts,
        "n_aliased": donation.get("n_aliased", 0),
        "n_donated_unaliased": donation.get("n_donated_unaliased", 0),
        "donation_hash": _short_hash(dsum),
        "num_partitions": report.get("num_partitions"),
        "hash": _short_hash({"collectives": counts, "donation": dsum}),
    }


def program_fingerprint(compiled: Any) -> Dict[str, Any]:
    """fingerprint_of(parse) straight from a compiled object, stamped with
    the live platform (fingerprints are only comparable same-platform —
    backends lower to different collective schedules)."""
    fp = fingerprint_of(parse_hlo_module(compiled.as_text()))
    try:
        import jax

        fp["platform"] = jax.devices()[0].platform
    except Exception:
        fp["platform"] = None
    return fp


def compare_fingerprints(recorded: Optional[Dict[str, Any]],
                         replayed: Optional[Dict[str, Any]]
                         ) -> tuple[bool, List[str]]:
    """(comparable, diffs). Not comparable when either side is missing or
    platform/partition count differ (a CPU replay of a TPU bundle is a
    different backend's schedule, not a regression). Comparable with empty
    diffs = same program structure."""
    if not recorded or not replayed:
        return False, []
    for k in ("platform", "num_partitions"):
        if recorded.get(k) != replayed.get(k):
            return False, [f"{k}: recorded {recorded.get(k)} vs "
                           f"replayed {replayed.get(k)} (not comparable)"]
    diffs: List[str] = []
    rc = recorded.get("collective_counts", {})
    pc = replayed.get("collective_counts", {})
    for kind in sorted(set(rc) | set(pc)):
        if rc.get(kind, 0) != pc.get(kind, 0):
            diffs.append(f"collective {kind}: recorded {rc.get(kind, 0)} "
                         f"vs replayed {pc.get(kind, 0)}")
    if recorded.get("donation_hash") != replayed.get("donation_hash"):
        diffs.append(
            f"donation summary: recorded {recorded.get('n_aliased')} "
            f"aliased/{recorded.get('n_donated_unaliased')} missed vs "
            f"replayed {replayed.get('n_aliased')}/"
            f"{replayed.get('n_donated_unaliased')}")
    return True, diffs
