"""Static analysis of lowered/compiled XLA programs.

`hlo` parses `jit(...).lower(...).compile().as_text()` into structured
program reports (collective inventory, donation table, per-input sharding
leaves, fingerprints); `passes` is the rule framework that turns a report
plus declared expectations into findings. Both are importable without jax
(text parsing is stdlib-only; pytree helpers import jax lazily), which is
what lets tools/graphcheck.py --validate-budgets run on a login host.
"""

from bert_pytorch_tpu.analysis.hlo import (collective_counts,  # noqa: F401
                                           collective_inventory,
                                           compare_fingerprints,
                                           fingerprint_of, parse_hlo_module,
                                           program_fingerprint,
                                           program_report, sharding_leaves,
                                           stablehlo_dot_dtypes)
from bert_pytorch_tpu.analysis.passes import (Finding,  # noqa: F401
                                              has_errors,
                                              replication_findings,
                                              run_passes)
