"""Rule framework over program reports: findings with severity.

Each pass maps (program report, declared expectations) -> findings. A
report comes from analysis/hlo.py (program_report on a compiled step, or
a hand-built dict in tests — the passes only read plain dicts, so the
whole module is stdlib-only and importable without jax; tools/graphcheck
--validate-budgets depends on that).

Shipped rules:

- collective_budget: per-kind op-count ceilings. Over budget is an error
  naming the op and both counts (the GSPMD-forked-all-gather class, round
  11); under budget is an info suggesting a re-baseline so the win locks
  in.
- donation: every donated argument must actually alias an output
  (`buffer_donor` entries are donate_argnums XLA accepted but never
  aliased — a silent double-HBM copy of that buffer); large undonated
  inputs are flagged as double-HBM candidates.
- replication: a leaf whose compiled in-sharding is fully replicated
  while the parallel plan expects it sharded (the fail-open-gate class,
  round 7) — the generalization of parallel/zero.assert_moments_sharded
  to all of params / moments / K-FAC state.
- sharding_rules: every input leaf with a rules-table-derived expected
  sharding (parallel/rules.py — the one logical-axis table) must compile
  with EXACTLY that in-sharding, not merely a non-replicated one; a
  mismatch names the rule, the leaf, and both shardings. A floor on the
  number of verified leaves catches the expectation derivation itself
  failing open.
- dtype: f32 matmuls in the LOWERED program when bf16 compute is
  configured (reads the StableHLO dot census — compiled HLO is useless
  here, backends rewrite dtypes).
- memory: static per-device estimate (arguments + temps + outputs -
  aliased) against an HBM budget.

tools/graphcheck.py wires these as the CI gate; docs/OBSERVABILITY.md
"Static graph analysis" is the operator guide.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Sequence

SEVERITIES = ("error", "warning", "info")


@dataclasses.dataclass
class Finding:
    severity: str  # 'error' fails the gate; 'warning'/'info' report only
    rule: str
    message: str
    op: Optional[str] = None     # HLO op kind the finding names, if any
    leaf: Optional[str] = None   # input-leaf path the finding names, if any

    def __str__(self) -> str:
        where = "".join(
            f" [{k}={v}]" for k, v in (("op", self.op), ("leaf", self.leaf))
            if v)
        return f"{self.severity.upper()} [{self.rule}] {self.message}{where}"

    def to_dict(self) -> Dict[str, Any]:
        return {k: v for k, v in dataclasses.asdict(self).items()
                if v is not None}


def _mb(n: float) -> str:
    return f"{n / 2**20:.2f} MB"


# -- rules ---------------------------------------------------------------------


def check_collective_budget(report: Dict[str, Any],
                            budget: Dict[str, int]) -> List[Finding]:
    counts = report.get("collective_counts", {}) or {}
    out: List[Finding] = []
    for kind in sorted(budget):
        limit = int(budget[kind])
        n = int(counts.get(kind, 0))
        if n > limit:
            out.append(Finding(
                "error", "collective_budget",
                f"{kind}: {n} ops compiled, budget is {limit} — the "
                f"program grew {n - limit} extra {kind}(s); if intentional "
                "re-baseline with graphcheck --write-budgets", op=kind))
        elif n < limit:
            out.append(Finding(
                "info", "collective_budget",
                f"{kind}: {n} ops compiled, below the budget of {limit} — "
                "re-baseline to lock the improvement in", op=kind))
    return out


def check_donation(report: Dict[str, Any],
                   expect: Dict[str, Any]) -> List[Finding]:
    don = report.get("donation", {}) or {}
    inputs = report.get("inputs") or []
    by_param = {row.get("param"): row for row in inputs}
    out: List[Finding] = []
    # `max_donated_unaliased` (default 0 — absent in every pre-round-15
    # budget, same strictness): a small BUDGETED orphan allowance for
    # programs where XLA's buffer assignment pairs a donated buffer with
    # a shape-twin output and leaves the twin's own donor unmatched (net
    # HBM is a wash — the kfac bucketed combo carries 3 such factor
    # leaves). Within the ceiling each orphan is still a named WARNING;
    # one past it is an error, so growth cannot hide.
    allowed = int(expect.get("max_donated_unaliased", 0))
    orphans = don.get("donated_unaliased", [])
    for p in orphans:
        row = by_param.get(p, {})
        out.append(Finding(
            "error" if len(orphans) > allowed else "warning", "donation",
            f"input #{p} was donated (donate_argnums) but XLA never "
            f"aliased it into an output — its "
            f"{_mb(row.get('bytes', 0))} live twice in HBM for the whole "
            "step"
            + (f" ({len(orphans)} orphan donor(s) within the budgeted "
               f"allowance of {allowed})" if len(orphans) <= allowed
               else ""),
            op="buffer_donor",
            leaf=row.get("path")))
    min_aliased = expect.get("min_aliased")
    if min_aliased is not None and don.get("n_aliased", 0) < int(min_aliased):
        out.append(Finding(
            "error", "donation",
            f"only {don.get('n_aliased', 0)} inputs are donation-aliased, "
            f"expected at least {min_aliased} — did a jit site lose its "
            "donate_argnums?", op="input_output_alias"))
    warn_bytes = expect.get("undonated_warn_bytes")
    if warn_bytes is not None:
        for row in inputs:
            if row.get("aliased") or row.get("donated_unaliased"):
                continue
            if row.get("bytes", 0) >= int(warn_bytes):
                out.append(Finding(
                    "warning", "donation",
                    f"undonated input of {_mb(row['bytes'])} — if this is "
                    "carried state (params/moments), donating it halves "
                    "its HBM residency", leaf=row.get("path")))
    return out


def replication_findings(leaves: Sequence[Dict[str, Any]],
                         rule: str = "replication") -> List[Finding]:
    """The core unexpected-replication check over a leaf table
    (analysis/hlo.sharding_leaves contract): expected sharded, actually
    fully replicated -> error naming the exact leaf."""
    out: List[Finding] = []
    for row in leaves:
        if row.get("expected_sharded") and row.get("replicated"):
            out.append(Finding(
                "error", rule,
                f"leaf is fully replicated but the plan expects "
                f"{row.get('expected_spec')} (shape "
                f"{tuple(row.get('shape', ()))}) — a sharding gate "
                "failed open", leaf=row.get("path")))
    return out


def check_replication(report: Dict[str, Any],
                      expect: Any = True) -> List[Finding]:
    """Per-leaf expected-vs-compiled check, plus (when `expect` is a dict
    with `min_sharded_inputs`) a floor on how many inputs compiled
    non-replicated at all — the count catches a fail-open state
    construction even when the per-leaf expectation shares its root cause
    with the regression."""
    inputs = report.get("inputs") or []
    out = replication_findings(inputs)
    floor = expect.get("min_sharded_inputs") \
        if isinstance(expect, dict) else None
    if floor is not None:
        n = sum(1 for r in inputs if r.get("replicated") is False)
        if n < int(floor):
            out.append(Finding(
                "error", "replication",
                f"only {n} program inputs compiled with a sharded layout, "
                f"budget floor is {floor} — state construction failed "
                "open (moments/params born replicated)",
                op="input_shardings"))
    return out


def check_sharding_rules(report: Dict[str, Any],
                         expect: Any = True) -> List[Finding]:
    """Verify every compiled in-sharding against the spec the
    logical-axis-rules table derived for it. The report rows carry the
    verdict (`matches_expected`, computed sharding-object-side by
    analysis/hlo.sharding_leaves so this pass stays jax-free) plus the
    deriving rule's label (`rule`) and both spec strings; a False is an
    error naming all three. `expect` may set `min_verified`: the floor
    on how many leaves carried an expectation at all — the count catches
    the derivation failing open (every expectation lost = every per-leaf
    check silently vacuous)."""
    inputs = report.get("inputs") or []
    out: List[Finding] = []
    n_checked = 0
    for row in inputs:
        verdict = row.get("matches_expected")
        if verdict is None:
            continue
        n_checked += 1
        if verdict is False:
            out.append(Finding(
                "error", "sharding_rules",
                f"compiled in-sharding {row.get('spec') or 'replicated'} "
                f"does not match the rules-table spec "
                f"{row.get('expected_spec')} derived by rule "
                f"[{row.get('rule') or 'unlabeled'}]",
                op="input_shardings", leaf=row.get("path")))
    floor = expect.get("min_verified") if isinstance(expect, dict) else None
    if floor is not None and n_checked < int(floor):
        out.append(Finding(
            "error", "sharding_rules",
            f"only {n_checked} input leaves carried a rules-table "
            f"expectation, floor is {floor} — the spec derivation failed "
            "open (the per-leaf checks above are vacuous)",
            op="input_shardings"))
    if not out:
        out.append(Finding(
            "info", "sharding_rules",
            f"{n_checked} input leaves match their rules-table specs"))
    return out


def check_dtype(report: Dict[str, Any],
                expect: Dict[str, Any]) -> List[Finding]:
    configured = str(expect.get("compute_dtype", "f32")).lower()
    dd = report.get("dot_dtypes")
    if dd is None:
        return [Finding("info", "dtype",
                        "no lowered (StableHLO) text in the report — "
                        "dtype lint skipped")]
    if configured in ("f32", "float32"):
        return []
    max_f32 = int(expect.get("max_f32_dots", 0))
    n32 = int(dd.get("f32", 0))
    if n32 > max_f32:
        return [Finding(
            "error", "dtype",
            f"{n32} f32 matmul(s) in the lowered program but compute "
            f"dtype is configured {configured} (budget {max_f32}) — an "
            "unintended upcast is burning 2x matmul bytes", op="dot")]
    return []


def estimate_device_bytes(report: Dict[str, Any]) -> Optional[int]:
    """Static per-device live-bytes estimate from the compiled program's
    buffer stats: arguments (params + optimizer state + batch at their
    per-partition shapes) + XLA temp buffers + outputs, minus what
    aliasing reuses. Peak may transiently exceed this (XLA's own
    accounting is the temp term); it is the right order for an HBM-fit
    gate."""
    mem = report.get("memory")
    if not isinstance(mem, dict):
        return None
    try:
        return (int(mem.get("argument_size_in_bytes", 0))
                + int(mem.get("temp_size_in_bytes", 0))
                + int(mem.get("output_size_in_bytes", 0))
                - int(mem.get("alias_size_in_bytes", 0)))
    except (TypeError, ValueError):
        return None


def check_memory(report: Dict[str, Any],
                 expect: Dict[str, Any]) -> List[Finding]:
    budget_mb = expect.get("budget_mb")
    if budget_mb is None:
        return []
    est = estimate_device_bytes(report)
    if est is None:
        return [Finding("info", "memory",
                        "no memory_analysis in the report — static HBM "
                        "estimate skipped")]
    if est > float(budget_mb) * 2**20:
        return [Finding(
            "error", "memory",
            f"static per-device estimate {_mb(est)} exceeds the "
            f"{budget_mb} MB HBM budget (args+temps+outputs-aliased)")]
    return [Finding(
        "info", "memory",
        f"static per-device estimate {_mb(est)} within the "
        f"{budget_mb} MB budget")]


# -- driver --------------------------------------------------------------------

# expectation key -> rule. Order is report order in the gate output.
PASSES: Dict[str, Callable[..., List[Finding]]] = {
    "collective_budget": check_collective_budget,
    "donation": check_donation,
    "replication": check_replication,
    "sharding_rules": check_sharding_rules,
    "dtype": check_dtype,
    "memory": check_memory,
}


def run_passes(report: Dict[str, Any],
               expectations: Dict[str, Any]) -> List[Finding]:
    """Apply every pass whose expectation key is declared. Unknown keys
    are a loud error finding (a typo in a budget file must not silently
    skip its rule)."""
    findings: List[Finding] = []
    for key, expect in expectations.items():
        rule = PASSES.get(key)
        if rule is None:
            findings.append(Finding(
                "error", "expectations",
                f"unknown expectation key '{key}' (valid: "
                f"{', '.join(sorted(PASSES))})"))
            continue
        findings.extend(rule(report, expect))
    return findings


def has_errors(findings: Sequence[Finding]) -> bool:
    return any(f.severity == "error" for f in findings)
