"""bert_pytorch_tpu — a TPU-native (JAX/XLA/Pallas/pjit) BERT pretraining framework.

A ground-up re-design of the capabilities of the skye-glitch/BERT-PyTorch
reference stack (NVIDIA-derived BERT pretraining with LAMB/K-FAC, sharded-HDF5
streaming data, SQuAD/NER finetuning) for TPU hardware:

- compute path: Flax modules compiled by XLA, with Pallas kernels for the hot
  fused ops (LayerNorm, bias-GELU, blockwise attention, multi-param LAMB update)
- parallelism: a single `jax.sharding.Mesh` with ``(data, fsdp, model, seq)``
  axes driven by `jit`/`shard_map`; gradients travel over ICI via XLA
  collectives instead of NCCL all-reduce
- precision: bf16 compute / fp32 params (no GradScaler state, unlike the
  reference's apex AMP path)
- data: the same sharded gzip'd-HDF5 container format as the reference's
  offline pipeline, streamed per-host with a resumable contiguous-chunk sampler

Layer map (mirrors SURVEY.md §1 of the reference, re-architected):
  models/    BERT encoder + task heads (reference: src/modeling.py)
  data/      streaming dataset, masking, tokenization (reference: src/dataset.py,
             src/tokenization.py, src/ner_dataset.py)
  optim/     LAMB/Adam/schedulers/K-FAC (reference: src/optimization.py,
             src/schedulers.py, external apex + kfac_pytorch)
  parallel/  mesh construction, distributed init, sharding rules, ring attention
  ops/       Pallas TPU kernels (reference: apex CUDA kernels)
  training/  train-step builders, checkpointing, logging (reference:
             run_pretraining.py internals)
  native/    C++ runtime pieces (tokenizer; reference: HF tokenizers in Rust)
"""

__version__ = "0.1.0"

from bert_pytorch_tpu.config import BertConfig  # noqa: F401
