"""Hung-step watchdog: a daemon thread that notices when the loop stops.

A wedged accelerator (collective deadlock, PJRT hang) or a dead data
source does not crash a training job — it freezes it, silently, until a
human notices the metrics stopped. The health pack cannot see it (no
step completes, so no readback) and the flight recorder cannot dump it
(no exception unwinds). The watchdog is the piece that CAN: it is fed by
StepWatch phase transitions (`StepWatch.phase_listener`), so it knows
which host phase is live and for how long; when a watched phase exceeds
`timeout_s` it

- dumps ALL thread stacks (sys._current_frames) to stderr and to a
  `watchdog_stacks_*.txt` next to the run's outputs,
- dumps a flight-recorder bundle (`reason=watchdog_<kind>`) so the
  postmortem has the batches and RNG in flight,
- bumps `bert_watchdog_stalls_total{kind=...}`,
- and, with `action="abort"`, hard-exits with a DISTINCT code:
  EXIT_WATCHDOG_DEVICE_HANG for a stalled dispatch/readback/h2d/
  checkpoint (device side) vs EXIT_WATCHDOG_INPUT_STARVED for a stalled
  data_wait (input side) — the supervisor treats them differently
  (a hung device is not blindly retried; a starved input is).

`os._exit` is deliberate: the main thread is by definition wedged
inside a blocking call, so raising into it or unwinding finally-blocks
is not available — the stacks + bundle ARE the orderly part of this
shutdown. With `action="warn"` the watchdog logs + dumps once per stall
and re-arms on the next phase transition (drills and soak runs).
"""

from __future__ import annotations

import io
import os
import sys
import threading
import time
import traceback
from typing import Callable, Optional

from bert_pytorch_tpu.resilience import (EXIT_WATCHDOG_DEVICE_HANG,
                                         EXIT_WATCHDOG_INPUT_STARVED)

# phase -> stall classification: everything that blocks on the device
# (or on a filesystem commit) is a device hang; only the input-pipeline
# wait is starvation. `metric_flush` is where the one-step-lag readback
# blocks, i.e. in steady state it IS the device step.
INPUT_PHASES = frozenset({"data_wait"})
DEVICE_PHASES = frozenset({"dispatch", "metric_flush", "h2d",
                           "checkpoint"})
WATCHED_PHASES = INPUT_PHASES | DEVICE_PHASES


class HungStepWatchdog:
    """Daemon-thread stall detector fed by StepWatch phase transitions.

    Usage (run_pretraining.py):
        wd = HungStepWatchdog(timeout_s=args.watchdog_timeout,
                              action=args.watchdog_action,
                              recorder=recorder, registry=tel.registry,
                              log=logger.info, out_dir=args.output_dir)
        sw.phase_listener = wd.on_phase
        wd.start()
        ...
        wd.close()
    """

    def __init__(self, timeout_s: float, action: str = "abort",
                 recorder=None, registry=None,
                 log: Callable[[str], None] = print,
                 out_dir: Optional[str] = None,
                 time_fn: Callable[[], float] = time.monotonic,
                 exit_fn: Callable[[int], None] = os._exit):
        if action not in ("abort", "warn"):
            raise ValueError(f"watchdog action {action!r}: want abort|warn")
        self.timeout_s = float(timeout_s)
        self.action = action
        self.recorder = recorder
        self._log = log
        self.out_dir = out_dir
        self._time = time_fn
        self._exit = exit_fn
        self._lock = threading.Lock()
        self._current: Optional[tuple] = None  # (phase, enter_time)
        self._tripped_entry: Optional[tuple] = None  # warn-mode re-arm key
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.stalls = 0
        self.last_stall: Optional[dict] = None
        self._stalls_total = None
        if registry is not None:
            self._stalls_total = registry.counter(
                "bert_watchdog_stalls_total",
                "hung-step watchdog trips (phase exceeded "
                "--watchdog_timeout)", labels=("kind",))

    # -- StepWatch feed ------------------------------------------------------

    def on_phase(self, name: str, entering: bool) -> None:
        """StepWatch.phase_listener hook — microseconds, no locks held
        beyond the tuple swap."""
        if name not in WATCHED_PHASES:
            return
        with self._lock:
            self._current = (name, self._time()) if entering else None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "HungStepWatchdog":
        self._thread = threading.Thread(target=self._loop,
                                        name="hung-step-watchdog",
                                        daemon=True)
        self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    # -- detection -----------------------------------------------------------

    def _loop(self) -> None:
        poll = max(0.05, min(1.0, self.timeout_s / 4.0))
        while not self._stop.wait(poll):
            with self._lock:
                current = self._current
            if current is None:
                continue
            name, t0 = current
            age = self._time() - t0
            if age < self.timeout_s:
                continue
            if self._tripped_entry == current:
                continue  # warn mode: one trip per stalled phase entry
            self._tripped_entry = current
            self._trip(name, age)

    def _trip(self, phase: str, age: float) -> None:
        kind = ("input_starvation" if phase in INPUT_PHASES
                else "device_hang")
        code = (EXIT_WATCHDOG_INPUT_STARVED if kind == "input_starvation"
                else EXIT_WATCHDOG_DEVICE_HANG)
        self.stalls += 1
        self.last_stall = {"phase": phase, "kind": kind,
                           "age_s": round(age, 3)}
        if self._stalls_total is not None:
            self._stalls_total.inc(kind=kind)
        stacks_path = self._dump_stacks(phase, kind)
        bundle = None
        if self.recorder is not None:
            try:
                bundle = self.recorder.dump(f"watchdog_{kind}")
            except Exception:
                pass  # the alarm must not die on a full disk
        self._log(
            f"WATCHDOG: phase '{phase}' stalled for {age:.1f}s "
            f"(> --watchdog_timeout {self.timeout_s:g}s) — classified "
            f"{kind}"
            + (f"; thread stacks: {stacks_path}" if stacks_path else "")
            + (f"; flight-recorder bundle: {bundle}" if bundle else "")
            + (f"; aborting with exit code {code}"
               if self.action == "abort" else "; action=warn, training on"))
        if self.action == "abort":
            self._exit(code)

    def _dump_stacks(self, phase: str, kind: str) -> Optional[str]:
        """All-thread stacks: to stderr always, and to a file next to the
        run outputs when out_dir is set (the stderr copy survives even
        when the disk is the problem). sys._current_frames + traceback
        rather than faulthandler: faulthandler needs a real fd, and a
        wedged main thread inside a C call still exposes its Python
        stack through _current_frames — which is the frame that names
        the hung jit dispatch."""
        buf = io.StringIO()
        buf.write(f"hung-step watchdog: phase={phase} kind={kind} "
                  f"timeout={self.timeout_s:g}s\n")
        names = {t.ident: t.name for t in threading.enumerate()}
        for ident, frame in sorted(sys._current_frames().items()):
            buf.write(f"\n--- thread {names.get(ident, '?')} "
                      f"(ident {ident}) ---\n")
            buf.write("".join(traceback.format_stack(frame)))
        text = buf.getvalue()
        sys.stderr.write(text)
        sys.stderr.flush()
        if not self.out_dir:
            return None
        try:
            path = os.path.join(
                self.out_dir,
                f"watchdog_stacks_{int(time.time())}_{kind}.txt")
            with open(path, "w", encoding="utf-8") as f:
                f.write(text)
            return path
        except OSError:
            return None


def arm_watchdog(timeout_s: float, action: str, stepwatch,
                 registry=None, log: Callable[[str], None] = print,
                 out_dir: Optional[str] = None, recorder=None
                 ) -> Optional[HungStepWatchdog]:
    """One-call wiring used by every training entry point: build, start,
    hook into the StepWatch, log the armed line. Returns None (off) when
    timeout_s <= 0."""
    if timeout_s <= 0:
        return None
    wd = HungStepWatchdog(timeout_s=timeout_s, action=action,
                          recorder=recorder, registry=registry,
                          log=log, out_dir=out_dir).start()
    stepwatch.phase_listener = wd.on_phase
    log(f"watchdog: armed at {timeout_s:g}s per host phase, "
        f"action={action} (device hang -> exit "
        f"{EXIT_WATCHDOG_DEVICE_HANG}, input starvation -> exit "
        f"{EXIT_WATCHDOG_INPUT_STARVED})")
    return wd
