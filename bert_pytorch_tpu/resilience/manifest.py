"""Checkpoint integrity sidecars: jax-free digests, verification,
quarantine.

An orbax checkpoint that lost a race with a preemption (torn write), a
disk that flipped bits, or an operator's stray `rsync --partial` all
present the same way at the worst time: auto-resume crashes deep inside a
deserialization stack, the error names a tensorstore shard instead of a
checkpoint, and the run is down until a human intervenes. The fix is the
standard one: every committed checkpoint gets a sidecar manifest of
content digests written AFTER commit, restore verifies digests BEFORE
deserializing, and a checkpoint that fails verification is quarantined
(renamed `<step>.corrupt` — recoverable by renaming back) so auto-resume
walks to the next-newest instead of crashing.

Everything here is stdlib-only (hashlib/json/os): the supervisor
(tools/supervise.py) reads `latest_step_on_disk` for crash-loop
detection from a jax-free parent, and the verification must be runnable
even when the training process's jax state is the thing being debugged.

Layout (orbax CheckpointManager, training/checkpoint.py):

    <ckpt_dir>/<step>/                  committed checkpoint
    <ckpt_dir>/<step>/state/...         TrainState item
    <ckpt_dir>/<step>/extra/...         JSON item (sampler cursor, epoch)
    <ckpt_dir>/<step>/integrity.json    this module's sidecar (post-commit)
    <ckpt_dir>/<step>.corrupt/          quarantined (failed verification)

The sidecar carries per-item content digests plus a provenance echo
(git SHA / mesh / program fingerprint when known) and the `extra` echo
(sampler / stream cursor) so an operator can read WHERE a checkpoint's
data plane stood without deserializing anything.
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from typing import Any, Dict, List, Optional

MANIFEST_NAME = "integrity.json"
MANIFEST_SCHEMA_VERSION = 1
QUARANTINE_SUFFIX = ".corrupt"

_CHUNK = 1 << 20


class CorruptCheckpointError(RuntimeError):
    """A checkpoint failed integrity verification (digest mismatch, torn
    or unreadable sidecar/data). Carries the step and the per-item error
    list so callers can name the failed item in their warning."""

    def __init__(self, step: Optional[int], errors: List[str]):
        self.step = step
        self.errors = list(errors)
        detail = "; ".join(self.errors) or "unknown corruption"
        super().__init__(
            f"checkpoint step {step}: integrity verification failed "
            f"({detail})")


def step_dir_path(ckpt_dir: str, step: int) -> str:
    return os.path.join(ckpt_dir, str(int(step)))


def _iter_files(step_dir: str):
    """Yield (relpath, abspath) for every file under step_dir except the
    sidecar itself, in sorted order (digests must be path-stable)."""
    out = []
    for root, _dirs, files in os.walk(step_dir):
        for name in files:
            ap = os.path.join(root, name)
            rp = os.path.relpath(ap, step_dir)
            if rp == MANIFEST_NAME:
                continue
            out.append((rp, ap))
    out.sort()
    return out


def compute_item_digests(step_dir: str) -> Dict[str, Dict[str, Any]]:
    """Per-item content digests for a committed step directory. An
    "item" is a top-level entry of the step dir (orbax item dirs like
    `state`/`extra`; loose root files such as `_CHECKPOINT_METADATA`
    group under `_root`). Each item's sha256 folds every file's relative
    path and bytes, so a missing, renamed, truncated, or bit-flipped
    file all change the digest."""
    items: Dict[str, Any] = {}
    for rp, ap in _iter_files(step_dir):
        head = rp.split(os.sep, 1)[0] if os.sep in rp else "_root"
        entry = items.setdefault(
            head, {"hash": hashlib.sha256(), "files": 0, "bytes": 0})
        entry["hash"].update(rp.replace(os.sep, "/").encode("utf-8"))
        entry["hash"].update(b"\0")
        with open(ap, "rb") as f:
            while True:
                chunk = f.read(_CHUNK)
                if not chunk:
                    break
                entry["hash"].update(chunk)
                entry["bytes"] += len(chunk)
        entry["hash"].update(b"\0")
        entry["files"] += 1
    return {
        name: {"sha256": e["hash"].hexdigest(), "files": e["files"],
               "bytes": e["bytes"]}
        for name, e in sorted(items.items())
    }


def write_step_manifest(step_dir: str, step: int,
                        extra_echo: Optional[Dict[str, Any]] = None,
                        provenance: Optional[Dict[str, Any]] = None,
                        program_fingerprint: Optional[Dict[str, Any]] = None
                        ) -> str:
    """Write the sidecar for a COMMITTED step directory (caller must have
    waited for the async save — digests of in-flight files would be
    lies). Atomic via tmp+rename so a preemption mid-write leaves either
    no sidecar (checkpoint merely unverifiable, not quarantined) or a
    complete one."""
    manifest = {
        "schema_version": MANIFEST_SCHEMA_VERSION,
        "step": int(step),
        "created_unix": round(time.time(), 3),
        "items": compute_item_digests(step_dir),
        "extra_echo": extra_echo,
        "provenance": provenance or {},
        "program_fingerprint": program_fingerprint,
    }
    path = os.path.join(step_dir, MANIFEST_NAME)
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(manifest, f, indent=2, sort_keys=True, default=str)
    os.replace(tmp, path)
    return path


def read_step_manifest(step_dir: str) -> Optional[Dict[str, Any]]:
    """The sidecar dict, or None when absent (pre-resilience checkpoint).
    An unreadable/truncated sidecar raises CorruptCheckpointError — a
    half-written manifest next to a checkpoint is itself evidence of a
    torn shutdown."""
    path = os.path.join(step_dir, MANIFEST_NAME)
    if not os.path.isfile(path):
        return None
    try:
        with open(path, encoding="utf-8") as f:
            return json.load(f)
    except Exception as e:
        raise CorruptCheckpointError(
            _step_of(step_dir), [f"sidecar {MANIFEST_NAME} unreadable: {e}"])


def _step_of(step_dir: str) -> Optional[int]:
    try:
        return int(os.path.basename(step_dir.rstrip(os.sep)))
    except ValueError:
        return None


def verify_step_dir(step_dir: str) -> Optional[List[str]]:
    """Verify a step directory against its sidecar.

    Returns None when there is no sidecar (nothing to verify against —
    the caller decides whether to trust a legacy checkpoint), [] when
    every item's digest matches, or a list of human-readable errors
    naming each failed item."""
    manifest = read_step_manifest(step_dir)
    if manifest is None:
        return None
    want = manifest.get("items")
    if not isinstance(want, dict) or not want:
        return ["sidecar carries no item digests"]
    got = compute_item_digests(step_dir)
    errors: List[str] = []
    for name, meta in sorted(want.items()):
        if name not in got:
            errors.append(f"item '{name}' missing "
                          f"({meta.get('files')} files expected)")
            continue
        if got[name]["sha256"] != meta.get("sha256"):
            errors.append(
                f"item '{name}' digest mismatch "
                f"(want {str(meta.get('sha256'))[:12]}..., got "
                f"{got[name]['sha256'][:12]}...; "
                f"{got[name]['files']} files / {got[name]['bytes']} bytes "
                f"on disk vs {meta.get('files')} / {meta.get('bytes')} "
                "recorded)")
    for name in sorted(set(got) - set(want)):
        errors.append(f"unexpected item '{name}' not covered by the "
                      "sidecar")
    return errors


def quarantine_step(ckpt_dir: str, step: int) -> str:
    """Rename <ckpt_dir>/<step> -> <step>.corrupt (first free suffix) so
    orbax's step scan no longer sees it. Recoverable: renaming back
    restores the checkpoint for offline forensics/repair."""
    src = step_dir_path(ckpt_dir, step)
    dst = src + QUARANTINE_SUFFIX
    n = 1
    while os.path.exists(dst):
        n += 1
        dst = f"{src}{QUARANTINE_SUFFIX}{n}"
    os.replace(src, dst)
    return dst


def all_steps_on_disk(ckpt_dir: str) -> List[int]:
    """Committed checkpoint steps by directory scan — integer-named dirs
    only (quarantined `.corrupt` and orbax's `*.orbax-checkpoint-tmp-*`
    in-flight dirs never parse as ints). jax/orbax-free on purpose: the
    supervisor's crash-loop detector runs in the parent process."""
    try:
        entries = os.listdir(ckpt_dir)
    except OSError:
        return []
    steps = []
    for name in entries:
        if not os.path.isdir(os.path.join(ckpt_dir, name)):
            continue
        try:
            steps.append(int(name))
        except ValueError:
            continue
    return sorted(steps)


def latest_step_on_disk(ckpt_dir: str) -> Optional[int]:
    """Newest committed step, or None — the supervisor's progress probe."""
    steps = all_steps_on_disk(ckpt_dir)
    return steps[-1] if steps else None
