"""Preemption guard: layered SIGTERM handling + the emergency checkpoint.

Before this module, SIGTERM on a training run meant: the flight
recorder's handler raised SystemExit(143), the except-path dumped a
debug bundle, and the process unwound — losing every step since the
last `--num_steps_per_checkpoint` boundary (up to 200 steps of real
work, the reference's default). On preemptible capacity that loss is
paid on EVERY preemption, which is the whole cost model of "Multi-node
BERT-pretraining: Cost-efficient Approach" (PAPERS.md 2008.00177).

`PreemptionGuard` layers on top of the flight recorder's handler chain
(it chains to, never replaces, whatever handler was installed before
it): on SIGTERM it notes the preemption, bumps
`bert_preemptions_total`, and lets the previous handler raise
SystemExit so the entry point's crash path still flushes metrics and
dumps the bundle. The entry point then calls `emergency_save(...)` from
its except-path: ONE final synchronous `manager.save` + `wait()` of the
last COMPLETED step, so a preempted run loses zero completed steps and
the restart (tools/supervise.py) resumes bit-identically.

The guard never saves from inside the signal handler — async-signal
context is no place for orbax. The handler only records; all real work
happens on the normal unwind path.
"""

from __future__ import annotations

import signal
from typing import Any, Callable, Dict, Optional


class PreemptionGuard:
    """Layered preemption-notice handler.

    Usage (run_pretraining.py — AFTER recorder.install_crash_handlers,
    so the chain is guard -> recorder -> SystemExit):

        guard = PreemptionGuard(registry=tel.registry, log=logger.info)
        guard.install()
        ...
        except BaseException as exc:
            if guard.preempted_signal is not None:
                emergency_save(...)
        finally:
            guard.close()
    """

    def __init__(self,
                 signals=(signal.SIGTERM, signal.SIGINT),
                 registry=None,
                 log: Callable[[str], None] = print):
        # SIGINT is in the default set on purpose: tools/supervise.py
        # forwards BOTH signals to the child for the emergency-save path,
        # and a finetune entry point without the flight recorder would
        # otherwise see a bare KeyboardInterrupt the guard never noted —
        # Ctrl-C on an unsupervised finetune would lose the whole run
        self._signals = tuple(signals)
        self._log = log
        self.preempted_signal: Optional[int] = None
        self._old: Dict[int, Any] = {}
        self._counter = None
        if registry is not None:
            self._counter = registry.counter(
                "bert_preemptions_total",
                "preemption notices (SIGTERM) received by this process")

    def install(self) -> None:
        """Install the layered handler; previous handlers are preserved
        and chained to. No-op per-signal when installation is impossible
        (non-main thread)."""
        for sig in self._signals:
            try:
                self._old[sig] = signal.signal(sig, self._on_signal)
            except (ValueError, OSError):
                pass

    def _on_signal(self, signum, frame):
        if self.preempted_signal is not None:
            # already unwinding toward the emergency checkpoint: a repeat
            # signal (double Ctrl-C, orchestrator re-notify) must not
            # raise INSIDE the in-flight save and tear the very
            # checkpoint this guard exists to guarantee
            self._log(f"preemption: {signal.Signals(signum).name} "
                      "repeated — emergency checkpoint already in "
                      "progress, ignoring")
            return
        self.preempted_signal = signum
        if self._counter is not None:
            self._counter.inc()
        old = self._old.get(signum)
        if callable(old):
            # the layer below (flight recorder) raises SystemExit(128+sig)
            old(signum, frame)
        else:
            # no layer below (recorder off / SIG_DFL): provide the same
            # contract ourselves so the except-path still runs
            raise SystemExit(128 + signum)

    def close(self) -> None:
        """Restore the handlers exactly as found. Idempotent."""
        for sig, old in self._old.items():
            try:
                signal.signal(sig, old)
            except (ValueError, OSError):
                pass
        self._old.clear()


def is_preemption_exit(exc: BaseException,
                       signals=(signal.SIGTERM, signal.SIGINT)) -> bool:
    """True when `exc` is the SystemExit a mapped preemption signal
    raises (128+sig convention, flight_recorder._on_signal)."""
    return (isinstance(exc, SystemExit)
            and isinstance(exc.code, int)
            and exc.code in {128 + int(s) for s in signals})


def finetune_emergency_save(guard: "PreemptionGuard",
                            exc: BaseException,
                            survival: Dict[str, Any],
                            ckpt_dir: str, task: str,
                            registry=None,
                            log: Callable[[str], None] = print) -> None:
    """The finetune entry points' except-path (run_squad/run_ner — ONE
    implementation, not two copies): when the unwind was a preemption and
    at least one step completed, save the in-progress state to
    `ckpt_dir`. Never raises — the original exception must keep
    propagating."""
    if not survival:
        return
    if guard.preempted_signal is None and not is_preemption_exit(exc):
        return
    from bert_pytorch_tpu.training.checkpoint import CheckpointManager

    mgr = CheckpointManager(ckpt_dir, registry=registry, log=log)
    try:
        emergency_save(mgr, survival["step"], survival["state"],
                       extra={"task": task, "emergency": True}, log=log)
    except Exception as e:
        log(f"WARNING: emergency checkpoint failed: {e}")
    finally:
        try:
            mgr.close()
        except Exception:
            pass


def emergency_save(manager, step: int, state, extra: Dict[str, Any],
                   log: Callable[[str], None] = print) -> bool:
    """The final synchronous checkpoint on the preemption unwind path:
    save the last COMPLETED step and wait for the commit (+ integrity
    sidecar) before the process exits. Returns True when a checkpoint
    was actually written, False when step was already on disk (the
    signal landed on a boundary — zero steps at risk, nothing to do).

    Idempotence against the atexit backstop and double signals is the
    caller's one-shot guard; this function itself is safe to call twice
    (the second save of the same step is a policy no-op in orbax)."""
    if manager.latest_step() == int(step):
        log(f"preemption: checkpoint for step {step} already on disk — "
            "zero completed steps at risk")
        return False
    saved = manager.save(int(step), state, extra=extra)
    manager.wait()
    if saved:
        log(f"preemption: emergency checkpoint saved at step {step} "
            "(synchronous save + wait — zero completed steps lost)")
    return bool(saved)
