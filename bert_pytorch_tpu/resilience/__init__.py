"""Resilience: the survival kit for preemptible / commodity capacity.

The observability stack (flight recorder, health pack, replay) can
*explain* a dead run; this package makes runs *survive*: preemption-safe
emergency checkpoints (preemption.py), checkpoint integrity sidecars +
corrupt-checkpoint quarantine/fallback (manifest.py, consumed by
training/checkpoint.py), a hung-step watchdog (watchdog.py), chaos fault
injection (chaos.py), and — outside the process — tools/supervise.py,
the restart loop that turns all of it into an unattended run.

Everything in this package except nothing is importable without jax:
the supervisor and the drill gate must run in a jax-free parent, and a
corrupted interpreter state is exactly when the survival code must still
work. docs/RESILIENCE.md is the operator guide; the exit-code contract
below is its source of truth.

On preemptible capacity ("Multi-node BERT-pretraining: Cost-efficient
Approach", PAPERS.md 2008.00177) preemption is a routine event, not an
incident; at pod scale ("Scalable Training of Language Models using JAX
pjit and TPUv4", 2204.06514) worker death and hung dispatches are
weekly weather. The deterministic-resume machinery (checkpointed
sampler/packer/stream cursors, per-step fold_in dropout keys) makes
surviving them *provable*: a SIGKILLed-and-restarted run is bit-identical
to an uninterrupted one, and tests/test_resilience.py drills exactly
that.
"""

from __future__ import annotations

# -- exit-code contract (docs/RESILIENCE.md) --------------------------------
# Signals keep the shell convention 128+signum (SIGTERM -> 143,
# SIGINT -> 130). The codes below are chosen outside 128+ and outside the
# small codes Python/argparse already use, so a supervisor can classify a
# death without parsing logs:
#
#   retryable      : 128+sig (preemption), any unlisted nonzero (crash),
#                    EXIT_WATCHDOG_INPUT_STARVED (often a transient data
#                    stall — retried, but still bounded by the restart
#                    budget and crash-loop detection), EXIT_SLO_BREACH
#                    (a sustained page-severity train SLO breach — step
#                    time, checkpoint freshness — is usually a stuck
#                    pipeline or straggler a fresh process clears)
#   NOT retryable  : EXIT_NONFINITE_HALT (restarting replays the same
#                    deterministic blowup), EXIT_WATCHDOG_DEVICE_HANG
#                    (a wedged accelerator wants a drain/reschedule, not
#                    the same host again)
EXIT_NONFINITE_HALT = 71        # --nonfinite_action=halt tripped
EXIT_WATCHDOG_DEVICE_HANG = 72  # dispatch/readback/h2d/checkpoint stalled
EXIT_WATCHDOG_INPUT_STARVED = 73  # data_wait stalled (input pipeline)
# supervisor's own verdicts (tools/supervise.py):
EXIT_CRASH_LOOP = 74            # restarts without checkpoint progress
EXIT_RESTART_BUDGET = 75        # max restarts exhausted
EXIT_SLO_BREACH = 76            # --slo_action=halt: sustained page breach

# exit codes tools/supervise.py refuses to retry by default
NO_RETRY_EXIT_CODES = (EXIT_NONFINITE_HALT, EXIT_WATCHDOG_DEVICE_HANG)

from bert_pytorch_tpu.resilience.manifest import (  # noqa: E402
    CorruptCheckpointError, MANIFEST_NAME, latest_step_on_disk,
    quarantine_step, step_dir_path, verify_step_dir, write_step_manifest)
from bert_pytorch_tpu.resilience.preemption import (  # noqa: E402
    PreemptionGuard)
from bert_pytorch_tpu.resilience.watchdog import HungStepWatchdog  # noqa: E402
from bert_pytorch_tpu.resilience.chaos import (  # noqa: E402
    CHAOS_MODES, ChaosMonkey, corrupt_newest_checkpoint)

__all__ = [
    "EXIT_NONFINITE_HALT", "EXIT_WATCHDOG_DEVICE_HANG",
    "EXIT_WATCHDOG_INPUT_STARVED", "EXIT_CRASH_LOOP",
    "EXIT_RESTART_BUDGET", "EXIT_SLO_BREACH", "NO_RETRY_EXIT_CODES",
    "CorruptCheckpointError", "MANIFEST_NAME", "latest_step_on_disk",
    "quarantine_step", "step_dir_path", "verify_step_dir",
    "write_step_manifest", "PreemptionGuard", "HungStepWatchdog",
    "CHAOS_MODES", "ChaosMonkey", "corrupt_newest_checkpoint",
]
