"""Chaos drills: deterministic fault injection for the survival kit.

A resilience feature that has never fired is a resilience bug waiting
for production to find it. `--chaos <mode> --chaos_step N` injects the
four deaths the kit must survive, at an exact step, so CI can drill the
full loop (scripts/check_resilience.sh, tests/test_resilience.py):

- `sigkill_at_step`    — SIGKILL self before dispatching step N: the
  un-catchable death (preemption without notice, OOM-killer). Proves
  the supervisor + deterministic resume path: the restarted run must be
  bit-identical to an uninterrupted one.
- `sigterm_at_step`    — SIGTERM self before step N: the polite
  preemption notice. Proves the layered handler chain: flight-recorder
  bundle AND emergency checkpoint of step N-1 both land, zero completed
  steps lost.
- `corrupt_newest_ckpt`— at the first checkpoint boundary at/after
  step N: wait for the commit + sidecar, flip bytes in the newest
  checkpoint's largest data file, then SIGKILL. Proves quarantine +
  fallback: resume must rename `<step>.corrupt`, warn loudly naming the
  failed item, and restore the next-newest.
- `stall_dispatch`     — sleep `stall_secs` inside the dispatch phase at
  step N. Proves the hung-step watchdog trips, classifies device_hang,
  and dumps stacks + bundle.

Chaos fires ONLY in the first supervised incarnation
(BERT_SUPERVISOR_RESTARTS unset or 0): the restarted run must sail past
the injection step, or every drill would be a crash loop.
"""

from __future__ import annotations

import os
import signal
import sys
import time
from typing import Callable, Optional, Tuple

CHAOS_MODES = ("sigkill_at_step", "sigterm_at_step",
               "corrupt_newest_ckpt", "stall_dispatch")

# number of mid-file bytes XOR-flipped by corrupt_newest_checkpoint —
# enough to guarantee a digest change even on a compressed store
_FLIP_BYTES = 64


def chaos_enabled_env() -> bool:
    """Chaos only fires in the first incarnation under the supervisor
    (or in an unsupervised run): restart N>0 must survive, not re-die."""
    try:
        return int(os.environ.get("BERT_SUPERVISOR_RESTARTS", "0")) == 0
    except ValueError:
        return True


def corrupt_newest_checkpoint(ckpt_dir: str,
                              log: Callable[[str], None] = print
                              ) -> Tuple[int, str]:
    """Flip bytes in the middle of the newest committed checkpoint's
    largest data file (the integrity sidecar itself is exempt — the
    drill corrupts DATA, verification catches it). Returns (step, path
    corrupted). Raises FileNotFoundError when there is no checkpoint."""
    from bert_pytorch_tpu.resilience.manifest import (MANIFEST_NAME,
                                                      latest_step_on_disk,
                                                      step_dir_path)

    step = latest_step_on_disk(ckpt_dir)
    if step is None:
        raise FileNotFoundError(f"no committed checkpoint under {ckpt_dir}")
    step_dir = step_dir_path(ckpt_dir, step)
    largest, size = None, -1
    for root, _dirs, files in os.walk(step_dir):
        for name in files:
            if name == MANIFEST_NAME:
                continue
            path = os.path.join(root, name)
            n = os.path.getsize(path)
            if n > size:
                largest, size = path, n
    if largest is None:
        raise FileNotFoundError(f"checkpoint step {step} holds no files")
    with open(largest, "r+b") as f:
        f.seek(max(0, size // 2 - _FLIP_BYTES // 2))
        chunk = f.read(min(_FLIP_BYTES, size))
        f.seek(max(0, size // 2 - _FLIP_BYTES // 2))
        f.write(bytes(b ^ 0xFF for b in chunk))
    log(f"CHAOS: corrupted checkpoint step {step} "
        f"({os.path.relpath(largest, step_dir)}, {size} bytes, "
        f"{len(chunk)} flipped mid-file)")
    return step, largest


class ChaosMonkey:
    """Per-run fault injector; the entry point calls the three hooks
    from its loop. Inert (all hooks no-op) when mode is None or a
    supervised restart (chaos_enabled_env)."""

    def __init__(self, mode: Optional[str], at_step: int,
                 stall_secs: float = 3.0,
                 log: Callable[[str], None] = print):
        if mode is not None and mode not in CHAOS_MODES:
            raise ValueError(f"chaos mode {mode!r}: want one of "
                             f"{CHAOS_MODES}")
        self.mode = mode if (mode and chaos_enabled_env()) else None
        if mode and self.mode is None:
            log(f"chaos: --chaos {mode} disarmed (supervised restart "
                f"#{os.environ.get('BERT_SUPERVISOR_RESTARTS')} — the "
                "drill fires only in the first incarnation)")
        self.at_step = int(at_step)
        self.stall_secs = float(stall_secs)
        self._log = log
        self._fired = False

    def before_dispatch(self, step: int) -> None:
        """Called with the global step ABOUT to execute: steps < step are
        completed and (up to the checkpoint policy) on disk. `>=` + the
        one-shot latch, not `==`: with --steps_per_loop > 1 the loop only
        presents chunk-aligned step ids, and an exact match on an
        unaligned --chaos_step would silently never fire — a drill that
        no-ops reads as a drill that passed."""
        if self._fired or self.mode not in ("sigkill_at_step",
                                            "sigterm_at_step") \
                or step < self.at_step:
            return
        self._fired = True
        sig = (signal.SIGKILL if self.mode == "sigkill_at_step"
               else signal.SIGTERM)
        self._log(f"CHAOS: raising {signal.Signals(sig).name} before "
                  f"step {step} ({self.mode})")
        sys.stderr.flush()
        sys.stdout.flush()
        os.kill(os.getpid(), sig)
        # SIGTERM: the layered handler raises SystemExit on this thread
        # at the next bytecode boundary; nothing more to do here.

    def stall(self, step: int) -> None:
        """Called inside the dispatch StepWatch phase (same >= + latch
        semantics as before_dispatch)."""
        if self._fired or self.mode != "stall_dispatch" \
                or step < self.at_step:
            return
        self._fired = True
        self._log(f"CHAOS: stalling dispatch of step {step} for "
                  f"{self.stall_secs:g}s (watchdog should trip)")
        time.sleep(self.stall_secs)

    def after_checkpoint(self, manager, step: int) -> None:
        """Called right after a periodic checkpoint save was issued."""
        if self._fired or self.mode != "corrupt_newest_ckpt" \
                or step < self.at_step:
            return
        self._fired = True
        manager.wait()  # commit + integrity sidecar must both be final
        corrupt_newest_checkpoint(manager.directory, log=self._log)
        self._log("CHAOS: raising SIGKILL after corrupting the newest "
                  "checkpoint (resume must quarantine + fall back)")
        sys.stderr.flush()
        sys.stdout.flush()
        os.kill(os.getpid(), signal.SIGKILL)
